"""Epochal mutable-index contract (ISSUE 18): delta tessellation
bit-identity, atomic epoch publish, crash-consistent delta log with
kill-at-every-boundary replay, typed corruption refusals, compaction
(auto, background, and killed mid-way), the torn-publish boundary, the
durable-stream epoch fence, and the router's per-tenant epoch advance —
`mosaic_tpu/index/epoch.py` + the `core/tessellate.py` surgery."""

import numpy as np
import pytest

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate, tessellate_subset
from mosaic_tpu.index import (
    EpochalIndex,
    EpochFingerprintMismatch,
    EpochLogCorrupt,
    chip_index_equal,
)
from mosaic_tpu.raster import Raster
from mosaic_tpu.raster.zonal import host_zonal_zones_oracle, zonal_zones
from mosaic_tpu.runtime import checkpoint, faults, telemetry
from mosaic_tpu.runtime.errors import TransientDeviceError
from mosaic_tpu.runtime.retry import RetryPolicy
from mosaic_tpu.serve import BucketLadder, ServeEngine, ServeRouter
from mosaic_tpu.sql.join import build_chip_index, host_join, pip_join
from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3
BBOX = (-25.0, -25.0, 35.0, 20.0)
FAST = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)

ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
    "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
    "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
]
#: epoch 1: zone 1 grows (a live edit of an existing geometry)
ZONE1_V2 = "POLYGON ((-22 -22, -4 -22, -4 -4, -22 -4, -22 -22))"
#: epoch 2: a brand-new zone under a fresh stable id
ZONE3_NEW = "POLYGON ((-15 5, -5 5, -5 15, -15 15, -15 5))"


def mk(log_dir=None, **kw):
    kw.setdefault("keep_core_geoms", False)
    return EpochalIndex(
        wkt.from_wkt(ZONES), CUSTOM, RES,
        log_dir=str(log_dir) if log_dir else None, **kw,
    )


def scratch(ep):
    """The from-scratch oracle: a full tessellate + build of the
    epochal index's CURRENT column — what every published epoch must be
    bit-identical to."""
    return build_chip_index(
        tessellate(ep.column(), CUSTOM, RES, keep_core_geoms=False)
    )


def edit_replace(ep):
    return ep.apply(upsert=wkt.from_wkt([ZONE1_V2]), ids=[1])


def edit_insert(ep):
    return ep.apply(upsert=wkt.from_wkt([ZONE3_NEW]), ids=[3])


def edit_remove(ep):
    return ep.apply(remove=[0])


EDITS = (edit_replace, edit_insert, edit_remove)

BOOM = lambda s: RuntimeError(f"synthetic kill @ {s}")  # noqa: E731


@pytest.fixture(scope="module")
def pts():
    rng = np.random.default_rng(3)
    return rng.uniform(BBOX[:2], BBOX[2:], (256, 2))


@pytest.fixture(scope="module")
def advanced():
    """One epochal index driven through every edit kind and published
    at the final epoch (shared by the read-only frontend tests)."""
    ep = mk()
    for e in EDITS:
        e(ep)
    ep.publish()
    return ep


# ------------------------------------------------- delta tessellation


class TestDeltaTessellation:
    def test_subset_equals_full_blocks(self):
        """THE pin `tessellate_subset`'s docstring names: tessellation
        is per-geometry independent, so a subset pass is bit-identical
        to the matching blocks of a full pass."""
        col = wkt.from_wkt(ZONES)
        full = tessellate(col, CUSTOM, RES, keep_core_geoms=False)
        for g in range(len(ZONES)):
            sub = tessellate_subset(
                col, np.array([g]), CUSTOM, RES, keep_core_geoms=False
            )
            rows = np.nonzero(np.asarray(full.geom_id) == g)[0]
            assert len(sub) == rows.size
            np.testing.assert_array_equal(sub.geom_id, g)
            np.testing.assert_array_equal(
                sub.cell_id, np.asarray(full.cell_id)[rows]
            )
            np.testing.assert_array_equal(
                sub.is_core, np.asarray(full.is_core)[rows]
            )
            np.testing.assert_array_equal(
                sub.has_geom, np.asarray(full.has_geom)[rows]
            )
            want = full.chips.take([int(r) for r in rows])
            got = sub.chips
            for f in ("xy", "ring_offsets", "part_offsets",
                      "geom_offsets", "geom_type", "srid"):
                np.testing.assert_array_equal(
                    getattr(got, f), getattr(want, f)
                )

    def test_subset_relabels_geom_ids(self):
        col = wkt.from_wkt(ZONES)
        sub = tessellate_subset(
            col, np.array([0, 2]), CUSTOM, RES, keep_core_geoms=False,
            geom_ids=np.array([7, 9]),
        )
        assert set(np.unique(sub.geom_id)) == {7, 9}


# ------------------------------------------------- epoch bit-identity


class TestEpochBitIdentity:
    def test_epoch0_matches_scratch(self):
        ep = mk()
        ep.publish()
        assert ep.epoch == 0 and ep.applied_epoch == 0
        assert chip_index_equal(ep.index, scratch(ep))

    def test_every_epoch_matches_scratch(self):
        """The invariant everything else rides on: after replace,
        insert, and remove edits, each published epoch is bit-identical
        to a from-scratch rebuild of the current column."""
        ep = mk()
        ep.publish()
        for n, edit in enumerate(EDITS, start=1):
            stats = edit(ep)
            assert stats["epoch"] == n
            assert ep.applied_epoch == n and ep.epoch == n - 1
            ep.publish()
            assert ep.epoch == n
            assert chip_index_equal(ep.index, scratch(ep))
            assert ep.index.epoch == n
            assert ep.index.epoch_token == ep.epoch_token(n)

    def test_grow_from_empty(self):
        ep = EpochalIndex(None, CUSTOM, RES, keep_core_geoms=False)
        assert len(ep) == 0
        ep.apply(upsert=wkt.from_wkt(ZONES), ids=[0, 1, 2])
        ep.publish()
        assert chip_index_equal(ep.index, scratch(ep))

    def test_apply_validation(self):
        ep = mk()
        with pytest.raises(ValueError, match="ids for"):
            ep.apply(upsert=wkt.from_wkt([ZONE1_V2]), ids=[1, 2])
        with pytest.raises(ValueError, match="both upserted and removed"):
            ep.apply(upsert=wkt.from_wkt([ZONE1_V2]), ids=[1], remove=[1])
        with pytest.raises(KeyError, match="unknown geometry ids"):
            ep.apply(remove=[99])
        assert ep.applied_epoch == 0  # nothing durable happened

    def test_index_identity_carries_epoch_token(self, advanced):
        ident = checkpoint.index_identity(advanced.index)
        assert "@" in ident
        assert ident.endswith(advanced.index.epoch_token)
        plain = build_chip_index(
            tessellate(wkt.from_wkt(ZONES), CUSTOM, RES,
                       keep_core_geoms=False)
        )
        assert "@" not in checkpoint.index_identity(plain)


# ------------------------------------------------- frontends vs oracle


class TestFrontendsVsOracle:
    def test_pip_join_matches_f64_oracle(self, advanced, pts):
        got = pip_join(
            pts, None, CUSTOM, RES, chip_index=advanced.index,
            recheck=False,
        )
        want = host_join(pts, advanced.index.host, CUSTOM, RES)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_zonal_matches_f64_oracle(self, advanced):
        rng = np.random.default_rng(5)
        data = rng.uniform(0, 100, (1, 40, 40))
        data[0][rng.random((40, 40)) < 0.1] = -9.0
        r = Raster(
            data=data, gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0), srid=0,
            nodata=-9.0,
        )
        got = zonal_zones(r, advanced.index, CUSTOM, RES, tile=(32, 32))
        want = host_zonal_zones_oracle(
            r, advanced.index, CUSTOM, RES, tile=(32, 32)
        )
        np.testing.assert_array_equal(got.keys, want.keys)
        np.testing.assert_array_equal(got.count, want.count)
        np.testing.assert_array_equal(got.sum, want.sum)
        np.testing.assert_array_equal(got.min, want.min)
        np.testing.assert_array_equal(got.max, want.max)

    def test_serve_engine_spans_epochs(self, pts):
        """Live edits published INTO a running engine: every epoch's
        answers match that epoch's f64 oracle, and a publish that fails
        before the swap leaves the engine serving the old epoch."""
        ep = mk()
        ep.publish()
        with ServeEngine(
            ep.index, CUSTOM, RES, ladder=BucketLadder(64, 1024),
            bounds=BBOX, max_wait_s=0.0,
        ) as eng:
            old = ep.index.host
            np.testing.assert_array_equal(
                np.asarray(eng.join(pts, deadline_s=60.0)),
                host_join(pts, old, CUSTOM, RES),
            )
            edit_replace(ep)
            with faults.transient_errors(
                1, sites=("epoch.publish",), exc_factory=BOOM
            ):
                with pytest.raises(RuntimeError, match="synthetic kill"):
                    ep.publish(eng)
            assert ep.epoch == 0  # epochal stayed put...
            np.testing.assert_array_equal(  # ...and so did the engine
                np.asarray(eng.join(pts, deadline_s=60.0)),
                host_join(pts, old, CUSTOM, RES),
            )
            ep.publish(eng)
            assert ep.epoch == 1
            np.testing.assert_array_equal(
                np.asarray(eng.join(pts, deadline_s=60.0)),
                host_join(pts, ep.index.host, CUSTOM, RES),
            )


# ------------------------------------------------- kill-storm replay


#: (fault site, matching calls let through, epoch the log must replay
#: to). apply's boundaries: pre-tessellate / pre-append / post-append —
#: the delta record is the durable point. publish writes nothing, so
#: both its boundaries (pre-build and the torn swap-vs-counter gap)
#: replay to the applied epoch. compact's boundaries: pre-snapshot /
#: post-snapshot-pre-truncate / post-truncate.
KILL_MATRIX = [
    ("epoch.apply", 0, 0),
    ("epoch.apply", 1, 0),
    ("epoch.apply", 2, 1),
    ("epoch.publish", 0, 1),
    ("epoch.publish", 1, 1),
    ("epoch.compact", 0, 1),
    ("epoch.compact", 1, 1),
    ("epoch.compact", 2, 1),
]


class TestKillReplay:
    @pytest.mark.parametrize("site,skip,survivor", KILL_MATRIX)
    def test_kill_at_every_boundary(self, tmp_path, site, skip, survivor):
        """A kill at ANY fault-site boundary leaves a log that replays
        to a bit-identical index at the surviving epoch."""
        d = tmp_path / "log"
        ep = mk(d)
        with faults.transient_errors(
            1, sites=(site,), skip_first=skip, exc_factory=BOOM
        ):
            with pytest.raises(RuntimeError, match="synthetic kill"):
                edit_replace(ep)
                if site == "epoch.publish":
                    ep.publish()
                elif site == "epoch.compact":
                    ep.compact()
        r = EpochalIndex.replay(str(d), CUSTOM)
        assert r.applied_epoch == survivor and r.epoch == survivor
        assert chip_index_equal(r.index, scratch(r))
        assert len(r) == 3 and list(r._order) == [0, 1, 2]

    def test_torn_publish_never_half_bumps(self, tmp_path):
        """The torn boundary: index swapped, counter not yet bumped. The
        published-epoch counter must NOT have advanced, and replay lands
        cleanly on the durable epoch."""
        d = tmp_path / "log"
        ep = mk(d)
        ep.publish()
        edit_replace(ep)
        with faults.transient_errors(
            1, sites=("epoch.publish",), skip_first=1, exc_factory=BOOM
        ):
            with pytest.raises(RuntimeError, match="synthetic kill"):
                ep.publish()
        assert ep.epoch == 0  # old epoch or a clean replay, never between
        r = EpochalIndex.replay(str(d), CUSTOM)
        assert r.epoch == 1
        assert chip_index_equal(r.index, scratch(r))

    def test_replay_equals_live_instance(self, tmp_path):
        d = tmp_path / "log"
        ep = mk(d)
        for e in EDITS:
            e(ep)
        ep.publish()
        r = EpochalIndex.replay(str(d), CUSTOM)
        assert r.applied_epoch == ep.applied_epoch == 3
        assert r.epoch_token() == ep.epoch_token()
        assert r.series == ep.series and r.chain == ep.chain
        assert chip_index_equal(r.index, ep.index)

    def test_replay_upto_historical_epoch(self, tmp_path):
        """``upto`` stops the replay at a historical epoch — the audit
        knob — and the result matches that epoch's from-scratch build."""
        d = tmp_path / "log"
        ep = mk(d)
        reference = {}
        ep.publish()
        reference[0] = ep.index
        for n, e in enumerate(EDITS, start=1):
            e(ep)
            ep.publish()
            reference[n] = ep.index
        for n in range(4):
            r = EpochalIndex.replay(str(d), CUSTOM, upto=n)
            assert r.applied_epoch == n
            assert chip_index_equal(r.index, reference[n])


# ------------------------------------------------- log refusals


class TestLogRefusals:
    def _logged(self, tmp_path, n_edits=2):
        d = tmp_path / "log"
        ep = mk(d)
        for e in EDITS[:n_edits]:
            e(ep)
        return d, ep

    def test_corrupt_tail_truncates(self, tmp_path):
        """Bit rot / kill-mid-write on the NEWEST delta is tail residue:
        replay truncates it (typed telemetry) and lands on the previous
        epoch, bit-identical."""
        d, _ = self._logged(tmp_path)
        p = d / "delta-00000002.npz"
        p.write_bytes(p.read_bytes()[:-7])
        with telemetry.capture() as events:
            r = EpochalIndex.replay(str(d), CUSTOM)
        assert r.applied_epoch == 1
        assert chip_index_equal(r.index, scratch(r))
        kinds = [
            e for e in events if e["event"] == "epoch_log_truncated"
        ]
        assert kinds and kinds[0]["kind"] == "delta"
        # the truncated record was unlinked: a second replay is clean
        with telemetry.capture() as events:
            EpochalIndex.replay(str(d), CUSTOM, publish=False)
        assert not [
            e for e in events if e["event"] == "epoch_log_truncated"
        ]

    def test_corrupt_interior_refuses_typed(self, tmp_path):
        """A damaged record with VALID successors is not a tail — data
        loss would be silent, so replay refuses typed."""
        d, _ = self._logged(tmp_path)
        p = d / "delta-00000001.npz"
        p.write_bytes(p.read_bytes()[:-7])
        with pytest.raises(EpochLogCorrupt, match="valid successors"):
            EpochalIndex.replay(str(d), CUSTOM)

    def test_missing_interior_epoch_refuses_typed(self, tmp_path):
        d, _ = self._logged(tmp_path)
        (d / "delta-00000001.npz").unlink()
        (d / "delta-00000001.json").unlink()
        with pytest.raises(EpochLogCorrupt, match="missing"):
            EpochalIndex.replay(str(d), CUSTOM)

    def test_forged_chain_refuses_typed(self, tmp_path):
        """A record whose checksum validates but whose ``prev`` does not
        bind to the predecessor is a forged/foreign record — replay
        refuses with the fingerprint mismatch, not a generic error."""
        import hashlib
        import json

        d, _ = self._logged(tmp_path)
        p = d / "delta-00000002.json"
        sidecar = json.loads(p.read_text())
        sidecar["prev"] = "f" * 64
        sidecar["chain"] = hashlib.sha256(
            f"{sidecar['prev']}:{sidecar['sha256']}".encode()
        ).hexdigest()
        p.write_text(json.dumps(sidecar))
        with pytest.raises(EpochFingerprintMismatch, match="chains from"):
            EpochalIndex.replay(str(d), CUSTOM)

    def test_wrong_index_system_refuses_typed(self, tmp_path):
        d, _ = self._logged(tmp_path)

        class OtherSystem(CustomIndexSystem):
            pass

        other = OtherSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
        with pytest.raises(EpochFingerprintMismatch, match="index"):
            EpochalIndex.replay(str(d), other)

    def test_empty_or_baseless_log_refuses_typed(self, tmp_path):
        with pytest.raises(EpochLogCorrupt, match="no delta log"):
            EpochalIndex.replay(str(tmp_path / "nothing"), CUSTOM)
        d, _ = self._logged(tmp_path, n_edits=1)
        (d / "base-00000000.npz").write_bytes(b"shredded")
        with pytest.raises(EpochLogCorrupt, match="base record"):
            EpochalIndex.replay(str(d), CUSTOM)


# ------------------------------------------------- compaction


class TestCompaction:
    def test_compact_preserves_identity_and_truncates(self, tmp_path):
        d = tmp_path / "log"
        ep = mk(d)
        edit_replace(ep)
        edit_insert(ep)
        stats = ep.compact()
        assert stats["epoch"] == 2 and stats["truncated"] == 3
        names = sorted(f.name for f in d.iterdir())
        assert names == ["compact-00000002.json", "compact-00000002.npz"]
        ep.publish()
        assert chip_index_equal(ep.index, scratch(ep))
        # the chain is untouched by compaction: a post-compact delta
        # still chains from the last delta's hash, and replay proves it
        edit_remove(ep)
        r = EpochalIndex.replay(str(d), CUSTOM)
        assert r.applied_epoch == 3
        assert chip_index_equal(r.index, scratch(r))
        assert r.series == ep.series  # sealed into the compact record

    def test_log_max_knob_autocompacts(self, tmp_path):
        """MOSAIC_EPOCH_LOG_MAX (here the explicit ``log_max=``, which
        beats the env): once that many deltas accumulate, apply triggers
        compaction-and-truncate with the prefix's fingerprint sealed
        into the snapshot."""
        d = tmp_path / "log"
        ep = mk(d, log_max=2)
        s1 = edit_replace(ep)
        assert "compacted" not in s1
        s2 = edit_insert(ep)
        assert s2["compacted"]["epoch"] == 2
        entries = sorted(f.name for f in d.iterdir())
        assert entries == ["compact-00000002.json", "compact-00000002.npz"]
        edit_remove(ep)  # 1 delta since compact: below the limit again
        assert (d / "delta-00000003.json").exists()
        r = EpochalIndex.replay(str(d), CUSTOM)
        assert r.applied_epoch == 3
        assert chip_index_equal(r.index, scratch(r))

    def test_log_max_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MOSAIC_EPOCH_LOG_MAX", "1")
        d = tmp_path / "log"
        ep = mk(d)
        s = edit_replace(ep)
        assert s["compacted"]["epoch"] == 1

    def test_background_compact_adopts_sinks(self, tmp_path):
        d = tmp_path / "log"
        ep = mk(d)
        edit_replace(ep)
        with telemetry.capture() as events:
            t = ep.compact(background=True)
            t.join(timeout=60)
        assert not t.is_alive()
        assert [e for e in events if e["event"] == "epoch_compacted"]
        assert len(ep._blocks) == 1
        ep.publish()
        assert chip_index_equal(ep.index, scratch(ep))

    def test_half_written_compact_falls_back(self, tmp_path):
        """A compact snapshot shredded BEFORE truncation ran (the
        kill-mid-compaction residue) must not poison replay: the base +
        delta prefix still replays the same epoch."""
        d = tmp_path / "log"
        ep = mk(d)
        edit_replace(ep)
        with faults.transient_errors(
            1, sites=("epoch.compact",), skip_first=1, exc_factory=BOOM
        ):
            with pytest.raises(RuntimeError, match="synthetic kill"):
                ep.compact()  # snapshot durable, prefix NOT truncated
        p = d / "compact-00000001.npz"
        p.write_bytes(p.read_bytes()[:-7])
        with telemetry.capture() as events:
            r = EpochalIndex.replay(str(d), CUSTOM)
        assert r.applied_epoch == 1
        assert chip_index_equal(r.index, scratch(r))
        trunc = [e for e in events if e["event"] == "epoch_log_truncated"]
        assert trunc and trunc[0]["kind"] == "compact"


# ------------------------------------------------- durable-stream fence


class TestStreamEpochFence:
    def test_resume_across_epoch_boundary(self, tmp_path):
        """A durable stream run killed mid-flight, with a compaction
        kill AND an epoch advance before anyone resumes: resume against
        the NEW epoch's index refuses typed; resume against the
        snapshot's OWN epoch finishes bit-identical to a clean run."""
        log_dir = tmp_path / "log"
        run_dir = str(tmp_path / "run")
        ep = mk(log_dir)
        ep.publish()
        idx0 = ep.index
        rng = np.random.default_rng(7)
        batches = [
            rng.uniform(BBOX[:2], BBOX[2:], (1024, 2)) for _ in range(3)
        ]
        ring = ring_from_host(batches)
        sj0 = StreamJoin(idx0, CUSTOM, RES, prefetch=True)
        clean = sj0.run(ring, 7, collect=True)
        with faults.inject(
            fail_first=99, skip_first=2, sites=("stream.scan_step",),
            exc_factory=BOOM,
        ):
            with pytest.raises(RuntimeError, match="synthetic kill"):
                sj0.run_durable(
                    ring, 7, run_dir=run_dir, snapshot_every=2,
                    retry_policy=FAST,
                )
        assert checkpoint.list_snapshots(run_dir)
        # the world moves on: an edit lands and a compaction dies
        edit_replace(ep)
        with faults.transient_errors(
            1, sites=("epoch.compact",), skip_first=1, exc_factory=BOOM
        ):
            with pytest.raises(RuntimeError, match="synthetic kill"):
                ep.compact()
        r = EpochalIndex.replay(str(log_dir), CUSTOM)
        assert r.epoch == 1
        # refusal direction: the snapshot is fenced to its epoch
        sj1 = StreamJoin(r.index, CUSTOM, RES, prefetch=True)
        with pytest.raises(EpochFingerprintMismatch, match="epoch"):
            sj1.resume(run_dir, ring, retry_policy=FAST)
        # completion direction: the snapshot's own index finishes the
        # run bit-identically to the clean epoch-0 run
        got = sj0.resume(run_dir, ring, retry_policy=FAST)
        assert (got.checksum, got.matches, got.overflow) == (
            clean.checksum, clean.matches, clean.overflow
        )


# ------------------------------------------------- router epoch advance


def make_router(store, **kw):
    kw.setdefault("program_store", store)
    kw.setdefault("engine_defaults", {
        "ladder": BucketLadder(64, 256),
        "bounds": BBOX,
        "max_wait_s": 0.01,
    })
    return ServeRouter(CUSTOM, **kw)


class TestRouterEpochAdvance:
    def test_advance_updates_tenant_and_metrics(self, tmp_path, pts):
        ep = mk()
        ep.publish()
        with make_router(str(tmp_path / "programs")) as router:
            router.add_tenant("a", ep.index, RES, warm=False)
            edit_replace(ep)
            stats = router.advance_epoch("a", ep)
            assert stats["epoch"] == 1
            m = router.metrics()["tenants"]["a"]
            assert m["epoch"] == 1 and m["epoch_advances"] == 1
            np.testing.assert_array_equal(
                np.asarray(router.join("a", pts)),
                host_join(pts, ep.index.host, CUSTOM, RES),
            )

    def test_failed_advance_keeps_old_snapshot(self, tmp_path, pts):
        """A fault at router.swap mid-advance: the tenant keeps serving
        its current snapshot bit-identically, the tenant's epoch
        accounting is untouched, AND the epochal index stays on its
        previous published epoch."""
        ep = mk()
        ep.publish()
        old_oracle = host_join(pts, ep.index.host, CUSTOM, RES)
        with make_router(str(tmp_path / "programs")) as router:
            router.add_tenant("a", ep.index, RES, warm=False)
            edit_replace(ep)
            with faults.transient_errors(1, sites=("router.swap",)):
                with pytest.raises(TransientDeviceError):
                    router.advance_epoch("a", ep)
            assert ep.epoch == 0
            m = router.metrics()["tenants"]["a"]
            assert m["epoch"] == 0 and m["epoch_advances"] == 0
            np.testing.assert_array_equal(
                np.asarray(router.join("a", pts)), old_oracle
            )
            # the delta log is durable: the retry publishes the epoch
            stats = router.advance_epoch("a", ep)
            assert stats["epoch"] == 1 and ep.epoch == 1
