"""Timeline attribution: interval reconstruction, the priority sweep's
exact-partition arithmetic, stall classification, and the stall_report
CLI over a REAL durable stream run.

The load-bearing invariant everything downstream trusts
(`tools/stall_report.py`'s ``sum_ok``, the CI ±5% lane): `flatten` is
a PARTITION — every instant of the window has exactly one owner class,
so the per-class seconds sum to the wall exactly, whatever the input
intervals overlap like.
"""

from __future__ import annotations

import json
import pathlib
import sys

import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "tools"))

from mosaic_tpu.obs import timeline
from mosaic_tpu.runtime import telemetry


def _span(name, start, seconds, seq=0, **attrs):
    return {
        "event": "span", "name": name, "start_mono": start,
        "seconds": seconds, "seq": seq, "ts_mono": start + seconds,
        **attrs,
    }


class TestKeysAndClasses:
    def test_event_key_conventions(self):
        assert timeline.event_key(
            {"event": "span", "name": "stream.segment", "seconds": 1}
        ) == "span.stream.segment"
        assert timeline.event_key(
            {"event": "serve_stage", "stage": "queue_wait", "seconds": 1}
        ) == "serve_stage.queue_wait"
        assert timeline.event_key(
            {"stage_key": "span.x", "seconds": 1}
        ) == "span.x"
        assert timeline.event_key(
            {"event": "recheck_narrow", "seconds": 0.1}
        ) == "recheck_narrow"
        assert timeline.event_key({"event": "snapshot_saved"}) is None

    @pytest.mark.parametrize("key,cls", [
        ("span.dispatch.transfer.h2d", "transfer"),
        ("span.dispatch.transfer.d2h", "transfer"),
        ("span.stream.ring_build", "transfer"),
        ("span.dispatch.compile", "compile"),
        ("stream_stage.compile", "compile"),
        ("stream_stage.gen_compile", "compile"),
        ("serve_stage.queue_wait", "queue_wait"),
        ("span.stream.snapshot", "host_callback"),
        ("span.raster.snapshot", "host_callback"),
        ("span.stream.segment", "device"),
        ("span.serve.dispatch", "device"),
        ("span.join.probe.scatter", "device"),
        ("probe_stage.heavy", "device"),
        ("raster_stage.zonal", "device"),
        ("span.stream.pipeline.drain", "device"),
        ("stream_stage.pipeline_drain", "device"),
        ("span.stream.pipeline.flush", "host_callback"),
        ("stream_stage.pipeline_flush", "host_callback"),
    ])
    def test_classifier_table(self, key, cls):
        assert timeline.classify_key(key) == cls

    def test_containers_and_unknowns_stay_unclassified(self):
        for key in (
            "span.stream.durable_run", "stream_stage.durable_loop",
            "span.serve.request", "span.stream_bench",
            "stream_stage.single_batch", "no_such_key", None,
        ):
            assert timeline.classify_key(key) is None


class TestIntervals:
    def test_span_uses_start_mono(self):
        iv = timeline.interval_of(_span("x", 10.0, 2.5))
        assert iv == (10.0, 12.5)

    def test_flat_timed_event_ends_at_ts_mono(self):
        iv = timeline.interval_of(
            {"event": "serve_stage", "stage": "queue_wait",
             "seconds": 0.5, "ts_mono": 4.0}
        )
        assert iv == (3.5, 4.0)

    def test_instants_and_negative_seconds_are_skipped(self):
        assert timeline.interval_of({"event": "x", "ts_mono": 1.0}) is None
        assert timeline.interval_of(
            {"event": "x", "seconds": -1, "ts_mono": 1.0}
        ) is None


class TestFlattenPartition:
    def test_partition_sums_to_window_exactly(self):
        evts = [
            _span("stream.segment", 0.0, 1.0, seq=1),
            _span("dispatch.transfer.h2d", 0.4, 0.2, seq=2),
            _span("stream.snapshot", 1.1, 0.3, seq=3),
        ]
        segs = timeline.flatten(timeline.intervals(evts), (0.0, 2.0))
        total = sum(s["end"] - s["start"] for s in segs)
        assert total == pytest.approx(2.0, abs=1e-9)
        by_cls = {}
        for s in segs:
            by_cls[s["cls"]] = by_cls.get(s["cls"], 0.0) + (
                s["end"] - s["start"]
            )
        # transfer outranks the device span it nests inside
        assert by_cls["transfer"] == pytest.approx(0.2)
        assert by_cls["device"] == pytest.approx(0.8)
        assert by_cls["host_callback"] == pytest.approx(0.3)
        assert by_cls["idle"] == pytest.approx(0.7)

    def test_priority_order_under_total_overlap(self):
        evts = [
            _span("stream.segment", 0.0, 1.0, seq=1),
            _span("stream.snapshot", 0.0, 1.0, seq=2),
            _span("dispatch.transfer.h2d", 0.0, 1.0, seq=3),
            _span("dispatch.compile", 0.0, 1.0, seq=4),
        ]
        segs = timeline.flatten(timeline.intervals(evts), (0.0, 1.0))
        assert len(segs) == 1 and segs[0]["cls"] == "compile"

    def test_intervals_clip_to_window(self):
        evts = [_span("stream.segment", -1.0, 4.0)]
        segs = timeline.flatten(timeline.intervals(evts), (0.0, 2.0))
        assert segs == [{"start": 0.0, "end": 2.0, "cls": "device"}]

    def test_empty_window_returns_nothing(self):
        assert timeline.flatten([], (1.0, 1.0)) == []


class TestAttribute:
    def test_durable_loop_event_picks_the_window(self):
        evts = [
            _span("stream.segment", 0.5, 1.0, seq=1),
            {"event": "stream_stage", "stage": "durable_loop",
             "seconds": 2.0, "ts_mono": 2.0, "seq": 2},
        ]
        rep = timeline.attribute(evts)
        assert rep["window"]["source"] == "stream_stage.durable_loop"
        assert rep["wall_s"] == pytest.approx(2.0)
        assert rep["sum_s"] == pytest.approx(rep["wall_s"], abs=1e-6)
        assert rep["classes"]["device"]["seconds"] == pytest.approx(1.0)
        assert rep["classes"]["idle"]["seconds"] == pytest.approx(1.0)

    def test_envelope_fallback_without_loop_events(self):
        evts = [
            _span("serve.dispatch", 1.0, 0.5, seq=1),
            _span("serve.dispatch", 2.0, 0.5, seq=2),
        ]
        rep = timeline.attribute(evts)
        assert rep["window"]["source"] == "envelope"
        assert rep["wall_s"] == pytest.approx(1.5)
        assert rep["classes"]["idle"]["seconds"] == pytest.approx(0.5)

    def test_no_intervals_returns_none(self):
        assert timeline.attribute([{"event": "x", "ts_mono": 1.0}]) is None


class TestTracks:
    def test_tracks_merge_and_gap(self):
        evts = [
            _span("stream.segment", 0.0, 1.0, seq=1),
            _span("stream.segment", 1.5, 1.0, seq=2),
            _span("stream.segment", 1.6, 0.2, seq=3),
        ]
        tr = timeline.build_tracks(evts)["span.stream.segment"]
        assert tr["count"] == 3
        assert tr["intervals"] == [(0.0, 1.0), (1.5, 2.5)]
        assert tr["busy_s"] == pytest.approx(2.0)
        assert tr["gap_s"] == pytest.approx(0.5)

    def test_overlap_measures_pipeline_hiding(self):
        a = [(0.0, 1.0), (2.0, 3.0)]
        b = [(0.5, 2.5)]
        assert timeline.overlap_s(a, b) == pytest.approx(1.0)
        assert timeline.overlap_s(a, [(5.0, 6.0)]) == 0.0


class TestOverlappedTimelines:
    """The pipelined executor's claim as interval arithmetic: snapshot
    ``host_callback`` intervals that genuinely OVERLAP ``device``
    intervals (the writer thread runs while the next segments compute)
    must still flatten to an exact partition, and the pinned
    ``overlap_fraction`` helper turns "off the critical path" into a
    number the bench and CI lanes can gate."""

    def test_overlapping_snapshot_partition_still_exact(self):
        # device busy 0..2 (two back-to-back segments); the async
        # snapshot write covers 0.5..1.5 ENTIRELY inside device time —
        # the pipelined shape a synchronous loop can never produce
        evts = [
            _span("stream.segment", 0.0, 1.0, seq=1),
            _span("stream.segment", 1.0, 1.0, seq=2),
            _span("stream.snapshot", 0.5, 1.0, seq=3, mode="async"),
        ]
        segs = timeline.flatten(timeline.intervals(evts), (0.0, 2.0))
        total = sum(s["end"] - s["start"] for s in segs)
        assert total == pytest.approx(2.0, abs=1e-9)
        by_cls = {}
        for s in segs:
            by_cls[s["cls"]] = by_cls.get(s["cls"], 0.0) + (
                s["end"] - s["start"]
            )
        # host_callback outranks device for the overlapped second;
        # nothing is double-counted and nothing leaks to idle
        assert by_cls["host_callback"] == pytest.approx(1.0)
        assert by_cls["device"] == pytest.approx(1.0)
        assert "idle" not in by_cls

    def test_drain_and_flush_classes_sweep_exactly(self):
        # drain (device: the window's one blocking pull) overlapping
        # the writer's flush barrier (host_callback) at the run tail
        evts = [
            _span("stream.pipeline.drain", 0.0, 1.0, seq=1),
            _span("stream.pipeline.flush", 0.8, 0.6, seq=2),
        ]
        segs = timeline.flatten(timeline.intervals(evts), (0.0, 1.5))
        total = sum(s["end"] - s["start"] for s in segs)
        assert total == pytest.approx(1.5, abs=1e-9)
        by_cls = {
            s["cls"]: sum(
                x["end"] - x["start"] for x in segs
                if x["cls"] == s["cls"]
            )
            for s in segs
        }
        assert by_cls["device"] == pytest.approx(0.8)
        assert by_cls["host_callback"] == pytest.approx(0.6)
        assert by_cls.get("idle", 0.1) == pytest.approx(0.1)

    def test_overlap_fraction_pinned(self):
        dev = [(0.0, 1.0), (2.0, 3.0)]
        # fully hidden under device -> 1.0
        assert timeline.overlap_fraction([(0.2, 0.8)], dev) == 1.0
        # serialized after device (the synchronous loop) -> 0.0
        assert timeline.overlap_fraction([(1.0, 2.0)], dev) == 0.0
        # half in, half out
        assert timeline.overlap_fraction(
            [(0.5, 1.5)], dev
        ) == pytest.approx(0.5)
        # empty snapshot track -> 0.0, never a ZeroDivisionError
        assert timeline.overlap_fraction([], dev) == 0.0

    def test_pipelined_run_emits_drain_intervals(self, tmp_path):
        from mosaic_tpu.core.geometry import wkt
        from mosaic_tpu.core.index import CustomIndexSystem, GridConf
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.sql.join import build_chip_index
        from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

        grid = CustomIndexSystem(
            GridConf(-180, 180, -90, 90, 2, 10.0, 10.0)
        )
        col = wkt.from_wkt(
            ["POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))"]
        )
        index = build_chip_index(
            tessellate(col, grid, 3, keep_core_geoms=False)
        )
        rng = np.random.default_rng(0)
        sj = StreamJoin(index, grid, 3, prefetch=True)
        ring = ring_from_host(
            [rng.uniform((-25, -25), (35, 20), (2048, 2))
             for _ in range(3)]
        )
        with telemetry.capture() as events:
            sj.run_durable(
                ring, 6, run_dir=str(tmp_path), snapshot_every=2,
                pipeline=True,
            )
        rep = timeline.attribute(events)
        assert rep["window"]["source"] == "stream_stage.durable_loop"
        # the partition invariant holds for a REAL overlapped trail
        # (writer-thread snapshot spans + main-thread drain spans)
        assert abs(rep["sum_s"] - rep["wall_s"]) <= 0.05 * rep["wall_s"]
        tracks = timeline.build_tracks(events)
        assert "span.stream.pipeline.drain" in tracks
        assert tracks["span.stream.pipeline.drain"]["count"] == 3
        assert "span.stream.snapshot" in tracks
        # the helper runs end to end on real tracks (the value itself
        # is timing-dependent on CPU; the bench pins the A/B claim)
        frac = timeline.overlap_fraction(
            tracks["span.stream.snapshot"]["intervals"],
            tracks["span.stream.pipeline.drain"]["intervals"]
            + tracks["span.stream.segment"]["intervals"],
        )
        assert 0.0 <= frac <= 1.0


# ------------------------------------------------ real durable stream


@pytest.fixture(scope="module")
def stream_setup():
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index
    from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    col = wkt.from_wkt(["POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))"])
    index = build_chip_index(
        tessellate(col, grid, 3, keep_core_geoms=False)
    )
    rng = np.random.default_rng(0)
    sj = StreamJoin(index, grid, 3, prefetch=True)
    ring = ring_from_host(
        [rng.uniform((-25, -25), (35, 20), (2048, 2)) for _ in range(3)]
    )
    return sj, ring


class TestRealDurableRunAttribution:
    def test_attribution_partitions_a_real_run(
        self, stream_setup, tmp_path
    ):
        sj, ring = stream_setup
        with telemetry.capture() as events:
            sj.run_durable(
                ring, 6, run_dir=str(tmp_path), snapshot_every=2
            )
        rep = timeline.attribute(events)
        assert rep["window"]["source"] == "stream_stage.durable_loop"
        assert abs(rep["sum_s"] - rep["wall_s"]) <= 0.05 * rep["wall_s"]
        # segments dominate a healthy CPU run; the snapshot D2H spans
        # (prefetch=True pulls cells) show up as transfer time
        assert rep["classes"]["device"]["seconds"] > 0
        assert rep["classes"]["transfer"]["seconds"] > 0
        assert rep["classes"]["host_callback"]["seconds"] > 0
        tracks = timeline.build_tracks(events)
        assert "span.stream.segment" in tracks
        assert tracks["span.stream.segment"]["count"] == 3
        assert "span.dispatch.transfer.d2h" in tracks

    def test_stall_report_cli_on_a_real_trail(
        self, stream_setup, tmp_path, monkeypatch, capsys
    ):
        import stall_report

        from mosaic_tpu.obs import export

        sj, ring = stream_setup
        with telemetry.capture() as events:
            sj.run_durable(
                ring, 6, run_dir=str(tmp_path / "run"), snapshot_every=2
            )
            # the single-batch rate stream_bench would have measured
            telemetry.record(
                "stream_stage", stage="single_batch", seconds=0.001,
                batch=2048, points_per_sec=2048 / 0.001,
            )
        trail = str(tmp_path / "t.jsonl")
        export.write_jsonl(events, trail)
        out = str(tmp_path / "stall.json")
        monkeypatch.setattr(
            "sys.argv", ["stall_report.py", trail, "--out", out]
        )
        assert stall_report.main() == 0
        last = capsys.readouterr().out.strip().splitlines()[-1]
        rep = json.loads(last)
        assert rep["metric"] == "stall_report"
        assert rep["sum_ok"] is True
        assert rep["loss"]["sustained_frac"] > 0
        lc = rep["loss"]["loss_classes"]
        assert abs(
            sum(lc.values()) + rep["loss"]["ideal_s"] - rep["wall_s"]
        ) <= 0.05 * rep["wall_s"]
        with open(out) as f:
            assert json.load(f)["metric"] == "stall_report"

    def test_injected_slowdown_lands_in_the_right_class(
        self, stream_setup, tmp_path, monkeypatch, capsys
    ):
        import stall_report

        from mosaic_tpu.obs import export

        sj, ring = stream_setup
        with telemetry.capture() as events:
            sj.run_durable(
                ring, 6, run_dir=str(tmp_path / "run"), snapshot_every=2
            )
        trail = str(tmp_path / "t.jsonl")
        export.write_jsonl(events, trail)

        def run(extra):
            monkeypatch.setattr(
                "sys.argv", ["stall_report.py", trail, *extra]
            )
            assert stall_report.main() == 0
            return json.loads(
                capsys.readouterr().out.strip().splitlines()[-1]
            )

        base = run([])
        slow = run(["--inject-slowdown", "span.stream.snapshot:25"])
        b = base["classes"]["host_callback"]
        s = slow["classes"]["host_callback"]
        # the stall must grow in ITS class: 5x the seconds, or — on a
        # warm tiny window — saturate most of the wall
        assert (
            s["seconds"] > 5 * max(b["seconds"], 1e-9)
            or s["share"] > 0.6
        ), (b, s)
        assert s["share"] > b["share"], (b, s)
        assert slow["sum_ok"] is True

    def test_diff_against_itself_is_zero(
        self, stream_setup, tmp_path, monkeypatch, capsys
    ):
        import stall_report

        from mosaic_tpu.obs import export

        sj, ring = stream_setup
        with telemetry.capture() as events:
            sj.run_durable(
                ring, 6, run_dir=str(tmp_path / "run"), snapshot_every=3
            )
        trail = str(tmp_path / "t.jsonl")
        export.write_jsonl(events, trail)
        monkeypatch.setattr(
            "sys.argv", ["stall_report.py", trail, "--against", trail]
        )
        assert stall_report.main() == 0
        rep = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]
        )
        assert all(
            v["seconds"] == 0 and v["share"] == 0
            for v in rep["diff"].values()
        )


# -------------------------------------------- seg-loop compile hoist


def _fresh_stream(found_cap):
    """A NOVEL static spec (unique found_cap) so the process-wide
    stream_programs cache misses and the seg_loop is genuinely cold."""
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index
    from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    col = wkt.from_wkt(["POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))"])
    index = build_chip_index(
        tessellate(col, grid, 3, keep_core_geoms=False)
    )
    rng = np.random.default_rng(1)
    sj = StreamJoin(index, grid, 3, prefetch=True, found_cap=found_cap)
    ring = ring_from_host(
        [rng.uniform((-25, -25), (35, 20), (512, 2)) for _ in range(3)]
    )
    return sj, ring


class TestSegLoopCompileHoist:
    """Satellite of ISSUE 13: STALL_r12.json booked 1.95 s of a 2.28 s
    durable run inside stream.segment[0] — the seg_loop trace+compile,
    misattributed as device time. The hoist compiles BEFORE the segment
    loop under a ``dispatch.compile`` span, so segment[0]'s device
    excess collapses to actual replay time."""

    def test_segment0_compile_hoisted(self, tmp_path):
        sj, ring = _fresh_stream(found_cap=251)
        with telemetry.capture() as events:
            sj.run_durable(
                ring, 5, run_dir=str(tmp_path), snapshot_every=2
            )
        spans = [e for e in events if e["event"] == "span"]
        comp = [
            e for e in spans
            if e["name"] == "dispatch.compile"
            and e.get("site") == "stream.seg_loop"
        ]
        assert len(comp) == 1
        assert comp[0]["backend_compiles"] >= 1
        # both static nb signatures warmed: snapshot_every=2 and the
        # tail remainder 1
        assert comp[0]["sizes"] == "[1, 2]"
        segs = sorted(
            (e for e in spans if e["name"] == "stream.segment"),
            key=lambda e: e["start_mono"],
        )
        assert segs
        # the compile ended before segment[0] began ...
        assert comp[0]["ts_mono"] <= segs[0]["start_mono"] + 1e-6
        # ... and segment[0] is now pure replay: its wall is a fraction
        # of the compile it used to contain
        assert segs[0]["seconds"] < comp[0]["seconds"]
        # timeline classifies the hoisted span as compile
        assert (
            timeline.classify_key("span.dispatch.compile") == "compile"
        )
        # second run on the same stream: everything warm, no new
        # compile span, bit-identical stats
        with telemetry.capture() as ev2:
            sj.run_durable(
                ring, 5, run_dir=str(tmp_path / "b"), snapshot_every=2
            )
        assert not [
            e for e in ev2
            if e["event"] == "span" and e["name"] == "dispatch.compile"
        ]

    def test_warmup_knob_disables_hoist(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MOSAIC_STREAM_NO_SEG_WARMUP", "1")
        sj, ring = _fresh_stream(found_cap=253)
        with telemetry.capture() as events:
            res = sj.run_durable(
                ring, 4, run_dir=str(tmp_path), snapshot_every=2
            )
        assert not [
            e for e in events
            if e["event"] == "span" and e["name"] == "dispatch.compile"
            and e.get("site") == "stream.seg_loop"
        ]
        # and the run itself still converges (compile just lands back
        # inside segment[0], as before the hoist)
        monkeypatch.delenv("MOSAIC_STREAM_NO_SEG_WARMUP")
        want = sj.run(ring, 4)
        assert (res.checksum, res.matches, res.overflow) == (
            want.checksum, want.matches, want.overflow
        )
