"""Adaptive per-cell probe routing: the bit-identity contracts.

The router (`sql/join.py pip_join_points(probe="adaptive")`) partitions
each compacted batch into light / heavy (Pallas `pip_heavy_tiled`,
interpret mode on CPU) / convex (reduced y-bucketed edge test) lanes.
What must hold on any backend:

1. every probe mode — fused adaptive and each forced single lane — is
   bit-identical to the scatter baseline, on adversarial batches
   (near-edge band, all-heavy, all-light, convex-only) and with the
   banded (near-mask) outputs included;
2. `MOSAIC_PROBE_FORCE_LANE` resolves BEFORE jit staging
   (`resolve_probe_mode`) so the env knob can never produce a stale
   compiled program;
3. the standalone kernel equals the `_ray_parity` reference row for
   row, sentinel semantics included;
4. heavy_cap/convex_cap overflow carries the OVERFLOW sentinel through
   the stream fold and the serve scatter-back (the batch path was
   already pinned), and the managed paths escalate back to exact;
5. `kernels/pip.py` tiling validation raises `TilingError` instead of
   miscompiling inside `pallas_call`.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.kernels.pip import (
    TilingError,
    _pad_to,
    edge_planes,
    pip_heavy_tiled,
)
from mosaic_tpu.runtime import faults, telemetry
from mosaic_tpu.sql import join as J
from mosaic_tpu.sql.join import (
    OVERFLOW,
    build_chip_index,
    host_join,
    pip_join,
    pip_join_points,
    resolve_probe_mode,
)

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3


def _star_wkt(cx=25.0, cy=-14.0, n=240):
    th = np.linspace(0.0, 2 * np.pi, n, endpoint=False)
    r = np.where(np.arange(n) % 2 == 0, 4.0, 2.0)
    x, y = cx + r * np.cos(th), cy + r * np.sin(th)
    ring = ", ".join(f"{a:.6f} {b:.6f}" for a, b in zip(x, y))
    return f"POLYGON (({ring}, {x[0]:.6f} {y[0]:.6f}))"


ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), "
    "(5 5, 5 8, 8 8, 8 5, 5 5))",
    "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
    "MULTIPOLYGON (((-20 -20, -12 -20, -12 -12, -20 -12, -20 -20)), "
    "((-8 -8, -2 -8, -2 -2, -8 -2, -8 -8)))",
    "POLYGON ((-24 5, -14 5, -14 15, -24 15, -24 5))",
    _star_wkt(),  # >32 edges per cell: guaranteed heavy (tier-2) cells
]

MODES = ("adaptive", "adaptive-light", "adaptive-heavy", "adaptive-convex")


@pytest.fixture(scope="module")
def index():
    col = wkt.from_wkt(ZONES)
    ix = build_chip_index(
        tessellate(col, CUSTOM, RES, keep_core_geoms=False), edge_cap=8
    )
    assert ix.num_heavy_cells > 0 and ix.num_convex_cells > 0
    return ix


@pytest.fixture(scope="module")
def batches(index):
    """{name: raw (n, 2) f64 points} — one batch per adversarial shape."""
    rng = np.random.default_rng(3)
    pts = rng.uniform((-25, -25), (35, 20), (20_000, 2))
    cells = np.asarray(CUSTOM.point_to_cell(jnp.asarray(pts), RES))
    ucells = np.asarray(index.cells)
    u = np.clip(np.searchsorted(ucells, cells), 0, len(ucells) - 1)
    found = ucells[u] == cells
    heavy = found & (np.asarray(index.cell_heavy)[u] >= 0)
    convex = found & (np.asarray(index.cell_convex)[u] >= 0)

    edges = np.asarray(index.cell_edges, dtype=np.float64)
    ab = edges[np.asarray(index.cell_ebits) != 0]
    ab = ab[rng.permutation(len(ab))[:800]]
    a, b = ab[:, 0:2], ab[:, 2:4]
    mid, t = 0.5 * (a + b), b - a
    nrm = np.stack([-t[:, 1], t[:, 0]], axis=1)
    nrm /= np.maximum(np.linalg.norm(nrm, axis=1, keepdims=True), 1e-30)
    shift = np.asarray(index.border.shift, dtype=np.float64)
    band = np.concatenate(
        [mid + d * s * nrm for d in (1e-6, 1e-4) for s in (1, -1)]
    ) + shift

    out = {
        "mixed": pts,
        "all_light": pts[found & ~heavy & ~convex],
        "all_heavy": pts[heavy],
        "convex_only": pts[convex],
        "near_edge_band": band,
    }
    for name, batch in out.items():
        assert len(batch) > 0, name
    return out


def _join(index, pts, probe, **kw):
    return np.asarray(
        pip_join(pts, None, CUSTOM, RES, chip_index=index, recheck=False,
                 probe=probe, **kw)
    )


# ------------------------------------------------- identity, all lanes


@pytest.mark.parametrize("mode", MODES)
def test_all_modes_bit_identical_to_scatter(index, batches, mode):
    for name, pts in batches.items():
        base = _join(index, pts, "scatter")
        got = _join(index, pts, mode)
        np.testing.assert_array_equal(got, base, err_msg=f"{mode}/{name}")


def test_adaptive_banded_outputs_identical(index, batches):
    """The banded variant (near-mask output) of every mode equals the
    scatter baseline bit for bit — match rows AND band flags."""
    pts = batches["near_edge_band"]
    cells = CUSTOM.point_to_cell(jnp.asarray(pts), RES)
    shifted = jnp.asarray(
        pts - np.asarray(index.border.shift, np.float64),
        dtype=index.border.verts.dtype,
    )
    eps2 = jnp.asarray(1e-10, index.border.verts.dtype)
    base, nbase = pip_join_points(
        shifted, cells, index, edge_eps2=eps2, probe="scatter"
    )
    for mode in MODES:
        m, nm = pip_join_points(
            shifted, cells, index, edge_eps2=eps2, probe=mode
        )
        np.testing.assert_array_equal(np.asarray(base), np.asarray(m), mode)
        np.testing.assert_array_equal(
            np.asarray(nbase), np.asarray(nm), mode
        )


def test_adaptive_recheck_equals_host_oracle(index, batches):
    for name in ("mixed", "near_edge_band"):
        pts = batches[name]
        want = host_join(pts, index.host, CUSTOM, RES)
        got = np.asarray(pip_join(
            pts, None, CUSTOM, RES, chip_index=index, recheck=True,
            probe="adaptive",
        ))
        np.testing.assert_array_equal(got, want, err_msg=name)


def test_route_counts_recorded(index, batches):
    with telemetry.capture() as events:
        _join(index, batches["mixed"], "adaptive")
    routes = [e for e in events if e["event"] == "probe_route"]
    assert routes and routes[0]["probe"] == "adaptive"
    r = routes[0]
    assert r["found"] == r["light"] + r["convex"]
    assert r["heavy"] > 0 and r["convex"] > 0


# --------------------------------------------- env knob / mode plumbing


def test_resolve_probe_mode_env_mapping(monkeypatch):
    monkeypatch.delenv("MOSAIC_PROBE_FORCE_LANE", raising=False)
    assert resolve_probe_mode("scatter") == "scatter"
    assert resolve_probe_mode("adaptive") == "adaptive"
    for lane in ("light", "heavy", "convex"):
        monkeypatch.setenv("MOSAIC_PROBE_FORCE_LANE", lane)
        assert resolve_probe_mode("adaptive") == f"adaptive-{lane}"
        # pinned modes and scatter ignore the knob (idempotent)
        assert resolve_probe_mode("scatter") == "scatter"
        assert resolve_probe_mode("adaptive-heavy") == "adaptive-heavy"


def test_resolve_probe_mode_rejects_garbage(monkeypatch):
    with pytest.raises(ValueError, match="probe"):
        resolve_probe_mode("mxu")
    monkeypatch.setenv("MOSAIC_PROBE_FORCE_LANE", "turbo")
    with pytest.raises(ValueError, match="MOSAIC_PROBE_FORCE_LANE"):
        resolve_probe_mode("adaptive")


def test_adaptive_rejects_direct_writeback(index, batches):
    with pytest.raises(ValueError, match="writeback"):
        pip_join(batches["mixed"][:64], None, CUSTOM, RES,
                 chip_index=index, recheck=False, probe="adaptive",
                 writeback="direct")


# ----------------------------------------------- standalone heavy kernel


def test_pip_heavy_tiled_matches_ray_parity_reference():
    """The kernel (interpret mode) equals the `_ray_parity` reference +
    slot-min merge row for row: parity, band mask, and the int32-max
    no-hit sentinel for pad rows."""
    rng = np.random.default_rng(9)
    H, E2, M2, K = 3, 24, 4, 300
    # random short edges, each assigned to one slot bit
    a = rng.uniform(-1, 1, (H, E2, 2))
    b = a + rng.uniform(-0.5, 0.5, (H, E2, 2))
    edges = np.concatenate([a, b], axis=2).astype(np.float32)
    slot = rng.integers(0, M2, (H, E2))
    bits = (np.uint32(1) << slot.astype(np.uint32)).astype(np.uint32)
    geom = rng.integers(0, 50, (H, M2)).astype(np.int32)
    geom[0, 1] = -1  # an empty slot must never win
    px = rng.uniform(-1, 1, K).astype(np.float32)
    py = rng.uniform(-1, 1, K).astype(np.float32)
    rows = rng.integers(0, H, K).astype(np.int32)
    rows[-7:] = -1  # pad rows
    eps2 = np.float32(1e-8)

    best, near = pip_heavy_tiled(
        jnp.asarray(px), jnp.asarray(py), jnp.asarray(rows),
        jnp.asarray(edges), jnp.asarray(bits), jnp.asarray(geom),
        eps2=jnp.asarray(eps2), interpret=True,
    )

    par, ref_near = J._ray_parity(
        jnp.asarray(px), jnp.asarray(py),
        jnp.asarray(edges)[np.maximum(rows, 0)],
        jnp.asarray(bits)[np.maximum(rows, 0)],
        eps2=jnp.asarray(eps2),
    )
    par = np.asarray(par)
    g = geom[np.maximum(rows, 0)]
    inside = ((par[:, None] >> np.arange(M2)) & 1).astype(bool) & (g >= 0)
    sent = np.iinfo(np.int32).max
    ref = np.where(inside, g, sent).min(axis=1)
    ref[rows < 0] = sent
    ref_near = np.asarray(ref_near) & (rows >= 0)

    np.testing.assert_array_equal(np.asarray(best), ref)
    np.testing.assert_array_equal(np.asarray(near), ref_near)


def test_pip_heavy_tiled_rejects_f64_tables():
    z = jnp.zeros((1,), jnp.float32)
    with pytest.raises(ValueError, match="float32"):
        pip_heavy_tiled(
            z, z, jnp.zeros((1,), jnp.int32),
            jnp.zeros((1, 8, 4), jnp.float64),
            jnp.zeros((1, 8), jnp.uint32),
            jnp.zeros((1, 2), jnp.int32),
            interpret=True,
        )


# ------------------------------------------------ tiling validation


def test_pad_to_refuses_to_shrink():
    with pytest.raises(TilingError, match="cannot shrink"):
        _pad_to(np.zeros((4, 4)), 2, axis=0)


@pytest.mark.parametrize(
    "kw", [{"g_pad": 100}, {"g_pad": 0}, {"e_pad": 12}, {"e_pad": 0}]
)
def test_edge_planes_rejects_untiled_pads(kw):
    from mosaic_tpu.core.geometry.device import pack_to_device

    col = wkt.from_wkt(["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"])
    dev = pack_to_device(col, dtype=jnp.float32, recenter=True)
    with pytest.raises(TilingError, match="multiple"):
        edge_planes(dev, **kw)


def test_pip_heavy_tiled_rejects_untiled_tiles():
    z = jnp.zeros((16,), jnp.float32)
    with pytest.raises(TilingError):
        pip_heavy_tiled(
            z, z, jnp.zeros((16,), jnp.int32),
            jnp.zeros((1, 8, 4), jnp.float32),
            jnp.zeros((1, 8), jnp.uint32),
            jnp.zeros((1, 2), jnp.int32),
            tile_g=100, interpret=True,
        )


# ------------------------------- overflow sentinel through the frontends


def test_convex_cap_overflow_marks_and_escalates(index, batches):
    pts = batches["convex_only"][:512]
    cells = CUSTOM.point_to_cell(jnp.asarray(pts), RES)
    shifted = jnp.asarray(
        pts - np.asarray(index.border.shift, np.float64),
        dtype=index.border.verts.dtype,
    )
    tiny = pip_join_points(
        shifted, cells, index, probe="adaptive", convex_cap=8
    )
    assert int((np.asarray(tiny) == OVERFLOW).sum()) > 0
    # the managed path escalates convex_cap until exact
    got = _join(index, pts, "adaptive")
    base = _join(index, pts, "scatter")
    np.testing.assert_array_equal(got, base)
    assert not (got == OVERFLOW).any()


def test_heavy_overflow_through_stream_fold(index, batches):
    """A too-small heavy_cap's OVERFLOW sentinel must survive the stream
    fold: outs carry -2 on exactly the per-batch rows and the folded
    overflow count equals the per-batch total."""
    from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

    pts = batches["all_heavy"]
    n = (len(pts) // 2) * 2
    host_batches = [pts[: n // 2], pts[n // 2 : n]]
    sj = StreamJoin(
        index, CUSTOM, RES, heavy_cap=4, prefetch=False,
        probe="adaptive",
    )
    res = sj.run(ring_from_host(host_batches), 2, collect=True)
    outs = np.asarray(res.outs)
    want = [
        np.asarray(pip_join(
            b, None, CUSTOM, RES, chip_index=index, recheck=False,
            batch_size=None,
        ))
        for b in host_batches
    ]
    per_batch = []
    for b in host_batches:
        cells = CUSTOM.point_to_cell(jnp.asarray(b), RES)
        shifted = jnp.asarray(
            b - np.asarray(index.border.shift, np.float64),
            dtype=index.border.verts.dtype,
        )
        per_batch.append(np.asarray(pip_join_points(
            shifted, cells, index, heavy_cap=4, probe="adaptive"
        )))
    n_over = sum(int((o == OVERFLOW).sum()) for o in per_batch)
    assert n_over > 0, "fixture must actually overflow heavy_cap=4"
    np.testing.assert_array_equal(outs, np.stack(per_batch))
    assert res.overflow == n_over
    del want


def test_heavy_overflow_through_serve_scatter_back(index, batches):
    """Serve full-bucket caps never overflow by construction; shrink the
    heavy cap at the dispatch boundary and assert the OVERFLOW sentinel
    reaches exactly the right caller rows through pad + scatter-back."""
    from mosaic_tpu.serve.bucket import BucketLadder
    from mosaic_tpu.serve.engine import ServeEngine

    pts = batches["all_heavy"][:300]
    eng = ServeEngine(
        index, CUSTOM, RES, ladder=BucketLadder(64, 1024),
        bounds=(-25.0, -25.0, 35.0, 20.0), max_wait_s=0.01,
        probe="adaptive",
        # the cap shim below clears the signature cache, so the second
        # join recompiles inside the request window — the default 1 s
        # deadline sheds it whenever CPU compile runs long
        default_deadline_s=60.0,
    )
    try:
        clean = np.asarray(eng.join(pts))
        caps0 = eng.core.caps
        eng.core.caps = lambda bucket: (
            caps0(bucket)[0], 4, caps0(bucket)[2]
        )
        eng.core.signatures.clear()
        over = np.asarray(eng.join(pts))
    finally:
        eng.shutdown() if hasattr(eng, "shutdown") else None
    bucket = 512  # pts pad to the 512 bucket
    cells = CUSTOM.point_to_cell(
        jnp.asarray(np.vstack([pts, np.repeat(pts[:1], bucket - len(pts),
                                              axis=0)]))
        , RES)
    shifted = jnp.asarray(
        np.vstack([pts, np.repeat(pts[:1], bucket - len(pts), axis=0)])
        - np.asarray(index.border.shift, np.float64),
        dtype=index.border.verts.dtype,
    )
    want = np.asarray(pip_join_points(
        shifted, cells, index, found_cap=bucket, heavy_cap=4,
        probe="adaptive",
    ))[: len(pts)]
    assert int((want == OVERFLOW).sum()) > 0
    np.testing.assert_array_equal(over, want)
    assert not (clean == OVERFLOW).any()


def test_shrunk_caps_fault_escalates_to_exact(index, batches):
    """faults.shrink_caps on the managed batch path: convex_cap joins
    found/heavy in the escalation engine and regrows to exact."""
    pts = batches["mixed"][:4096]
    base = _join(index, pts, "scatter")
    with telemetry.capture() as events:
        with faults.inject(shrink_caps={
            "found_cap": 8, "heavy_cap": 8, "convex_cap": 8,
        }):
            got = _join(index, pts, "adaptive")
    np.testing.assert_array_equal(got, base)
    assert any(e["event"] == "capacity_overflow" for e in events) or any(
        e["event"] == "escalation_resolved" for e in events
    )
