"""H3 index system: remembered spec vectors, invariants, round trips.

The implementation is derived from first principles (no H3 library in the
image); external anchors are bit-exact spec examples remembered from the
public H3 documentation plus structural invariants (122 res-0 cells, 12
pentagons at the published numbers, cell counts, round trips).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem, core, tables

H3 = H3IndexSystem()


def sphere_points(n, seed=0):
    rng = np.random.default_rng(seed)
    lng = rng.uniform(-180, 180, n)
    lat = np.degrees(np.arcsin(rng.uniform(-1, 1, n)))
    return np.column_stack([lng, lat])


class TestSpecAnchors:
    def test_sf_res9(self):
        # H3 docs example: geoToH3(37.7752702151959257, -122.418307270836565, 9)
        cell = H3.point_to_cell(
            np.array([[-122.418307270836565, 37.7752702151959257]]), 9
        )
        assert int(cell[0]) == 0x8928308280FFFFF

    def test_statue_of_liberty_res10(self):
        # h3-js docs example
        cell = H3.point_to_cell(np.array([[-74.044444, 40.689167]]), 10)
        assert int(cell[0]) == 0x8A2A1072B59FFFF

    def test_sf_center(self):
        # docs: h3ToGeo(8928308280fffff) ~ (37.77670234943567, -122.41845932318311)
        c = H3.cell_center(np.array([0x8928308280FFFFF], dtype=np.int64))
        np.testing.assert_allclose(
            c[0], [-122.41845932318311, 37.77670234943567], atol=1e-6
        )

    def test_pentagon_numbers(self):
        t = tables.derive()
        assert sorted(np.nonzero(t.is_pentagon)[0].tolist()) == sorted(
            tables.PENTAGON_IDS
        )


class TestInvariants:
    def test_res0_count(self):
        pts = sphere_points(30000)
        cells = np.unique(H3.point_to_cell(pts, 0))
        assert len(cells) == 122

    def test_res1_count(self):
        pts = sphere_points(200000, seed=3)
        cells = np.unique(H3.point_to_cell(pts, 1))
        assert len(cells) == 842  # 122*7 - 12*2

    def test_valid(self):
        pts = sphere_points(5000, seed=1)
        for res in [0, 5, 15]:
            cells = H3.point_to_cell(pts, res)
            assert np.asarray(H3.is_valid(cells)).all()
            assert np.asarray(H3.resolution_of(cells) == res).all()

    @pytest.mark.parametrize("res", [0, 1, 2, 4, 7, 10, 15])
    def test_roundtrip(self, res):
        pts = sphere_points(5000, seed=res)
        cells = H3.point_to_cell(pts, res)
        centers = H3.cell_center(cells)
        cells2 = H3.point_to_cell(centers, res)
        # exact everywhere, including pentagon base cells (round-3 repair)
        np.testing.assert_array_equal(np.asarray(cells), np.asarray(cells2))

    def test_jnp_matches_numpy(self):
        pts = sphere_points(2000, seed=7)
        c_np = H3.point_to_cell(pts, 9)
        c_jnp = np.asarray(H3.point_to_cell(jnp.asarray(pts), 9))
        np.testing.assert_array_equal(c_np, c_jnp)


class TestNeighbors:
    def test_neighbor_count_hexagon(self):
        cells = H3.point_to_cell(np.array([[-122.4, 37.77], [0.0, 51.5]]), 7)
        nbrs = H3.neighbors(cells)
        assert ((nbrs >= 0).sum(axis=1) == 6).all()
        # symmetric: each neighbor's neighbors include the original
        for row, c in enumerate(cells):
            back = H3.neighbors(nbrs[row])
            assert all(int(c) in set(b.tolist()) for b in back)

    def test_k_ring_counts(self):
        cells = H3.point_to_cell(np.array([[-73.98, 40.75]]), 8)
        for k in [1, 2, 3]:
            ring = H3.k_ring(cells, k)
            assert (ring[0] >= 0).sum() == 1 + 3 * k * (k + 1)
            loop = H3.k_loop(cells, k)
            assert (loop[0] >= 0).sum() == 6 * k

    def test_grid_distance(self):
        cells = H3.point_to_cell(np.array([[-73.98, 40.75]]), 8)
        loop3 = H3.k_loop(cells, 3)[0]
        loop3 = loop3[loop3 >= 0]
        d = H3.grid_distance(
            np.repeat(cells, len(loop3)), loop3
        )
        assert (d == 3).all()

    def test_grid_distance_cross_face_flagged(self):
        """Pairs spanning icosahedron faces return -1 (reference
        `h3Distance` failure contract), not a silent wrong answer."""
        a = H3.point_to_cell(np.array([[-73.98, 40.75]]), 5)  # NYC
        b = H3.point_to_cell(np.array([[139.7, 35.7]]), 5)  # Tokyo
        assert H3.grid_distance(a, b)[0] == -1


class TestPentagons:
    """Round-3 pentagon exactness (VERDICT round-2 task #4)."""

    def _pent_cells(self, res, n=150):
        t = tables.derive()
        from mosaic_tpu.core.index.h3 import core, hexmath as hm

        rng = np.random.default_rng(42 + res)
        pts = []
        for bc in np.nonzero(t.is_pentagon)[0]:
            c0 = hm.pack(np.asarray([bc]), np.full((1, 15), 7, np.int64), 0, np)
            lat0, lng0 = core.cell_to_geo(c0, np)
            r = rng.uniform(0, 0.2, n)
            th = rng.uniform(0, 2 * np.pi, n)
            lat = lat0 + r * np.cos(th)
            lng = lng0 + r * np.sin(th) / max(np.cos(lat0[0]), 0.2)
            pts.append(np.column_stack([np.degrees(lng), np.degrees(lat)]))
        return np.concatenate(pts)

    @pytest.mark.parametrize("res", list(range(10)))
    def test_pentagon_area_roundtrip(self, res):
        """cell -> center -> cell round-trips for points sampled in ALL 12
        pentagon base cells at every res 0-9."""
        pts = self._pent_cells(res)
        cells = H3.point_to_cell(pts, res)
        back = H3.point_to_cell(H3.cell_center(cells), res)
        np.testing.assert_array_equal(np.asarray(cells), np.asarray(back))

    def test_pentagon_boundary_five_vertices(self):
        t = tables.derive()
        from mosaic_tpu.core.index.h3 import hexmath as hm

        for res in [0, 2]:
            for bc in np.nonzero(t.is_pentagon)[0][:4]:
                digits = np.full((1, 15), 7, np.int64)
                digits[:, :res] = 0  # center child: still a pentagon
                cell = hm.pack(np.asarray([bc]), digits, res, np)
                assert bool(H3.is_pentagon(cell)[0])
                b = np.asarray(H3.cell_boundary(cell))[0]  # (7, 2)
                uniq = np.unique(np.round(b, 9), axis=0)
                assert uniq.shape[0] == 5, f"bc={bc} res={res}: {uniq.shape}"
                # every vertex is a real 3-cell meeting point: roughly
                # equidistant from the pentagon center and finite
                assert np.isfinite(b).all()

    @pytest.mark.parametrize("res", [2, 5, 7])
    def test_uniform_sphere_roundtrip_max_error(self, res):
        """cell_to_geo(point_to_cell(p)) stays within ~1 cell circumradius
        of p over a uniform sphere sample — the PR-4 regression guard for
        the pentagon corner-entry rotation bug, where ~0.9% of points
        near icosahedron vertices were assigned a cell decoding ~11 deg
        away (hundreds of circumradii) while still round-tripping
        self-consistently."""
        from mosaic_tpu.core.index.h3 import core
        from mosaic_tpu.core.index.h3.constants import (
            RES0_U_GNOMONIC,
            SQRT7,
        )

        rng = np.random.default_rng(1234 + res)
        n = 20000
        u = rng.normal(size=(n, 3))
        u /= np.linalg.norm(u, axis=1, keepdims=True)
        lat = np.arcsin(np.clip(u[:, 2], -1, 1))
        lng = np.arctan2(u[:, 1], u[:, 0])
        cells = core.geo_to_cell(lat, lng, res, np)
        cla, clo = core.cell_to_geo(cells, np)
        d = np.arccos(
            np.clip(
                np.sin(lat) * np.sin(cla)
                + np.cos(lat) * np.cos(cla) * np.cos(lng - clo),
                -1,
                1,
            )
        )
        circum = float(
            np.arctan(RES0_U_GNOMONIC / np.sqrt(3.0) / SQRT7**res)
        )
        assert float(d.max()) <= 1.5 * circum, (
            f"res {res}: max round-trip error {np.degrees(d.max()):.3f} deg "
            f"= {d.max() / circum:.1f} circumradii"
        )

    def test_pentagon_five_neighbors(self):
        t = tables.derive()
        from mosaic_tpu.core.index.h3 import hexmath as hm

        for res in [0, 1, 3]:
            for bc in np.nonzero(t.is_pentagon)[0]:
                digits = np.full((1, 15), 7, np.int64)
                digits[:, :res] = 0
                cell = hm.pack(np.asarray([bc]), digits, res, np)
                nb = H3.neighbors(cell)[0]
                valid = nb[nb >= 0]
                assert valid.size == 5, f"bc={bc} res={res}: {valid}"
                assert np.unique(valid).size == 5
                # symmetry: the pentagon is a neighbor of each neighbor
                back = H3.neighbors(valid)
                assert all(int(cell[0]) in set(row.tolist()) for row in back)


class TestBoundaryPolyfill:
    def test_boundary_contains_center(self):
        cells = H3.point_to_cell(np.array([[-122.4, 37.77]]), 9)
        b = np.asarray(H3.cell_boundary(cells))[0]  # (7,2)
        c = np.asarray(H3.cell_center(cells))[0]
        assert b.shape == (7, 2)
        np.testing.assert_allclose(b[0], b[6])
        # center inside boundary bbox
        assert b[:, 0].min() < c[0] < b[:, 0].max()
        assert b[:, 1].min() < c[1] < b[:, 1].max()
        # hex edge lengths roughly equal
        e = np.linalg.norm(np.diff(b, axis=0), axis=1)
        assert e.max() / e.min() < 1.3

    def test_polyfill_candidates_cover(self):
        bounds = np.array([-74.1, 40.6, -73.7, 40.9])
        cand = H3.polyfill_candidates(bounds, 7)
        assert len(cand) > 20
        centers = H3.cell_center(cand)
        # all candidate centers near the bbox
        assert (centers[:, 0] > -74.5).all() and (centers[:, 0] < -73.3).all()

    def test_format_parse(self):
        cells = H3.point_to_cell(sphere_points(50, seed=5), 9)
        s = H3.format(cells)
        np.testing.assert_array_equal(H3.parse(s), cells)
        assert s[0] == "%x" % int(cells[0])


class TestCellMembership:
    def test_points_inside_own_cell_boundary(self):
        """Regression: hex2d cube-rounding must use the (ii, -jj) basis —
        with the textbook basis ~1/6 of points land in a neighbor cell."""
        from mosaic_tpu.core.tessellate import _dedupe_boundary, _even_odd_inside

        rng = np.random.default_rng(11)
        pts = np.column_stack(
            [rng.uniform(-74.1, -73.8, 400), rng.uniform(40.6, 40.8, 400)]
        )
        cells = np.asarray(H3.point_to_cell(jnp.asarray(pts), 8))
        bnd = np.asarray(H3.cell_boundary(cells))
        misses = 0
        for i in range(len(pts)):
            ring = _dedupe_boundary(bnd[i])
            if not _even_odd_inside(pts[i : i + 1], [ring])[0]:
                misses += 1
        # allow icosahedron-edge stragglers only
        assert misses <= 1, f"{misses}/400 points outside their own cell"


def test_unit_vecs_encode_digit_bits():
    """unit_ijk_to_digit_i32's arithmetic form relies on UNIT_VECS[d]
    being exactly the bit decomposition of d — pin it, plus the invalid
    cases (non-unit and (1,1,1) vectors map to INVALID_DIGIT)."""
    import numpy as np

    from mosaic_tpu.core.index.h3 import constants as C
    from mosaic_tpu.core.index.h3.hexmath import unit_ijk_to_digit_i32

    uv = np.asarray(C.UNIT_VECS)
    for d, (i, j, k) in enumerate(uv):
        assert (i, j, k) == (d >> 2, (d >> 1) & 1, d & 1)
    i, j, k = (np.asarray(v, np.int32) for v in uv.T)
    np.testing.assert_array_equal(
        unit_ijk_to_digit_i32(i, j, k), np.arange(7, dtype=np.int32)
    )
    bad = np.asarray(
        [[1, 1, 1], [2, 0, 0], [0, 2, 1], [-1, 0, 0], [0, 0, 3]], np.int32
    )
    i, j, k = (np.asarray(v, np.int32) for v in bad.T)
    np.testing.assert_array_equal(
        unit_ijk_to_digit_i32(i, j, k),
        np.full(len(bad), C.INVALID_DIGIT, np.int32),
    )
