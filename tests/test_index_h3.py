"""H3 index system: remembered spec vectors, invariants, round trips.

The implementation is derived from first principles (no H3 library in the
image); external anchors are bit-exact spec examples remembered from the
public H3 documentation plus structural invariants (122 res-0 cells, 12
pentagons at the published numbers, cell counts, round trips).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem, core, tables
from mosaic_tpu.core.index.h3 import constants as C

H3 = H3IndexSystem()


def sphere_points(n, seed=0):
    rng = np.random.default_rng(seed)
    lng = rng.uniform(-180, 180, n)
    lat = np.degrees(np.arcsin(rng.uniform(-1, 1, n)))
    return np.column_stack([lng, lat])


class TestSpecAnchors:
    def test_sf_res9(self):
        # H3 docs example: geoToH3(37.7752702151959257, -122.418307270836565, 9)
        cell = H3.point_to_cell(
            np.array([[-122.418307270836565, 37.7752702151959257]]), 9
        )
        assert int(cell[0]) == 0x8928308280FFFFF

    def test_statue_of_liberty_res10(self):
        # h3-js docs example
        cell = H3.point_to_cell(np.array([[-74.044444, 40.689167]]), 10)
        assert int(cell[0]) == 0x8A2A1072B59FFFF

    def test_sf_center(self):
        # docs: h3ToGeo(8928308280fffff) ~ (37.77670234943567, -122.41845932318311)
        c = H3.cell_center(np.array([0x8928308280FFFFF], dtype=np.int64))
        np.testing.assert_allclose(
            c[0], [-122.41845932318311, 37.77670234943567], atol=1e-6
        )

    def test_pentagon_numbers(self):
        t = tables.derive()
        assert sorted(np.nonzero(t.is_pentagon)[0].tolist()) == sorted(
            tables.PENTAGON_IDS
        )


class TestInvariants:
    def test_res0_count(self):
        pts = sphere_points(30000)
        cells = np.unique(H3.point_to_cell(pts, 0))
        assert len(cells) == 122

    def test_res1_count(self):
        pts = sphere_points(200000, seed=3)
        cells = np.unique(H3.point_to_cell(pts, 1))
        assert len(cells) == 842  # 122*7 - 12*2

    def test_valid(self):
        pts = sphere_points(5000, seed=1)
        for res in [0, 5, 15]:
            cells = H3.point_to_cell(pts, res)
            assert np.asarray(H3.is_valid(cells)).all()
            assert np.asarray(H3.resolution_of(cells) == res).all()

    @pytest.mark.parametrize("res", [0, 1, 2, 4, 7, 10, 15])
    def test_roundtrip(self, res):
        pts = sphere_points(5000, seed=res)
        cells = H3.point_to_cell(pts, res)
        centers = H3.cell_center(cells)
        cells2 = H3.point_to_cell(centers, res)
        t = tables.derive()
        bc = (np.asarray(cells) >> 45) & 0x7F
        hexagon = ~t.is_pentagon[bc]
        # hexagon base cells round-trip exactly; pentagons are a documented
        # round-1 limitation
        assert (cells[hexagon] == cells2[hexagon]).all()
        assert (cells == cells2).mean() > 0.99

    def test_jnp_matches_numpy(self):
        pts = sphere_points(2000, seed=7)
        c_np = H3.point_to_cell(pts, 9)
        c_jnp = np.asarray(H3.point_to_cell(jnp.asarray(pts), 9))
        np.testing.assert_array_equal(c_np, c_jnp)


class TestNeighbors:
    def test_neighbor_count_hexagon(self):
        cells = H3.point_to_cell(np.array([[-122.4, 37.77], [0.0, 51.5]]), 7)
        nbrs = H3.neighbors(cells)
        assert ((nbrs >= 0).sum(axis=1) == 6).all()
        # symmetric: each neighbor's neighbors include the original
        for row, c in enumerate(cells):
            back = H3.neighbors(nbrs[row])
            assert all(int(c) in set(b.tolist()) for b in back)

    def test_k_ring_counts(self):
        cells = H3.point_to_cell(np.array([[-73.98, 40.75]]), 8)
        for k in [1, 2, 3]:
            ring = H3.k_ring(cells, k)
            assert (ring[0] >= 0).sum() == 1 + 3 * k * (k + 1)
            loop = H3.k_loop(cells, k)
            assert (loop[0] >= 0).sum() == 6 * k

    def test_grid_distance(self):
        cells = H3.point_to_cell(np.array([[-73.98, 40.75]]), 8)
        loop3 = H3.k_loop(cells, 3)[0]
        loop3 = loop3[loop3 >= 0]
        d = H3.grid_distance(
            np.repeat(cells, len(loop3)), loop3
        )
        assert (d == 3).all()


class TestBoundaryPolyfill:
    def test_boundary_contains_center(self):
        cells = H3.point_to_cell(np.array([[-122.4, 37.77]]), 9)
        b = np.asarray(H3.cell_boundary(cells))[0]  # (7,2)
        c = np.asarray(H3.cell_center(cells))[0]
        assert b.shape == (7, 2)
        np.testing.assert_allclose(b[0], b[6])
        # center inside boundary bbox
        assert b[:, 0].min() < c[0] < b[:, 0].max()
        assert b[:, 1].min() < c[1] < b[:, 1].max()
        # hex edge lengths roughly equal
        e = np.linalg.norm(np.diff(b, axis=0), axis=1)
        assert e.max() / e.min() < 1.3

    def test_polyfill_candidates_cover(self):
        bounds = np.array([-74.1, 40.6, -73.7, 40.9])
        cand = H3.polyfill_candidates(bounds, 7)
        assert len(cand) > 20
        centers = H3.cell_center(cand)
        # all candidate centers near the bbox
        assert (centers[:, 0] > -74.5).all() and (centers[:, 0] < -73.3).all()

    def test_format_parse(self):
        cells = H3.point_to_cell(sphere_points(50, seed=5), 9)
        s = H3.format(cells)
        np.testing.assert_array_equal(H3.parse(s), cells)
        assert s[0] == "%x" % int(cells[0])


class TestCellMembership:
    def test_points_inside_own_cell_boundary(self):
        """Regression: hex2d cube-rounding must use the (ii, -jj) basis —
        with the textbook basis ~1/6 of points land in a neighbor cell."""
        from mosaic_tpu.core.tessellate import _dedupe_boundary, _even_odd_inside

        rng = np.random.default_rng(11)
        pts = np.column_stack(
            [rng.uniform(-74.1, -73.8, 400), rng.uniform(40.6, 40.8, 400)]
        )
        cells = np.asarray(H3.point_to_cell(jnp.asarray(pts), 8))
        bnd = np.asarray(H3.cell_boundary(cells))
        misses = 0
        for i in range(len(pts)):
            ring = _dedupe_boundary(bnd[i])
            if not _even_odd_inside(pts[i : i + 1], [ring])[0]:
                misses += 1
        # allow icosahedron-edge stragglers only
        assert misses <= 1, f"{misses}/400 points outside their own cell"
