"""Reference-fixture golden snapshots of the two headline workloads:
tessellation chip structure and the PIP join result.

Reference analog: the reference pins tessellation outputs against checked-in
expected tables (`MosaicFrameBehaviors` / Quickstart cell counts); here the
NYC taxi-zone fixture (the reference's own test resource) is tessellated and
joined once, and structural digests — chip counts, core/border split, area
conservation, per-zone match counts, a match-array checksum — are snapshotted
in `tests/goldens/workload.json`.

Regenerate intentionally with MOSAIC_UPDATE_GOLDENS=1 after an algorithm
change; an unexpected diff is a correctness regression in tessellation,
indexing, or the join probe.
"""

import json
import os
import zlib
from pathlib import Path

import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.sql.join import build_chip_index, pip_join

GOLDEN = Path(__file__).parent / "goldens" / "workload.json"
NYC = "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"
RES = 8  # one level coarser than the bench: fast enough for CI


@pytest.fixture(scope="module")
def zones():
    try:
        from mosaic_tpu.readers.vector import read_geojson

        col = read_geojson(NYC).geometry
    except Exception:
        pytest.skip("reference NYC fixture unavailable")
    if not len(col):
        pytest.skip("reference NYC fixture empty")
    return col


@pytest.fixture(scope="module")
def table(zones):
    # keep_core_geoms so every chip row carries its polygon and the area
    # conservation check can integrate core + border uniformly
    return tessellate(zones, H3IndexSystem(), RES, keep_core_geoms=True)


@pytest.fixture(scope="module")
def digests(zones, table):
    return _digests(zones, table)


def _digests(zones, table):
    from mosaic_tpu.core.geometry import oracle

    is_core = np.asarray(table.is_core)
    geom_id = np.asarray(table.geom_id)
    per_zone = np.bincount(geom_id, minlength=len(zones))

    # area conservation: chips of each zone must tile the zone
    h3 = H3IndexSystem()
    chip_area = np.zeros(len(zones))
    np.add.at(chip_area, geom_id, oracle.area(table.chips))
    zone_area = oracle.area(zones)
    rel_err = float(
        np.max(np.abs(chip_area - zone_area) / np.maximum(zone_area, 1e-12))
    )

    # seeded join over the zone bbox
    b = zones.bounds()
    bbox = (
        float(np.nanmin(b[:, 0])),
        float(np.nanmin(b[:, 1])),
        float(np.nanmax(b[:, 2])),
        float(np.nanmax(b[:, 3])),
    )
    rng = np.random.default_rng(42)
    pts = np.stack(
        [
            rng.uniform(bbox[0], bbox[2], 20_000),
            rng.uniform(bbox[1], bbox[3], 20_000),
        ],
        axis=1,
    )
    index = build_chip_index(table)
    match = pip_join(pts, zones, h3, RES, chip_index=index)
    match_per_zone = np.bincount(match[match >= 0], minlength=len(zones))

    return {
        "n_zones": int(len(zones)),
        "n_chips": int(len(table)),
        "n_core": int(is_core.sum()),
        "n_border": int((~is_core).sum()),
        "chips_per_zone": per_zone.tolist(),
        # raw float kept out of the exact-equality golden (summation-order
        # noise across backends); the bound test enforces the invariant
        "_rel_err": rel_err,
        "area_conservation_ok": bool(rel_err < 1e-6),
        "join_matched": int((match >= 0).sum()),
        "join_per_zone": match_per_zone.tolist(),
        "join_checksum": int(
            zlib.crc32(np.ascontiguousarray(match, dtype=np.int32).tobytes())
        ),
    }


def test_workload_goldens(digests):
    got = {k: v for k, v in digests.items() if not k.startswith("_")}
    if os.environ.get("MOSAIC_UPDATE_GOLDENS") or not GOLDEN.exists():
        GOLDEN.write_text(json.dumps(got, indent=1))
        if not os.environ.get("MOSAIC_UPDATE_GOLDENS"):
            pytest.skip("golden created; rerun to compare")
    want = json.loads(GOLDEN.read_text())
    assert got == want


def test_area_conservation_bound(digests):
    """Chips must tile each zone to float tolerance regardless of goldens."""
    assert digests["_rel_err"] < 1e-6
