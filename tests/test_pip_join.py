"""PIP join tests: chip-index join vs the dense host oracle.

Reference analog: `PointInPolygonJoinTest` — a point lands in polygon P iff
the managed join reports P (`sql/join/PointInPolygonJoin.scala:15-98`).
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry import oracle, wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf, H3
from mosaic_tpu.sql.join import build_chip_index, pip_join
from mosaic_tpu.core.tessellate import tessellate

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))

# disjoint "zones" with concave shapes and a hole
ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), (5 5, 5 8, 8 8, 8 5, 5 5))",
    "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
    "MULTIPOLYGON (((-20 -20, -12 -20, -12 -12, -20 -12, -20 -20)), ((-8 -8, -2 -8, -2 -2, -8 -2, -8 -8)))",
]


def oracle_match(col, pts):
    """Smallest polygon row containing each point, -1 if none."""
    out = np.full(pts.shape[0], -1, dtype=np.int32)
    for g in reversed(range(len(col))):
        inside = oracle.contains_points(col, g, pts)
        out[inside] = g
    return out


@pytest.mark.parametrize("res", [2, 3])
def test_join_matches_oracle(res):
    col = wkt.from_wkt(ZONES)
    rng = np.random.default_rng(7)
    pts = np.column_stack(
        [rng.uniform(-25, 35, 4000), rng.uniform(-25, 20, 4000)]
    )
    got = pip_join(pts, col, CUSTOM, res)
    want = oracle_match(col, pts)
    # f32 device coords: points within ~1e-5 of any edge may legitimately
    # classify either way — exclude the epsilon band from exact comparison
    diff = np.nonzero(got != want)[0]
    if diff.size:
        for i in diff:
            d = min(
                float(oracle.point_boundary_distance(col, g, pts[i]))
                for g in range(len(col))
            )
            assert d < 1e-4, f"point {i} misjoined at boundary distance {d}"


def test_join_batched_equals_single():
    col = wkt.from_wkt(ZONES)
    rng = np.random.default_rng(3)
    pts = np.column_stack([rng.uniform(-25, 35, 1000), rng.uniform(-25, 20, 1000)])
    a = pip_join(pts, col, CUSTOM, 3)
    b = pip_join(pts, col, CUSTOM, 3, batch_size=137)
    np.testing.assert_array_equal(a, b)


def test_prebuilt_chip_index_reused():
    col = wkt.from_wkt(ZONES)
    table = tessellate(col, CUSTOM, 3, keep_core_geoms=False)
    ci = build_chip_index(table)
    rng = np.random.default_rng(4)
    pts = np.column_stack([rng.uniform(0, 14, 500), rng.uniform(0, 14, 500)])
    got = pip_join(pts, col, CUSTOM, 3, chip_index=ci)
    want = oracle_match(col, pts)
    ok = got == want
    assert ok.mean() > 0.99


def test_join_h3_nyc_box():
    """H3 at res 8 over an NYC-scale box — core-vs-border paths both hit."""
    zones = [
        "POLYGON ((-74.02 40.70, -73.96 40.70, -73.96 40.76, -74.02 40.76, -74.02 40.70))",
        "POLYGON ((-73.96 40.70, -73.90 40.70, -73.90 40.76, -73.96 40.76, -73.96 40.70))",
    ]
    col = wkt.from_wkt(zones)
    rng = np.random.default_rng(5)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 2000), rng.uniform(40.68, 40.78, 2000)]
    )
    got = pip_join(pts, col, H3, 8)
    want = oracle_match(col, pts)
    # away from shared boundary everything must agree
    off_boundary = np.abs(pts[:, 0] - -73.96) > 1e-3
    np.testing.assert_array_equal(got[off_boundary], want[off_boundary])


def test_writeback_variants_identical():
    """The gather writeback is an autotuning knob: results must be
    bitwise identical to the scatter path, bands included."""
    import jax.numpy as jnp

    from mosaic_tpu.core.index import H3
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index, pip_join_points

    col = wkt.from_wkt([
        "POLYGON ((-74.02 40.70, -73.96 40.70, -73.96 40.76, "
        "-74.02 40.76, -74.02 40.70))",
        "POLYGON ((-73.96 40.70, -73.90 40.70, -73.90 40.76, "
        "-73.96 40.76, -73.96 40.70))",
    ])
    idx = build_chip_index(tessellate(col, H3, 8, keep_core_geoms=False))
    rng = np.random.default_rng(2)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 5000), rng.uniform(40.68, 40.78, 5000)]
    )
    cells = H3.point_to_cell(jnp.asarray(pts), 8)
    shifted = jnp.asarray(
        pts - np.asarray(idx.border.shift, np.float64),
        dtype=idx.border.verts.dtype,
    )
    eps2 = jnp.asarray(1e-10, idx.border.verts.dtype)
    a, na = pip_join_points(shifted, cells, idx, edge_eps2=eps2)
    for wb in ("gather", "direct"):
        g, ng = pip_join_points(
            shifted, cells, idx, edge_eps2=eps2, writeback=wb
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(g), wb)
        np.testing.assert_array_equal(np.asarray(na), np.asarray(ng), wb)
    # capped case: overflow marks must agree too (direct has no tier-1
    # cap, so it is exact wherever the capped runs did not overflow)
    a2 = pip_join_points(shifted, cells, idx, found_cap=64)
    g2 = pip_join_points(shifted, cells, idx, found_cap=64, writeback="gather")
    np.testing.assert_array_equal(np.asarray(a2), np.asarray(g2))
    d2 = np.asarray(pip_join_points(shifted, cells, idx, writeback="direct"))
    a2 = np.asarray(a2)
    ok = a2 != -2
    np.testing.assert_array_equal(a2[ok], d2[ok])


def test_mxu_lookup_bit_exact():
    """The one-hot MXU row lookup is an autotuning knob: `_mm_rows` must
    be a bit-exact f32 gather (3-term bf16 split, single one-hot hit per
    row), and the full join must be bitwise identical to the gather
    lookup, bands included."""
    import jax
    import jax.numpy as jnp

    from mosaic_tpu.core.index import H3
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import _mm_rows, build_chip_index, pip_join_points

    rng = np.random.default_rng(5)
    # exponents spanning the f32 range stress the bf16 split exactness
    tab = jnp.asarray(
        (rng.standard_normal((90, 50))
         * (10.0 ** rng.integers(-20, 20, (90, 50)))).astype(np.float32)
    )
    idx = jnp.asarray(rng.integers(0, 90, 2048).astype(np.int32))
    got = np.asarray(jax.jit(_mm_rows)(idx, tab))
    np.testing.assert_array_equal(got, np.asarray(tab)[np.asarray(idx)])

    col = wkt.from_wkt(ZONES)
    cidx = build_chip_index(tessellate(col, H3, 3, keep_core_geoms=False))
    pts = np.column_stack(
        [rng.uniform(-25, 35, 20000), rng.uniform(-25, 20, 20000)]
    )
    cells = H3.point_to_cell(jnp.asarray(pts, jnp.float32), 3)
    shifted = jnp.asarray(
        pts - np.asarray(cidx.border.shift, np.float64),
        dtype=cidx.border.verts.dtype,
    )
    eps2 = jnp.asarray(1e-10, cidx.border.verts.dtype)
    for wb in ("scatter", "gather"):
        a, na = pip_join_points(
            shifted, cells, cidx, edge_eps2=eps2, writeback=wb
        )
        for lk in ("mxu", "mxu2"):
            m, nm = pip_join_points(
                shifted, cells, cidx, edge_eps2=eps2, writeback=wb, lookup=lk
            )
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(m), f"{wb}/{lk}"
            )
            np.testing.assert_array_equal(
                np.asarray(na), np.asarray(nm), f"{wb}/{lk}"
            )
    assert (np.asarray(a) >= 0).any()


def test_direct_chunked_path_identical(monkeypatch):
    """direct mode chunks its tier-1 row work above _DIRECT_CHUNK points
    (XLA's 2 GB buffer limit at 4M on TPU); shrink the chunk so the
    lax.map path runs on a small batch and assert bitwise equality with
    the unchunked scatter path, bands included."""
    import jax.numpy as jnp

    from mosaic_tpu.core.index import H3
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql import join as J

    col = wkt.from_wkt(ZONES)
    cidx = J.build_chip_index(tessellate(col, H3, 3, keep_core_geoms=False))
    rng = np.random.default_rng(11)
    pts = np.column_stack(
        [rng.uniform(-25, 35, 10000), rng.uniform(-25, 20, 10000)]
    )
    cells = H3.point_to_cell(jnp.asarray(pts, jnp.float32), 3)
    shifted = jnp.asarray(
        pts - np.asarray(cidx.border.shift, np.float64),
        dtype=cidx.border.verts.dtype,
    )
    eps2 = jnp.asarray(1e-10, cidx.border.verts.dtype)
    a, na = J.pip_join_points(shifted, cells, cidx, edge_eps2=eps2)
    monkeypatch.setattr(J, "_DIRECT_CHUNK", 1536)  # non-divisor: pads
    d, nd = J.pip_join_points(
        shifted, cells, cidx, edge_eps2=eps2, writeback="direct"
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(d))
    np.testing.assert_array_equal(np.asarray(na), np.asarray(nd))
    assert (np.asarray(a) >= 0).any()


def test_mxu_compaction_identical():
    """_compact_mxu (block one-hot int8 matmuls + one small unique
    scatter) must match _compact exactly, including pos, validity and
    both overflow kinds (global cap + block-local s_cap)."""
    import jax
    import jax.numpy as jnp

    from mosaic_tpu.sql.join import _compact, _compact_mxu

    rng = np.random.default_rng(0)
    for n, p, cap, s_cap in [
        (100000, 0.09, 16384, 256),
        (70000, 0.5, 65536, 1280),
        (2048, 1.0, 4096, 2048),
    ]:
        flag = jnp.asarray(rng.random(n) < p)
        a = jax.jit(lambda f, cap=cap: _compact(f, cap))(flag)
        m = jax.jit(
            lambda f, cap=cap, s=s_cap: _compact_mxu(f, cap, s)
        )(flag)
        for x, y, name in zip(a, m, ("src", "valid", "over", "pos")):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), f"{n}/{p}/{name}"
            )
        # the vals channel (4x6-bit int8 dots) must equal vals[src]
        vals = jnp.asarray(
            rng.integers(0, 1 << 24, n).astype(np.int32)
        )
        mv = jax.jit(
            lambda f, v, cap=cap, s=s_cap: _compact_mxu(f, cap, s, vals=v)
        )(flag, vals)
        got_v = np.asarray(mv[4])
        want_v = np.asarray(vals)[np.asarray(a[0])]
        valid_np = np.asarray(a[1])
        np.testing.assert_array_equal(
            got_v[valid_np], want_v[valid_np], f"{n}/{p}/vals"
        )
    # clustered flags exceeding s_cap in one block: dropped rows must be
    # flagged overflow (never a silently wrong/missing result)
    flag = np.zeros(100000, bool)
    flag[1000:1900] = True
    fm = jnp.asarray(flag)
    a = [np.asarray(x) for x in _compact(fm, 4096)]
    m = [np.asarray(x) for x in _compact_mxu(fm, 4096, 256)]
    np.testing.assert_array_equal(a[3], m[3])
    np.testing.assert_array_equal(m[0][:256], a[0][:256])
    assert m[2][1256:1900].all() and not m[2][:1256].any()
    assert not m[1][256:900].any()


def test_compaction_knob_end_to_end():
    import jax.numpy as jnp

    from mosaic_tpu.core.index import H3
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql import join as J

    col = wkt.from_wkt(ZONES)
    cidx = J.build_chip_index(tessellate(col, H3, 3, keep_core_geoms=False))
    rng = np.random.default_rng(13)
    n = 1 << 17  # above the mxu-compaction threshold
    pts = np.column_stack(
        [rng.uniform(-25, 35, n), rng.uniform(-25, 20, n)]
    )
    cells = H3.point_to_cell(jnp.asarray(pts, jnp.float32), 3)
    shifted = jnp.asarray(
        pts - np.asarray(cidx.border.shift, np.float64),
        dtype=cidx.border.verts.dtype,
    )
    eps2 = jnp.asarray(1e-10, cidx.border.verts.dtype)
    a, na = J.pip_join_points(shifted, cells, cidx, edge_eps2=eps2)
    m, nm = J.pip_join_points(
        shifted, cells, cidx, edge_eps2=eps2, lookup="mxu",
        compaction="mxu", compact_block=1024,
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(m))
    np.testing.assert_array_equal(np.asarray(na), np.asarray(nm))
