"""Device overlay lane vs the pure-f64 host oracle.

The acceptance contract of the device overlay join: candidates generated
on device (sorted segment equi-join) and measures fused into one program
must be BIT-IDENTICAL to `expr.host_oracle.host_overlay_measures` — the
numpy twin that under x64 IS the pure-f64 oracle — on adversarial
fixtures: self-joins, shared-edge-only contact (touches, not overlaps),
all-border multi-cell spans, empty-intersection candidates, and the
OVERFLOW(-2) cap through the fused expr path.
"""

import numpy as np
import pytest

from mosaic_tpu import expr as E
from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.dispatch import core as dispatch
from mosaic_tpu.sql.join import OVERFLOW
from mosaic_tpu.sql.overlay import (
    overlay_measures,
    prepare_overlay,
    warmup_overlay,
)


def _grid():
    # 1.25-degree cells at res 3: hermetic, fast, no external index dep
    return CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))


RES = 3


def _squares(specs):
    out = []
    for x0, y0, w, h in specs:
        out.append(
            f"POLYGON (({x0} {y0}, {x0 + w} {y0}, {x0 + w} {y0 + h},"
            f" {x0} {y0 + h}, {x0} {y0}))"
        )
    return wkt.from_wkt(out)


def _assert_bitwise(got, want):
    for field in ("pairs", "value", "valid", "area", "sure"):
        a = np.asarray(getattr(got, field))
        b = np.asarray(getattr(want, field))
        assert a.shape == b.shape and a.dtype == b.dtype, field
        assert a.tobytes() == b.tobytes(), (
            f"{field} diverged from the f64 host oracle"
        )


def _both_lanes(left, right, value=None, **kw):
    grid = _grid()
    dev = overlay_measures(left, right, grid, RES, value, **kw)
    host = overlay_measures(left, right, grid, RES, value,
                            lane="host", **kw)
    return dev, host


def test_device_matches_host_oracle_bitwise():
    left = _squares([(i * 2.9, j * 2.9, 2.7, 2.7)
                     for i in range(4) for j in range(4)])
    right = _squares([(i * 2.9 + 0.9, j * 2.9 + 0.6, 2.4, 2.4)
                      for i in range(4) for j in range(4)])
    dev, host = _both_lanes(left, right, E.overlap_fraction())
    assert dev.lane == "device" and not dev.degraded
    assert host.lane == "host"
    _assert_bitwise(dev, host)
    assert dev.pairs.shape[0] > 0
    assert np.nanmax(dev.value) > 0.0


def test_self_join_symmetry():
    """Identical tables: the pair set is symmetric, the diagonal's
    overlap fraction is ~1.0, and both lanes agree bit for bit."""
    geoms = _squares([(0.2, 0.3, 2.6, 2.6), (2.0, 2.1, 3.1, 2.2),
                      (5.4, 0.7, 1.9, 3.3)])
    dev, host = _both_lanes(geoms, geoms, E.overlap_fraction())
    _assert_bitwise(dev, host)
    pairs = {(int(a), int(b)) for a, b in dev.pairs}
    assert pairs == {(b, a) for a, b in pairs}
    diag = dev.pairs[:, 0] == dev.pairs[:, 1]
    assert set(dev.pairs[diag, 0].tolist()) == {0, 1, 2}
    # the folded per-cell decomposition and the whole-geometry shoelace
    # agree to rounding, not bitwise — allclose is the right contract
    np.testing.assert_allclose(dev.value[diag], 1.0, rtol=1e-12)


def test_shared_edge_only_touches_not_overlaps():
    """Two squares sharing exactly one edge: the shared cell makes them
    candidates, but the overlap measure must be exactly zero."""
    left = _squares([(0.0, 0.0, 1.0, 1.0)])
    right = _squares([(1.0, 0.0, 1.0, 1.0)])
    dev, host = _both_lanes(left, right)
    _assert_bitwise(dev, host)
    assert dev.pairs.shape[0] == 1
    assert float(dev.area[0]) == 0.0
    assert float(dev.value[0]) == 0.0


def test_all_border_multicell_span():
    """A thin rectangle spanning many cells — every chip a border chip,
    no core shortcut anywhere — still folds to the exact area."""
    left = _squares([(0.1, 0.2, 5.9, 0.6)])    # 5 cells, all border
    right = _squares([(0.3, 0.4, 5.2, 0.6)])
    dev, host = _both_lanes(left, right)
    _assert_bitwise(dev, host)
    assert not bool(dev.sure.any())
    assert dev.pairs.shape[0] == 1
    np.testing.assert_allclose(float(dev.area[0]), 5.2 * 0.4,
                               rtol=1e-12)


def test_empty_intersection_candidate_reports_zero():
    """Disjoint polygons sharing a cell are candidates; the fused
    measure must answer 0.0, not drop the pair."""
    left = _squares([(0.0, 0.0, 0.5, 0.5)])
    right = _squares([(0.7, 0.0, 0.5, 0.5)])
    dev, host = _both_lanes(left, right, E.overlap_fraction())
    _assert_bitwise(dev, host)
    assert dev.pairs.shape[0] == 1
    assert float(dev.area[0]) == 0.0
    assert float(dev.value[0]) == 0.0


def test_overflow_cap_through_fused_path():
    """A candidate cap below the stream size must surface as a trailing
    OVERFLOW(-2) row with NaN measures — in BOTH lanes, identically."""
    left = _squares([(i * 2.9, 0.0, 2.7, 2.7) for i in range(4)])
    right = _squares([(i * 2.9 + 0.8, 0.5, 2.4, 2.4) for i in range(4)])
    dev, host = _both_lanes(left, right, E.overlap_fraction(),
                            pair_cap=2)
    assert dev.overflow > 0 and host.overflow == dev.overflow
    assert tuple(dev.pairs[-1]) == (OVERFLOW, OVERFLOW)
    assert np.isnan(dev.value[-1]) and np.isnan(dev.area[-1])
    assert not dev.valid[-1]
    # NaN payloads compare equal at the byte level
    _assert_bitwise(dev, host)


def test_zero_cold_compiles_after_warmup():
    left = _squares([(0.3, 0.1, 2.6, 2.6), (3.3, 0.1, 2.6, 2.6)])
    right = _squares([(1.0, 0.8, 2.6, 2.6), (4.0, 0.8, 2.6, 2.6)])
    grid = _grid()
    value = E.overlap_fraction()
    prep = warmup_overlay(left, right, grid, RES, value)
    c0 = dispatch.backend_compiles()
    out = overlay_measures(left, right, grid, RES, value, prep=prep)
    assert out.lane == "device"
    assert (dispatch.backend_compiles() - c0) == 0


def test_device_failure_degrades_to_host_oracle(monkeypatch):
    """A device fault past the retry budget must degrade the WHOLE lane
    to the host oracle with the result flagged — same numbers, lane and
    flag tell the truth."""
    left = _squares([(0.3, 0.1, 2.6, 2.6)])
    right = _squares([(1.0, 0.8, 2.6, 2.6)])
    grid = _grid()
    want = overlay_measures(left, right, grid, RES, lane="host")

    import mosaic_tpu.expr.compile as _compile

    def boom(*a, **k):
        raise RuntimeError("injected device fault")

    monkeypatch.setattr(_compile, "run_tracked", boom)
    got = overlay_measures(left, right, grid, RES)
    assert got.lane == "host" and got.degraded
    assert "injected device fault" in got.reason
    _assert_bitwise(got, want)


def test_mesh_sharded_bit_identity():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("single-device runtime (no host platform mesh)")
    left = _squares([(i * 2.9, j * 2.9, 2.7, 2.7)
                     for i in range(3) for j in range(3)])
    right = _squares([(i * 2.9 + 0.9, j * 2.9 + 0.6, 2.4, 2.4)
                      for i in range(3) for j in range(3)])
    grid = _grid()
    value = E.overlap_fraction()
    single = overlay_measures(left, right, grid, RES, value)
    meshed = overlay_measures(left, right, grid, RES, value,
                              mesh=len(jax.devices()))
    assert meshed.lane == "device" and not meshed.degraded
    _assert_bitwise(meshed, single)


def test_function_frontends():
    from mosaic_tpu.functions.geometry import (
        st_intersection_area,
        st_overlap_fraction,
    )

    left = _squares([(0.3, 0.1, 2.6, 2.6)])
    right = _squares([(1.0, 0.8, 2.6, 2.6)])
    grid = _grid()
    area = st_intersection_area(left, right, grid, RES)
    frac = st_overlap_fraction(left, right, grid, RES)
    np.testing.assert_allclose(float(area.area[0]), 1.9 * 1.9,
                               rtol=1e-12)
    np.testing.assert_allclose(
        float(frac.value[0]), (1.9 * 1.9) / (2.6 * 2.6), rtol=1e-12
    )


def test_prepared_overlay_reuse_is_identical():
    """The amortized prep must answer exactly like the from-scratch
    path (same shift frame, same buckets, same programs)."""
    left = _squares([(0.3, 0.1, 2.6, 2.6), (3.3, 0.1, 2.6, 2.6)])
    right = _squares([(1.0, 0.8, 2.6, 2.6)])
    grid = _grid()
    lt = tessellate(left, grid, RES)
    rt = tessellate(right, grid, RES)
    prep = prepare_overlay(lt, rt, left, right, grid, RES)
    a = overlay_measures(left, right, grid, RES, prep=prep)
    b = overlay_measures(left, right, grid, RES)
    _assert_bitwise(a, b)
