"""OSM-buildings tessellation workload (BASELINE config #2).

Reference analog: the OpenStreetMaps notebook
(`notebooks/examples/python/OpenStreetMaps/`) chips building polygons
with grid_tessellate — the opposite regime from the taxi-zone workload:
thousands of SMALL polygons, each spanning only a handful of cells at a
resolution where cell size ~ building size. Synthetic buildings
(rotated rectangles + L-shapes, deterministic) stand in for the OSM
extract; structural digests are golden-pinned and area conservation is
asserted per building.
"""

import json
import os
from pathlib import Path

import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.core.types import GeometryBuilder, GeometryType

GOLDEN = Path(__file__).parent / "goldens" / "osm_workload.json"
RES = 12  # ~300 m2 hex cells: building-scale
N_BUILDINGS = 800
BBOX = (-73.99, 40.72, -73.95, 40.75)


def _buildings(n=N_BUILDINGS, seed=20):
    """Rotated rectangles (80%) and L-shapes (20%), ~10-60 m across."""
    rng = np.random.default_rng(seed)
    b = GeometryBuilder()
    deg = 1.0 / 111_000.0  # ~meters to degrees at NYC latitude
    for i in range(n):
        cx = rng.uniform(BBOX[0], BBOX[2])
        cy = rng.uniform(BBOX[1], BBOX[3])
        w, h = rng.uniform(10, 60, 2) * deg
        th = rng.uniform(0, np.pi)
        c, s = np.cos(th), np.sin(th)
        R = np.array([[c, -s], [s, c]])
        if i % 5 == 0:  # L-shape: rectangle minus a corner quadrant
            base = np.array(
                [
                    [0, 0], [w, 0], [w, h / 2], [w / 2, h / 2],
                    [w / 2, h], [0, h],
                ]
            )
        else:
            base = np.array([[0, 0], [w, 0], [w, h], [0, h]])
        ring = (base - [w / 2, h / 2]) @ R.T + [cx, cy]
        b.add_ring(ring)
        b.end_part()
        b.end_geom(GeometryType.POLYGON, 4326)
    return b.build()


@pytest.fixture(scope="module")
def table():
    return tessellate(_buildings(), H3IndexSystem(), RES, keep_core_geoms=True)


def test_osm_profile_structure(table):
    from mosaic_tpu.core.geometry import oracle

    col = _buildings()
    n_chips = len(table.cell_id)
    core = int(np.asarray(table.is_core).sum())
    # building-scale cells: nearly every chip is a border chip, and each
    # building spans only a handful of cells
    per_geom = np.bincount(np.asarray(table.geom_id), minlength=N_BUILDINGS)
    assert (per_geom >= 1).all()
    assert np.median(per_geom) <= 8
    # area conservation per building (clipped chips tile each polygon)
    chip_area = oracle.area(table.chips)
    per_area = np.zeros(N_BUILDINGS)
    np.add.at(per_area, np.asarray(table.geom_id), chip_area)
    want = oracle.area(col)
    rel = np.abs(per_area - want) / want
    # cell-boundary vertex precision (~1e-9 deg seams between adjacent
    # res-12 hexagons) bounds conservation for building-sized polygons;
    # absolute leakage stays < 4e-12 deg^2 (~50 cm^2) per building
    assert rel.max() < 1e-4, rel.max()
    assert np.abs(per_area - want).max() < 4e-12

    dig = {
        "n_chips": n_chips,
        "core": core,
        "cells_xor": int(np.bitwise_xor.reduce(np.asarray(table.cell_id))),
        "median_chips_per_building": float(np.median(per_geom)),
        "max_chips_per_building": int(per_geom.max()),
    }
    if GOLDEN.exists() and not os.environ.get("MOSAIC_UPDATE_GOLDENS"):
        want_dig = json.loads(GOLDEN.read_text())
        assert want_dig == dig, (want_dig, dig)
    else:
        GOLDEN.parent.mkdir(exist_ok=True)
        GOLDEN.write_text(json.dumps(dig, indent=1, sort_keys=True))
        pytest.skip("golden created; rerun to compare")


def test_osm_profile_join_roundtrip(table):
    """Building centroids must join back to their own building."""
    from mosaic_tpu.core.geometry import oracle
    from mosaic_tpu.sql.join import build_chip_index, pip_join

    col = _buildings()
    cent = oracle.centroid(col)
    # L-shape centroids stay inside for this construction; verify and
    # keep only interior centroids to make the assertion exact
    inside = np.asarray(
        [oracle.contains_points(col, g, cent[g : g + 1])[0] for g in range(len(col))]
    )
    index = build_chip_index(table)
    match = np.asarray(
        pip_join(cent, col, H3IndexSystem(), RES, chip_index=index)
    )
    # randomly-placed buildings overlap (~2%), so a centroid may join a
    # DIFFERENT containing building; correct = matched building contains it
    rows = np.nonzero(inside)[0]
    assert (match[rows] >= 0).all()
    for i in rows:
        m = int(match[i])
        assert m == i or oracle.contains_points(col, m, cent[i : i + 1])[0], (
            i, m,
        )
