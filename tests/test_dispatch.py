"""The unified dispatch core (ISSUE 11): one compile-cache/execution
path for batch, stream, serve, and raster, with a sharded lane.

Contracts under test:

1. **Sharded bit-identity.** Every frontend taking ``mesh=`` — batch
   `pip_join`, `StreamJoin`, `ServeEngine`, `ZonalEngine`/`RasterStream`
   — returns EXACTLY the single-device bits at mesh size 1, 2, 4, and 8
   (the conftest forces 8 virtual CPU devices), and matches the f64
   host oracle. Per-point results depend only on the point and the
   replicated index, so this is structural, not approximate.
2. **Compile discipline.** After `warmup()` there is at most one
   compile per `(bucket, index, mesh)` signature — co-batched serve
   traffic and batch `pip_join(mesh=...)` calls replay the same
   process-wide executables (zero cold compiles, zero new XLA backend
   compiles where the meter exists).
3. **One observability surface.** `dispatch.cache_stats()` /
   `clear_caches()` cover every registered program cache and emit
   telemetry; the legacy per-frontend views serve from the registry.
4. **Ring donation.** `StreamJoin(donate_ring=True)` warms the donating
   executable on scratch (the caller's ring survives `compile()`) and
   reports whether the backend applied the donation.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.dispatch import core as dispatch
from mosaic_tpu.dispatch.bucket import BucketLadder, backend_compiles
from mosaic_tpu.raster import Raster
from mosaic_tpu.raster.zonal import ZonalEngine, host_zonal_zones_oracle
from mosaic_tpu.runtime import telemetry
from mosaic_tpu.serve import ServeEngine
from mosaic_tpu.sql import RasterStream
from mosaic_tpu.sql.join import build_chip_index, host_join, pip_join
from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3
BBOX = (-25.0, -25.0, 35.0, 20.0)
ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), "
    "(5 5, 5 8, 8 8, 8 5, 5 5))",
    "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
    "MULTIPOLYGON (((-20 -20, -12 -20, -12 -12, -20 -12, -20 -20)), "
    "((-8 -8, -2 -8, -2 -2, -8 -2, -8 -8)))",
]
MESHES = (1, 2, 4, 8)


@pytest.fixture(scope="module")
def index():
    col = wkt.from_wkt(ZONES)
    return build_chip_index(
        tessellate(col, CUSTOM, RES, keep_core_geoms=False)
    )


@pytest.fixture(scope="module")
def points():
    rng = np.random.default_rng(7)
    return rng.uniform(BBOX[:2], BBOX[2:], (1024, 2))


# --------------------------------------------------- mesh normalization


class TestResolveMesh:
    def test_none_without_knob_is_single_device(self, monkeypatch):
        monkeypatch.delenv("MOSAIC_MESH", raising=False)
        assert dispatch.resolve_mesh(None) is None

    @pytest.mark.parametrize("raw,n", [("2", 2), ("dp4", 4), ("8", 8)])
    def test_env_knob(self, monkeypatch, raw, n):
        monkeypatch.setenv("MOSAIC_MESH", raw)
        assert dispatch.resolve_mesh(None).size == n

    @pytest.mark.parametrize("raw", ["", "0", "1"])
    def test_env_knob_degenerate_is_single_device(self, monkeypatch, raw):
        monkeypatch.setenv("MOSAIC_MESH", raw)
        assert dispatch.resolve_mesh(None) is None

    def test_env_knob_garbage_raises(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_MESH", "lots")
        with pytest.raises(ValueError, match="MOSAIC_MESH"):
            dispatch.resolve_mesh(None)

    def test_int_and_mesh_passthrough(self):
        m = dispatch.resolve_mesh(4)
        assert m.size == 4 and m.axis_names == ("dp",)
        assert dispatch.resolve_mesh(m) is m
        assert dispatch.resolve_mesh(1) is None

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="devices"):
            dispatch.data_mesh(99)


# ------------------------------------------- sharded ≡ single ≡ oracle


class TestShardedBitIdentity:
    @pytest.mark.parametrize("mesh", MESHES)
    def test_pip_join(self, index, points, mesh):
        single = pip_join(
            points, None, CUSTOM, RES, chip_index=index, recheck=False
        )
        oracle = host_join(points, index.host, CUSTOM, RES)
        np.testing.assert_array_equal(single, oracle)
        sharded = pip_join(
            points, None, CUSTOM, RES, chip_index=index,
            recheck=False, mesh=mesh,
        )
        np.testing.assert_array_equal(np.asarray(sharded), oracle)

    def test_pip_join_mesh_rejects_recheck(self, index, points):
        with pytest.raises(ValueError, match="recheck"):
            pip_join(
                points, None, CUSTOM, RES, chip_index=index,
                recheck=True, mesh=2,
            )

    @pytest.mark.parametrize("mesh", MESHES)
    def test_stream_join(self, index, mesh):
        rng = np.random.default_rng(3)
        batches = [
            rng.uniform((-25, -25), (35, 20), (1024, 2)) for _ in range(2)
        ]
        ring = ring_from_host(batches)
        base = StreamJoin(index, CUSTOM, RES).run(ring, 3, collect=True)
        got = StreamJoin(index, CUSTOM, RES, mesh=mesh).run(
            ring, 3, collect=True
        )
        assert (got.checksum, got.matches, got.overflow) == (
            base.checksum, base.matches, base.overflow
        )
        np.testing.assert_array_equal(
            np.asarray(got.outs), np.asarray(base.outs)
        )
        # every scanned batch also matches the f64 host oracle (batches
        # 2.. re-visit ring rows 0..)
        for i in range(3):
            np.testing.assert_array_equal(
                np.asarray(got.outs)[i],
                host_join(batches[i % 2], index.host, CUSTOM, RES),
            )

    def test_stream_join_batch_must_divide(self, index):
        sj = StreamJoin(index, CUSTOM, RES, mesh=8)
        with pytest.raises(ValueError, match="divide"):
            sj.step(jnp.zeros((100, 2)))

    @pytest.mark.parametrize("mesh", MESHES)
    def test_serve_engine(self, index, mesh):
        rng = np.random.default_rng(11)
        reqs = [
            rng.uniform(BBOX[:2], BBOX[2:], (n, 2))
            for n in (17, 64, 130, 1000)
        ]
        want = [host_join(p, index.host, CUSTOM, RES) for p in reqs]
        with ServeEngine(
            index, CUSTOM, RES, ladder=BucketLadder(64, 1024),
            bounds=BBOX, max_wait_s=0.0, mesh=mesh,
        ) as eng:
            for p, w in zip(reqs, want):
                np.testing.assert_array_equal(
                    np.asarray(eng.join(p, deadline_s=60.0)), w
                )

    @pytest.mark.parametrize("mesh", MESHES)
    def test_zonal_zones(self, index, mesh):
        r = _mk_raster()
        base = ZonalEngine(CUSTOM, RES, chip_index=index).zones(
            r, tile=(32, 32)
        )
        got = ZonalEngine(CUSTOM, RES, chip_index=index, mesh=mesh).zones(
            r, tile=(32, 32)
        )
        want = host_zonal_zones_oracle(r, index, CUSTOM, RES, tile=(32, 32))
        for a in ("keys", "count", "sum", "min", "max"):
            np.testing.assert_array_equal(getattr(got, a), getattr(base, a))
            np.testing.assert_array_equal(getattr(got, a), getattr(want, a))

    def test_raster_stream_scan(self, index):
        r = _mk_raster()
        base = RasterStream(index, CUSTOM, RES).scan(r, tile=(32, 32))
        got = RasterStream(index, CUSTOM, RES, mesh=4).scan(r, tile=(32, 32))
        for a in ("keys", "count", "sum", "min", "max"):
            np.testing.assert_array_equal(
                getattr(got.stats, a), getattr(base.stats, a)
            )


def _mk_raster(h=75, w=90, seed=5):
    rng = np.random.default_rng(seed)
    data = rng.uniform(0, 100, (1, h, w))
    data[0][rng.random((h, w)) < 0.1] = -9.0
    return Raster(
        data=data, gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0), srid=0,
        nodata=-9.0,
    )


# ------------------------------------------------- compile discipline


class TestCompileDiscipline:
    def test_warmup_one_compile_per_signature_across_frontends(self, index):
        """After warmup, serve dispatches AND batch pip_join(mesh=...)
        calls introduce zero new signatures and zero new XLA backend
        compiles — the executables are process-shared, keyed on
        (bucket, index, mesh)."""
        ladder = BucketLadder(64, 512)
        with ServeEngine(
            index, CUSTOM, RES, ladder=ladder, bounds=BBOX,
            max_wait_s=0.0, mesh=2,
        ) as eng:
            report = eng.warmup()
            assert report["signatures"] == len(ladder.buckets)
            assert len(eng.core.signatures) == len(ladder.buckets)
            t0 = backend_compiles()
            rng = np.random.default_rng(0)
            for n in (5, 64, 65, 200, 512, 30):
                eng.join(
                    rng.uniform(BBOX[:2], BBOX[2:], (n, 2)),
                    deadline_s=60.0,
                )
            # the batch frontend rides the same compiled programs
            pip_join(
                rng.uniform(BBOX[:2], BBOX[2:], (300, 2)), None, CUSTOM,
                RES, chip_index=index, recheck=False, mesh=2,
            )
            t1 = backend_compiles()
            assert eng.core.cold_compiles == 0
            assert len(eng.core.signatures) == len(ladder.buckets)
            if t0 is not None and t1 is not None:
                assert t1 - t0 == 0, "post-warmup dispatches recompiled"

    def test_warmup_emits_spans_and_stage_timings(self, index):
        core = dispatch.DispatchCore(
            index, CUSTOM, RES, ladder=BucketLadder(64, 128)
        )
        with telemetry.capture() as events:
            report = core.warmup()
        assert report["buckets"] == 2 and core.warmed
        stages = [
            e for e in events
            if e.get("event") == "dispatch_stage"
            and e.get("stage") == "warmup"
        ]
        assert [e["bucket"] for e in stages] == [64, 128]
        assert all(e["seconds"] >= 0 for e in stages)
        assert any(e.get("event") == "dispatch_warmup" for e in events)
        spans = [
            e for e in events
            if e.get("event") == "span" and e.get("name") == "dispatch.warmup"
        ]
        assert len(spans) == 1

    def test_post_freeze_compile_emits_event(self, index):
        core = dispatch.DispatchCore(
            index, CUSTOM, RES, ladder=BucketLadder(64, 128)
        )
        core.freeze()  # arm the tripwire without warming
        with telemetry.capture() as events:
            core.execute(np.zeros((10, 2)))
        assert core.cold_compiles == 1
        assert any(e.get("event") == "dispatch_compile" for e in events)

    def test_mesh_must_divide_min_bucket(self, index):
        with pytest.raises(ValueError, match="divide"):
            dispatch.DispatchCore(
                index, CUSTOM, RES, ladder=BucketLadder(4, 64), mesh=8
            )


# ---------------------------------------------- cache observability


class TestCacheRegistry:
    def test_cache_stats_covers_every_registered_cache(self, index):
        # the distributed caches register at module import; force it so
        # the registry names are present regardless of test ordering
        import mosaic_tpu.parallel.dist_join  # noqa: F401
        import mosaic_tpu.parallel.dist_knn  # noqa: F401

        # touch a program cache so the registry has something to report
        pip_join(
            np.zeros((8, 2)), None, CUSTOM, RES, chip_index=index,
            recheck=False,
        )
        with telemetry.capture() as events:
            stats = dispatch.cache_stats()
        assert any(
            e.get("event") == "dispatch_cache_stats" for e in events
        )
        for name in (
            "jit_join", "cells_prog", "stream_programs", "sharded_join",
            "dist_join_step", "knn_sharded_distance",
        ):
            assert set(stats[name]) == {
                "hits", "misses", "maxsize", "currsize"
            }, name
        # batch_cores carries eviction-policy extras on top of the base
        assert set(stats["batch_cores"]) == {
            "hits", "misses", "maxsize", "currsize",
            "evictions", "occupancy",
        }
        assert set(stats["jit_programs"]) == {"join", "counts", "compact"}

    def test_clear_caches_is_selective_and_emits(self, index):
        StreamJoin(index, CUSTOM, RES)  # populate stream_programs
        assert dispatch.cache_view("stream_programs")["currsize"] > 0
        before = dispatch.cache_view("cells_prog")["currsize"]
        assert before > 0
        with telemetry.capture() as events:
            pre = dispatch.clear_caches(names=("stream_programs",))
        assert any(
            e.get("event") == "dispatch_caches_cleared" for e in events
        )
        assert pre["stream_programs"]["currsize"] > 0  # pre-clear view
        assert dispatch.cache_view("stream_programs")["currsize"] == 0
        # unnamed caches survive a selective clear
        assert dispatch.cache_view("cells_prog")["currsize"] == before

    def test_unbounded_cache_rejected(self):
        with pytest.raises(ValueError, match="bounded"):
            dispatch.bounded_cache("nope", None)

    def test_legacy_views_serve_from_registry(self, index):
        from mosaic_tpu.parallel.dist_knn import knn_cache_stats
        from mosaic_tpu.sql.join import join_cache_stats

        legacy = join_cache_stats(emit=False)
        assert legacy["cells_prog"] == dispatch.cache_view("cells_prog")
        knn = knn_cache_stats(emit=False)
        assert knn["sharded_distance"] == dispatch.cache_view(
            "knn_sharded_distance"
        )

    def test_stream_program_bundle_is_shared(self, index):
        a = StreamJoin(index, CUSTOM, RES, prefetch=True)
        b = StreamJoin(index, CUSTOM, RES, prefetch=True)
        assert a._loop is b._loop  # one compiled scan, not one per join


# --------------------------------------------------------- donation


class TestRingDonation:
    def test_compile_preserves_ring_and_run_reports(self, index):
        rng = np.random.default_rng(9)
        ring = ring_from_host(
            [rng.uniform((-25, -25), (35, 20), (512, 2)) for _ in range(2)]
        )
        base = StreamJoin(index, CUSTOM, RES).run(ring, 3)
        sj = StreamJoin(index, CUSTOM, RES, donate_ring=True)
        sj.compile(ring, 3)
        assert not ring.is_deleted()  # warmed on scratch, not our ring
        res = sj.run(jnp.array(ring, copy=True), 3)
        assert (res.checksum, res.matches, res.overflow) == (
            base.checksum, base.matches, base.overflow
        )
        assert res.metrics["donate_ring"] is True
        assert isinstance(res.metrics["ring_donated"], bool)
        assert res.metrics["ring_bytes"] == int(ring.nbytes)
