"""Round-3 format readers vs the reference's own binary fixtures.

Reference: `datasource/OGRFileFormat.scala:26` (any OGR driver),
`core/raster/MosaicRasterGDAL.scala:182-187` (any GDAL raster), fixtures
at `src/test/resources/binary/{grib-cams,zarr-example}`.
"""

import glob
import os

import numpy as np
import pytest

from mosaic_tpu.readers import (
    read,
    read_geopackage,
    read_grib2,
    read_zarr,
    write_geopackage,
)

GRIB_DIR = "/root/reference/src/test/resources/binary/grib-cams"
ZARR_ZIP = "/root/reference/src/test/resources/binary/zarr-example/zarr_test_data.zip"

needs_fixtures = pytest.mark.skipif(
    not os.path.isdir(GRIB_DIR), reason="reference fixtures unavailable"
)


# ------------------------------------------------------------------- GRIB2
@needs_fixtures
def test_grib_all_fixtures_decode():
    files = sorted(glob.glob(f"{GRIB_DIR}/*.grib"))
    assert len(files) == 3
    for p in files:
        r = read_grib2(p)
        # 6 GRIB2 + 8 GRIB1 messages per file, one band each (as GDAL does)
        assert r.num_bands == 14 and r.data.shape == (14, 14, 14)
        assert r.srid == 4326
        # CAMS aerosol mixing ratios: positive, tiny
        assert 0 < np.nanmin(r.data) and np.nanmax(r.data) < 1e-3
        # regular 0.75-degree lat/lon grid over north Africa
        x0, dx, _, y0, _, dy = r.gt
        assert dx == pytest.approx(0.75) and dy == pytest.approx(-0.75)
        assert y0 == pytest.approx(10.125) and x0 == pytest.approx(-0.375)


@needs_fixtures
def test_grib_matches_gdal_statistics():
    """Band min/max must reproduce the STATISTICS_* values GDAL itself
    computed into the fixture's .aux.xml sidecar — an independent oracle."""
    import re

    p = glob.glob(f"{GRIB_DIR}/*1650626995*.grib")[0]
    xml = open(p + ".aux.xml").read()
    mins = sorted(float(v) for v in re.findall(r'STATISTICS_MINIMUM">([^<]+)', xml))
    maxs = sorted(float(v) for v in re.findall(r'STATISTICS_MAXIMUM">([^<]+)', xml))
    r = read_grib2(p)
    got_min = sorted(float(r.data[b].min()) for b in range(r.num_bands))
    got_max = sorted(float(r.data[b].max()) for b in range(r.num_bands))
    np.testing.assert_allclose(got_min, mins, rtol=1e-9)
    np.testing.assert_allclose(got_max, maxs, rtol=1e-9)


@needs_fixtures
def test_grib_through_read_raster_and_rst():
    from mosaic_tpu.raster import read_raster

    p = sorted(glob.glob(f"{GRIB_DIR}/*.grib"))[0]
    r = read_raster(p)  # extension dispatch
    assert r.num_bands == 14
    # rst_* surface applies to grib rasters unchanged
    from mosaic_tpu.functions import raster as R

    assert R.rst_numbands(r) == 14
    wx, wy = r.raster_to_world(0, 0)
    assert wx == pytest.approx(r.gt[0]) and wy == pytest.approx(r.gt[3])


def test_grib_rejects_garbage(tmp_path):
    p = tmp_path / "bad.grib"
    p.write_bytes(b"GRIB" + b"\x00" * 40)
    with pytest.raises(ValueError):
        read_grib2(str(p))


# -------------------------------------------------------------------- Zarr
@needs_fixtures
def test_zarr_fixture_arrays():
    store_arrays = {
        "group_with_dims/var2D": (20, 20),
        "group_with_dims/var3D": (20, 20, 20),
        "group_with_attrs/F_order_array": (20, 20),
        "group_with_attrs/nested": (20, 20),
    }
    for name, shape in store_arrays.items():
        arr, _attrs = read_zarr(ZARR_ZIP, array=name)
        assert arr.shape == shape, name
    # C vs F order must decode to the same logical values
    a, _ = read_zarr(ZARR_ZIP, array="group_with_dims/var2D")
    f, _ = read_zarr(ZARR_ZIP, array="group_with_attrs/F_order_array")
    assert a.dtype == np.int32
    # var2D rows are 0..19 repeated (written by the fixture generator)
    assert (a[0] == np.arange(20)).all()


@needs_fixtures
def test_zarr_missing_chunks_use_fill():
    arr, _ = read_zarr(ZARR_ZIP, array="group_with_attrs/partial_fill1")
    assert (arr == 999.0).any() and arr.dtype == np.float32


@needs_fixtures
def test_zarr_via_registry():
    arr, attrs = read("zarr").option("array", "group_with_dims/var1D").load(ZARR_ZIP)
    assert arr.shape == (20,)


def test_zarr_directory_store(tmp_path):
    import json

    d = tmp_path / "store"
    (d / "a").mkdir(parents=True)
    (d / "a" / ".zarray").write_text(
        json.dumps(
            {
                "zarr_format": 2,
                "shape": [4, 6],
                "chunks": [2, 3],
                "dtype": "<f8",
                "order": "C",
                "fill_value": -1.0,
                "compressor": {"id": "zlib", "level": 1},
                "filters": None,
            }
        )
    )
    import zlib

    block = np.arange(6, dtype=np.float64).reshape(2, 3)
    (d / "a" / "0.0").write_bytes(zlib.compress(block.tobytes()))
    arr, _ = read_zarr(str(d), array="a")
    np.testing.assert_array_equal(arr[:2, :3], block)
    assert (arr[2:, :] == -1.0).all()  # missing chunks -> fill


# -------------------------------------------------------------- GeoPackage
def test_geopackage_roundtrip(tmp_path):
    from mosaic_tpu.core.geometry import wkt as W
    from mosaic_tpu.readers.vector import VectorTable

    wkts = [
        "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), (5 5, 5 8, 8 8, 8 5, 5 5))",
        "MULTIPOLYGON (((-20 -20, -12 -20, -12 -12, -20 -12, -20 -20)))",
        "POINT (5 5)",
        "LINESTRING (0 0, 3 4, 6 0)",
    ]
    col = W.from_wkt(wkts)
    vt = VectorTable(
        geometry=col, columns={"score": np.asarray([1.0, 2.5, -3.0, 0.0])}
    )
    p = tmp_path / "zones.gpkg"
    write_geopackage(str(p), vt, layer="zones", srid=4326)
    back = read_geopackage(str(p))
    assert len(back.geometry) == 4
    assert back.columns["score"].tolist() == [1.0, 2.5, -3.0, 0.0]
    # geometry-exact roundtrip
    got = W.to_wkt(back.geometry)
    want = W.to_wkt(col)
    assert got == want
    assert (np.asarray(back.geometry.srid) == 4326).all()


def test_geopackage_layer_listing_and_errors(tmp_path):
    from mosaic_tpu.core.geometry import wkt as W
    from mosaic_tpu.readers.geopackage import list_layers
    from mosaic_tpu.readers.vector import VectorTable

    col = W.from_wkt(["POINT (0 0)"])
    p = tmp_path / "one.gpkg"
    write_geopackage(str(p), VectorTable(geometry=col, columns={}), layer="pts")
    assert list_layers(str(p)) == ["pts"]
    with pytest.raises(ValueError):
        read_geopackage(str(p), layer="absent")


def test_geopackage_envelope_flag_variants(tmp_path):
    """Blobs with a 32-byte envelope (flag code 1) must parse too."""
    import sqlite3
    import struct

    from mosaic_tpu.core.geometry import wkt as W
    from mosaic_tpu.core.geometry import wkb as B
    from mosaic_tpu.readers.vector import VectorTable

    col = W.from_wkt(["POINT (7 8)"])
    p = tmp_path / "env.gpkg"
    write_geopackage(str(p), VectorTable(geometry=col, columns={}), layer="pts")
    con = sqlite3.connect(str(p))
    w = B.to_wkb(col)[0]
    blob = (
        b"GP\x00\x03"  # flags: envelope code 1 | little-endian
        + struct.pack("<i", 4326)
        + struct.pack("<4d", 7.0, 7.0, 8.0, 8.0)
        + w
    )
    con.execute('UPDATE "pts" SET geom=?', (blob,))
    con.commit()
    con.close()
    back = read_geopackage(str(p))
    assert W.to_wkt(back.geometry) == ["POINT (7 8)"]


# ----------------------------------------------------------- NetCDF-4/HDF5
NC_DIR = "/root/reference/src/test/resources/binary/netcdf-coral"


@needs_fixtures
def test_netcdf_coral_decode():
    """NOAA CRW 5km coral product: global 0.05-degree uint8 grids."""
    from mosaic_tpu.readers import H5Lite

    p = sorted(glob.glob(f"{NC_DIR}/*.nc"))[0]
    h5 = H5Lite(p)
    assert set(h5.datasets()) == {
        "bleaching_alert_area", "crs", "lat", "lon", "mask", "time",
    }
    lat = h5.read("lat")
    lon = h5.read("lon")
    assert lat.shape == (3600,) and lon.shape == (7200,)
    np.testing.assert_allclose(lat[0], 89.975)
    np.testing.assert_allclose(lat[-1], -89.975)
    np.testing.assert_allclose(lon[0], -179.975)
    baa = h5.read("bleaching_alert_area")
    assert baa.shape == (1, 3600, 7200) and baa.dtype == np.uint8
    assert h5.fill_value("bleaching_alert_area") == 251
    vals = set(np.unique(baa).tolist())
    assert vals <= {0, 1, 2, 3, 4, 251}  # alert levels + fill


@needs_fixtures
def test_netcdf_all_fixture_files_consistent():
    """Every day of the coral series decodes to the same grid."""
    from mosaic_tpu.readers import read_netcdf

    for p in sorted(glob.glob(f"{NC_DIR}/*.nc"))[:4]:
        r = read_netcdf(p)
        assert r.data.shape == (2, 3600, 7200)
        # coordinate variables are f32: compare to f32 precision
        np.testing.assert_allclose(
            r.gt, (-180.0, 0.05, 0.0, 90.0, 0.0, -0.05), atol=1e-4
        )
        assert 0.5 < float(np.isfinite(r.data).mean()) <= 1.0


@needs_fixtures
def test_netcdf_via_read_raster_and_registry():
    from mosaic_tpu.raster import read_raster

    p = sorted(glob.glob(f"{NC_DIR}/*.nc"))[0]
    r = read_raster(p)  # .nc extension dispatch
    assert r.num_bands == 2
    r2 = read("netcdf").option("variable", "mask").load(p)
    assert r2.num_bands == 1
    from mosaic_tpu.functions import raster as R

    assert int(R.rst_width([r])[0]) == 7200


def test_netcdf_rejects_non_hdf5(tmp_path):
    from mosaic_tpu.readers import H5Lite

    p = tmp_path / "no.nc"
    p.write_bytes(b"CDF\x01" + b"\x00" * 64)  # netCDF-3 classic
    with pytest.raises(ValueError):
        H5Lite(str(p))


# ------------------------------------------------------------ ESRI FileGDB
GDB_ZIP = "/root/reference/src/test/resources/binary/geodb/bridges.gdb.zip"


@needs_fixtures
def test_filegdb_bridges_fixture():
    """All 19,890 NYSDOT bridges decode; geometry agrees with the
    fixture's own LATITUDE/LONGITUDE attribute columns after UTM->WGS84
    (our CRS stack) for >90% of rows at <1e-6 deg (the rest are source
    data discrepancies — the median error is ~4e-9 deg)."""
    from mosaic_tpu.core import crs
    from mosaic_tpu.readers import read_filegdb

    vt = read_filegdb(GDB_ZIP)
    assert len(vt.geometry) == 19890
    assert len(vt.columns) == 41
    n = 2000
    xy = np.stack([vt.geometry.geom_xy(i)[0] for i in range(n)])
    ll = crs.to_wgs84(xy, 26918, np)
    lat, lon = vt.columns["LATITUDE"][:n], vt.columns["LONGITUDE"][:n]
    ok = np.isfinite(lat) & np.isfinite(lon)
    err = np.hypot(ll[ok, 1] - lat[ok], ll[ok, 0] - lon[ok])
    assert np.median(err) < 1e-7
    assert (err < 1e-6).mean() > 0.85
    # attribute columns decode with real content
    assert "STEUBEN" in set(
        v for v in vt.columns["COUNTY_NAME"][:50] if v is not None
    )


@needs_fixtures
def test_filegdb_layer_listing_and_registry():
    import tempfile
    import zipfile

    from mosaic_tpu.readers.filegdb import list_gdb_layers

    tmp = tempfile.mkdtemp()
    with zipfile.ZipFile(GDB_ZIP) as z:
        z.extractall(tmp)
    gdb = os.path.join(tmp, "NYSDOTBridges.gdb")
    assert list(list_gdb_layers(gdb)) == ["Bridges_Feb2019"]
    vt = read("geodb").option("layer", "Bridges_Feb2019").load(gdb)
    assert len(vt.geometry) == 19890
    with pytest.raises(ValueError):
        read("geodb").option("layer", "nope").load(gdb)


# ----------------------------------------------------------------- KML
_KML_DOC = """<?xml version="1.0" encoding="UTF-8"?>
<kml xmlns="http://www.opengis.net/kml/2.2">
 <Document>
  <Folder>
   <Placemark>
    <name>hq</name>
    <ExtendedData><Data name="kind"><value>office</value></Data></ExtendedData>
    <Point><coordinates>-73.98,40.75,12.5</coordinates></Point>
   </Placemark>
   <Placemark>
    <name>route</name>
    <LineString><coordinates>
      -74.0,40.7 -73.95,40.72 -73.9,40.76
    </coordinates></LineString>
   </Placemark>
  </Folder>
  <Placemark>
   <name>zone</name>
   <ExtendedData><SchemaData><SimpleData name="code">Z1</SimpleData></SchemaData></ExtendedData>
   <Polygon>
    <outerBoundaryIs><LinearRing><coordinates>
      -74.02,40.70 -73.96,40.70 -73.96,40.76 -74.02,40.76 -74.02,40.70
    </coordinates></LinearRing></outerBoundaryIs>
    <innerBoundaryIs><LinearRing><coordinates>
      -74.00,40.72 -73.98,40.72 -73.98,40.74 -74.00,40.74 -74.00,40.72
    </coordinates></LinearRing></innerBoundaryIs>
   </Polygon>
  </Placemark>
  <Placemark>
   <name>islands</name>
   <MultiGeometry>
    <Polygon><outerBoundaryIs><LinearRing><coordinates>
      0,0 1,0 1,1 0,1 0,0
    </coordinates></LinearRing></outerBoundaryIs></Polygon>
    <Polygon><outerBoundaryIs><LinearRing><coordinates>
      2,2 3,2 3,3 2,3 2,2
    </coordinates></LinearRing></outerBoundaryIs></Polygon>
   </MultiGeometry>
  </Placemark>
 </Document>
</kml>
"""


def test_kml_reader(tmp_path):
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.registry import read

    p = tmp_path / "sample.kml"
    p.write_text(_KML_DOC)
    t = read("kml").load(str(p))
    assert len(t) == 4
    assert [t.geometry.geometry_type(g) for g in range(4)] == [
        GeometryType.POINT, GeometryType.LINESTRING,
        GeometryType.POLYGON, GeometryType.MULTIPOLYGON,
    ]
    assert t.columns["name"].tolist() == ["hq", "route", "zone", "islands"]
    assert t.columns["kind"][0] == "office"
    assert t.columns["code"][2] == "Z1"
    # point carries altitude as z, lon/lat order per spec
    np.testing.assert_allclose(t.geometry.geom_xy(0), [[-73.98, 40.75]])
    assert t.geometry.has_z(0)
    # holed polygon: area = outer - inner
    from mosaic_tpu import functions as F

    a = float(np.asarray(F.st_area(t.geometry.slice(2, 3)))[0])
    np.testing.assert_allclose(a, 0.06 * 0.06 - 0.02 * 0.02, atol=1e-12)
    # multipolygon: two parts, total area 2
    a2 = float(np.asarray(F.st_area(t.geometry.slice(3, 4)))[0])
    np.testing.assert_allclose(a2, 2.0, atol=1e-12)
    # srid is fixed to 4326 by the KML spec
    assert int(t.geometry.srid[2]) == 4326


def test_kml_mixed_multigeometry_uses_collection_rule(tmp_path):
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.kml import read_kml

    doc = """<?xml version="1.0"?>
    <kml xmlns="http://www.opengis.net/kml/2.2"><Document><Placemark>
     <MultiGeometry>
      <Point><coordinates>5,5</coordinates></Point>
      <Polygon><outerBoundaryIs><LinearRing><coordinates>
        0,0 2,0 2,2 0,2 0,0
      </coordinates></LinearRing></outerBoundaryIs></Polygon>
     </MultiGeometry>
    </Placemark></Document></kml>"""
    p = tmp_path / "mixed.kml"
    p.write_text(doc)
    t = read_kml(p)
    # first-polygonal rule (shared with the WKT/WKB/GeoJSON codecs)
    assert t.geometry.geometry_type(0) == GeometryType.POLYGON
    assert t.geometry.geom_xy(0).shape[0] == 4


def test_kml_nested_mixed_multigeometry_and_sloppy_coords(tmp_path):
    # a nested MIXED MultiGeometry must not win the first-polygonal rule
    # over a real later Polygon; trailing commas must parse
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.kml import read_kml

    doc = """<?xml version="1.0"?>
    <kml xmlns="http://www.opengis.net/kml/2.2"><Document><Placemark>
     <MultiGeometry>
      <MultiGeometry>
       <Point><coordinates>5,5,</coordinates></Point>
       <LineString><coordinates>0,0 1,1</coordinates></LineString>
      </MultiGeometry>
      <Polygon><outerBoundaryIs><LinearRing><coordinates>
        0,0 2,0 2,2 0,2 0,0
      </coordinates></LinearRing></outerBoundaryIs></Polygon>
     </MultiGeometry>
    </Placemark></Document></kml>"""
    p = tmp_path / "nested.kml"
    p.write_text(doc)
    t = read_kml(p)
    assert t.geometry.geometry_type(0) == GeometryType.POLYGON
    assert t.geometry.geom_xy(0).shape[0] == 4  # the real polygon won


# ----------------------------------------------------------- GML + GPX
_GML_DOC = """<?xml version="1.0" encoding="utf-8" ?>
<ogr:FeatureCollection xmlns:gml="http://www.opengis.net/gml"
                       xmlns:ogr="http://ogr.maptools.org/">
 <gml:featureMember>
  <ogr:zone>
   <ogr:name>alpha</ogr:name>
   <ogr:pop>120</ogr:pop>
   <ogr:geometryProperty>
    <gml:Polygon srsName="EPSG:4326">
     <gml:exterior><gml:LinearRing>
      <gml:posList>0 0 4 0 4 4 0 4 0 0</gml:posList>
     </gml:LinearRing></gml:exterior>
     <gml:interior><gml:LinearRing>
      <gml:posList>1 1 1 2 2 2 2 1 1 1</gml:posList>
     </gml:LinearRing></gml:interior>
    </gml:Polygon>
   </ogr:geometryProperty>
  </ogr:zone>
 </gml:featureMember>
 <gml:featureMember>
  <ogr:stop>
   <ogr:name>beta</ogr:name>
   <ogr:geometryProperty>
    <gml:Point><gml:pos>-73.98 40.75</gml:pos></gml:Point>
   </ogr:geometryProperty>
  </ogr:stop>
 </gml:featureMember>
 <gml:featureMember>
  <ogr:path>
   <ogr:geometryProperty>
    <gml:LineString>
     <gml:coordinates>0,0 1,1 2,0</gml:coordinates>
    </gml:LineString>
   </ogr:geometryProperty>
  </ogr:path>
 </gml:featureMember>
 <gml:featureMember>
  <ogr:lakes>
   <ogr:geometryProperty>
    <gml:MultiSurface>
     <gml:surfaceMember><gml:Polygon><gml:exterior><gml:LinearRing>
      <gml:posList>0 0 1 0 1 1 0 1 0 0</gml:posList>
     </gml:LinearRing></gml:exterior></gml:Polygon></gml:surfaceMember>
     <gml:surfaceMember><gml:Polygon><gml:exterior><gml:LinearRing>
      <gml:posList>3 3 4 3 4 4 3 4 3 3</gml:posList>
     </gml:LinearRing></gml:exterior></gml:Polygon></gml:surfaceMember>
    </gml:MultiSurface>
   </ogr:geometryProperty>
  </ogr:lakes>
 </gml:featureMember>
</ogr:FeatureCollection>
"""

_GPX_DOC = """<?xml version="1.0"?>
<gpx xmlns="http://www.topografix.com/GPX/1/1" version="1.1">
 <wpt lat="40.75" lon="-73.98"><ele>12.5</ele><name>hq</name></wpt>
 <rte><name>r1</name>
  <rtept lat="40.7" lon="-74.0"/><rtept lat="40.72" lon="-73.95"/>
 </rte>
 <trk><name>t1</name>
  <trkseg>
   <trkpt lat="40.60" lon="-74.05"/><trkpt lat="40.61" lon="-74.04"/>
   <trkpt lat="40.62" lon="-74.02"/>
  </trkseg>
 </trk>
</gpx>
"""


def test_gml_reader(tmp_path):
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.registry import read
    from mosaic_tpu import functions as F

    p = tmp_path / "sample.gml"
    p.write_text(_GML_DOC)
    t = read("gml").load(str(p))
    assert len(t) == 4
    assert [t.geometry.geometry_type(g) for g in range(4)] == [
        GeometryType.POLYGON, GeometryType.POINT,
        GeometryType.LINESTRING, GeometryType.MULTIPOLYGON,
    ]
    assert t.columns["name"].tolist() == ["alpha", "beta", "", ""]
    assert t.columns["pop"][0] == "120"
    a = float(np.asarray(F.st_area(t.geometry.slice(0, 1)))[0])
    np.testing.assert_allclose(a, 16.0 - 1.0, atol=1e-12)
    a2 = float(np.asarray(F.st_area(t.geometry.slice(3, 4)))[0])
    np.testing.assert_allclose(a2, 2.0, atol=1e-12)
    np.testing.assert_allclose(t.geometry.geom_xy(1), [[-73.98, 40.75]])


def test_gpx_reader(tmp_path):
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.vector import open_any

    p = tmp_path / "sample.gpx"
    p.write_text(_GPX_DOC)
    t = open_any(str(p))
    assert len(t) == 3
    assert [t.geometry.geometry_type(g) for g in range(3)] == [
        GeometryType.POINT, GeometryType.LINESTRING, GeometryType.LINESTRING,
    ]
    assert t.columns["kind"].tolist() == ["wpt", "rte", "trkseg"]
    assert t.columns["name"].tolist() == ["hq", "r1", "t1"]  # trk name rides its segments
    assert t.geometry.has_z(0)  # ele became z
    assert t.geometry.geom_xy(2).shape[0] == 3


def test_gml_edge_cases(tmp_path):
    # mixed MultiGeometry -> collection rule; 3D posList via srsDimension
    # on the Polygon; multi-segment Curve concatenation
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.gml import read_gml

    doc = """<?xml version="1.0"?>
    <c xmlns:gml="http://www.opengis.net/gml">
     <gml:featureMember><f><geom>
      <gml:MultiGeometry>
       <gml:geometryMember><gml:Point><gml:pos>9 9</gml:pos></gml:Point></gml:geometryMember>
       <gml:geometryMember><gml:Polygon><gml:exterior><gml:LinearRing>
         <gml:posList>0 0 2 0 2 2 0 2 0 0</gml:posList>
       </gml:LinearRing></gml:exterior></gml:Polygon></gml:geometryMember>
      </gml:MultiGeometry>
     </geom></f></gml:featureMember>
     <gml:featureMember><f><geom>
      <gml:Polygon srsDimension="3"><gml:exterior><gml:LinearRing>
        <gml:posList>0 0 5 4 0 5 4 4 5 0 4 5 0 0 5</gml:posList>
      </gml:LinearRing></gml:exterior></gml:Polygon>
     </geom></f></gml:featureMember>
     <gml:featureMember><f><geom>
      <gml:Curve><gml:segments>
       <gml:LineStringSegment><gml:posList>0 0 1 1</gml:posList></gml:LineStringSegment>
       <gml:LineStringSegment><gml:posList>1 1 2 0</gml:posList></gml:LineStringSegment>
      </gml:segments></gml:Curve>
     </geom></f></gml:featureMember>
     <gml:featureMember><f><geom>
      <gml:MultiGeometry>
       <gml:geometryMember><gml:Point><gml:pos>1 1</gml:pos></gml:Point></gml:geometryMember>
       <gml:geometryMember><gml:Point><gml:pos>2 2</gml:pos></gml:Point></gml:geometryMember>
      </gml:MultiGeometry>
     </geom></f></gml:featureMember>
    </c>"""
    p = tmp_path / "edge.gml"
    p.write_text(doc)
    t = read_gml(p)
    assert len(t) == 4
    g = t.geometry
    # mixed members: first-polygonal rule keeps the polygon
    assert g.geometry_type(0) == GeometryType.POLYGON
    assert g.geom_xy(0).shape[0] == 4
    # 3D ring: 4 vertices (closing dropped), z preserved
    assert g.geometry_type(1) == GeometryType.POLYGON
    assert g.geom_xy(1).shape[0] == 4
    assert g.has_z(1)
    # multi-segment curve concatenated, joint vertex deduped
    np.testing.assert_allclose(g.geom_xy(2), [[0, 0], [1, 1], [2, 0]])
    # homogeneous point members collapse to MULTIPOINT
    assert g.geometry_type(3) == GeometryType.MULTIPOINT


def test_gml_3d_poslist_without_srsdimension(tmp_path):
    # real-world GML omits srsDimension on 3-D posLists; the reader must
    # infer dim=3 when the token count divides only by 3 (9 tokens here),
    # not silently reshape to (-1, 2)
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.gml import read_gml

    doc = """<c xmlns:gml="http://www.opengis.net/gml">
     <gml:featureMember><f><geom>
      <gml:LineString><gml:posList>0 0 5 1 1 6 2 0 7</gml:posList></gml:LineString>
     </geom></f></gml:featureMember>
    </c>"""
    p = tmp_path / "nodim3d.gml"
    p.write_text(doc)
    t = read_gml(p)
    g = t.geometry
    assert g.geometry_type(0) == GeometryType.LINESTRING
    np.testing.assert_allclose(g.geom_xy(0), [[0, 0], [1, 1], [2, 0]])
    assert g.has_z(0)


def test_mif_reader(tmp_path):
    """MapInfo MIF/MID: points, lines, multi-section plines, and a holed
    region (MIF marks no holes — nesting is resolved by containment)."""
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.registry import read

    mif = """VERSION 300
Charset "WindowsLatin1"
DELIMITER ","
COLUMNS 2
  name Char(20)
  val Decimal(10,2)
DATA
POINT 10 20
  SYMBOL (34,0,12)
LINE 0 0 5 5
PLINE 3
0 0
2 2
4 0
  PEN (1,2,0)
REGION 2
  5
0 0
10 0
10 10
0 10
0 0
  4
2 2
2 4
4 2
2 2
  BRUSH (2,16777215,16777215)
PLINE MULTIPLE 2
2
0 0
1 1
2
5 5
6 6
"""
    mid = '"zoneA",1.50\n"zoneB",2\n"zoneC",3\n"zoneD",4.25\n"zoneE",5\n'
    (tmp_path / "t.mif").write_text(mif)
    (tmp_path / "t.mid").write_text(mid)
    t = read("mapinfo").load(tmp_path / "t.mif")
    assert len(t) == 5
    g = t.geometry
    assert g.geometry_type(0) == GeometryType.POINT
    np.testing.assert_allclose(g.geom_xy(0), [[10, 20]])
    assert g.geometry_type(1) == GeometryType.LINESTRING
    assert g.geometry_type(2) == GeometryType.LINESTRING
    assert g.geom_xy(2).shape[0] == 3
    # region: outer shell + contained hole
    assert g.geometry_type(3) == GeometryType.POLYGON
    from mosaic_tpu import functions as F

    area = float(np.asarray(F.st_area(t.geometry.take([3])))[0])
    assert abs(area - (100.0 - 2.0)) < 1e-9  # hole area 2 removed
    assert g.geometry_type(4) == GeometryType.MULTILINESTRING
    assert t.columns["name"][3] == "zoneD"
    assert t.columns["val"][3] == 4.25


def test_dxf_reader(tmp_path):
    """DXF entities: POINT, LINE, closed LWPOLYLINE, POLYLINE+VERTEX,
    CIRCLE tessellation; layer attribute column."""
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.registry import read

    def pairs(*kv):
        return "\n".join(str(x) for x in kv)

    doc = pairs(
        0, "SECTION", 2, "ENTITIES",
        0, "POINT", 8, "sites", 10, 3.0, 20, 4.0,
        0, "LINE", 8, "roads", 10, 0.0, 20, 0.0, 11, 5.0, 21, 5.0,
        0, "LWPOLYLINE", 8, "parcels", 70, 1,
        10, 0.0, 20, 0.0, 10, 4.0, 20, 0.0, 10, 4.0, 20, 3.0, 10, 0.0, 20, 3.0,
        0, "POLYLINE", 8, "paths", 70, 0,
        0, "VERTEX", 10, 0.0, 20, 0.0,
        0, "VERTEX", 10, 1.0, 20, 2.0,
        0, "VERTEX", 10, 2.0, 20, 0.0,
        0, "SEQEND",
        0, "CIRCLE", 8, "wells", 10, 10.0, 20, 10.0, 40, 2.0,
        0, "ENDSEC",
        0, "EOF",
    ) + "\n"
    p = tmp_path / "t.dxf"
    p.write_text(doc)
    t = read("dxf").load(p)
    assert len(t) == 5
    g = t.geometry
    assert g.geometry_type(0) == GeometryType.POINT
    assert g.geometry_type(1) == GeometryType.LINESTRING
    assert g.geometry_type(2) == GeometryType.POLYGON
    from mosaic_tpu import functions as F

    assert abs(float(np.asarray(F.st_area(g.take([2])))[0]) - 12.0) < 1e-9
    assert g.geometry_type(3) == GeometryType.LINESTRING
    assert g.geom_xy(3).shape[0] == 3
    assert g.geometry_type(4) == GeometryType.POLYGON
    circ = float(np.asarray(F.st_area(g.take([4])))[0])
    assert abs(circ - np.pi * 4.0) < 0.1  # 64-gon approximation
    assert list(t.columns["layer"]) == [
        "sites", "roads", "parcels", "paths", "wells"
    ]


def test_mif_skips_unsupported_objects_keeping_mid_alignment(tmp_path):
    """TEXT/RECT objects become empty rows (OGR-skip analog) so the .mid
    attribute rows stay aligned; a hole touching its shell still nests."""
    from mosaic_tpu.readers.registry import read

    mif = """VERSION 300
COLUMNS 1
  name Char(10)
DATA
POINT 1 2
TEXT
  "caption here"
  0 0 5 1
REGION 2
  5
0 0
8 0
8 8
0 8
0 0
  4
0 0
3 1
1 3
0 0
"""
    mid = '"a"\n"skip"\n"holed"\n'
    (tmp_path / "s.mif").write_text(mif)
    (tmp_path / "s.mid").write_text(mid)
    t = read("mif").load(tmp_path / "s.mif")
    assert len(t) == 3
    assert list(t.columns["name"]) == ["a", "skip", "holed"]
    from mosaic_tpu import functions as F

    # hole (area 4) shares vertex (0,0) with the shell — must still nest
    area = float(np.asarray(F.st_area(t.geometry.take([2])))[0])
    assert abs(area - (64.0 - 4.0)) < 1e-9


def test_mif_dxf_through_open_any(tmp_path):
    from mosaic_tpu.readers.vector import open_any

    (tmp_path / "p.mif").write_text("VERSION 300\nCOLUMNS 0\nDATA\nPOINT 7 8\n")
    assert len(open_any(tmp_path / "p.mif")) == 1
    (tmp_path / "p.dxf").write_text(
        "0\nSECTION\n2\nENTITIES\n0\nPOINT\n8\nL\n10\n1.0\n20\n2.0\n"
        "0\nENDSEC\n0\nEOF\n"
    )
    assert len(open_any(tmp_path / "p.dxf")) == 1


def _shp_record(recno: int, payload: bytes) -> bytes:
    import struct

    return struct.pack(">ii", recno, len(payload) // 2) + payload


def test_shapefile_all_shape_types_and_dbf_typing(tmp_path):
    """Hand-built .shp exercising NULL/POINT/MULTIPOINT/POLYLINE/POLYGON
    records plus .dbf C/N/F/L typing and the .prj srid sniff."""
    import struct

    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.vector import read_shapefile

    recs = []
    # null shape
    recs.append(_shp_record(1, struct.pack("<i", 0)))
    # point
    recs.append(_shp_record(2, struct.pack("<idd", 1, 3.0, 4.0)))
    # multipoint: bbox + count + 2 points
    mp = struct.pack("<i4di", 8, 0, 0, 2, 2, 2) + struct.pack(
        "<4d", 0.0, 0.0, 2.0, 2.0
    )
    recs.append(_shp_record(3, mp))
    # polyline, two parts
    pl = (
        struct.pack("<i4dii", 3, 0, 0, 5, 5, 2, 4)
        + struct.pack("<2i", 0, 2)
        + struct.pack("<8d", 0, 0, 1, 1, 2, 2, 3, 1)
    )
    recs.append(_shp_record(4, pl))
    # polygon: CW shell + CCW hole (closed rings)
    shell = [(0, 0), (0, 8), (8, 8), (8, 0), (0, 0)]  # CW (area<0 shoelace)
    hole = [(2, 2), (4, 2), (4, 4), (2, 4), (2, 2)]  # CCW
    pts = shell + hole
    pg = (
        struct.pack("<i4dii", 5, 0, 0, 8, 8, 2, len(pts))
        + struct.pack("<2i", 0, len(shell))
        + b"".join(struct.pack("<2d", x, y) for x, y in pts)
    )
    recs.append(_shp_record(5, pg))
    body = b"".join(recs)
    hdr = struct.pack(">i", 9994) + b"\0" * 20 + struct.pack(
        ">i", (100 + len(body)) // 2
    ) + struct.pack("<ii", 1000, 0) + struct.pack("<8d", 0, 0, 8, 8, 0, 0, 0, 0)
    (tmp_path / "t.shp").write_bytes(hdr + body)

    # dbf: name C(6), n N(6,0), f F(8,2), flag L(1)
    def field(name, ftype, flen, fdec):
        return name.ljust(11, "\0").encode() + ftype.encode() + b"\0" * 4 + bytes(
            [flen, fdec]
        ) + b"\0" * 14

    fields = field("name", "C", 6, 0) + field("n", "N", 6, 0) + field(
        "f", "F", 8, 2
    ) + field("flag", "L", 1, 0)
    rec_len = 1 + 6 + 6 + 8 + 1
    rows = b""
    for k in range(5):
        rows += b" " + f"r{k}".ljust(6).encode() + str(k).rjust(6).encode() + (
            f"{k + 0.5:8.2f}".encode()
        ) + (b"T" if k % 2 else b"F")
    hdr_len = 32 + 4 * 32 + 1
    dbf = (
        bytes([3, 126, 1, 1])
        + struct.pack("<IHH", 5, hdr_len, rec_len)
        + b"\0" * 20
        + fields
        + b"\x0d"
        + rows
    )
    (tmp_path / "t.dbf").write_bytes(dbf)
    (tmp_path / "t.prj").write_text('PROJCS["OSGB 1936 / British National Grid"]')

    t = read_shapefile(str(tmp_path / "t.shp"))
    g = t.geometry
    assert len(t) == 5
    assert g.geometry_type(1) == GeometryType.POINT
    assert g.geometry_type(2) == GeometryType.MULTIPOINT
    assert g.geometry_type(3) == GeometryType.MULTILINESTRING
    assert g.geometry_type(4) == GeometryType.POLYGON
    assert (np.asarray(g.srid) == 27700).all()  # .prj sniffed
    from mosaic_tpu import functions as F

    area = float(np.asarray(F.st_area(g.take([4])))[0])
    assert abs(area - (64.0 - 4.0)) < 1e-9  # hole subtracted
    assert t.columns["n"].dtype == np.int64 and t.columns["n"][3] == 3
    assert t.columns["f"].dtype == np.float64 and t.columns["f"][2] == 2.5
    assert t.columns["flag"].dtype == bool and list(t.columns["flag"][:2]) == [
        False, True,
    ]
    assert t.columns["name"][0] == "r0"


# ---------------------------------------------------------------- TopoJSON
def test_topojson_quantized_shared_arc(tmp_path):
    """Two unit squares sharing a delta-encoded arc; the right square
    traverses it reversed (~0). Decoded areas and the junction-point
    dedup are asserted against hand-computed coordinates."""
    import json

    from mosaic_tpu import functions as F
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.registry import read

    topo = {
        "type": "Topology",
        "transform": {"scale": [0.001, 0.001], "translate": [0.0, 0.0]},
        "arcs": [
            [[1000, 0], [0, 1000]],                                # shared
            [[1000, 1000], [-1000, 0], [0, -1000], [1000, 0]],     # left
            [[1000, 0], [1000, 0], [0, 1000], [-1000, 0]],         # right
        ],
        "objects": {
            "squares": {
                "type": "GeometryCollection",
                "geometries": [
                    {"type": "Polygon", "arcs": [[0, 1]],
                     "properties": {"name": "L"}},
                    {"type": "Polygon", "arcs": [[2, -1]],
                     "properties": {"name": "R"}},
                ],
            },
            "site": {"type": "Point", "coordinates": [500, 500],
                     "properties": {"name": "P"}},
        },
    }
    p = tmp_path / "t.topojson"
    p.write_text(json.dumps(topo))
    t = read("topojson").load(str(p))
    assert len(t) == 3
    g = t.geometry
    assert g.geometry_type(0) == GeometryType.POLYGON
    # left ring: stitched (1,0),(1,1),(0,1),(0,0) — junction appears once
    np.testing.assert_allclose(
        g.geom_xy(0), [[1, 0], [1, 1], [0, 1], [0, 0]], atol=1e-12
    )
    areas = np.asarray(F.st_area(g))
    np.testing.assert_allclose(areas[:2], [1.0, 1.0], atol=1e-12)
    # quantized Point positions are absolute, not deltas
    assert g.geometry_type(2) == GeometryType.POINT
    np.testing.assert_allclose(g.geom_xy(2), [[0.5, 0.5]], atol=1e-12)
    assert list(t.columns["layer"]) == ["squares", "squares", "site"]
    assert list(t.columns["name"]) == ["L", "R", "P"]
    # layer selection mirrors OGR's per-object layers
    only = read("topojson").option("layer", "site").load(str(p))
    assert len(only) == 1 and only.columns["layer"][0] == "site"
    with pytest.raises(ValueError, match="no such TopoJSON object"):
        read("topojson").option("layer", "nope").load(str(p))


def test_topojson_unquantized_hole_line_and_open_any(tmp_path):
    """No transform: arc positions are absolute floats (no cumsum). A
    holed polygon and a two-arc line round-trip; open_any dispatches on
    the .topojson suffix."""
    import json

    from mosaic_tpu import functions as F
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.vector import open_any

    topo = {
        "type": "Topology",
        "arcs": [
            [[0.0, 0.0], [4.0, 0.0], [4.0, 4.0], [0.0, 4.0], [0.0, 0.0]],
            [[1.0, 1.0], [1.0, 2.0], [2.0, 2.0], [2.0, 1.0], [1.0, 1.0]],
            [[0.0, 0.0], [1.0, 1.0]],
            [[1.0, 1.0], [3.0, 1.0]],
        ],
        "objects": {
            "poly": {"type": "Polygon", "arcs": [[0], [1]]},
            "path": {"type": "LineString", "arcs": [2, 3]},
        },
    }
    p = tmp_path / "h.topojson"
    p.write_text(json.dumps(topo))
    t = open_any(str(p))
    assert len(t) == 2
    area = float(np.asarray(F.st_area(t.geometry.take([0])))[0])
    assert abs(area - (16.0 - 1.0)) < 1e-12
    assert t.geometry.geometry_type(1) == GeometryType.LINESTRING
    np.testing.assert_allclose(
        t.geometry.geom_xy(1), [[0, 0], [1, 1], [3, 1]], atol=1e-12
    )


def test_csv_wkt_reader(tmp_path):
    """OGR CSV-driver analog: a WKT geometry column plus attributes."""
    from mosaic_tpu import functions as F
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.registry import read

    p = tmp_path / "t.csv"
    p.write_text(
        'id,wkt,score\n'
        '1,"POINT (3 4)",0.5\n'
        '2,"POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))",1.5\n'
        '3,"LINESTRING (0 0, 1 1)",2.5\n'
    )
    t = read("csv_wkt").load(str(p))
    assert len(t) == 3
    g = t.geometry
    assert g.geometry_type(0) == GeometryType.POINT
    assert g.geometry_type(1) == GeometryType.POLYGON
    assert float(np.asarray(F.st_area(g.take([1])))[0]) == 4.0
    assert int(g.srid[0]) == 4326
    assert list(t.columns["id"]) == ["1", "2", "3"]
    assert list(t.columns["score"]) == ["0.5", "1.5", "2.5"]
    with pytest.raises(ValueError, match="no column"):
        read("csv_wkt").option("wktCol", "geom").load(str(p))


# -------------------------------------------------------------- FlatGeobuf
def test_flatgeobuf_roundtrip_all_types(tmp_path):
    """Writer->reader round-trip across every geometry type, with typed
    attribute columns. Both ends hand-speak the flatbuffers wire format;
    coordinates must survive bit-exactly (f64 end to end)."""
    from mosaic_tpu.functions.formats import st_astext
    from mosaic_tpu.core.geometry import wkt as W
    from mosaic_tpu.readers.flatgeobuf import read_flatgeobuf, write_flatgeobuf
    from mosaic_tpu.readers.registry import read
    from mosaic_tpu.readers.vector import VectorTable

    wkts = [
        "POINT (3 4)",
        "LINESTRING (0 0, 1 1, 2 0)",
        "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 1 2, 2 2, 2 1, 1 1))",
        "MULTIPOINT ((0 0), (1 2))",
        "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3, 4 2))",
        "MULTIPOLYGON (((0 0, 2 0, 2 2, 0 2, 0 0)), "
        "((5 5, 7 5, 7 7, 5 7, 5 5), (5.5 5.5, 5.5 6, 6 6, 6 5.5, 5.5 5.5)))",
    ]
    cols = {
        "name": np.asarray([f"f{i}" for i in range(len(wkts))], dtype=object),
        "score": np.asarray([0.5 * i for i in range(len(wkts))]),
    }
    p = str(tmp_path / "t.fgb")
    write_flatgeobuf(p, VectorTable(geometry=W.from_wkt(wkts), columns=cols))
    r = read_flatgeobuf(p)
    assert len(r) == len(wkts)

    def norm(s):
        return s.replace(", ", ",")

    for want, got in zip(wkts, st_astext(r.geometry)):
        assert norm(want) == norm(got)
    assert list(r.columns["name"]) == [f"f{i}" for i in range(len(wkts))]
    np.testing.assert_allclose(r.columns["score"], cols["score"])
    assert r.geometry.srid[0] == 4326
    # registry + suffix dispatch
    from mosaic_tpu.readers.vector import open_any

    assert len(read("flatgeobuf").load(p)) == len(wkts)
    assert len(open_any(p)) == len(wkts)


def test_flatgeobuf_coordinates_bit_exact(tmp_path):
    """Irrational coordinates survive the f64 vectors bit for bit."""
    from mosaic_tpu.core.types import GeometryBuilder, GeometryType
    from mosaic_tpu.readers.flatgeobuf import read_flatgeobuf, write_flatgeobuf
    from mosaic_tpu.readers.vector import VectorTable

    rng = np.random.default_rng(42)
    xy = rng.uniform(-180, 180, (7, 2))
    b = GeometryBuilder()
    b.add_ring(xy)
    b.end_part()
    b.end_geom(GeometryType.LINESTRING, 4326)
    p = str(tmp_path / "bits.fgb")
    write_flatgeobuf(p, VectorTable(geometry=b.build(), columns={}))
    r = read_flatgeobuf(p)
    got = r.geometry.geom_xy(0)
    assert (got == xy).all()  # bit-exact, no tolerance


def test_flatgeobuf_header_and_errors(tmp_path):
    from mosaic_tpu.readers.flatgeobuf import (
        _index_bytes,
        read_flatgeobuf,
        write_flatgeobuf,
    )

    # packed-R-tree size recurrence (spec): 100 leaves at node 16 ->
    # 100 + 7 + 1 nodes of 40 bytes
    assert _index_bytes(100, 16) == 108 * 40
    assert _index_bytes(0, 16) == 0
    assert _index_bytes(5, 0) == 0  # no index
    bad = tmp_path / "bad.fgb"
    bad.write_bytes(b"nonsense")
    with pytest.raises(ValueError, match="not a FlatGeobuf"):
        read_flatgeobuf(str(bad))
    # truncated feature count: header promises more features than present
    from mosaic_tpu.core.geometry import wkt as W
    from mosaic_tpu.readers.vector import VectorTable

    p = str(tmp_path / "t.fgb")
    write_flatgeobuf(p, VectorTable(
        geometry=W.from_wkt(["POINT (1 2)"] * 3), columns={}
    ))
    whole = open(p, "rb").read()
    # chop the last feature frame off
    import struct as _s

    cut = whole
    # walk frames to find the final feature start
    q = 8
    (hl,) = _s.unpack_from("<I", cut, q)
    q += 4 + hl
    starts = []
    while q < len(cut):
        starts.append(q)
        (fl,) = _s.unpack_from("<I", cut, q)
        q += 4 + fl
    open(p, "wb").write(cut[: starts[-1]])
    with pytest.raises(ValueError, match="promises 3 features"):
        read_flatgeobuf(p)


def test_flatgeobuf_null_geometry_and_trailing_bytes(tmp_path):
    """Empty collections (the null-geometry marker) round-trip as
    null-geometry features; trailing bytes after the promised feature
    count are ignored, but a truncated frame errors loudly."""
    from mosaic_tpu.core.geometry import wkt as W
    from mosaic_tpu.core.types import GeometryType
    from mosaic_tpu.readers.flatgeobuf import read_flatgeobuf, write_flatgeobuf
    from mosaic_tpu.readers.vector import VectorTable

    wkts = ["POINT (1 2)", "GEOMETRYCOLLECTION EMPTY", "POINT (3 4)"]
    p = str(tmp_path / "n.fgb")
    write_flatgeobuf(p, VectorTable(geometry=W.from_wkt(wkts), columns={}))
    r = read_flatgeobuf(p)
    assert len(r) == 3
    assert r.geometry.geometry_type(1) == GeometryType.GEOMETRYCOLLECTION
    np.testing.assert_allclose(r.geometry.geom_xy(2), [[3, 4]])
    # trailing garbage after the promised count is not a frame
    with open(p, "ab") as f:
        f.write(b"\x00\x01\x02\x03\x04\x05")
    assert len(read_flatgeobuf(p)) == 3
    # a frame length overrunning the file is a loud error
    whole = open(p, "rb").read()
    open(p, "wb").write(whole[:-10])
    with pytest.raises(ValueError):
        read_flatgeobuf(p)


def test_flatgeobuf_z_roundtrip(tmp_path):
    """3D geometries keep their Z through write->read (header has_z flag
    + slot-2 z vectors, closed in step with polygon rings)."""
    from mosaic_tpu.core.geometry import wkt as W
    from mosaic_tpu.readers.flatgeobuf import read_flatgeobuf, write_flatgeobuf
    from mosaic_tpu.readers.vector import VectorTable

    wkts = [
        "POINT Z (1 2 7)",
        "LINESTRING Z (0 0 1, 1 1 2, 2 0 3)",
        "POLYGON Z ((0 0 5, 4 0 6, 4 4 7, 0 4 8, 0 0 5))",
    ]
    p = str(tmp_path / "z.fgb")
    write_flatgeobuf(p, VectorTable(geometry=W.from_wkt(wkts), columns={}))
    r = read_flatgeobuf(p)
    g = r.geometry
    assert all(g.has_z(i) for i in range(3))
    np.testing.assert_allclose(g.ring_z(0), [7.0])
    np.testing.assert_allclose(g.ring_z(1), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(g.ring_z(2), [5.0, 6.0, 7.0, 8.0])
    # 2D rows written alongside 3D stay 2D (per-geometry z emission)
    p2 = str(tmp_path / "mix.fgb")
    write_flatgeobuf(p2, VectorTable(
        geometry=W.from_wkt(["POINT Z (1 2 7)", "POINT (3 4)"]), columns={}
    ))
    r2 = read_flatgeobuf(p2)
    assert r2.geometry.has_z(0) and not r2.geometry.has_z(1)


def test_write_shapefile_round_trip(tmp_path):
    """write_shapefile -> read_shapefile: geometry, typed DBF columns
    (N/C/L), NULL shapes for empties, ring orientation (shp CW shells)."""
    import numpy as np

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.readers.vector import (
        VectorTable,
        read_shapefile,
        write_shapefile,
    )

    col = wkt.from_wkt([
        "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 2 4, 4 4, 4 2, 2 2))",
        "MULTIPOLYGON (((20 0, 30 0, 25 9, 20 0)), ((40 0, 50 0, 45 9, 40 0)))",
        "POLYGON EMPTY",
    ])
    t = VectorTable(
        geometry=col,
        columns={
            "name": np.asarray(["a", "b", "c"], object),
            "v": np.asarray([1.25, -2.5, 3.0]),
            "n": np.asarray([7, 8, 9], np.int64),
            "f": np.asarray([True, False, True]),
        },
    )
    p = tmp_path / "zones.shp"
    write_shapefile(str(p), t)
    r = read_shapefile(str(p))
    assert len(r) == 3
    assert list(r.columns["name"]) == ["a", "b", "c"]
    np.testing.assert_allclose(r.columns["v"], t.columns["v"])
    np.testing.assert_array_equal(r.columns["n"], t.columns["n"])
    np.testing.assert_array_equal(r.columns["f"], t.columns["f"])
    from mosaic_tpu.core.geometry import oracle

    # same containment behavior after the round trip (vertex order may
    # rotate; the polygon must not change)
    pts = np.asarray([[5.0, 5.0], [3.0, 3.0], [25.0, 3.0], [45.0, 3.0]])
    for g in range(2):
        np.testing.assert_array_equal(
            oracle.contains_points(r.geometry, g, pts),
            oracle.contains_points(col, g, pts),
        )
    assert r.geometry.geom_xy(2).shape[0] == 0


def test_write_geojson_seq_round_trip(tmp_path):
    import numpy as np

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.readers import read, write_geojson
    from mosaic_tpu.readers.vector import VectorTable

    col = wkt.from_wkt(["POINT (1 2)", "LINESTRING (0 0, 2 3)"])
    t = VectorTable(
        geometry=col, columns={"v": np.asarray([np.nan, 2.0])}
    )
    p = tmp_path / "x.geojsonl"
    write_geojson(str(p), t, seq=True)
    r = read("geojsonseq").load(str(p))
    assert len(r) == 2 and np.isnan(r.columns["v"][0])
    assert "LINESTRING" in wkt.to_wkt(r.geometry)[1]


def test_write_registry_round_trips(tmp_path):
    """write(fmt).save -> read(fmt).load across every registered writer."""
    import numpy as np

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.readers import read, write
    from mosaic_tpu.readers.vector import VectorTable

    col = wkt.from_wkt(
        ["POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))", "POLYGON ((5 5, 9 5, 9 9, 5 9, 5 5))"]
    )
    t = VectorTable(
        geometry=col,
        columns={"v": np.asarray([1.5, 2.5])},
    )
    cases = {
        "geojson": "a.geojson",
        "geojsonseq": "a.geojsonl",
        "shapefile": "a.shp",
        "flatgeobuf": "a.fgb",
        "geopackage": "a.gpkg",
    }
    for fmt, name in cases.items():
        p = str(tmp_path / name)
        write(fmt).save(p, t)
        r = read(fmt).load(p)
        assert len(r) == 2, fmt
        np.testing.assert_allclose(
            np.sort(np.asarray(r.columns["v"], float)), [1.5, 2.5],
            err_msg=fmt,
        )
        ws = " ".join(wkt.to_wkt(r.geometry))
        assert ws.count("POLYGON") == 2, (fmt, ws)


def test_osm_reader(tmp_path):
    """OSM XML: tagged nodes -> points, closed area-tagged ways ->
    polygons, highways stay lines, multipolygon relations chain their
    member ways into rings (reference: the OGR OSM driver behind
    OGRFileFormat.scala:26-47)."""
    import numpy as np

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.readers import read

    osm = """<?xml version='1.0'?>
<osm version="0.6">
 <node id="1" lat="40.0" lon="-74.0"><tag k="amenity" v="cafe"/></node>
 <node id="2" lat="40.001" lon="-74.0"/>
 <node id="3" lat="40.001" lon="-73.999"/>
 <node id="4" lat="40.0" lon="-73.999"/>
 <node id="5" lat="40.0" lon="-74.0"/>
 <way id="100"><nd ref="5"/><nd ref="2"/><nd ref="3"/><nd ref="4"/>
   <nd ref="5"/><tag k="building" v="yes"/></way>
 <way id="101"><nd ref="2"/><nd ref="3"/>
   <tag k="highway" v="residential"/></way>
 <way id="200"><nd ref="5"/><nd ref="2"/><nd ref="3"/></way>
 <way id="201"><nd ref="3"/><nd ref="4"/><nd ref="5"/></way>
 <relation id="300"><tag k="type" v="multipolygon"/>
   <member type="way" ref="200" role="outer"/>
   <member type="way" ref="201" role="outer"/></relation>
</osm>"""
    p = tmp_path / "x.osm"
    p.write_text(osm)
    t = read("osm").load(str(p))
    kinds = list(t.columns["kind"])
    assert kinds == ["point", "polygon", "line", "multipolygon"]
    assert list(t.columns["osm_id"]) == [1, 100, 101, 300]
    w = wkt.to_wkt(t.geometry)
    assert w[0].startswith("POINT") and w[1].startswith("POLYGON")
    from mosaic_tpu.core.geometry import oracle

    # the relation's chained rings enclose the same square as way 100
    inside = oracle.contains_points(
        t.geometry, 3, np.asarray([[-73.9995, 40.0005]])
    )
    assert bool(inside[0])


def test_write_kml_round_trip(tmp_path):
    import numpy as np

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.readers import read, write
    from mosaic_tpu.readers.vector import VectorTable

    col = wkt.from_wkt([
        "POLYGON ((0 0, 5 0, 5 5, 0 5, 0 0), (1 1, 1 2, 2 2, 2 1, 1 1))",
        "MULTIPOLYGON (((10 0, 12 0, 11 2, 10 0)), ((20 0, 22 0, 21 2, 20 0)))",
        "LINESTRING (0 0, 2 3)",
    ])
    t = VectorTable(
        geometry=col,
        columns={
            "nm": np.asarray(["a", "b", "c"], object),
            "v": np.asarray([1.5, 2.5, 3.5]),
        },
    )
    p = str(tmp_path / "x.kml")
    write("kml").option("name_col", "nm").save(p, t)
    r = read("kml").load(p)
    assert len(r) == 3
    w = wkt.to_wkt(r.geometry)
    assert w[0].startswith("POLYGON") and "1 1" in w[0]  # hole survives
    assert w[1].startswith("MULTIPOLYGON")
    assert list(r.columns["name"]) == ["a", "b", "c"]
    np.testing.assert_allclose(
        np.asarray(r.columns["v"], float), [1.5, 2.5, 3.5]
    )


def test_osm_closed_waterway_and_place_are_polygons(tmp_path):
    """`waterway` and `place` are SEPARATE area keys: the seed's missing
    comma concatenated them into one bogus "waterwayplace" key, so a
    closed riverbank way came back as a line (ADVICE.md)."""
    from mosaic_tpu.readers import read

    osm = """<?xml version='1.0'?>
<osm version="0.6">
 <node id="1" lat="40.0" lon="-74.0"/>
 <node id="2" lat="40.001" lon="-74.0"/>
 <node id="3" lat="40.001" lon="-73.999"/>
 <node id="4" lat="40.0" lon="-73.999"/>
 <way id="10"><nd ref="1"/><nd ref="2"/><nd ref="3"/><nd ref="4"/>
   <nd ref="1"/><tag k="waterway" v="riverbank"/></way>
 <way id="11"><nd ref="1"/><nd ref="2"/><nd ref="3"/><nd ref="4"/>
   <nd ref="1"/><tag k="place" v="island"/></way>
</osm>"""
    p = tmp_path / "water.osm"
    p.write_text(osm)
    t = read("osm").load(str(p))
    assert list(t.columns["kind"]) == ["polygon", "polygon"]


def test_write_kml_quoted_attribute_round_trip(tmp_path):
    """Column names land in Data name="..." attributes: quotes must be
    escaped quoteattr-style or the attribute terminates early."""
    import numpy as np

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.readers.kml import read_kml, write_kml
    from mosaic_tpu.readers.vector import VectorTable

    col = wkt.from_wkt(["POINT (1 2)", "POINT (3 4)"])
    quoted = 'he said "hi" & <ok>\'s'
    t = VectorTable(
        geometry=col,
        columns={quoted: np.asarray(["a\"b", "c'd"], object)},
    )
    p = str(tmp_path / "q.kml")
    write_kml(p, t)
    r = read_kml(p)
    assert quoted in r.columns
    assert list(r.columns[quoted]) == ['a"b', "c'd"]
