"""Durable streaming contracts (ISSUE 3): checkpoint/resume, watchdog,
poisoned-input quarantine, and stream-aware fault injection.

The acceptance criteria pinned here, all on CPU:

1. **kill-and-resume ≡ clean run, bit-identically** — interrupting a
   `StreamJoin.run_durable` after ANY snapshot boundary and resuming
   from the run directory yields the exact final (checksum, matches,
   overflow) of an uninterrupted run, under every injected fault plan
   (fatal kill, transient errors, corrupt snapshot on disk).
2. **quarantine exactness** — injected NaN/Inf/out-of-bounds rows
   appear exactly (and only) in the quarantine report; admitted-row
   results are bit-identical to the clean ring's rows, and the final
   fold equals the clean fold with the poison rows' contributions
   removed (parked rows contribute exactly zero).
3. **watchdog** — an injected stall becomes a typed
   `StalledDeviceError` that the retry layer recovers within budget:
   no hang, no silent partial stats.
4. **degradation visibility** — a segment that exhausts its retry
   budget answers from the f64 host oracle and surfaces
   ``metrics["degraded"]`` at the stream level (satellite: never
   vanishing into the fold).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.runtime import (
    RetryPolicy,
    StalledDeviceError,
    TransientDeviceError,
    backoff_delays,
    checkpoint,
    faults,
    is_transient,
    quarantine,
    telemetry,
    watchdog,
)
from mosaic_tpu.sql.join import build_chip_index
from mosaic_tpu.sql.stream import StreamJoin, fold_stats_np, ring_from_host

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3
ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), "
    "(5 5, 5 8, 8 8, 8 5, 5 5))",
    "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
]
K, BATCH, NB = 3, 1024, 7
SNAP = 2  # snapshot every 2 ring cycles -> boundaries at 2, 4, 6, 7
BOUNDS = (-25.0, -25.0, 35.0, 20.0)
FAST = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)


@pytest.fixture(scope="module")
def index():
    col = wkt.from_wkt(ZONES)
    return build_chip_index(
        tessellate(col, CUSTOM, RES, keep_core_geoms=False)
    )


@pytest.fixture(scope="module")
def batches():
    rng = np.random.default_rng(7)
    return [
        rng.uniform(BOUNDS[:2], BOUNDS[2:], (BATCH, 2)) for _ in range(K)
    ]


@pytest.fixture(scope="module")
def ring(batches):
    return ring_from_host(batches)


@pytest.fixture(scope="module")
def sj(index):
    return StreamJoin(index, CUSTOM, RES, prefetch=True)


@pytest.fixture(scope="module")
def clean(sj, ring):
    return sj.run(ring, NB, collect=True)


def _stats(r):
    return (r.checksum, r.matches, r.overflow)


# ------------------------------------------------------------ checkpoint


def test_durable_run_equals_plain_run(sj, ring, clean, tmp_path):
    r = sj.run_durable(
        ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
        retry_policy=FAST,
    )
    assert _stats(r) == _stats(clean)
    assert r.metrics["degraded"] is False
    assert r.metrics["snapshots"] == 4  # boundaries 2, 4, 6, 7
    assert checkpoint.list_snapshots(str(tmp_path)) == [2, 4, 6, 7]


def test_durable_non_prefetch_equals_plain_run(index, ring, clean, tmp_path):
    sj0 = StreamJoin(index, CUSTOM, RES, prefetch=False)
    r = sj0.run_durable(
        ring, NB, run_dir=str(tmp_path), snapshot_every=3,
        retry_policy=FAST,
    )
    assert _stats(r) == _stats(clean)


@pytest.mark.parametrize("kill_after", [1, 2, 3])
def test_kill_and_resume_bit_identical(sj, ring, clean, tmp_path, kill_after):
    """A fatal (non-transient) device loss after ``kill_after`` segments
    aborts the run; resume() from the last snapshot converges to the
    clean run's exact final stats."""
    d = str(tmp_path / f"kill{kill_after}")
    with faults.inject(
        fail_first=99, skip_first=kill_after,
        sites=("stream.scan_step",),
        exc_factory=lambda s: RuntimeError(f"simulated device loss @ {s}"),
    ):
        with pytest.raises(RuntimeError, match="simulated device loss"):
            sj.run_durable(
                ring, NB, run_dir=d, snapshot_every=SNAP,
                retry_policy=FAST,
            )
    assert checkpoint.list_snapshots(d)  # at least one boundary persisted
    r = sj.resume(d, ring, retry_policy=FAST)
    assert _stats(r) == _stats(clean)
    assert r.metrics["resumed_from"] == kill_after * SNAP


def test_resume_skips_corrupt_snapshot(sj, ring, clean, tmp_path):
    """Bit rot / a kill mid-write on the NEWEST snapshot must fall back
    to the previous valid boundary, not fail the resume."""
    d = str(tmp_path)
    with faults.inject(
        fail_first=99, skip_first=2, sites=("stream.scan_step",),
        exc_factory=lambda s: RuntimeError("simulated device loss"),
    ):
        with pytest.raises(RuntimeError):
            sj.run_durable(
                ring, NB, run_dir=d, snapshot_every=SNAP,
                retry_policy=FAST,
            )
    steps = checkpoint.list_snapshots(d)
    assert steps == [2, 4]
    # truncate the newest npz: its sidecar hash no longer matches
    with open(os.path.join(d, "snap-00000004.npz"), "r+b") as f:
        f.truncate(64)
    with telemetry.capture() as ev:
        r = sj.resume(d, ring, retry_policy=FAST)
    assert _stats(r) == _stats(clean)
    assert r.metrics["resumed_from"] == 2
    kinds = [e["event"] for e in ev]
    assert "snapshot_corrupt_skipped" in kinds
    assert "snapshot_resumed" in kinds


def test_resume_rejects_wrong_ring(sj, ring, tmp_path):
    d = str(tmp_path)
    sj.run_durable(
        ring, NB, run_dir=d, snapshot_every=SNAP, retry_policy=FAST
    )
    other = jnp.asarray(np.asarray(ring) + 1.0)
    with pytest.raises(ValueError, match="fingerprint"):
        sj.resume(d, other, retry_policy=FAST)


def test_resume_without_snapshots_raises(sj, ring, tmp_path):
    with pytest.raises(FileNotFoundError):
        sj.resume(str(tmp_path / "empty"), ring)


def test_snapshot_atomicity_and_checksum_roundtrip(tmp_path):
    d = str(tmp_path)
    arrays = {"acc": np.arange(3, dtype=np.int32), "cells": np.arange(8)}
    checkpoint.save_snapshot(d, 5, arrays, {"n_batches": 9})
    loaded = checkpoint.load_latest(d)
    assert loaded is not None
    step, arrs, meta = loaded
    assert step == 5 and meta["n_batches"] == 9
    np.testing.assert_array_equal(arrs["acc"], arrays["acc"])
    np.testing.assert_array_equal(arrs["cells"], arrays["cells"])
    assert not [p for p in os.listdir(d) if p.endswith(".tmp")]


# --------------------------------------------------- transient + degraded


def test_transient_scan_faults_retry_to_clean(sj, ring, clean, tmp_path):
    with telemetry.capture() as ev:
        with faults.transient_errors(2, sites=("stream.scan_step",)):
            r = sj.run_durable(
                ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                retry_policy=FAST,
            )
    assert _stats(r) == _stats(clean)
    assert r.metrics["degraded"] is False
    assert [e["event"] for e in ev].count("transient_retry") == 2


def test_exhausted_segment_degrades_to_host_oracle(sj, ring, clean, tmp_path):
    """Satellite: DegradedResult-style degradation must surface in the
    STREAM metrics, never vanish into the fold. The degraded segment is
    answered by the f64 host oracle; on this fixture the oracle agrees
    with the device bit-for-bit, so the final stats still equal clean."""
    with telemetry.capture() as ev:
        with faults.transient_errors(
            3, sites=("stream.scan_step",)
        ):  # == FAST.max_attempts: the first segment's budget exhausts
            r = sj.run_durable(
                ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                retry_policy=FAST,
            )
    assert r.metrics["degraded"] is True
    assert r.metrics["degraded_segments"] == 1
    assert _stats(r) == _stats(clean)
    kinds = [e["event"] for e in ev]
    assert "degraded" in kinds


def test_snapshot_failure_does_not_kill_run(sj, ring, clean, tmp_path):
    """A sick disk (every snapshot write failing) coarsens durability,
    but the stream still converges with the snapshot_skipped trail."""
    with telemetry.capture() as ev:
        with faults.transient_errors(999, sites=("stream.snapshot",)):
            r = sj.run_durable(
                ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                retry_policy=FAST,
            )
    assert _stats(r) == _stats(clean)
    assert r.metrics["snapshots"] == 0
    assert [e["event"] for e in ev].count("snapshot_skipped") == 4


# -------------------------------------------------------------- watchdog


def test_watchdog_guard_raises_typed_stall(monkeypatch):
    monkeypatch.setenv("MOSAIC_WATCHDOG_UNIT_SITE", "0.05")
    with telemetry.capture() as ev:
        with pytest.raises(StalledDeviceError) as ei:
            with faults.stalls(0.5, sites=("unit.site",)):
                watchdog.guard("unit.site", lambda: 42)
    assert ei.value.site == "unit.site"
    assert ei.value.deadline_s == pytest.approx(0.05)
    assert is_transient(ei.value)  # stalls ride the retry path
    assert isinstance(ei.value, TransientDeviceError)
    assert any(e["event"] == "watchdog_stall" for e in ev)


def test_watchdog_inline_when_disabled(monkeypatch):
    monkeypatch.delenv("MOSAIC_WATCHDOG_S", raising=False)
    assert watchdog.guard("no.deadline", lambda: 7) == 7
    assert watchdog.deadline_for("no.deadline") is None
    monkeypatch.setenv("MOSAIC_WATCHDOG_S", "3.5")
    assert watchdog.deadline_for("any.site") == 3.5
    monkeypatch.setenv("MOSAIC_WATCHDOG_ANY_SITE", "0")  # 0 disables
    assert watchdog.deadline_for("any.site") is None


def test_watchdog_stall_recovered_by_retry(sj, ring, clean, tmp_path,
                                           monkeypatch):
    """Acceptance: an injected mid-stream stall becomes a typed
    StalledDeviceError the retry layer recovers — the run completes with
    full, exact stats and the stall is visible in telemetry."""
    monkeypatch.setenv("MOSAIC_WATCHDOG_STREAM_SCAN_STEP", "0.15")
    with telemetry.capture() as ev:
        with faults.stalls(1.2, n=1, sites=("stream.scan_step",)):
            r = sj.run_durable(
                ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                retry_policy=FAST,
            )
    assert _stats(r) == _stats(clean)
    assert r.metrics["degraded"] is False
    kinds = [e["event"] for e in ev]
    assert "fault_stall_injected" in kinds
    assert "watchdog_stall" in kinds
    assert "transient_retry" in kinds


# ------------------------------------------------------------ quarantine


def test_quarantine_exact_poison_set(sj, batches, clean):
    """Injected poison rows appear exactly (and only) in the quarantine;
    admitted rows' results are bit-identical to the clean ring's, and
    the final fold equals the clean fold minus the poison rows'
    contributions (parked rows contribute exactly zero)."""
    poisoned = [b.copy() for b in batches]
    poison = [(0, 3), (1, 5), (1, 6), (2, 100)]
    for bi, row in poison[:3]:
        poisoned[bi][row] = np.nan
    poisoned[2][100] = (1e6, 1e6)  # finite but far out of CRS bounds
    with telemetry.capture() as ev:
        ring_q, rep = sj.admit(poisoned, bounds=BOUNDS)
    assert rep.n_quarantined == 4
    assert sorted(rep.rows) == sorted(poison)
    assert rep.reasons["nonfinite"] == 3
    assert rep.reasons["out_of_bounds"] == 1
    assert rep.buffer.shape == (4, 2)
    assert any(e["event"] == "stream_quarantine" for e in ev)

    r = sj.run(ring_q, NB, collect=True)
    # admitted rows row-for-row identical to the clean ring's results
    mask = np.zeros((NB, BATCH), dtype=bool)
    for i in range(NB):
        for bi, row in poison:
            if i % K == bi:
                mask[i, row] = True
    np.testing.assert_array_equal(r.outs[~mask], clean.outs[~mask])
    # parked rows miss: exactly -1, zero fold contribution
    assert (r.outs[mask] == -1).all()
    want = fold_stats_np(np.where(mask, -1, clean.outs))
    assert (r.checksum & 0xFFFFFFFF) == (int(want[0]) & 0xFFFFFFFF)
    assert r.matches == int(want[1]) and r.overflow == int(want[2])


def test_quarantine_via_fault_injection(sj, batches):
    """faults.corrupt_batches poisons admission inputs; the quarantine
    must catch exactly the corrupted rows and never mutate the caller's
    arrays."""
    originals = [b.copy() for b in batches]
    with faults.corrupt_batches(rows=4, n=1, sites=("stream.admit",)):
        ring_q, rep = sj.admit(batches, bounds=BOUNDS)
    for b, o in zip(batches, originals):
        np.testing.assert_array_equal(b, o)  # inputs untouched
    assert rep.n_quarantined == 4
    assert rep.rows == [(0, 0), (0, 1), (0, 2), (0, 3)]
    assert rep.reasons["nonfinite"] == 4


def test_quarantine_metrics_surface_in_durable_run(sj, batches, tmp_path):
    poisoned = [b.copy() for b in batches]
    poisoned[0][0] = np.inf
    ring_q, rep = sj.admit(poisoned, bounds=BOUNDS)
    r = sj.run_durable(
        ring_q, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
        retry_policy=FAST,
    )
    assert r.metrics["quarantined"] == 1
    assert r.metrics["quarantine_reasons"] == {"nonfinite": 1}


def test_clean_admission_is_bit_identical_to_ring_from_host(sj, batches,
                                                            ring):
    ring_a, rep = sj.admit(batches, bounds=BOUNDS)
    assert rep.n_quarantined == 0
    np.testing.assert_array_equal(np.asarray(ring_a), np.asarray(ring))


def test_degenerate_zone_mask_host_oracle():
    col = wkt.from_wkt([
        ZONES[0],                                      # healthy
        "POLYGON ((0 0, 2 2, 2 0, 0 2, 0 0))",         # bowtie
        "POLYGON ((0 0, 1 1, 2 2, 0 0))",              # zero area
        "POINT (3 3)",                                 # non-polygon: pass
    ])
    mask, reasons = quarantine.degenerate_zone_mask(col)
    np.testing.assert_array_equal(mask, [False, True, True, False])
    assert reasons["self_intersecting"] == 1
    assert reasons["tiny_area"] == 1


# ----------------------------------------------- telemetry + retry seeds


def test_telemetry_events_totally_ordered(sj, ring, tmp_path):
    with telemetry.capture() as ev:
        sj.run_durable(
            ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
            retry_policy=FAST,
        )
    assert len(ev) >= 5
    seqs = [e["seq"] for e in ev]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    ts = [e["ts_mono"] for e in ev]
    assert all(b >= a for a, b in zip(ts, ts[1:]))


def test_snapshot_precedes_resume_in_event_order(sj, ring, clean, tmp_path):
    d = str(tmp_path)
    with telemetry.capture() as ev:
        with faults.inject(
            fail_first=9, skip_first=1, sites=("stream.scan_step",),
            exc_factory=lambda s: RuntimeError("kill"),
        ):
            with pytest.raises(RuntimeError):
                sj.run_durable(
                    ring, NB, run_dir=d, snapshot_every=SNAP,
                    retry_policy=FAST,
                )
        sj.resume(d, ring, retry_policy=FAST)
    saved = [e["seq"] for e in ev if e["event"] == "snapshot_saved"]
    resumed = [e["seq"] for e in ev if e["event"] == "snapshot_resumed"]
    assert saved and resumed
    assert min(resumed) > saved[0]  # the resume reads an earlier save


def test_backoff_jitter_deterministic_under_seed(monkeypatch):
    pol = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, jitter=0.5)
    monkeypatch.setenv("MOSAIC_RETRY_SEED", "1234")
    a = [next(backoff_delays(pol)) for _ in range(1)]
    d1 = backoff_delays(pol)
    d2 = backoff_delays(pol)
    assert [next(d1) for _ in range(5)] == [next(d2) for _ in range(5)]
    monkeypatch.delenv("MOSAIC_RETRY_SEED")
    import random as _random

    d3 = backoff_delays(pol, rng=_random.Random(9))
    d4 = backoff_delays(pol, rng=_random.Random(9))
    assert [next(d3) for _ in range(5)] == [next(d4) for _ in range(5)]
    assert a  # seeded env path produced a value at all


# ------------------------------------------------- pipelined executor


class TestPipelinedDurable:
    """The ISSUE-14 contract: the asynchronous pipelined mode
    (`dispatch/pipeline.py`) is bit-identical to the synchronous loop
    under EVERY fault plan the synchronous matrix pins — same sites,
    same budgets, same degradation — plus kill-at-every-boundary +
    resume. The pipeline changes wall time, never the answer."""

    def test_pipelined_equals_plain_and_sync(self, sj, ring, clean,
                                             tmp_path):
        r = sj.run_durable(
            ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
            retry_policy=FAST, pipeline=True,
        )
        assert _stats(r) == _stats(clean)
        assert r.metrics["degraded"] is False
        assert r.metrics["snapshots"] == 4  # boundaries 2, 4, 6, 7
        assert checkpoint.list_snapshots(str(tmp_path)) == [2, 4, 6, 7]
        p = r.metrics["pipeline"]
        assert p["launched"] == 4 and p["landed"] == 4
        assert p["window"] >= 1

    def test_pipelined_collect_outs_bit_identical(self, sj, ring, clean,
                                                  tmp_path):
        r = sj.run_durable(
            ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
            retry_policy=FAST, pipeline=True, collect=True,
        )
        np.testing.assert_array_equal(r.outs, clean.outs)

    def test_pipelined_non_prefetch_equals_plain(self, index, ring,
                                                 clean, tmp_path):
        sj0 = StreamJoin(index, CUSTOM, RES, prefetch=False)
        r = sj0.run_durable(
            ring, NB, run_dir=str(tmp_path), snapshot_every=3,
            retry_policy=FAST, pipeline=True,
        )
        assert _stats(r) == _stats(clean)

    def test_env_knob_selects_pipelined_mode(self, sj, ring, clean,
                                             tmp_path, monkeypatch):
        monkeypatch.setenv("MOSAIC_STREAM_PIPELINE", "1")
        monkeypatch.setenv("MOSAIC_STREAM_WINDOW", "2")
        r = sj.run_durable(
            ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
            retry_policy=FAST,
        )
        assert _stats(r) == _stats(clean)
        assert r.metrics["pipeline"]["window"] == 2

    @pytest.mark.parametrize("kill_after", [1, 2, 3])
    def test_kill_and_resume_bit_identical(self, sj, ring, clean,
                                           tmp_path, kill_after):
        """Fatal device loss mid-flight: the pipeline's best-effort
        drain makes every already-launched segment durable, so the
        newest snapshot is exactly the kill boundary — and a PIPELINED
        resume converges to the clean stats bit for bit."""
        d = str(tmp_path / f"kill{kill_after}")
        with faults.inject(
            fail_first=99, skip_first=kill_after,
            sites=("stream.scan_step",),
            exc_factory=lambda s: RuntimeError(
                f"simulated device loss @ {s}"
            ),
        ):
            with pytest.raises(RuntimeError, match="simulated device loss"):
                sj.run_durable(
                    ring, NB, run_dir=d, snapshot_every=SNAP,
                    retry_policy=FAST, pipeline=True,
                )
        assert checkpoint.list_snapshots(d)
        r = sj.resume(d, ring, retry_policy=FAST, pipeline=True)
        assert _stats(r) == _stats(clean)
        assert r.metrics["resumed_from"] == kill_after * SNAP

    def test_transient_faults_retry_to_clean(self, sj, ring, clean,
                                             tmp_path):
        with telemetry.capture() as ev:
            with faults.transient_errors(2, sites=("stream.scan_step",)):
                r = sj.run_durable(
                    ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                    retry_policy=FAST, pipeline=True,
                )
        assert _stats(r) == _stats(clean)
        assert r.metrics["degraded"] is False
        assert [e["event"] for e in ev].count("transient_retry") == 2

    def test_exhausted_segment_degrades_to_host_oracle(self, sj, ring,
                                                       clean, tmp_path):
        with telemetry.capture() as ev:
            with faults.transient_errors(3, sites=("stream.scan_step",)):
                r = sj.run_durable(
                    ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                    retry_policy=FAST, pipeline=True,
                )
        assert _stats(r) == _stats(clean)
        assert r.metrics["degraded"] is True
        assert r.metrics["degraded_segments"] == 1
        assert "degraded" in [e["event"] for e in ev]

    def test_snapshot_failure_does_not_kill_run(self, sj, ring, clean,
                                                tmp_path):
        """Sick disk with the writes on the BACKGROUND thread: the
        adopted fault plans trip inside the writer's guarded call,
        every boundary degrades to ``snapshot_skipped``, and the run
        still answers exactly."""
        with telemetry.capture() as ev:
            with faults.transient_errors(
                999, sites=("stream.snapshot",)
            ):
                r = sj.run_durable(
                    ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                    retry_policy=FAST, pipeline=True,
                )
        assert _stats(r) == _stats(clean)
        assert r.metrics["snapshots"] == 0
        skipped = [e for e in ev if e["event"] == "snapshot_skipped"]
        assert len(skipped) == 4

    def test_watchdog_stall_recovered_by_retry(self, sj, ring, clean,
                                               tmp_path, monkeypatch):
        monkeypatch.setenv("MOSAIC_WATCHDOG_STREAM_SCAN_STEP", "0.15")
        with telemetry.capture() as ev:
            with faults.stalls(1.2, n=1, sites=("stream.scan_step",)):
                r = sj.run_durable(
                    ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                    retry_policy=FAST, pipeline=True,
                )
        assert _stats(r) == _stats(clean)
        assert r.metrics["degraded"] is False
        kinds = [e["event"] for e in ev]
        assert "watchdog_stall" in kinds
        assert "transient_retry" in kinds

    def test_snapshot_spans_marked_async(self, sj, ring, tmp_path):
        """The writer thread emits the same ``stream.snapshot`` spans
        (adopted trace context: same trail, same parentage rules) with
        ``mode="async"`` so the timeline can tell the two shapes
        apart."""
        with telemetry.capture() as ev:
            sj.run_durable(
                ring, NB, run_dir=str(tmp_path), snapshot_every=SNAP,
                retry_policy=FAST, pipeline=True,
            )
        snaps = [
            e for e in ev
            if e["event"] == "span" and e.get("name") == "stream.snapshot"
        ]
        assert len(snaps) == 4
        assert all(s.get("mode") == "async" for s in snaps)
        flushes = [
            e for e in ev
            if e["event"] == "span"
            and e.get("name") == "stream.pipeline.flush"
        ]
        assert len(flushes) == 1
