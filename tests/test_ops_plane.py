"""Live ops plane acceptance (PR 20): incarnation stitching, the fleet
report, the doctor CLI, the ops pull endpoint, the metric cardinality
cap, and the pinned observer-overhead budget.

The cross-cutting contracts:

- every trail opens with an incarnation header; `tools/fleet_report.py`
  merges N processes' trails onto one wall-clock axis with restart-gap
  links and cross-incarnation trace links;
- `tools/doctor.py` runs the known failure signatures over any mix of
  artifacts/trails/snapshots: green over clean evidence, red under an
  injected regression, exit code to match;
- the ops server answers /metrics, /health, /slo, / on an ephemeral
  port with no new dependencies;
- one misbehaving label producer cannot grow a metric's series map past
  the cap (overflow series + ONE typed warning);
- the whole ops plane (SLO + health observers on top of the standing
  bridge + recorder) costs ≤ 1.15x the bare record() path.
"""

import http.client
import json
import re
import sys
import time
from pathlib import Path

import pytest

from mosaic_tpu import obs
from mosaic_tpu.obs import health, metrics as obs_metrics, ops_server, slo
from mosaic_tpu.runtime import telemetry

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))


# ----------------------------------------------------------- incarnation


class TestIncarnation:
    def test_format_and_stability(self):
        inc = telemetry.incarnation()
        assert re.fullmatch(r"[0-9a-f]{8}-\d+-[0-9a-f]{6}", inc)
        assert inc == telemetry.INCARNATION == telemetry.incarnation()

    def test_incarnation_event_pairs_the_clocks(self):
        e = telemetry.incarnation_event()
        assert e["event"] == "incarnation"
        assert e["incarnation"] == telemetry.INCARNATION
        assert isinstance(e["ts_mono"], float)
        assert isinstance(e["ts_epoch"], float)
        # the pair is sampled together: epoch-mono offset is stable
        # within sampling noise between two anchor events
        e2 = telemetry.incarnation_event()
        off1 = e["ts_epoch"] - e["ts_mono"]
        off2 = e2["ts_epoch"] - e2["ts_mono"]
        assert abs(off1 - off2) < 0.05


# --------------------------------------------------------- fleet stitch


def _write_trail(path, inc, mono0, epoch0, n, trace=None, pid=1):
    rows = [{
        "event": "incarnation", "incarnation": inc, "pid": pid,
        "ts_mono": mono0, "ts_epoch": epoch0,
    }]
    for i in range(n):
        e = {
            "event": "serve_request", "seq": i,
            "ts_mono": round(mono0 + i * 0.1, 6), "seconds": 0.01,
        }
        if trace:
            e["trace_id"] = trace
        rows.append(e)
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(path)


class TestFleetStitch:
    def test_two_incarnations_one_wall_axis(self, tmp_path):
        import fleet_report

        # two processes with WILDLY different monotonic bases whose
        # wall-clock anchors interleave them 5 s apart
        a = _write_trail(tmp_path / "a.jsonl", "inc-a", 100.0,
                         1000.0, 5, trace="t-shared")
        b = _write_trail(tmp_path / "b.jsonl", "inc-b", 90000.0,
                         1005.0, 5, trace="t-shared", pid=2)
        events, summary = fleet_report.stitch([a, b])
        assert len(events) == 10  # headers dropped from the merge
        assert all("incarnation" in e and "ts_wall" in e for e in events)
        # merged order is wall-clock order: all of a, then all of b
        assert [e["incarnation"] for e in events] == ["inc-a"] * 5 + ["inc-b"] * 5
        walls = [e["ts_wall"] for e in events]
        assert walls == sorted(walls)
        assert walls[0] == pytest.approx(1000.0)
        assert walls[5] == pytest.approx(1005.0)
        chain = summary["chain"]
        assert [c["incarnation"] for c in chain] == ["inc-a", "inc-b"]
        assert "prev" not in chain[0]
        assert chain[1]["prev"] == "inc-a"
        # dark gap: a's last event at 1000.4, b starts at 1005.0
        assert chain[1]["gap_s"] == pytest.approx(4.6)
        # the shared trace id links the incarnations
        assert summary["cross_incarnation_traces"] == {
            "t-shared": ["inc-a", "inc-b"],
        }

    def test_headerless_trail_gets_synthetic_incarnation(self, tmp_path):
        import fleet_report

        p = tmp_path / "legacy.jsonl"
        rows = [
            {"event": "serve_request", "seq": i, "ts_mono": 50.0 + i}
            for i in range(3)
        ]
        p.write_text("".join(json.dumps(r) + "\n" for r in rows))
        events, summary = fleet_report.stitch([str(p)])
        assert len(events) == 3
        assert all(e["incarnation"] == "file:legacy" for e in events)
        info = summary["incarnations"]["file:legacy"]
        assert info["synthetic"] is True

    def test_fleet_report_cli_writes_mergeable_trail(
        self, tmp_path, monkeypatch, capsys
    ):
        import fleet_report

        a = _write_trail(tmp_path / "a.jsonl", "inc-a", 0.0, 1000.0, 3)
        b = _write_trail(tmp_path / "b.jsonl", "inc-b", 0.0, 1010.0, 3)
        out = str(tmp_path / "merged.jsonl")
        monkeypatch.setattr(
            sys, "argv", ["fleet_report.py", a, b, "--out", out]
        )
        fleet_report.main()
        rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["metric"] == "fleet_report"
        assert rep["incarnations"] == 2 and rep["events"] == 6
        merged = obs.read_trail(out)
        assert len(merged) == 6  # multi-incarnation: no new header
        assert merged[0]["incarnation"] == "inc-a"

    def test_trace_report_fleet_mode(self, tmp_path, monkeypatch, capsys):
        import trace_report

        a = _write_trail(tmp_path / "a.jsonl", "inc-a", 0.0, 1000.0, 4)
        b = _write_trail(tmp_path / "b.jsonl", "inc-b", 0.0, 1010.0, 4)
        monkeypatch.setattr(
            sys, "argv", ["trace_report.py", "--fleet", a, b]
        )
        trace_report.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["metric"] == "trace_report"
        assert out["fleet"]["incarnations"] == 2
        assert out["fleet"]["chain"][1]["prev"] == "inc-a"
        # the stage breakdown still works over the merged events
        assert out["stages"]["serve_request"]["count"] == 8

    def test_multiple_trails_without_fleet_flag_error(
        self, tmp_path, monkeypatch
    ):
        import trace_report

        a = _write_trail(tmp_path / "a.jsonl", "inc-a", 0.0, 1000.0, 1)
        b = _write_trail(tmp_path / "b.jsonl", "inc-b", 0.0, 1001.0, 1)
        monkeypatch.setattr(sys, "argv", ["trace_report.py", a, b])
        with pytest.raises(SystemExit):
            trace_report.main()


# --------------------------------------------------------------- doctor


def _artifact(tmp_path, name, detail):
    p = tmp_path / name
    p.write_text(json.dumps({
        "metric": "m", "value": 1.0, "unit": "x", "detail": detail,
    }) + "\n")
    return str(p)


def _trail_file(tmp_path, name, events):
    p = tmp_path / name
    p.write_text("".join(json.dumps(e) + "\n" for e in events))
    return str(p)


class TestDoctor:
    def test_green_over_clean_evidence(self, tmp_path):
        import doctor

        art = _artifact(tmp_path, "clean.json", {
            "cold_compiles": 0, "snapshot_overlap_fraction": 0.96,
        })
        trail = _trail_file(tmp_path, "clean.jsonl", [
            {"event": "incarnation", "incarnation": "x", "ts_mono": 0.0,
             "ts_epoch": 0.0},
            {"event": "serve_request", "seq": 1, "seconds": 0.01,
             "ts_mono": 1.0},
        ])
        report = doctor.diagnose([art, trail])
        assert report["status"] == "green"
        assert report["red_checks"] == []
        assert report["inputs"]["by_kind"] == {"artifact": 1, "trail": 1}

    def test_red_on_cold_compile_regression(self, tmp_path):
        import doctor

        art = _artifact(tmp_path, "bad.json", {
            "relaunch": {"relaunch_cold_compiles": 3},
        })
        report = doctor.diagnose([art])
        assert report["status"] == "red"
        assert report["red_checks"] == ["cold_compiles"]
        (f,) = next(
            c for c in report["checks"] if c["check"] == "cold_compiles"
        )["findings"]
        assert f["count"] == 3 and "relaunch_cold_compiles" in f["where"]

    def test_red_on_serve_compile_in_trail(self, tmp_path):
        import doctor

        trail = _trail_file(tmp_path, "t.jsonl", [
            {"event": "serve_request", "seq": 1, "ts_mono": 1.0},
            {"event": "serve_compile", "seq": 2, "ts_mono": 2.0},
        ])
        report = doctor.diagnose([trail])
        assert "cold_compiles" in report["red_checks"]

    def test_red_on_low_snapshot_overlap(self, tmp_path):
        import doctor

        art = _artifact(tmp_path, "o.json", {
            "snapshot_overlap_fraction": 0.3,
        })
        assert doctor.diagnose([art])["red_checks"] == ["snapshot_overlap"]

    def test_red_on_slo_violation_in_trail_and_artifact(self, tmp_path):
        import doctor

        trail = _trail_file(tmp_path, "v.jsonl", [
            {"event": "slo_violation", "slo": "serve.shed", "seq": 1,
             "burn_rate": 10.0, "window_s": 60.0, "ts_mono": 1.0},
            {"event": "serve_request", "seq": 2, "ts_mono": 2.0},
        ])
        art = _artifact(tmp_path, "slo.json", {
            "slo": {"breached": ["serve.latency"], "ok": False},
        })
        report = doctor.diagnose([trail, art])
        assert report["red_checks"] == ["burn_rate"]
        findings = next(
            c for c in report["checks"] if c["check"] == "burn_rate"
        )["findings"]
        assert {f["slo"] for f in findings} == {
            "serve.shed", "serve.latency",
        }

    def test_red_on_shed_imbalance_in_trail_only(self, tmp_path):
        import doctor

        noisy = [
            {"event": "router_shed", "tenant": "hog", "seq": i,
             "ts_mono": float(i)}
            for i in range(60)
        ] + [
            {"event": "router_shed", "tenant": "victim", "seq": 99,
             "ts_mono": 99.0},
        ]
        trail = _trail_file(tmp_path, "shed.jsonl", noisy)
        assert doctor.diagnose([trail])["red_checks"] == ["shed_imbalance"]
        # the SAME evidence inside a bench artifact is excluded on
        # purpose (A/B benches shed on purpose)
        art = _artifact(tmp_path, "ab.json", {"trail": noisy})
        # an artifact's embedded trail reads as kind=artifact -> the
        # imbalance check skips it
        assert doctor.diagnose([art])["status"] == "green"

    def test_red_on_cache_thrash_stats(self, tmp_path):
        import doctor

        trail = _trail_file(tmp_path, "c.jsonl", [
            {"event": "dispatch_cache_stats", "seq": 1, "ts_mono": 1.0,
             "lowered": {"hits": 10, "misses": 500, "maxsize": 64,
                         "currsize": 64}},
            {"event": "serve_request", "seq": 2, "ts_mono": 2.0},
        ])
        report = doctor.diagnose([trail])
        assert report["red_checks"] == ["cache_thrash"]

    def test_ops_snapshot_breach_is_red(self, tmp_path):
        import doctor

        p = tmp_path / "ops.json"
        p.write_text(json.dumps({
            "incarnation": "x", "pid": 1, "metrics": {},
            "health": {"window_s": 60, "scopes": {}},
            "slo": {"slos": {"serve.shed": {
                "breached": True, "burn_short": 12.0,
            }}},
        }) + "\n")
        report = doctor.diagnose([str(p)])
        assert report["inputs"]["by_kind"] == {"ops": 1}
        assert report["red_checks"] == ["burn_rate"]

    def test_cli_exit_codes_and_last_line_json(
        self, tmp_path, monkeypatch, capsys
    ):
        import doctor

        good = _artifact(tmp_path, "good.json", {"cold_compiles": 0})
        monkeypatch.setattr(sys, "argv", ["doctor.py", good])
        assert doctor.main() == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["metric"] == "doctor" and out["status"] == "green"
        bad = _artifact(tmp_path, "bad.json", {"cold_compiles": 7})
        trail_out = str(tmp_path / "doc.jsonl")
        monkeypatch.setattr(
            sys, "argv", ["doctor.py", bad, "--trail", trail_out]
        )
        assert doctor.main() == 1
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["status"] == "red"
        # the doctor's own work rode the spine: ops_stage events in
        # the exported trail (perf_gate gates them like any stage)
        rows = obs.read_trail(trail_out)
        stages = {
            e.get("stage") for e in rows if e.get("event") == "ops_stage"
        }
        assert {"scan", "checks"} <= stages

    def test_committed_artifacts_are_green(self):
        """The acceptance lane: the doctor must be green over the
        repo's own committed evidence."""
        import doctor

        paths = [
            str(REPO / name) for name in (
                "SERVE_TENANT_r16.json", "SERVE_RESTART_r16.json",
                "STREAM_CPU_r14.json", "KNN_r19.json", "EPOCH_r18.json",
                "OVERLAY_r17.json", "OPS_r20.json",
            ) if (REPO / name).exists()
        ]
        assert len(paths) >= 5, "committed artifacts went missing"
        report = doctor.diagnose(paths)
        assert report["status"] == "green", report["checks"]


# ------------------------------------------------------------ ops server


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.getheader("Content-Type"), resp.read()
    finally:
        conn.close()


class TestOpsServer:
    def test_endpoints_serve_the_ops_plane(self):
        with ops_server.OpsServer(0) as srv:
            assert srv.port > 0
            status, ctype, body = _get(srv.port, "/metrics")
            assert status == 200 and "text/plain" in ctype
            assert b"# TYPE" in body
            status, ctype, body = _get(srv.port, "/health")
            doc = json.loads(body)
            assert status == 200 and "scopes" in doc
            status, ctype, body = _get(srv.port, "/slo")
            doc = json.loads(body)
            assert status == 200 and "burn_threshold" in doc
            status, ctype, body = _get(srv.port, "/")
            doc = json.loads(body)
            assert doc["incarnation"] == telemetry.INCARNATION
            assert {"metrics", "health", "slo", "pid"} <= set(doc)
            status, _, _ = _get(srv.port, "/nonesuch")
            assert status == 404

    def test_start_records_typed_event_and_stop_releases(self):
        with telemetry.capture() as events:
            srv = ops_server.OpsServer(0).start()
            port = srv.port
            srv.stop()
        started = [e for e in events if e["event"] == "ops_server_started"]
        assert len(started) == 1 and started[0]["port"] == port
        # the port is actually released: rebinding succeeds
        srv2 = ops_server.OpsServer(port).start()
        srv2.stop()

    def test_maybe_start_is_env_gated(self, monkeypatch):
        monkeypatch.delenv("MOSAIC_OPS_PORT", raising=False)
        assert ops_server.maybe_start() is None
        monkeypatch.setenv("MOSAIC_OPS_PORT", "not-a-port")
        assert ops_server.maybe_start() is None
        monkeypatch.setenv("MOSAIC_OPS_PORT", "0")
        try:
            srv = ops_server.maybe_start()
            assert srv is not None and srv.port > 0
            # idempotent: second call returns the same server
            assert ops_server.maybe_start() is srv
        finally:
            ops_server.stop()

    def test_bind_failure_records_error_not_raise(self, monkeypatch):
        blocker = ops_server.OpsServer(0).start()
        try:
            monkeypatch.setenv("MOSAIC_OPS_PORT", str(blocker.port))
            with telemetry.capture() as events:
                assert ops_server.maybe_start() is None
            errs = [e for e in events if e["event"] == "ops_server_error"]
            assert len(errs) == 1 and "error" in errs[0]
        finally:
            blocker.stop()
            ops_server.stop()


# ------------------------------------------------------ cardinality cap


class TestCardinalityCap:
    def test_counter_series_bounded_with_overflow_fold(self):
        c = obs_metrics.Counter("cap.unit_counter", max_series=8)
        with telemetry.capture() as events:
            for i in range(100):
                c.inc(tenant=f"t{i:03d}")
        # 8 real series + the reserved overflow series
        assert len(c._series) == 9
        assert c._series[obs_metrics.OVERFLOW_KEY] == 92
        # exactly ONE typed warning crossed the spine
        warns = [
            e for e in events if e["event"] == "metric_series_overflow"
        ]
        assert len(warns) == 1
        assert warns[0]["metric"] == "cap.unit_counter"
        assert warns[0]["max_series"] == 8

    def test_existing_series_still_write_at_the_cap(self):
        c = obs_metrics.Counter("cap.unit_existing", max_series=4)
        for i in range(4):
            c.inc(tenant=f"t{i}")
        c.inc(5, tenant="t0")  # pre-existing series: not folded
        assert c.value(tenant="t0") == 6
        c.inc(tenant="t999")  # new series at the cap: folded
        assert c.value(tenant="t999") == 0
        assert c._series[obs_metrics.OVERFLOW_KEY] == 1

    def test_gauge_and_histogram_respect_the_cap(self):
        g = obs_metrics.Gauge("cap.unit_gauge", max_series=2)
        for i in range(10):
            g.set(float(i), scope=f"s{i}")
        assert len(g._series) == 3
        h = obs_metrics.Histogram(
            "cap.unit_hist", buckets=(1.0,), max_series=2
        )
        for i in range(10):
            h.observe(0.5, site=f"x{i}")
        assert len(h._series) == 3
        snap = h.snapshot()
        overflow = next(
            s for s in snap["series"]
            if s["labels"] == {"overflow": "true"}
        )
        assert overflow["value"]["count"] == 8

    def test_overflow_series_renders_in_prometheus_text(self):
        c = obs_metrics.Counter("cap.unit_prom", max_series=1)
        c.inc(tenant="a")
        c.inc(tenant="b")
        text = obs.prometheus_text({"cap.unit_prom": c.snapshot()})
        assert 'cap_unit_prom{overflow="true"} 1' in text


# ------------------------------------------------------ overhead budget


def test_ops_plane_overhead_within_budget():
    """SLO + health observers on top of the standing plane (bridge +
    recorder) hold installed record() to ≤ 1.15x.  A bare/installed
    pair inside one round shares ambient load, so the min of per-round
    ratios is the noise-robust estimator (a real 1.3x plane would show
    it in every round; one quiet round proves the budget holds)."""
    n = 20_000

    def once() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            telemetry.record("serve_request", seconds=0.001)
        return time.perf_counter() - t0

    def measure() -> float:
        # Shed scopes/series accumulated by earlier suites so the
        # installed path measures the plane, not their leftovers.
        health.MONITOR.reset()
        slo.MONITOR.reset()
        ratio = float("inf")
        try:
            for _ in range(12):
                slo.uninstall()
                health.uninstall()
                bare = once()
                slo.install()
                health.install()
                ratio = min(ratio, once() / bare)
        finally:
            slo.install()
            health.install()
        return ratio

    ratio = measure()
    if ratio > 1.15:
        ratio = min(ratio, measure())
    assert ratio <= 1.15, (
        f"ops-plane overhead {ratio:.3f}x exceeds the 1.15x budget"
    )
