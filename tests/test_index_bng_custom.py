"""BNG + Custom grid index systems: encode/decode/neighbors/polyfill."""

import jax.numpy as jnp
import numpy as np

from mosaic_tpu.core.index import (
    BNG,
    CustomIndexSystem,
    GridConf,
    custom_from_name,
)


class TestBNG:
    def test_point_roundtrip_all_res(self):
        rng = np.random.default_rng(0)
        pts = np.column_stack(
            [rng.uniform(0, 700_000, 200), rng.uniform(0, 1_300_000, 200)]
        )
        for res in BNG.resolutions():
            cells = np.asarray(BNG.point_to_cell(jnp.asarray(pts), res))
            assert np.asarray(BNG.resolution_of(cells)).tolist() == [res] * 200
            centers = np.asarray(BNG.cell_center(cells))
            # center of the cell must map back to the same cell
            cells2 = np.asarray(BNG.point_to_cell(jnp.asarray(centers), res))
            np.testing.assert_array_equal(cells, cells2)
            # original point within cell bounds
            edge = BNG.edge_size(res)
            assert np.all(np.abs(centers - pts) <= edge)

    def test_known_strings(self):
        # Ben Nevis-ish: eastings 216650 northings 771250 -> NN 16 71 (10km "NN17"?)
        pts = jnp.asarray([[216650.0, 771250.0]])
        c1 = np.asarray(BNG.point_to_cell(pts, 1))[0]
        assert BNG.format([c1]) == ["NN"]
        c2 = np.asarray(BNG.point_to_cell(pts, 2))[0]
        assert BNG.format([c2]) == ["NN17"]
        c4 = np.asarray(BNG.point_to_cell(pts, 4))[0]
        assert BNG.format([c4]) == ["NN166712"]

    def test_quadrant_res(self):
        # 50km quadrants of square TQ (e 5xx, n 1xx): TQ SW corner 500000,100000
        pts = jnp.asarray(
            [
                [510_000.0, 110_000.0],  # SW
                [510_000.0, 160_000.0],  # NW
                [560_000.0, 160_000.0],  # NE
                [560_000.0, 110_000.0],  # SE
            ]
        )
        cells = np.asarray(BNG.point_to_cell(pts, -2))
        assert BNG.format(cells) == ["TQSW", "TQNW", "TQNE", "TQSE"]
        # parse inverse
        np.testing.assert_array_equal(BNG.parse(BNG.format(cells)), cells)

    def test_format_parse_roundtrip(self):
        rng = np.random.default_rng(1)
        pts = np.column_stack(
            [rng.uniform(0, 700_000, 50), rng.uniform(0, 1_300_000, 50)]
        )
        for res in [1, 2, 3, -2, -3, 4, -4, 5, 6]:
            cells = np.asarray(BNG.point_to_cell(jnp.asarray(pts), res))
            strs = BNG.format(cells)
            np.testing.assert_array_equal(BNG.parse(strs), cells)

    def test_k_ring_loop(self):
        pts = jnp.asarray([[400_000.0, 400_000.0]])
        c = BNG.point_to_cell(pts, 3)
        ring = np.asarray(BNG.k_ring(c, 1))[0]
        assert (ring >= 0).sum() == 9
        loop = np.asarray(BNG.k_loop(c, 1))[0]
        assert (loop >= 0).sum() == 8
        assert int(np.asarray(c)[0]) not in loop.tolist()
        # edge of grid: fewer valid neighbors
        edge_c = BNG.point_to_cell(jnp.asarray([[500.0, 500.0]]), 3)
        ring_e = np.asarray(BNG.k_ring(edge_c, 1))[0]
        assert (ring_e >= 0).sum() == 4

    def test_grid_distance(self):
        a = BNG.point_to_cell(jnp.asarray([[100_500.0, 100_500.0]]), 3)
        b = BNG.point_to_cell(jnp.asarray([[103_500.0, 104_500.0]]), 3)
        # Chebyshev: consistent with the square k_loop rings
        assert int(np.asarray(BNG.grid_distance(a, b))[0]) == 4

    def test_distance_consistent_with_kloop(self):
        c = BNG.point_to_cell(jnp.asarray([[400_000.0, 400_000.0]]), 3)
        for k in [1, 2, 3]:
            loop = np.asarray(BNG.k_loop(c, k))[0]
            loop = loop[loop >= 0]
            cc = jnp.broadcast_to(c, (len(loop),))
            d = np.asarray(BNG.grid_distance(cc, jnp.asarray(loop)))
            assert (d == k).all()

    def test_boundary(self):
        c = BNG.point_to_cell(jnp.asarray([[216_650.0, 771_250.0]]), 2)
        b = np.asarray(BNG.cell_boundary(c))[0]
        np.testing.assert_allclose(b[0], [210_000, 770_000])
        np.testing.assert_allclose(b[2], [220_000, 780_000])
        np.testing.assert_allclose(b[0], b[4])

    def test_polyfill_candidates(self):
        cand = BNG.polyfill_candidates(
            np.array([100_000, 100_000, 130_000, 120_000]), 2
        )
        assert len(cand) == 3 * 2
        assert len(set(cand.tolist())) == 6

    def test_500km_blocks(self):
        pts = jnp.asarray([[100.0, 100.0], [600_000.0, 100.0], [100.0, 1_200_000.0]])
        cells = np.asarray(BNG.point_to_cell(pts, -1))
        assert BNG.format(cells) == ["S", "T", "H"]
        np.testing.assert_array_equal(BNG.parse(["S", "T", "H"]), cells)


class TestCustom:
    conf = GridConf(-180, 180, -90, 90, 2, 360, 180)

    def test_factory_name_roundtrip(self):
        ix = CustomIndexSystem(self.conf)
        ix2 = custom_from_name(ix.name)
        assert ix2.conf == ix.conf

    def test_roundtrip(self):
        ix = CustomIndexSystem(self.conf)
        rng = np.random.default_rng(2)
        pts = np.column_stack([rng.uniform(-180, 180, 100), rng.uniform(-90, 90, 100)])
        for res in [0, 1, 2, 5, 8]:
            cells = np.asarray(ix.point_to_cell(jnp.asarray(pts), res))
            assert np.all(np.asarray(ix.resolution_of(cells)) == res)
            centers = np.asarray(ix.cell_center(cells))
            cells2 = np.asarray(ix.point_to_cell(jnp.asarray(centers), res))
            np.testing.assert_array_equal(cells, cells2)
            assert np.asarray(ix.is_valid(cells)).all()

    def test_cell_counts(self):
        ix = CustomIndexSystem(self.conf)
        assert ix.cells_x(0) == 1 and ix.cells_y(0) == 1
        assert ix.cells_x(3) == 8 and ix.cells_y(3) == 8

    def test_neighbors(self):
        ix = CustomIndexSystem(self.conf)
        c = ix.point_to_cell(jnp.asarray([[0.1, 0.1]]), 4)
        ring = np.asarray(ix.k_ring(c, 1))[0]
        assert (ring >= 0).sum() == 9
        loop = np.asarray(ix.k_loop(c, 2))[0]
        assert (loop >= 0).sum() == 16

    def test_polyfill(self):
        ix = CustomIndexSystem(self.conf)
        cand = ix.polyfill_candidates(np.array([-10.0, -10.0, 10.0, 10.0]), 5)
        centers = np.asarray(ix.cell_center(jnp.asarray(cand)))
        assert np.all(centers[:, 0] > -12) and np.all(centers[:, 0] < 12)
        w, h = ix.cell_size(5)
        assert len(cand) >= (20 / w - 1) * (20 / h - 1)
