"""Canonical geometry fixtures (role of the reference's `test/package.scala`
mocks object — fresh WKT values, EPSG:4326)."""

import numpy as np

POINT_WKT = [
    "POINT (10 10)",
    "POINT (-73.985 40.748)",
    "POINT (0 0)",
]

LINE_WKT = [
    "LINESTRING (0 0, 1 1, 2 0, 3 1)",
    "LINESTRING (-73.99 40.73, -73.98 40.74, -73.97 40.75)",
]

POLY_WKT = [
    # simple square
    "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
    # square with hole
    "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 2 4, 4 4, 4 2, 2 2))",
    # convex pentagon
    "POLYGON ((0 0, 2 -1, 4 0, 3 3, 1 3, 0 0))",
]

MULTIPOLY_WKT = [
    "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), ((5 5, 7 5, 7 7, 5 7, 5 5)))",
]

MULTIPOINT_WKT = ["MULTIPOINT ((1 1), (2 2), (3 3))"]
MULTILINE_WKT = ["MULTILINESTRING ((0 0, 1 1), (2 2, 3 3, 4 4))"]

ALL_WKT = (
    POINT_WKT + LINE_WKT + POLY_WKT + MULTIPOLY_WKT + MULTIPOINT_WKT + MULTILINE_WKT
)


def random_points(n, bbox=(-74.3, 40.4, -73.6, 41.0), seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(bbox[0], bbox[2], n)
    y = rng.uniform(bbox[1], bbox[3], n)
    return np.column_stack([x, y])


def oracle_pairs(left, right):
    """Dense O(L*R) f64-oracle st_intersects pair matrix (tests)."""
    from mosaic_tpu.functions import geometry as F

    pairs = []
    for i in range(len(left)):
        a = left.slice(i, i + 1)
        for j in range(len(right)):
            hit = F.st_intersects(a, right.slice(j, j + 1), backend="oracle")
            if bool(np.asarray(hit)[0]):
                pairs.append((i, j))
    return np.asarray(sorted(pairs), np.int64).reshape(-1, 2)
