"""Host geometry engine tests: boolean ops, buffer, hull, simplify.

No third-party oracle exists in this environment (no shapely/JTS), so
correctness is established through *identities*:

- membership sampling: for random probe points,
  ``p ∈ A op B  ⇔  (p ∈ A) op (p ∈ B)`` via the numpy even-odd oracle;
- area conservation: ``|A∩B| + |A\\B| = |A|`` and
  ``|A∪B| = |A| + |B| - |A∩B|``;
- buffer monotonicity and disc-area convergence.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry import hostops, oracle
from mosaic_tpu.core.geometry.wkt import from_wkt
from mosaic_tpu.core.types import GeometryType


def _probe(col, g, pts):
    return oracle.contains_points(col, g, pts)


def _rand_poly(rng, cx, cy, rmax=2.0, verts=12):
    ang = np.sort(rng.uniform(0, 2 * np.pi, verts))
    rad = rng.uniform(0.3, 1.0, verts) * rmax
    ring = np.column_stack([cx + rad * np.cos(ang), cy + rad * np.sin(ang)])
    from mosaic_tpu.core.types import GeometryBuilder

    b = GeometryBuilder()
    b.add_geometry(GeometryType.POLYGON, [[ring]], 4326)
    return b.build()


SQ1 = "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"
SQ2 = "POLYGON ((2 2, 6 2, 6 6, 2 6, 2 2))"
HOLEY = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (3 3, 3 7, 7 7, 7 3, 3 3))"
FAR = "POLYGON ((100 100, 101 100, 101 101, 100 101, 100 100))"


class TestBoolOps:
    def test_square_intersection_known(self):
        a, b = from_wkt([SQ1]), from_wkt([SQ2])
        out = hostops.intersection(a, b)
        assert oracle.area(out)[0] == pytest.approx(4.0)

    def test_square_union_known(self):
        a, b = from_wkt([SQ1]), from_wkt([SQ2])
        out = hostops.union(a, b)
        assert oracle.area(out)[0] == pytest.approx(16 + 16 - 4)

    def test_square_difference_known(self):
        a, b = from_wkt([SQ1]), from_wkt([SQ2])
        out = hostops.difference(a, b)
        assert oracle.area(out)[0] == pytest.approx(16 - 4)

    def test_xor_known(self):
        a, b = from_wkt([SQ1]), from_wkt([SQ2])
        out = hostops.sym_difference(a, b)
        assert oracle.area(out)[0] == pytest.approx(16 + 16 - 2 * 4)

    def test_disjoint(self):
        a, b = from_wkt([SQ1]), from_wkt([FAR])
        assert oracle.area(hostops.intersection(a, b))[0] == pytest.approx(0.0)
        assert oracle.area(hostops.union(a, b))[0] == pytest.approx(17.0)
        assert oracle.area(hostops.difference(a, b))[0] == pytest.approx(16.0)

    def test_hole_semantics(self):
        a, b = from_wkt([HOLEY]), from_wkt([SQ1])
        out = hostops.intersection(a, b)
        # SQ1 ∩ HOLEY: 4x4 square minus the overlapping hole part (3..4)^2
        assert oracle.area(out)[0] == pytest.approx(16 - 1)

    def test_contained(self):
        a = from_wkt([SQ1])
        b = from_wkt(["POLYGON ((1 1, 2 1, 2 2, 1 2, 1 1))"])
        assert oracle.area(hostops.intersection(a, b))[0] == pytest.approx(1.0)
        assert oracle.area(hostops.difference(a, b))[0] == pytest.approx(15.0)
        out = hostops.difference(a, b)
        # difference must carve a hole
        assert out.num_rings == 2

    def test_identical(self):
        a = from_wkt([SQ1])
        assert oracle.area(hostops.intersection(a, a))[0] == pytest.approx(16.0)
        assert oracle.area(hostops.union(a, a))[0] == pytest.approx(16.0)
        assert oracle.area(hostops.difference(a, a))[0] == pytest.approx(0.0)

    def test_shared_edge(self):
        a = from_wkt(["POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))"])
        b = from_wkt(["POLYGON ((2 0, 4 0, 4 2, 2 2, 2 0))"])
        assert oracle.area(hostops.union(a, b))[0] == pytest.approx(8.0)
        assert oracle.area(hostops.intersection(a, b))[0] == pytest.approx(0.0)

    @pytest.mark.parametrize("seed", range(8))
    def test_random_membership_and_areas(self, seed):
        rng = np.random.default_rng(seed)
        a = _rand_poly(rng, 0.0, 0.0)
        b = _rand_poly(rng, rng.uniform(-1, 1), rng.uniform(-1, 1))
        inter = hostops.intersection(a, b)
        uni = hostops.union(a, b)
        diff = hostops.difference(a, b)
        ai, au, ad = (oracle.area(c)[0] for c in (inter, uni, diff))
        aa, ab = oracle.area(a)[0], oracle.area(b)[0]
        assert ai + ad == pytest.approx(aa, rel=1e-9, abs=1e-12)
        assert au == pytest.approx(aa + ab - ai, rel=1e-9, abs=1e-12)
        pts = rng.uniform(-3, 3, size=(400, 2))
        in_a = _probe(a, 0, pts)
        in_b = _probe(b, 0, pts)
        got_i = _probe(inter, 0, pts)
        got_u = _probe(uni, 0, pts)
        got_d = _probe(diff, 0, pts)
        # boundary-grazing probes can disagree; demand near-total agreement
        assert np.mean(got_i == (in_a & in_b)) > 0.995
        assert np.mean(got_u == (in_a | in_b)) > 0.995
        assert np.mean(got_d == (in_a & ~in_b)) > 0.995


class TestUnion:
    def test_union_all(self):
        col = from_wkt([SQ1, SQ2, FAR])
        out = hostops.union_all(col)
        assert oracle.area(out)[0] == pytest.approx(16 + 16 - 4 + 1)

    def test_unary_union(self):
        col = from_wkt(
            ["MULTIPOLYGON (((0 0, 4 0, 4 4, 0 4, 0 0)), ((2 2, 6 2, 6 6, 2 6, 2 2)))"]
        )
        out = hostops.unary_union(col)
        assert oracle.area(out)[0] == pytest.approx(28.0)


class TestBuffer:
    def test_point_buffer_is_disc(self):
        col = from_wkt(["POINT (1 1)"])
        out = hostops.buffer(col, 2.0, quad_segs=16)
        assert oracle.area(out)[0] == pytest.approx(np.pi * 4, rel=0.01)

    def test_polygon_buffer_grows(self):
        col = from_wkt([SQ1])
        out = hostops.buffer(col, 1.0, quad_segs=8)
        # 4x4 square + 1: area = 16 + perimeter*1 + pi*1^2
        assert oracle.area(out)[0] == pytest.approx(16 + 16 + np.pi, rel=0.01)

    def test_negative_buffer_erodes(self):
        col = from_wkt([SQ1])
        out = hostops.buffer(col, -1.0)
        assert oracle.area(out)[0] == pytest.approx(4.0, rel=0.01)

    def test_line_buffer(self):
        col = from_wkt(["LINESTRING (0 0, 10 0)"])
        out = hostops.buffer(col, 1.0, quad_segs=16)
        assert oracle.area(out)[0] == pytest.approx(20 + np.pi, rel=0.01)

    def test_buffer_roundtrip_contains_original(self):
        rng = np.random.default_rng(5)
        col = _rand_poly(rng, 0, 0)
        out = hostops.buffer(col, 0.5)
        pts = rng.uniform(-2.5, 2.5, size=(300, 2))
        in_orig = _probe(col, 0, pts)
        in_buf = _probe(out, 0, pts)
        assert not np.any(in_orig & ~in_buf)


class TestHullSimplify:
    def test_hull_of_square_plus_inner(self):
        col = from_wkt(["MULTIPOINT ((0 0), (4 0), (4 4), (0 4), (2 2))"])
        out = hostops.convex_hull(col)
        assert out.geometry_type(0) == GeometryType.POLYGON
        assert oracle.area(out)[0] == pytest.approx(16.0)

    def test_hull_collinear(self):
        col = from_wkt(["MULTIPOINT ((0 0), (1 1), (2 2))"])
        out = hostops.convex_hull(col)
        assert out.geometry_type(0) == GeometryType.LINESTRING

    def test_simplify_line(self):
        col = from_wkt(["LINESTRING (0 0, 1 0.001, 2 0, 3 0.001, 4 0)"])
        out = hostops.simplify(col, 0.01)
        assert out.num_vertices == 2

    def test_simplify_keeps_shape(self):
        col = from_wkt(["LINESTRING (0 0, 1 1, 2 0, 3 1, 4 0)"])
        out = hostops.simplify(col, 0.1)
        assert out.num_vertices == 5

    def test_simplify_ring_preserved(self):
        col = from_wkt([SQ1])
        out = hostops.simplify(col, 0.5)
        assert oracle.area(out)[0] == pytest.approx(16.0)
