"""PackedGeometry + WKT/WKB/GeoJSON codec round-trips."""

import numpy as np

from mosaic_tpu.core.geometry import geojson, wkb, wkt
from mosaic_tpu.core.types import GeometryType, PackedGeometry

import fixtures as fx


def test_from_wkt_counts():
    col = wkt.from_wkt(fx.ALL_WKT)
    assert len(col) == len(fx.ALL_WKT)
    assert col.geometry_type(0) == GeometryType.POINT
    assert col.geometry_type(5) == GeometryType.POLYGON


def test_wkt_roundtrip():
    col = wkt.from_wkt(fx.ALL_WKT)
    out = wkt.to_wkt(col)
    col2 = wkt.from_wkt(out)
    assert len(col2) == len(col)
    np.testing.assert_allclose(col2.xy, col.xy)
    np.testing.assert_array_equal(col2.geom_type, col.geom_type)
    np.testing.assert_array_equal(col2.ring_offsets, col.ring_offsets)


def test_wkb_roundtrip():
    col = wkt.from_wkt(fx.ALL_WKT)
    blobs = wkb.to_wkb(col)
    col2 = wkb.from_wkb(blobs)
    np.testing.assert_allclose(col2.xy, col.xy)
    np.testing.assert_array_equal(col2.geom_type, col.geom_type)
    np.testing.assert_array_equal(col2.ring_offsets, col.ring_offsets)
    np.testing.assert_array_equal(col2.part_offsets, col.part_offsets)
    np.testing.assert_array_equal(col2.geom_offsets, col.geom_offsets)


def test_hex_roundtrip():
    col = wkt.from_wkt(fx.POLY_WKT)
    hexes = wkb.to_hex(col)
    col2 = wkb.from_hex(hexes)
    np.testing.assert_allclose(col2.xy, col.xy)


def test_geojson_roundtrip():
    col = wkt.from_wkt(fx.ALL_WKT)
    docs = geojson.to_geojson(col)
    col2 = geojson.from_geojson(docs)
    np.testing.assert_allclose(col2.xy, col.xy)
    np.testing.assert_array_equal(col2.geom_type, col.geom_type)


def test_wkb_z_roundtrip():
    col = wkt.from_wkt(["POINT Z (1 2 3)", "LINESTRING Z (0 0 1, 1 1 2)"])
    assert col.z is not None
    np.testing.assert_allclose(col.z, [3, 1, 2])
    col2 = wkb.from_wkb(wkb.to_wkb(col))
    np.testing.assert_allclose(col2.z, [3, 1, 2])


def test_srid_parse():
    col = wkt.from_wkt(["SRID=27700;POINT (400000 100000)"])
    assert col.srid[0] == 27700


def test_from_points_vectorized():
    pts = np.random.default_rng(0).uniform(-10, 10, (100, 2))
    col = PackedGeometry.from_points(pts)
    assert len(col) == 100
    np.testing.assert_allclose(col.geom_xy(7), pts[7:8])


def test_take_and_concat():
    col = wkt.from_wkt(fx.ALL_WKT)
    sub = col.take([5, 0, 8])
    assert len(sub) == 3
    assert sub.geometry_type(0) == GeometryType.POLYGON
    assert wkt.to_wkt(sub)[1] == wkt.to_wkt(col)[0]
    both = sub.concat(col)
    assert len(both) == 3 + len(col)


def test_padded_form():
    col = wkt.from_wkt(fx.POLY_WKT)
    padded = col.to_padded()
    assert padded.verts.shape[0] == 3
    assert padded.ring_len[1, 1] == 4  # hole ring, open form
    assert padded.ring_is_hole[1, 1]
    # closing vertex present
    v = padded.verts[0, 0]
    n = padded.ring_len[0, 0]
    np.testing.assert_allclose(v[n], v[0])


def test_bounds():
    col = wkt.from_wkt(fx.POLY_WKT)
    b = col.bounds()
    np.testing.assert_allclose(b[0], [0, 0, 4, 4])
    np.testing.assert_allclose(b[1], [0, 0, 10, 10])


def test_bounds_trailing_empty():
    # regression: an empty geometry after a nonempty one must not truncate
    # the nonempty segment's reduceat range (last vertex is often extremal)
    col = wkt.from_wkt(["LINESTRING (0 0, 1 1, 5 5)", "POLYGON EMPTY"])
    b = col.bounds()
    np.testing.assert_allclose(b[0], [0, 0, 5, 5])
    assert np.isnan(b[1]).all()
    # empty between nonempties
    col = wkt.from_wkt(
        ["POLYGON EMPTY", "LINESTRING (2 3, -1 7)", "POLYGON EMPTY"]
    )
    b = col.bounds()
    assert np.isnan(b[0]).all() and np.isnan(b[2]).all()
    np.testing.assert_allclose(b[1], [-1, 3, 2, 7])


def test_feature_collection(tmp_path):
    fc = {
        "type": "FeatureCollection",
        "features": [
            {
                "type": "Feature",
                "properties": {"name": "a"},
                "geometry": {"type": "Point", "coordinates": [1.0, 2.0]},
            },
            {
                "type": "Feature",
                "properties": {"name": "b"},
                "geometry": {
                    "type": "Polygon",
                    "coordinates": [[[0, 0], [1, 0], [1, 1], [0, 0]]],
                },
            },
        ],
    }
    import json

    p = tmp_path / "fc.geojson"
    p.write_text(json.dumps(fc))
    col, props = geojson.read_feature_collection(str(p))
    assert len(col) == 2
    assert props[0]["name"] == "a"
    assert col.geometry_type(1) == GeometryType.POLYGON


# ------------------------------------------------------- GeometryCollection
# Reference semantics (`MosaicGeometryJTS.scala:179-192`): a non-empty
# collection keeps its FIRST polygonal top-level member, else POLYGON EMPTY.

_GC_WKT = (
    "GEOMETRYCOLLECTION (POINT (9 9), "
    "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 1 2, 2 2, 2 1, 1 1)), "
    "LINESTRING (0 0, 9 9), "
    "MULTIPOLYGON (((5 5, 6 5, 6 6, 5 6, 5 5))))"
)


def test_collection_wkt_first_polygonal():
    col = wkt.from_wkt([_GC_WKT])
    assert col.geometry_type(0) == GeometryType.POLYGON
    # the hole survives the copy; the later multipolygon is discarded
    assert wkt.to_wkt(col)[0] == (
        "POLYGON ((0 0,4 0,4 4,0 4,0 0),(1 1,1 2,2 2,2 1,1 1))"
    )


def test_collection_wkt_multipolygon_first():
    col = wkt.from_wkt(
        ["GEOMETRYCOLLECTION (MULTIPOLYGON (((0 0, 2 0, 2 2, 0 2, 0 0)),"
         " ((3 3, 4 3, 4 4, 3 4, 3 3))), POLYGON ((9 9, 10 9, 10 10, 9 10, 9 9)))"]
    )
    assert col.geometry_type(0) == GeometryType.MULTIPOLYGON
    assert len(list(col.geom_parts(0))) == 2


def test_collection_wkt_no_polygonal_is_empty_polygon():
    col = wkt.from_wkt(
        ["GEOMETRYCOLLECTION (POINT (1 2), LINESTRING (0 0, 1 1))"]
    )
    assert col.geometry_type(0) == GeometryType.POLYGON
    assert wkt.to_wkt(col)[0] == "POLYGON EMPTY"


def test_collection_wkt_nested_collection_not_searched():
    # the reference's find() only inspects top-level member types, so a
    # polygon inside a nested collection must NOT be selected
    col = wkt.from_wkt(
        ["GEOMETRYCOLLECTION (GEOMETRYCOLLECTION ("
         "POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))), POINT (5 5))"]
    )
    assert col.geometry_type(0) == GeometryType.POLYGON
    assert wkt.to_wkt(col)[0] == "POLYGON EMPTY"


def test_collection_wkb_roundtrip_via_members():
    import struct

    members = wkt.from_wkt(
        [
            "POINT (9 9)",
            "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 1 2, 2 2, 2 1, 1 1))",
            "LINESTRING (0 0, 9 9)",
        ]
    )
    blobs = wkb.to_wkb(members)
    gc = b"\x01" + struct.pack("<I", 7) + struct.pack("<I", len(blobs))
    gc += b"".join(blobs)
    col = wkb.from_wkb([gc])
    assert col.geometry_type(0) == GeometryType.POLYGON
    want = wkt.from_wkt([_GC_WKT])
    np.testing.assert_allclose(
        np.asarray(col.xy), np.asarray(want.xy), atol=1e-12
    )


def test_collection_geojson():
    doc = {
        "type": "GeometryCollection",
        "geometries": [
            {"type": "Point", "coordinates": [9, 9]},
            {
                "type": "Polygon",
                "coordinates": [
                    [[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]],
                    [[1, 1], [1, 2], [2, 2], [2, 1], [1, 1]],
                ],
            },
        ],
    }
    col = geojson.from_geojson([doc])
    assert col.geometry_type(0) == GeometryType.POLYGON
    assert len(list(col.part_rings(list(col.geom_parts(0))[0]))) == 2
    # empty collection keeps its type (null-geometry feature encoding)
    empty = geojson.from_geojson([{"type": "GeometryCollection", "geometries": []}])
    assert empty.geometry_type(0) == GeometryType.GEOMETRYCOLLECTION
