"""Per-subsystem / per-tenant health state machine (PR 20).

The acceptance contract of `mosaic_tpu/obs/health.py`:

- healthy → degrading → unhealthy on the windowed bad fraction, with
  hysteresis (clear only below ``clear_factor x`` the enter threshold);
- below ``min_events`` the state HOLDS; an empty window decays healthy;
- tenant scopes build from ``router_shed``/``router_stage`` events;
- every transition emits one typed ``health_transition`` and updates
  the ``obs.health{scope}`` gauge;
- the ServeRouter's eviction order prefers unhealthy tenants over
  warm/LRU considerations.
"""

from types import SimpleNamespace

import pytest

from mosaic_tpu.obs import health
from mosaic_tpu.obs import metrics as obs_metrics
from mosaic_tpu.runtime import telemetry


@pytest.fixture(autouse=True)
def _quiesce_process_monitor():
    """The process-wide monitor also watches the live spine; once other
    suites have fed it, its piggybacked cadence evaluations can emit
    their own ``health_transition`` inside these tests' captures (and
    overwrite the ``obs.health`` gauge). Private monitors only."""
    health.uninstall()
    try:
        yield
    finally:
        health.install()


def _feed(m, event, n, t, **fields):
    for _ in range(n):
        m.observer({"event": event, "ts_mono": t, **fields})


class TestStateMachine:
    def test_shed_storm_goes_unhealthy_with_transition_event(self):
        m = health.HealthMonitor(window_s=10.0)
        with telemetry.capture() as events:
            _feed(m, "serve_shed", 5, 100.0)
            m.evaluate(100.0)
        assert m.state("serve") == "unhealthy"
        trans = [e for e in events if e["event"] == "health_transition"]
        assert len(trans) == 1
        assert trans[0]["scope"] == "serve"
        assert trans[0]["prev"] == "healthy"
        assert trans[0]["to"] == "unhealthy"
        assert trans[0]["bad_ratio"] == 1.0
        g = obs_metrics.gauge("obs.health")
        assert g.value(scope="serve") == health.RANK["unhealthy"]

    def test_hysteresis_clears_stepwise_below_half_threshold(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "serve_shed", 5, 100.0)
        m.evaluate(100.0)
        assert m.state("serve") == "unhealthy"
        # ratio 5/20 = 0.25 >= 0.5*unhealthy_ratio: still unhealthy
        _feed(m, "serve_request", 15, 100.0)
        m.evaluate(100.5)
        assert m.state("serve") == "unhealthy"
        # ratio 5/40 = 0.125 < 0.25 clear floor: down to degrading
        # (still >= 0.05, the degrading clear floor)
        _feed(m, "serve_request", 20, 100.0)
        m.evaluate(101.0)
        assert m.state("serve") == "degrading"
        # ratio 5/120 < 0.05: all the way back to healthy
        _feed(m, "serve_request", 80, 100.0)
        m.evaluate(101.5)
        assert m.state("serve") == "healthy"

    def test_min_events_holds_state(self):
        m = health.HealthMonitor(window_s=10.0, min_events=5)
        _feed(m, "serve_shed", 2, 100.0)  # 100% bad but only 2 events
        m.evaluate(100.0)
        assert m.state("serve") == "healthy"

    def test_empty_window_decays_to_healthy(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "serve_shed", 5, 100.0)
        m.evaluate(100.0)
        assert m.state("serve") == "unhealthy"
        with telemetry.capture() as events:
            m.evaluate(500.0)  # storm long gone
        assert m.state("serve") == "healthy"
        (t,) = [e for e in events if e["event"] == "health_transition"]
        assert t["prev"] == "unhealthy" and t["to"] == "healthy"

    def test_degrading_band(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "serve_request", 8, 100.0)
        _feed(m, "serve_shed", 2, 100.0)  # ratio 0.2: degrading band
        m.evaluate(100.0)
        assert m.state("serve") == "degrading"

    def test_unknown_scope_reads_healthy(self):
        m = health.HealthMonitor()
        assert m.state("nonesuch") == "healthy"
        assert m.tenant_state("ghost") == "healthy"


class TestTenantScoping:
    def test_router_events_build_tenant_scopes(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "router_shed", 5, 100.0, tenant="noisy", reason="queue_full")
        _feed(m, "router_stage", 20, 100.0, tenant="quiet", stage="admit")
        m.evaluate(100.0)
        assert m.tenant_state("noisy") == "unhealthy"
        assert m.tenant_state("quiet") == "healthy"
        # router_shed is also a serve-subsystem bad
        assert m.state("serve") == "unhealthy"

    def test_non_admit_router_stage_is_ignored(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "router_stage", 5, 100.0, tenant="t", stage="revive")
        m.evaluate(100.0)
        assert "tenant:t" not in m.snapshot(100.0)["scopes"]

    def test_snapshot_shape(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "serve_shed", 5, 100.0)
        snap = m.snapshot(100.0)
        assert snap["window_s"] == 10.0
        s = snap["scopes"]["serve"]
        assert s["state"] == "unhealthy" and s["rank"] == 2
        assert s["events"] == 5 and s["transitions"] == 1


class TestSubsystemRouting:
    @pytest.mark.parametrize("event,scope", [
        ("transient_retry", "runtime"),
        ("retry_exhausted", "runtime"),
        ("watchdog_stall", "runtime"),
        ("capacity_overflow", "stream"),
        ("stream_quarantine", "stream"),
    ])
    def test_bad_events_route_to_their_subsystem(self, event, scope):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, event, 5, 100.0)
        m.evaluate(100.0)
        assert m.state(scope) == "unhealthy"

    def test_stream_stage_is_a_stream_good(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "capacity_overflow", 2, 100.0)
        _feed(m, "stream_stage", 48, 100.0, stage="join_loop")
        m.evaluate(100.0)
        assert m.state("stream") == "healthy"  # ratio 0.04 < 0.10


class TestRouterEviction:
    """The router's eviction key consumes tenant health: sickest first,
    then cold engines, then LRU — probed against the real
    ``ServeRouter._eviction_victim`` with duck-typed tenants."""

    @staticmethod
    def _tenant(name, warmed, last_used):
        return SimpleNamespace(
            name=name,
            engine=SimpleNamespace(core=SimpleNamespace(warmed=warmed)),
            last_used=last_used,
        )

    def _stub(self, tenants, monitor):
        from mosaic_tpu.serve.router import ServeRouter

        stub = SimpleNamespace(
            _tenants={t.name: t for t in tenants},
            health_monitor=monitor,
        )
        return lambda exclude: ServeRouter._eviction_victim(
            stub, exclude=exclude
        )

    def test_unhealthy_tenant_is_evicted_first(self):
        m = health.HealthMonitor(window_s=10.0)
        _feed(m, "router_shed", 10, 100.0, tenant="sick")
        m.evaluate(100.0)
        assert m.tenant_state("sick") == "unhealthy"
        # "sick" is warm and most-recently-used; "fresh" is cold and
        # oldest — health outranks both signals
        victim = self._stub([
            self._tenant("sick", warmed=True, last_used=100.0),
            self._tenant("fresh", warmed=False, last_used=1.0),
        ], m)(exclude="incoming")
        assert victim.name == "sick"

    def test_healthy_fleet_falls_back_to_cold_then_lru(self):
        m = health.HealthMonitor(window_s=10.0)
        victim = self._stub([
            self._tenant("warm_old", warmed=True, last_used=1.0),
            self._tenant("cold_new", warmed=False, last_used=100.0),
        ], m)(exclude="incoming")
        assert victim.name == "cold_new"  # cold loses residency first
        victim = self._stub([
            self._tenant("warm_old", warmed=True, last_used=1.0),
            self._tenant("warm_new", warmed=True, last_used=100.0),
        ], m)(exclude="incoming")
        assert victim.name == "warm_old"  # then LRU

    def test_exclude_is_never_chosen(self):
        m = health.HealthMonitor()
        pick = self._stub(
            [self._tenant("only", warmed=True, last_used=1.0)], m
        )
        assert pick(exclude="only") is None
