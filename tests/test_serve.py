"""Serving-engine contract (PR 4): shape-bucketed compile discipline,
co-batched bit-identity, deadline shedding, backpressure, quarantine
isolation, and degradation — `mosaic_tpu/serve/`. PR 5 adds the trace
contract: one request, one connected trace across threads."""

import time

import numpy as np
import pytest

from mosaic_tpu import obs
from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.runtime import faults, telemetry
from mosaic_tpu.runtime.errors import DegradedResult, Overloaded
from mosaic_tpu.serve import BucketLadder, ServeEngine
from mosaic_tpu.sql.join import (
    build_chip_index,
    clear_join_caches,
    join_cache_stats,
    pip_join,
)

BBOX = (-25.0, -25.0, 35.0, 20.0)
RES = 3


@pytest.fixture(scope="module")
def grid():
    return CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))


@pytest.fixture(scope="module")
def index(grid):
    col = wkt.from_wkt(
        [
            "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
            "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
            "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
        ]
    )
    return build_chip_index(tessellate(col, grid, RES, keep_core_geoms=False))


def make_engine(index, grid, **kw):
    kw.setdefault("ladder", BucketLadder(64, 4096))
    kw.setdefault("bounds", BBOX)
    kw.setdefault("max_wait_s", 0.01)
    return ServeEngine(index, grid, RES, **kw)


def rand_points(rng, n):
    return rng.uniform(BBOX[:2], BBOX[2:], (n, 2))


class TestBucketLadder:
    def test_ladder_rungs(self):
        lad = BucketLadder(64, 1024)
        assert lad.buckets == (64, 128, 256, 512, 1024)

    @pytest.mark.parametrize(
        "n,expect", [(1, 64), (64, 64), (65, 128), (1000, 1024), (1024, 1024)]
    )
    def test_bucket_for(self, n, expect):
        assert BucketLadder(64, 1024).bucket_for(n) == expect

    def test_bucket_for_over_max_raises(self):
        with pytest.raises(ValueError, match="exceeds the top bucket"):
            BucketLadder(64, 1024).bucket_for(1025)

    def test_pad_repeats_first_row(self):
        pts = np.array([[1.0, 2.0], [3.0, 4.0]])
        padded, n = BucketLadder(4, 16).pad(pts)
        assert n == 2 and padded.shape == (4, 2)
        np.testing.assert_array_equal(padded[:2], pts)
        np.testing.assert_array_equal(padded[2:], [[1.0, 2.0], [1.0, 2.0]])


class TestCompileDiscipline:
    def test_one_compile_per_bucket_after_warmup(self, index, grid):
        """Over randomized request sizes spanning the ladder, the engine
        introduces ZERO new compile signatures after warmup()."""
        with make_engine(index, grid) as eng:
            info = eng.warmup()
            assert info["signatures"] == len(eng.ladder.buckets)
            rng = np.random.default_rng(7)
            futs = [
                eng.submit(
                    rand_points(rng, int(rng.integers(1, 3000))),
                    deadline_s=30.0,
                )
                for _ in range(40)
            ]
            for f in futs:
                f.result(timeout=30)
            m = eng.metrics()
            assert m["cold_compiles"] == 0, m
            assert m["compile_signatures"] == len(eng.ladder.buckets)
            assert m["completed"] == 40

    def test_cold_dispatch_counts_without_warmup(self, index, grid):
        with make_engine(index, grid) as eng:
            eng.core.freeze()  # arm the tripwire, skip warmup
            eng.join(rand_points(np.random.default_rng(0), 10),
                     deadline_s=30.0)
            assert eng.metrics()["cold_compiles"] == 1


class TestBitIdentity:
    def test_cobatched_equals_solo_across_bucket_boundaries(
        self, index, grid
    ):
        """Concurrent requests coalesced into one device batch return
        EXACTLY the bits of solo execution — including sizes straddling
        bucket boundaries (63..65, 255..257, ...)."""
        rng = np.random.default_rng(3)
        sizes = [63, 64, 65, 1, 255, 256, 257, 100, 1023, 17]
        reqs = [rand_points(rng, n) for n in sizes]
        # solo: one engine per request so every dispatch is unbatched
        solo = []
        with make_engine(index, grid, max_wait_s=0.0) as eng1:
            eng1.warmup()
            for pts in reqs:
                solo.append(np.asarray(eng1.join(pts, deadline_s=30.0)))
        # co-batched: submitted together inside one batching window
        with make_engine(index, grid, max_wait_s=0.05) as eng2:
            eng2.warmup()
            futs = [eng2.submit(p, deadline_s=30.0) for p in reqs]
            outs = [np.asarray(f.result(timeout=30)) for f in futs]
            assert eng2.metrics()["batches"] < len(reqs)  # really coalesced
        for pts, a, b in zip(reqs, solo, outs):
            np.testing.assert_array_equal(a, b)
            # and both equal the offline batch API
            ref = np.asarray(
                pip_join(pts, None, grid, RES, chip_index=index,
                         recheck=False)
            )
            np.testing.assert_array_equal(b, ref)


class TestDeadlinesAndShedding:
    def test_dispatch_stall_sheds_only_the_late_request(self, index, grid):
        """An injected ``serve.dispatch`` stall delays the shared batch;
        the request whose deadline expires is shed (typed Overloaded,
        metrics["shed"]), its batchmate still gets exact results."""
        with make_engine(index, grid, max_wait_s=0.05) as eng:
            eng.warmup()
            rng = np.random.default_rng(11)
            tight = rand_points(rng, 40)
            slack = rand_points(rng, 50)
            with faults.stalls(0.8, n=1, sites=("serve.dispatch",)):
                f_tight = eng.submit(tight, deadline_s=0.15)
                f_slack = eng.submit(slack, deadline_s=30.0)
                with pytest.raises(Overloaded) as exc:
                    f_tight.result(timeout=30)
                assert exc.value.reason == "deadline"
                out = np.asarray(f_slack.result(timeout=30))
            ref = np.asarray(
                pip_join(slack, None, grid, RES, chip_index=index,
                         recheck=False)
            )
            np.testing.assert_array_equal(out, ref)
            m = eng.metrics()
            assert m["shed"] == 1 and m["shed_deadline"] == 1
            assert m["completed"] == 1

    def test_expired_before_dispatch_is_shed_without_device_work(
        self, index, grid
    ):
        with make_engine(index, grid, max_wait_s=0.05) as eng:
            eng.warmup()
            batches_before = eng.metrics()["batches"]
            f = eng.submit(
                rand_points(np.random.default_rng(2), 10),
                deadline_s=0.0,  # already expired at formation
            )
            with pytest.raises(Overloaded) as exc:
                f.result(timeout=30)
            assert exc.value.reason == "deadline"
            assert eng.metrics()["batches"] == batches_before

    def test_queue_full_sheds_with_typed_overloaded(self, index, grid):
        """With the queue at capacity behind a stalled dispatch, admission
        refuses instead of queueing without bound."""
        with make_engine(
            index, grid, queue_capacity=2, max_wait_s=0.0
        ) as eng:
            eng.warmup()
            rng = np.random.default_rng(5)
            with telemetry.capture() as events, faults.stalls(
                0.7, n=1, sites=("serve.dispatch",)
            ):
                futs = [
                    eng.submit(rand_points(rng, 8), deadline_s=30.0)
                ]  # occupies the worker (stalled)
                time.sleep(0.1)
                shed = 0
                for _ in range(6):
                    try:
                        futs.append(
                            eng.submit(rand_points(rng, 8), deadline_s=30.0)
                        )
                    except Overloaded as e:
                        assert e.reason == "queue_full"
                        assert e.capacity == 2
                        shed += 1
                assert shed >= 1
                for f in futs:
                    f.result(timeout=30)
            assert eng.metrics()["shed"] >= shed
            assert any(
                e["event"] == "serve_shed"
                and e.get("reason") == "queue_full"
                for e in events
            )


class TestQuarantine:
    def test_poison_request_leaves_batchmates_untouched(self, index, grid):
        """A co-batched request carrying NaN/out-of-bounds rows is parked
        through runtime/quarantine.py; its batchmates' bits are identical
        to a poison-free run and the poisoned rows answer -1."""
        rng = np.random.default_rng(13)
        clean_a = rand_points(rng, 120)
        clean_b = rand_points(rng, 77)
        poison = rand_points(rng, 60)
        poison[5] = np.nan
        poison[17, 0] = np.inf
        poison[33] = (1e6, 1e6)  # far outside BBOX bounds
        with make_engine(index, grid, max_wait_s=0.05) as eng:
            eng.warmup()
            fa = eng.submit(clean_a, deadline_s=30.0)
            fp = eng.submit(poison, deadline_s=30.0)
            fb = eng.submit(clean_b, deadline_s=30.0)
            out_a = np.asarray(fa.result(timeout=30))
            out_p = np.asarray(fp.result(timeout=30))
            out_b = np.asarray(fb.result(timeout=30))
            m = eng.metrics()
        for pts, out in ((clean_a, out_a), (clean_b, out_b)):
            ref = np.asarray(
                pip_join(pts, None, grid, RES, chip_index=index,
                         recheck=False)
            )
            np.testing.assert_array_equal(out, ref)
        assert out_p[5] == -1 and out_p[17] == -1 and out_p[33] == -1
        good = np.ones(60, bool)
        good[[5, 17, 33]] = False
        ref_p = np.asarray(
            pip_join(poison[good], None, grid, RES, chip_index=index,
                     recheck=False)
        )
        np.testing.assert_array_equal(out_p[good], ref_p)
        assert m["quarantined"] == 3
        assert m["poisoned_requests"] == 1

    def test_corrupt_injection_at_admit_site(self, index, grid):
        """`faults.corrupt_batches` at serve.admit poisons rows before
        scrubbing — exactly those rows must be parked."""
        with make_engine(index, grid) as eng:
            eng.warmup()
            with faults.corrupt_batches(4, sites=("serve.admit",)):
                out = np.asarray(
                    eng.join(
                        rand_points(np.random.default_rng(1), 30),
                        deadline_s=30.0,
                    )
                )
            assert (out[:4] == -1).all()
            assert eng.metrics()["quarantined"] == 4


class TestResilience:
    def test_transient_dispatch_failure_retries_to_success(
        self, index, grid, monkeypatch
    ):
        monkeypatch.setenv("MOSAIC_RETRY_BASE_S", "0.01")
        rng = np.random.default_rng(21)
        pts = rand_points(rng, 90)
        with make_engine(index, grid) as eng:
            eng.warmup()
            with telemetry.capture() as events, faults.transient_errors(
                1, sites=("serve.dispatch",)
            ):
                out = np.asarray(eng.join(pts, deadline_s=30.0))
        ref = np.asarray(
            pip_join(pts, None, grid, RES, chip_index=index, recheck=False)
        )
        np.testing.assert_array_equal(out, ref)
        assert any(e["event"] == "transient_retry" for e in events)

    def test_retry_exhaustion_degrades_to_host_oracle(
        self, index, grid, monkeypatch
    ):
        monkeypatch.setenv("MOSAIC_RETRY_ATTEMPTS", "2")
        monkeypatch.setenv("MOSAIC_RETRY_BASE_S", "0.01")
        rng = np.random.default_rng(23)
        pts = rand_points(rng, 70)
        with make_engine(index, grid) as eng:
            eng.warmup()
            with faults.transient_errors(10, sites=("serve.dispatch",)):
                out = eng.join(pts, deadline_s=30.0)
        assert isinstance(out, DegradedResult)
        ref = np.asarray(
            pip_join(pts, None, grid, RES, chip_index=index, recheck=False)
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert eng.metrics()["degraded"] == 1


class TestJoinCacheHatch:
    def test_stats_and_clear(self):
        with telemetry.capture() as events:
            stats = join_cache_stats()
            cleared = clear_join_caches()
        assert stats["cells_prog"]["maxsize"] == 64
        assert cleared["cells_prog"]["currsize"] >= 0
        assert join_cache_stats(emit=False)["cells_prog"]["currsize"] == 0
        names = [e["event"] for e in events]
        assert "join_cache_stats" in names
        assert "join_caches_cleared" in names


class TestTracing:
    def test_one_request_is_one_connected_trace(self, index, grid):
        """A request submitted on the test thread and dispatched on the
        batcher thread yields ONE trace: every span shares the
        trace_id, parent links resolve, no orphans — admit (submit
        thread) through batch/dispatch (batcher thread) to the
        request-root close at scatter-back."""
        with make_engine(index, grid) as eng:
            eng.warmup()
            with telemetry.capture() as events:
                out = eng.join(
                    rand_points(np.random.default_rng(31), 25),
                    deadline_s=30.0,
                )
        assert np.asarray(out).shape == (25,)
        spans = [e for e in events if e["event"] == "span"]
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) >= {
            "serve.request", "serve.admit", "serve.batch",
            "serve.dispatch",
        }
        summ = obs.trace_summary(events)
        assert len(summ) == 1, f"expected ONE trace, got {summ}"
        ((tid, t),) = summ.items()
        assert t["roots"] == 1 and not t["orphans"], t
        root = by_name["serve.request"]
        assert root["parent_id"] is None and root["trace_id"] == tid
        assert by_name["serve.admit"]["parent_id"] == root["span_id"]
        assert by_name["serve.batch"]["parent_id"] == root["span_id"]
        assert (
            by_name["serve.dispatch"]["parent_id"]
            == by_name["serve.batch"]["span_id"]
        )
        # the per-request latency event carries the same trace
        req_ev = next(e for e in events if e["event"] == "serve_request")
        assert req_ev["trace_id"] == tid

    def test_batchmates_keep_their_own_traces(self, index, grid):
        """Co-batched requests stay separate traces; each request's
        serve_request event and root span carry its OWN trace_id."""
        with make_engine(index, grid, max_wait_s=0.05) as eng:
            eng.warmup()
            rng = np.random.default_rng(33)
            with telemetry.capture() as events:
                futs = [
                    eng.submit(rand_points(rng, 30), deadline_s=30.0)
                    for _ in range(3)
                ]
                for f in futs:
                    f.result(timeout=30)
            assert eng.metrics()["batches"] < 3  # really coalesced
        roots = [
            e for e in events
            if e["event"] == "span" and e["name"] == "serve.request"
        ]
        assert len(roots) == 3
        assert len({r["trace_id"] for r in roots}) == 3
        req_evs = [e for e in events if e["event"] == "serve_request"]
        assert sorted(e["trace_id"] for e in req_evs) == sorted(
            r["trace_id"] for r in roots
        )

    def test_retry_attaches_to_the_request_trace(self, index, grid,
                                                 monkeypatch):
        """A transient dispatch failure's retry events land INSIDE the
        request's trace — the causal link the flat trail never had."""
        monkeypatch.setenv("MOSAIC_RETRY_BASE_S", "0.01")
        with make_engine(index, grid) as eng:
            eng.warmup()
            with telemetry.capture() as events, faults.transient_errors(
                1, sites=("serve.dispatch",)
            ):
                eng.join(
                    rand_points(np.random.default_rng(35), 40),
                    deadline_s=30.0,
                )
        root = next(
            e for e in events
            if e["event"] == "span" and e["name"] == "serve.request"
        )
        retry = next(e for e in events if e["event"] == "transient_retry")
        assert retry["trace_id"] == root["trace_id"]
        # and the shed path stamps too: root span closed exactly once
        dispatch = next(
            e for e in events
            if e["event"] == "span" and e["name"] == "serve.dispatch"
        )
        assert dispatch["trace_id"] == root["trace_id"]

    def test_shed_request_trace_records_the_reason(self, index, grid):
        with make_engine(index, grid, max_wait_s=0.05) as eng:
            eng.warmup()
            with telemetry.capture() as events:
                f = eng.submit(
                    rand_points(np.random.default_rng(37), 10),
                    deadline_s=0.0,
                )
                with pytest.raises(Overloaded):
                    f.result(timeout=30)
        root = next(
            e for e in events
            if e["event"] == "span" and e["name"] == "serve.request"
        )
        assert root["error"] == "Overloaded"
        assert root["reason"] == "deadline"
        shed = next(e for e in events if e["event"] == "serve_shed")
        assert shed["trace_id"] == root["trace_id"]


class TestSummarize:
    def test_percentiles(self):
        events = [
            {"event": "serve_request", "seconds": s}
            for s in (0.01, 0.02, 0.03, 0.04, 0.10)
        ] + [{"event": "other", "seconds": 99.0}, {"event": "serve_request"}]
        s = telemetry.summarize(events, event="serve_request")
        assert s["count"] == 5
        assert s["p50"] == 0.03
        assert s["max"] == 0.10
        assert s["p99"] == 0.10

    def test_empty(self):
        s = telemetry.summarize([], event="x")
        assert s == {
            "count": 0, "p50": 0.0, "p90": 0.0, "p99": 0.0,
            "mean": 0.0, "max": 0.0, "sum": 0.0,
        }
