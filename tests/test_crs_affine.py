"""CRS transforms + affine ops.

Anchors: the OS Guide transverse-Mercator worked example (OSGB36 lat/lon ->
BNG easting/northing), the Web Mercator closed form, and round-trips for
every supported SRID in both the numpy and the jitted jax path.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core import crs
from mosaic_tpu.core.geometry import affine
from mosaic_tpu.core.geometry.wkt import from_wkt, to_wkt


# OS Guide worked example: OSGB36 lat 52°39'27.2531"N, lon 1°43'4.5177"E
_OS_LAT = 52 + 39 / 60 + 27.2531 / 3600
_OS_LON = 1 + 43 / 60 + 4.5177 / 3600
_OS_E, _OS_N = 651409.903, 313177.270


def test_tm_forward_os_anchor():
    ll = np.radians(np.array([[_OS_LON, _OS_LAT]]))
    en = crs.tm_forward(crs.BNG_TM, ll)
    assert abs(en[0, 0] - _OS_E) < 2e-3
    assert abs(en[0, 1] - _OS_N) < 2e-3


def test_tm_inverse_os_anchor():
    ll = crs.tm_inverse(crs.BNG_TM, np.array([[_OS_E, _OS_N]]))
    deg = np.degrees(ll)
    assert abs(deg[0, 0] - _OS_LON) < 1e-8
    assert abs(deg[0, 1] - _OS_LAT) < 1e-8


def test_webmercator_closed_form():
    pts = np.array([[45.0, 0.0], [-180.0, 0.0], [0.0, 45.0]])
    out = crs.from_wgs84(pts, 3857)
    assert abs(out[0, 0] - crs.WGS84_A * math.pi / 4) < 1e-6
    assert abs(out[1, 0] + 20037508.342789244) < 1e-6
    back = crs.to_wgs84(out, 3857)
    np.testing.assert_allclose(back, pts, atol=1e-9)


@pytest.mark.parametrize("srid", [3857, 27700, 32630, 32733])
def test_roundtrip_numpy(srid):
    rng = np.random.default_rng(srid)
    if srid == 27700:
        lon = rng.uniform(-5, 1.5, 64)
        lat = rng.uniform(50, 58, 64)
    elif srid == 32630:
        lon = rng.uniform(-6, 0, 64)
        lat = rng.uniform(1, 60, 64)
    elif srid == 32733:
        lon = rng.uniform(12, 18, 64)
        lat = rng.uniform(-60, -1, 64)
    else:
        lon = rng.uniform(-179, 179, 64)
        lat = rng.uniform(-84, 84, 64)
    pts = np.stack([lon, lat], axis=-1)
    # 2e-7 deg ~ 2 cm: the Helmert inverse (negated params) is approximate
    back = crs.to_wgs84(crs.from_wgs84(pts, srid), srid)
    np.testing.assert_allclose(back, pts, atol=2e-7)


def test_transform_jax_matches_numpy():
    pts = np.array([[-0.1195, 51.5033], [-2.0, 53.0], [0.5, 52.0]])
    host = crs.from_wgs84(pts, 27700)

    @jax.jit
    def f(x):
        return crs.from_wgs84(x, 27700, xp=jnp)

    dev = np.asarray(f(jnp.asarray(pts, dtype=jnp.float64)))
    np.testing.assert_allclose(dev, host, atol=1e-5)


def test_bng_known_point_tolerance():
    # London Eye, WGS84 -> BNG grid ref TQ 30620 79940 (±20 m: single
    # 7-parameter Helmert, like proj4j's +towgs84 path, not OSTN15)
    out = crs.from_wgs84(np.array([[-0.119543, 51.503324]]), 27700)
    assert abs(out[0, 0] - 530620) < 20
    assert abs(out[0, 1] - 179940) < 20


def test_crs_bounds_lookup():
    geo = crs.crs_bounds(27700, reprojected=False)
    proj = crs.crs_bounds(27700, reprojected=True)
    assert geo[0] < -8 and proj[2] > 600000
    assert crs.parse_crs_code("EPSG:27700") == 27700
    assert crs.parse_crs_code(4326) == 4326


# ----------------------------------------------------------------- affine


def test_translate_scale_rotate():
    col = from_wkt(["POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))", "POINT (1 1)"])
    t = affine.translate(col, 10, 20)
    assert to_wkt(t.take([1]))[0] == "POINT (11 21)"
    s = affine.scale(col, 2, 3)
    np.testing.assert_allclose(s.geom_xy(1), [[2.0, 3.0]])
    r = affine.rotate(col, math.pi / 2)
    np.testing.assert_allclose(r.geom_xy(1), [[-1.0, 1.0]], atol=1e-12)


def test_per_geometry_params():
    col = from_wkt(["POINT (1 0)", "POINT (1 0)"])
    r = affine.rotate(col, np.array([0.0, math.pi]))
    np.testing.assert_allclose(r.geom_xy(0), [[1.0, 0.0]], atol=1e-12)
    np.testing.assert_allclose(r.geom_xy(1), [[-1.0, 0.0]], atol=1e-12)


def test_transform_srid_roundtrip():
    col = from_wkt(["POINT (-0.5 51.6)", "LINESTRING (-1 52, -0.9 52.1)"])
    bng = affine.transform_srid(col, 27700)
    assert set(bng.srid.tolist()) == {27700}
    assert bng.geom_xy(0)[0, 0] > 100000  # easting, not degrees
    back = affine.transform_srid(bng, 4326)
    np.testing.assert_allclose(back.xy, col.xy, atol=1e-7)


def test_set_srid_labels_only():
    col = from_wkt(["POINT (1 2)"])
    out = affine.set_srid(col, 27700)
    assert out.srid[0] == 27700
    np.testing.assert_array_equal(out.xy, col.xy)


# ---------------------------------------------------------------- round 3:
# arbitrary-EPSG families (VERDICT round-2 task #5)


class TestProjectionFamilies:
    ANCHORS = [
        # (srid, natural origin lon/lat, false easting/northing)
        (2154, (3.0, 46.5), (700000.0, 6600000.0)),   # Lambert-93 LCC-2SP
        (5070, (-96.0, 23.0), (0.0, 0.0)),            # CONUS Albers
        (3035, (10.0, 52.0), (4321000.0, 3210000.0)), # LAEA Europe
        (3413, (-45.0, 90.0), (0.0, 0.0)),            # polar stereo N
        (3031, (0.0, -90.0), (0.0, 0.0)),             # polar stereo S
        (32661, (0.0, 90.0), (2000000.0, 2000000.0)), # UPS North
        (2193, (173.0, 0.0), (1600000.0, 10000000.0)),# NZTM2000
        (3034, (10.0, 52.0), (4000000.0, 2800000.0)), # LCC Europe
        (3978, (-95.0, 49.0), (0.0, 0.0)),            # Canada Atlas Lambert
        (3310, (-120.0, 0.0), (0.0, -4000000.0)),     # California Albers
        (6931, (0.0, 90.0), (0.0, 0.0)),              # EASE-Grid 2.0 North
        (6932, (0.0, -90.0), (0.0, 0.0)),             # EASE-Grid 2.0 South
        (3995, (0.0, 90.0), (0.0, 0.0)),              # Arctic Polar Stereo
        (2180, (19.0, 0.0), (500000.0, -5300000.0)),  # Poland CS92
        (5186, (127.5, 38.0), (200000.0, 600000.0)),  # Korea Central Belt
    ]

    @pytest.mark.parametrize("srid,ll,en", ANCHORS)
    def test_natural_origin_anchor(self, srid, ll, en):
        got = crs.from_wgs84(np.asarray([ll]), srid, np)[0]
        np.testing.assert_allclose(got, en, atol=1e-6)

    @pytest.mark.parametrize(
        "srid",
        [2154, 5070, 3035, 3577, 3413, 3031, 32661, 32761, 2193, 25832,
         26917, 3034, 3347, 3978, 3112, 6350, 102003, 3310, 3573, 3574,
         3575, 3576, 6931, 6932, 3995, 3976, 2180, 5186],
    )
    def test_roundtrip_under_1e6_deg(self, srid):
        rng = np.random.default_rng(srid)
        x0, y0, x1, y1 = crs.crs_bounds(srid, reprojected=False)
        ll = np.stack(
            [rng.uniform(x0, x1, 500), rng.uniform(y0, min(y1, 89.5), 500)], -1
        )
        back = crs.to_wgs84(crs.from_wgs84(ll, srid, np), srid, np)
        dl = np.abs((back[:, 0] - ll[:, 0] + 180) % 360 - 180)
        assert max(dl.max(), np.abs(back[:, 1] - ll[:, 1]).max()) < 1e-6

    def test_polar_scale_at_standard_parallel(self):
        """rho at the standard parallel must equal a*m(lat_ts) — catches
        self-consistent scale errors that round trips cannot."""
        for srid, lon0, lat_ts in [(3413, -45.0, 70.0), (3031, 0.0, -71.0)]:
            en = crs.from_wgs84(np.asarray([[lon0, lat_ts]]), srid, np)[0]
            e2 = crs.WGS84_F * (2 - crs.WGS84_F)
            s = np.sin(np.radians(abs(lat_ts)))
            m = np.cos(np.radians(abs(lat_ts))) / np.sqrt(1 - e2 * s * s)
            np.testing.assert_allclose(np.hypot(*en), crs.WGS84_A * m, atol=0.5)

    def test_jnp_matches_numpy_families(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        ll = np.stack([rng.uniform(-5, 9, 200), rng.uniform(42, 51, 200)], -1)
        for srid in [2154, 3035]:
            a = crs.from_wgs84(ll, srid, np)
            b = np.asarray(crs.from_wgs84(jnp.asarray(ll), srid, jnp))
            np.testing.assert_allclose(a, b, atol=1e-6)

    def test_projected_bounds_contain_samples(self):
        rng = np.random.default_rng(2)
        for srid in [2154, 5070, 3035, 3031, 26910]:
            gx0, gy0, gx1, gy1 = crs.crs_bounds(srid, reprojected=False)
            px0, py0, px1, py1 = crs.crs_bounds(srid, reprojected=True)
            ll = np.stack(
                [rng.uniform(gx0, gx1, 300), rng.uniform(gy0, gy1, 300)], -1
            )
            en = crs.from_wgs84(ll, srid, np)
            pad = 1e-6 * max(abs(px1 - px0), abs(py1 - py0))
            assert (en[:, 0] >= px0 - pad).all() and (en[:, 0] <= px1 + pad).all()
            assert (en[:, 1] >= py0 - pad).all() and (en[:, 1] <= py1 + pad).all()

    def test_st_transform_and_validity(self):
        from mosaic_tpu.core.geometry import wkt
        from mosaic_tpu.functions import geometry as F

        col = wkt.from_wkt(["POINT (2.3522 48.8566)"])  # Paris, WGS84
        out = F.st_transform(F.st_setsrid(col, 4326), 2154)
        xy = out.geom_xy(0)
        # Lambert-93 Paris is ~(652.7 km, 6.862 Mm); definitional bounds
        assert 6e5 < xy[0, 0] < 7.1e5 and 6.8e6 < xy[0, 1] < 6.93e6
        assert bool(F.st_hasvalidcoordinates(out, "EPSG:2154", "reprojected_bounds")[0])
        back = F.st_transform(out, 4326)
        np.testing.assert_allclose(back.geom_xy(0), col.geom_xy(0), atol=1e-6)
