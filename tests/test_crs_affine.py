"""CRS transforms + affine ops.

Anchors: the OS Guide transverse-Mercator worked example (OSGB36 lat/lon ->
BNG easting/northing), the Web Mercator closed form, and round-trips for
every supported SRID in both the numpy and the jitted jax path.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core import crs
from mosaic_tpu.core.geometry import affine
from mosaic_tpu.core.geometry.wkt import from_wkt, to_wkt


# OS Guide worked example: OSGB36 lat 52°39'27.2531"N, lon 1°43'4.5177"E
_OS_LAT = 52 + 39 / 60 + 27.2531 / 3600
_OS_LON = 1 + 43 / 60 + 4.5177 / 3600
_OS_E, _OS_N = 651409.903, 313177.270


def test_tm_forward_os_anchor():
    ll = np.radians(np.array([[_OS_LON, _OS_LAT]]))
    en = crs.tm_forward(crs.BNG_TM, ll)
    assert abs(en[0, 0] - _OS_E) < 2e-3
    assert abs(en[0, 1] - _OS_N) < 2e-3


def test_tm_inverse_os_anchor():
    ll = crs.tm_inverse(crs.BNG_TM, np.array([[_OS_E, _OS_N]]))
    deg = np.degrees(ll)
    assert abs(deg[0, 0] - _OS_LON) < 1e-8
    assert abs(deg[0, 1] - _OS_LAT) < 1e-8


def test_webmercator_closed_form():
    pts = np.array([[45.0, 0.0], [-180.0, 0.0], [0.0, 45.0]])
    out = crs.from_wgs84(pts, 3857)
    assert abs(out[0, 0] - crs.WGS84_A * math.pi / 4) < 1e-6
    assert abs(out[1, 0] + 20037508.342789244) < 1e-6
    back = crs.to_wgs84(out, 3857)
    np.testing.assert_allclose(back, pts, atol=1e-9)


@pytest.mark.parametrize("srid", [3857, 27700, 32630, 32733])
def test_roundtrip_numpy(srid):
    rng = np.random.default_rng(srid)
    if srid == 27700:
        lon = rng.uniform(-5, 1.5, 64)
        lat = rng.uniform(50, 58, 64)
    elif srid == 32630:
        lon = rng.uniform(-6, 0, 64)
        lat = rng.uniform(1, 60, 64)
    elif srid == 32733:
        lon = rng.uniform(12, 18, 64)
        lat = rng.uniform(-60, -1, 64)
    else:
        lon = rng.uniform(-179, 179, 64)
        lat = rng.uniform(-84, 84, 64)
    pts = np.stack([lon, lat], axis=-1)
    # 2e-7 deg ~ 2 cm: the Helmert inverse (negated params) is approximate
    back = crs.to_wgs84(crs.from_wgs84(pts, srid), srid)
    np.testing.assert_allclose(back, pts, atol=2e-7)


def test_transform_jax_matches_numpy():
    pts = np.array([[-0.1195, 51.5033], [-2.0, 53.0], [0.5, 52.0]])
    host = crs.from_wgs84(pts, 27700)

    @jax.jit
    def f(x):
        return crs.from_wgs84(x, 27700, xp=jnp)

    dev = np.asarray(f(jnp.asarray(pts, dtype=jnp.float64)))
    np.testing.assert_allclose(dev, host, atol=1e-5)


def test_bng_known_point_tolerance():
    # London Eye, WGS84 -> BNG grid ref TQ 30620 79940 (±20 m: single
    # 7-parameter Helmert, like proj4j's +towgs84 path, not OSTN15)
    out = crs.from_wgs84(np.array([[-0.119543, 51.503324]]), 27700)
    assert abs(out[0, 0] - 530620) < 20
    assert abs(out[0, 1] - 179940) < 20


def test_crs_bounds_lookup():
    geo = crs.crs_bounds(27700, reprojected=False)
    proj = crs.crs_bounds(27700, reprojected=True)
    assert geo[0] < -8 and proj[2] > 600000
    assert crs.parse_crs_code("EPSG:27700") == 27700
    assert crs.parse_crs_code(4326) == 4326


# ----------------------------------------------------------------- affine


def test_translate_scale_rotate():
    col = from_wkt(["POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))", "POINT (1 1)"])
    t = affine.translate(col, 10, 20)
    assert to_wkt(t.take([1]))[0] == "POINT (11 21)"
    s = affine.scale(col, 2, 3)
    np.testing.assert_allclose(s.geom_xy(1), [[2.0, 3.0]])
    r = affine.rotate(col, math.pi / 2)
    np.testing.assert_allclose(r.geom_xy(1), [[-1.0, 1.0]], atol=1e-12)


def test_per_geometry_params():
    col = from_wkt(["POINT (1 0)", "POINT (1 0)"])
    r = affine.rotate(col, np.array([0.0, math.pi]))
    np.testing.assert_allclose(r.geom_xy(0), [[1.0, 0.0]], atol=1e-12)
    np.testing.assert_allclose(r.geom_xy(1), [[-1.0, 0.0]], atol=1e-12)


def test_transform_srid_roundtrip():
    col = from_wkt(["POINT (-0.5 51.6)", "LINESTRING (-1 52, -0.9 52.1)"])
    bng = affine.transform_srid(col, 27700)
    assert set(bng.srid.tolist()) == {27700}
    assert bng.geom_xy(0)[0, 0] > 100000  # easting, not degrees
    back = affine.transform_srid(bng, 4326)
    np.testing.assert_allclose(back.xy, col.xy, atol=1e-7)


def test_set_srid_labels_only():
    col = from_wkt(["POINT (1 2)"])
    out = affine.set_srid(col, 27700)
    assert out.srid[0] == 27700
    np.testing.assert_array_equal(out.xy, col.xy)
