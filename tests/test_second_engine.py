"""Cross-engine conformance: the independent C++ second engine vs the
numpy oracle vs the device kernels.

This is the reference's dual-engine contract (JTS vs ESRI,
`MosaicSpatialQueryTest.scala` runs each expression under both
`GeometryAPI`s and asserts agreement): three implementations in different
languages with different numerics must agree on the same inputs. Unlike the
device/oracle pair (same author, shared helpers), `native/src/evalgeom.cpp`
shares no code with the Python side.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry import oracle, second, wkt
from mosaic_tpu.functions import geometry as F

import fixtures as fx

ALL_WKT, LINE_WKT, POLY_WKT = fx.ALL_WKT, fx.LINE_WKT, fx.POLY_WKT

HOLED = [
    "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 2 4, 4 4, 4 2, 2 2))",
    "POLYGON ((0 0, 8 0, 8 8, 0 8, 0 0), (1 1, 1 2, 2 2, 2 1, 1 1),"
    " (5 5, 5 7, 7 7, 7 5, 5 5))",
    "MULTIPOLYGON (((0 0, 3 0, 3 3, 0 3, 0 0)), ((5 5, 9 5, 9 9, 5 9, 5 5),"
    " (6 6, 6 7, 7 7, 7 6, 6 6)))",
]


@pytest.fixture(scope="module")
def zones():
    """NYC taxi zones when the reference fixture is readable, else the
    holed synthetics — either way real multi-ring polygons."""
    try:
        from mosaic_tpu.readers.vector import read_geojson

        col = read_geojson(
            "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"
        ).geometry
        if len(col):
            return col
    except Exception:
        pass
    return wkt.from_wkt(HOLED)


def test_area_cross_engine(zones):
    a_second = second.area(zones)
    a_oracle = oracle.area(zones)
    np.testing.assert_allclose(a_second, a_oracle, rtol=1e-12)


def test_area_holed_exact():
    col = wkt.from_wkt(HOLED)
    np.testing.assert_allclose(second.area(col), [96.0, 59.0, 24.0], rtol=0)


def test_length_cross_engine(zones):
    np.testing.assert_allclose(
        second.length(zones), oracle.length(zones), rtol=1e-12
    )


def test_length_linestrings():
    col = wkt.from_wkt(LINE_WKT)
    np.testing.assert_allclose(
        second.length(col), oracle.length(col), rtol=1e-12
    )


def test_centroid_cross_engine(zones):
    np.testing.assert_allclose(
        second.centroid(zones), oracle.centroid(zones), rtol=1e-9, atol=1e-12
    )


def test_bounds_cross_engine(zones):
    np.testing.assert_allclose(second.bounds(zones), zones.bounds(), rtol=0)


def test_contains_cross_engine(zones):
    b = zones.bounds()
    lo = np.nanmin(b[:, :2], axis=0)
    hi = np.nanmax(b[:, 2:], axis=0)
    rng = np.random.default_rng(7)
    pts = lo + rng.random((500, 2)) * (hi - lo)
    for g in range(min(len(zones), 8)):
        got = second.contains_points(zones, g, pts)
        want = oracle.contains_points(zones, g, pts)
        assert (got == want).all()


def test_contains_holes_exact():
    col = wkt.from_wkt(HOLED)
    pts = np.array([[3.0, 3.0], [1.0, 1.5], [5.0, 5.0], [-1.0, -1.0]])
    got = second.contains_points(col, 0, pts)
    # (3,3) falls in the 2..4 hole, (1,1.5) and (5,5) in the shell,
    # (-1,-1) outside entirely
    assert got.tolist() == [False, True, True, False]


def test_distance_cross_engine(zones):
    b = zones.bounds()
    lo = np.nanmin(b[:, :2], axis=0)
    hi = np.nanmax(b[:, 2:], axis=0)
    rng = np.random.default_rng(11)
    pts = lo + rng.random((64, 2)) * (hi - lo)
    for g in range(min(len(zones), 4)):
        got = second.point_distance(zones, g, pts)
        inside = oracle.contains_points(zones, g, pts)
        want = np.asarray(
            [
                0.0
                if inside[i]
                else oracle.point_boundary_distance(zones, g, pts[i])
                for i in range(len(pts))
            ]
        )
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)


def test_native_backend_api():
    """`backend='native'` flows through the ST_ function surface."""
    a = F.st_area(HOLED, backend="native")
    np.testing.assert_allclose(a, [96.0, 59.0, 24.0])
    le = F.st_length(ALL_WKT, backend="native")
    np.testing.assert_allclose(
        le, F.st_length(ALL_WKT, backend="oracle"), rtol=1e-12
    )
    bx = F.st_xmin(POLY_WKT, backend="native")
    np.testing.assert_allclose(
        bx, F.st_xmin(POLY_WKT, backend="oracle"), rtol=0
    )
    c_n = F.st_centroid(POLY_WKT, backend="native")
    c_o = F.st_centroid(POLY_WKT, backend="oracle")
    assert c_n == c_o


def test_native_backend_config():
    """MosaicConfig accepts 'native'; unsupported ops fall back to oracle."""
    from mosaic_tpu.context import MosaicContext

    try:
        MosaicContext.build("H3", geometry_backend="native")
        a = F.st_area(HOLED)
        np.testing.assert_allclose(a, [96.0, 59.0, 24.0])
        d = F.st_distance(POLY_WKT, POLY_WKT)  # no native impl -> oracle
        assert np.isfinite(d).all()
    finally:
        MosaicContext.reset()


def test_device_vs_second_engine(zones):
    """The headline triple check: jitted device kernels vs the C++ engine."""
    a_dev = F.st_area(zones, backend="device")
    a_sec = second.area(zones)
    np.testing.assert_allclose(a_dev, a_sec, rtol=2e-5)
