"""Cross-engine conformance: the independent C++ second engine vs the
numpy oracle vs the device kernels.

This is the reference's dual-engine contract (JTS vs ESRI,
`MosaicSpatialQueryTest.scala` runs each expression under both
`GeometryAPI`s and asserts agreement): three implementations in different
languages with different numerics must agree on the same inputs. Unlike the
device/oracle pair (same author, shared helpers), `native/src/evalgeom.cpp`
shares no code with the Python side.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry import oracle, second, wkt
from mosaic_tpu.functions import geometry as F

import fixtures as fx

ALL_WKT, LINE_WKT, POLY_WKT = fx.ALL_WKT, fx.LINE_WKT, fx.POLY_WKT

HOLED = [
    "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 2 4, 4 4, 4 2, 2 2))",
    "POLYGON ((0 0, 8 0, 8 8, 0 8, 0 0), (1 1, 1 2, 2 2, 2 1, 1 1),"
    " (5 5, 5 7, 7 7, 7 5, 5 5))",
    "MULTIPOLYGON (((0 0, 3 0, 3 3, 0 3, 0 0)), ((5 5, 9 5, 9 9, 5 9, 5 5),"
    " (6 6, 6 7, 7 7, 7 6, 6 6)))",
]


@pytest.fixture(scope="module")
def zones():
    """NYC taxi zones when the reference fixture is readable, else the
    holed synthetics — either way real multi-ring polygons."""
    try:
        from mosaic_tpu.readers.vector import read_geojson

        col = read_geojson(
            "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"
        ).geometry
        if len(col):
            return col
    except Exception:
        pass
    return wkt.from_wkt(HOLED)


def test_area_cross_engine(zones):
    a_second = second.area(zones)
    a_oracle = oracle.area(zones)
    np.testing.assert_allclose(a_second, a_oracle, rtol=1e-12)


def test_area_holed_exact():
    col = wkt.from_wkt(HOLED)
    np.testing.assert_allclose(second.area(col), [96.0, 59.0, 24.0], rtol=0)


def test_length_cross_engine(zones):
    np.testing.assert_allclose(
        second.length(zones), oracle.length(zones), rtol=1e-12
    )


def test_length_linestrings():
    col = wkt.from_wkt(LINE_WKT)
    np.testing.assert_allclose(
        second.length(col), oracle.length(col), rtol=1e-12
    )


def test_centroid_cross_engine(zones):
    np.testing.assert_allclose(
        second.centroid(zones), oracle.centroid(zones), rtol=1e-9, atol=1e-12
    )


def test_bounds_cross_engine(zones):
    np.testing.assert_allclose(second.bounds(zones), zones.bounds(), rtol=0)


def test_contains_cross_engine(zones):
    b = zones.bounds()
    lo = np.nanmin(b[:, :2], axis=0)
    hi = np.nanmax(b[:, 2:], axis=0)
    rng = np.random.default_rng(7)
    pts = lo + rng.random((500, 2)) * (hi - lo)
    for g in range(min(len(zones), 8)):
        got = second.contains_points(zones, g, pts)
        want = oracle.contains_points(zones, g, pts)
        assert (got == want).all()


def test_contains_holes_exact():
    col = wkt.from_wkt(HOLED)
    pts = np.array([[3.0, 3.0], [1.0, 1.5], [5.0, 5.0], [-1.0, -1.0]])
    got = second.contains_points(col, 0, pts)
    # (3,3) falls in the 2..4 hole, (1,1.5) and (5,5) in the shell,
    # (-1,-1) outside entirely
    assert got.tolist() == [False, True, True, False]


def test_distance_cross_engine(zones):
    b = zones.bounds()
    lo = np.nanmin(b[:, :2], axis=0)
    hi = np.nanmax(b[:, 2:], axis=0)
    rng = np.random.default_rng(11)
    pts = lo + rng.random((64, 2)) * (hi - lo)
    for g in range(min(len(zones), 4)):
        got = second.point_distance(zones, g, pts)
        inside = oracle.contains_points(zones, g, pts)
        want = np.asarray(
            [
                0.0
                if inside[i]
                else oracle.point_boundary_distance(zones, g, pts[i])
                for i in range(len(pts))
            ]
        )
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)


def test_native_backend_api():
    """`backend='native'` flows through the ST_ function surface."""
    a = F.st_area(HOLED, backend="native")
    np.testing.assert_allclose(a, [96.0, 59.0, 24.0])
    le = F.st_length(ALL_WKT, backend="native")
    np.testing.assert_allclose(
        le, F.st_length(ALL_WKT, backend="oracle"), rtol=1e-12
    )
    bx = F.st_xmin(POLY_WKT, backend="native")
    np.testing.assert_allclose(
        bx, F.st_xmin(POLY_WKT, backend="oracle"), rtol=0
    )
    c_n = F.st_centroid(POLY_WKT, backend="native")
    c_o = F.st_centroid(POLY_WKT, backend="oracle")
    assert c_n == c_o


def test_native_backend_config():
    """MosaicConfig accepts 'native'; unsupported ops fall back to oracle."""
    from mosaic_tpu.context import MosaicContext

    try:
        MosaicContext.build("H3", geometry_backend="native")
        a = F.st_area(HOLED)
        np.testing.assert_allclose(a, [96.0, 59.0, 24.0])
        d = F.st_distance(POLY_WKT, POLY_WKT)  # no native impl -> oracle
        assert np.isfinite(d).all()
    finally:
        MosaicContext.reset()


def test_device_vs_second_engine(zones):
    """The headline triple check: jitted device kernels vs the C++ engine."""
    a_dev = F.st_area(zones, backend="device")
    a_sec = second.area(zones)
    np.testing.assert_allclose(a_dev, a_sec, rtol=2e-5)


# ----------------------------------------------------- boolean-op witness
# The Martinez sweep (`native/src/martinez.cpp`, the primary clipper) vs
# the independent edge-classification clipper (`mg_eval_clip` in
# `native/src/evalgeom.cpp`) — the reference's JTS-vs-ESRI dual-engine
# contract extended to the hardest code in the repo. Agreement is checked
# on area, bounds, and sampled point membership (the latter also validates
# against the logical op of per-operand membership — an oracle neither
# clipper can bias).

_OPS = {"intersection": 0, "union": 1, "difference": 2, "xor": 3}


def _random_poly(rng, cx, cy, r, n, hole=False):
    # jittered regular angles: every gap < pi, so the star polygon is
    # guaranteed simple (a >pi gap lets the closing chord cross other
    # edges — even-odd area of such invalid input is generator noise,
    # not an engine property). Shell chords may still cross the hole:
    # that degeneracy is intended coverage.
    ang = 2 * np.pi * (np.arange(n) + rng.uniform(0.1, 0.9, n)) / n
    rad = rng.uniform(0.4 * r, r, n)
    xy = np.stack([cx + rad * np.cos(ang), cy + rad * np.sin(ang)], -1)
    ring = ", ".join(f"{p[0]:.9f} {p[1]:.9f}" for p in np.vstack([xy, xy[:1]]))
    if hole:
        h = 0.25 * r
        hr = (f"({cx - h} {cy - h}, {cx - h} {cy + h}, {cx + h} {cy + h}, "
              f"{cx + h} {cy - h}, {cx - h} {cy - h})")
        return f"POLYGON (({ring}), {hr})"
    return f"POLYGON (({ring}))"


def _membership_check(a, b, op, result, rng, n=256):
    """Sampled ground truth: for points away from any boundary,
    in(result) == op(in(a), in(b))."""
    from mosaic_tpu.core.geometry import oracle as _o

    bb = np.vstack([a.bounds(), b.bounds()])
    lo = np.nanmin(bb[:, :2], axis=0) - 0.1
    hi = np.nanmax(bb[:, 2:], axis=0) + 0.1
    pts = rng.uniform(lo, hi, (n, 2))
    ina = second.contains_points(a, 0, pts)
    inb = second.contains_points(b, 0, pts)
    want = {
        0: ina & inb, 1: ina | inb, 2: ina & ~inb, 3: ina ^ inb,
    }[op]
    got = (
        second.contains_points(result, 0, pts)
        if len(result) and result.geom_xy(0).shape[0]
        else np.zeros(n, bool)
    )
    # exclude points within eps of any operand/result boundary (membership
    # is genuinely ambiguous there)
    d = np.minimum(
        second.point_distance(a, 0, pts), second.point_distance(b, 0, pts)
    )
    near = d < 1e-6
    mism = (want != got) & ~near
    assert mism.sum() == 0, f"membership mismatch at {pts[mism][:4]}"


@pytest.mark.parametrize("op_name", sorted(_OPS))
def test_clip_fuzz_random_pairs(op_name):
    from mosaic_tpu.core.geometry import hostops

    op = _OPS[op_name]
    rng = np.random.default_rng(99 + op)
    for trial in range(25):
        a = wkt.from_wkt(
            [_random_poly(rng, 0, 0, 2.0, rng.integers(4, 12),
                          hole=bool(trial % 3 == 0))]
        )
        b = wkt.from_wkt(
            [_random_poly(rng, rng.uniform(-1.5, 1.5),
                          rng.uniform(-1.5, 1.5), 2.0,
                          rng.integers(4, 12))]
        )
        m = hostops.bool_op(op, a, b)
        s = second.clip(op, a, b)
        am, as_ = float(oracle.area(m)[0]), float(oracle.area(s)[0])
        ref = max(float(oracle.area(a)[0]), float(oracle.area(b)[0]))
        assert abs(am - as_) < 1e-7 * ref, (trial, am, as_)
        _membership_check(a, b, op, s, rng)


@pytest.mark.parametrize("op_name", ["intersection", "union", "difference"])
def test_clip_fuzz_nyc_zone_pairs(zones, op_name):
    """Real-data pairs, including ADJACENT zones sharing boundary edges —
    exactly where clipping bugs live."""
    from mosaic_tpu.core.geometry import hostops

    op = _OPS[op_name]
    rng = np.random.default_rng(7)
    n = len(zones)
    bb = zones.bounds()
    # pair nearby zones (bbox overlap or touch) for interesting cases
    pairs = []
    for i in range(n):
        for j in range(i + 1, n):
            if (
                bb[i, 0] <= bb[j, 2] and bb[j, 0] <= bb[i, 2]
                and bb[i, 1] <= bb[j, 3] and bb[j, 1] <= bb[i, 3]
            ):
                pairs.append((i, j))
    rng.shuffle(pairs)
    for i, j in pairs[:12]:
        a, b = zones.slice(i, i + 1), zones.slice(j, j + 1)
        m = hostops.bool_op(op, a, b)
        s = second.clip(op, a, b)
        am, as_ = float(oracle.area(m)[0]), float(oracle.area(s)[0])
        ref = max(float(oracle.area(a)[0]), float(oracle.area(b)[0]), 1e-12)
        assert abs(am - as_) < 1e-5 * ref, (i, j, am, as_)


def test_clip_shared_edge_exact():
    # adjacent squares: the degenerate shared-edge cases both engines must
    # agree on exactly
    from mosaic_tpu.core.geometry import hostops

    a = wkt.from_wkt(["POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"])
    c = wkt.from_wkt(["POLYGON ((4 0, 8 0, 8 4, 4 4, 4 0))"])
    for op, want in [(0, 0.0), (1, 32.0), (2, 16.0), (3, 32.0)]:
        am = float(oracle.area(hostops.bool_op(op, a, c))[0])
        as_ = float(oracle.area(second.clip(op, a, c))[0])
        assert abs(am - want) < 1e-9
        assert abs(as_ - want) < 1e-9


def test_clip_functions_backend_consistency():
    # the functions-layer boolean ops (Martinez path) agree with the
    # second engine on a holed fixture
    a = wkt.from_wkt([HOLED[0]])
    b = wkt.from_wkt(["POLYGON ((3 3, 12 3, 12 12, 3 12, 3 3))"])
    ai = float(np.asarray(F.st_area(F.st_intersection(a, b)))[0])
    si = float(oracle.area(second.intersection(a, b))[0])
    assert abs(ai - si) < 1e-9


def test_boolean_ops_native_backend_selection(zones):
    # the functions layer routes boolean ops through the independent
    # clipper under backend="native" (the reference's GeometryAPI choice)
    a = zones.slice(0, 3)
    b = F.st_translate(zones.slice(0, 3), 0.004, 0.004)
    for fn in (F.st_intersection, F.st_union, F.st_difference,
               F.st_symdifference):
        d = np.asarray(F.st_area(fn(a, b)))
        n = np.asarray(F.st_area(fn(a, b, backend="native")))
        np.testing.assert_allclose(n, d, rtol=1e-8, atol=1e-12)


def test_native_pip_join_matches_f64_oracle():
    """The single-thread C++ join lane (bench baseline; the JTS-codegen
    row-path analog) agrees with the exact f64 host oracle."""
    from mosaic_tpu.core.geometry.second import chip_index_csr, eval_pip_join
    from mosaic_tpu.core.index import H3
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index, host_join

    col = wkt.from_wkt([
        "POLYGON ((-74.02 40.70, -73.96 40.70, -73.96 40.76, "
        "-74.02 40.76, -74.02 40.70))",
        "POLYGON ((-73.96 40.70, -73.90 40.70, -73.90 40.76, "
        "-73.96 40.76, -73.96 40.70))",
    ])
    idx = build_chip_index(tessellate(col, H3, 8, keep_core_geoms=False))
    rng = np.random.default_rng(1)
    pts = np.column_stack(
        [rng.uniform(-74.05, -73.87, 20_000), rng.uniform(40.68, 40.78, 20_000)]
    )
    cells = np.asarray(H3.point_to_cell(pts, 8))
    xy, ro, cro = chip_index_csr(
        np.asarray(idx.border.verts), np.asarray(idx.border.ring_len)
    )
    nat = eval_pip_join(
        xy, ro, cro, np.asarray(idx.chip_core), np.asarray(idx.chip_geom),
        np.asarray(idx.cells), np.asarray(idx.chip_rows),
        pts - idx.host.shift, cells,
    )
    truth = host_join(pts, idx.host, H3, 8)
    np.testing.assert_array_equal(nat, truth)
