"""Observability subsystem contract (PR 5): spans + cross-thread
propagation, typed metrics + event bridge, exporters (JSONL / Chrome
trace / Prometheus), the telemetry satellites (hot-path logging guard,
timed() error stamping, nearest-rank percentiles), the durable-stream
single-trace contract, and the perf regression gate."""

import json
import logging
import re
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from mosaic_tpu import obs
from mosaic_tpu.obs import metrics as obs_metrics
from mosaic_tpu.runtime import faults, telemetry

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))


# ------------------------------------------------------------------ spans


class TestSpans:
    def test_ids_nesting_and_parent_links(self):
        with telemetry.capture() as events:
            with obs.span("outer", a=1):
                with obs.span("inner"):
                    pass
        spans = {e["name"]: e for e in events if e["event"] == "span"}
        outer, inner = spans["outer"], spans["inner"]
        assert len(outer["trace_id"]) == 32
        assert len(outer["span_id"]) == 16
        assert inner["trace_id"] == outer["trace_id"]
        assert inner["parent_id"] == outer["span_id"]
        assert outer["parent_id"] is None
        assert outer["a"] == 1
        assert outer["seconds"] >= inner["seconds"] >= 0.0
        # inner ends before outer: the trail is ordered by seq
        assert inner["seq"] < outer["seq"]

    def test_exception_stamps_error_and_reraises(self):
        with telemetry.capture() as events:
            with pytest.raises(ValueError):
                with obs.span("doomed"):
                    raise ValueError("boom")
        (s,) = [e for e in events if e["event"] == "span"]
        assert s["name"] == "doomed" and s["error"] == "ValueError"

    def test_event_stamping_inside_and_outside(self):
        with telemetry.capture() as events:
            telemetry.record("before")
            with obs.span("scope") as sp:
                telemetry.record("inside")
                telemetry.record("explicit", trace_id="mine")
            telemetry.record("after")
        by = {e["event"]: e for e in events if e["event"] != "span"}
        assert "trace_id" not in by["before"]
        assert by["inside"]["trace_id"] == sp.context.trace_id
        assert by["inside"]["span_id"] == sp.context.span_id
        # explicitly passed ids win over the ambient span
        assert by["explicit"]["trace_id"] == "mine"
        assert "trace_id" not in by["after"]

    def test_detached_span_does_not_become_ambient_parent(self):
        with telemetry.capture() as events:
            root = obs.start_span("request", detached=True)
            with obs.span("sibling"):
                pass
            root.end()
        spans = {e["name"]: e for e in events if e["event"] == "span"}
        # the detached root never occupied the stack: the sibling is its
        # own fresh trace, not a child
        assert spans["sibling"]["trace_id"] != spans["request"]["trace_id"]
        assert spans["sibling"]["parent_id"] is None

    def test_end_is_idempotent(self):
        with telemetry.capture() as events:
            sp = obs.start_span("once", detached=True)
            assert sp.end() is not None
            assert sp.end() is None
        assert sum(e["event"] == "span" for e in events) == 1

    def test_cross_thread_adoption_joins_the_trace(self):
        """A worker thread that adopts the caller's context emits spans
        and events into the SAME trace, with valid parent links."""
        with telemetry.capture() as events:
            sinks = telemetry.current_sinks()
            with obs.span("caller") as sp:
                ctx = obs.current_context()

                def work():
                    telemetry.adopt_sinks(sinks)
                    obs.adopt_context(ctx)
                    telemetry.record("worker_event")
                    with obs.span("worker_span"):
                        pass

                t = threading.Thread(target=work)  # lint: thread-context-adoption-ok (this IS the adoption test fixture; no fault plans in scope)
                t.start()
                t.join()
        spans = {e["name"]: e for e in events if e["event"] == "span"}
        ev = next(e for e in events if e["event"] == "worker_event")
        assert ev["trace_id"] == sp.context.trace_id
        assert spans["worker_span"]["trace_id"] == sp.context.trace_id
        assert spans["worker_span"]["parent_id"] == sp.context.span_id
        summ = obs.trace_summary(events)
        assert len(summ) == 1
        (t_sum,) = summ.values()
        assert t_sum["roots"] == 1 and not t_sum["orphans"]

    def test_watchdog_worker_inherits_the_span(self):
        """Events recorded inside a watchdog-guarded callable (which
        runs on a worker thread) attach to the caller's span."""
        from mosaic_tpu.runtime import watchdog

        with telemetry.capture() as events:
            with obs.span("guarded") as sp:
                with faults.stalls(0.0, n=1, sites=("unit.site",)):
                    watchdog.guard(
                        "unit.site",
                        lambda: telemetry.record("from_worker"),
                        default_s=30.0,
                    )
        ev = next(e for e in events if e["event"] == "from_worker")
        assert ev["trace_id"] == sp.context.trace_id


# ---------------------------------------------------------------- metrics


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = obs_metrics.Registry()
        c = reg.counter("c.requests", "reqs")
        c.inc()
        c.inc(2, reason="deadline")
        assert c.value() == 1
        assert c.value(reason="deadline") == 2
        g = reg.gauge("g.depth")
        g.set(7)
        g.set(3)
        assert g.value() == 3.0
        h = reg.histogram("h.lat", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        hv = h.value()
        assert hv["counts"] == [1, 1, 1, 1]  # one per bucket + overflow
        assert hv["count"] == 4
        assert hv["sum"] == pytest.approx(5.555)

    def test_kind_conflict_raises(self):
        reg = obs_metrics.Registry()
        reg.counter("x")
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("x")

    def test_snapshot_is_json_clean(self):
        reg = obs_metrics.Registry()
        reg.counter("a").inc(site="s1")
        reg.histogram("b", buckets=(1.0,)).observe(0.5)
        snap = {
            name: m.snapshot() for name, m in reg._metrics.items()
        }
        parsed = json.loads(json.dumps(snap))
        assert parsed["a"]["series"][0]["labels"] == {"site": "s1"}
        assert parsed["b"]["series"][0]["value"]["buckets"] == [1.0]

    def test_event_bridge_counts_runtime_events(self):
        """The telemetry→metrics bridge folds well-known events into
        the standard registry without touching their emitters."""
        before = obs.counter("join.cap_overflows").value(stage="unit_t")
        shed_before = obs.counter("serve.requests_shed").value(
            reason="unit_reason"
        )
        telemetry.record("capacity_overflow", stage="unit_t", attempt=1)
        telemetry.record("serve_shed", reason="unit_reason")
        assert (
            obs.counter("join.cap_overflows").value(stage="unit_t")
            == before + 1
        )
        assert (
            obs.counter("serve.requests_shed").value(reason="unit_reason")
            == shed_before + 1
        )

    def test_prometheus_exposition(self):
        reg = obs_metrics.Registry()
        reg.counter("serve.requests_shed", "shed requests").inc(
            3, reason="deadline"
        )
        reg.gauge("queue.depth").set(2)
        reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.05)
        snap = {
            name: m.snapshot() for name, m in reg._metrics.items()
        }
        text = obs.prometheus_text(snap)
        assert "# TYPE serve_requests_shed counter" in text
        assert "# HELP serve_requests_shed shed requests" in text
        assert 'serve_requests_shed{reason="deadline"} 3' in text
        assert "queue_depth 2.0" in text
        assert 'lat_bucket{le="0.1"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "lat_count 1" in text
        assert text.endswith("\n")


# --------------------------------------------------------------- exporters


def _span_evt(name, trace, span_id, parent, seconds=0.25, **attrs):
    return {
        "event": "span", "seq": 0, "ts_mono": 100.0 + seconds,
        "name": name, "trace_id": trace, "span_id": span_id,
        "parent_id": parent, "seconds": seconds,
        "start_mono": 100.0, **attrs,
    }


class TestExport:
    def test_jsonl_roundtrip(self, tmp_path):
        events = [
            {"event": "a", "seq": 0, "ts_mono": 1.0, "x": 1},
            _span_evt("s", "t1", "a1", None),
        ]
        p = tmp_path / "trail.jsonl"
        # +1: write_jsonl opens the trail with an incarnation meta line
        # (the fleet_report stitching anchor)
        assert obs.write_jsonl(events, str(p)) == 3
        rows = obs.read_trail(str(p))
        assert rows[0]["event"] == "incarnation"
        assert rows[0]["incarnation"] == telemetry.INCARNATION
        assert rows[1:] == events
        # an already-stamped trail is NOT double-stamped on re-write
        assert obs.write_jsonl(rows, str(p)) == 3

    def test_read_trail_accepts_bench_artifact(self, tmp_path):
        stages = [{"event": "stream_stage", "stage": "x", "seconds": 1.0}]
        artifact = {"metric": "m", "value": 1, "detail": {"stages": stages}}
        p = tmp_path / "bench.json"
        p.write_text(json.dumps(artifact) + "\n")
        assert obs.read_trail(str(p)) == stages

    def test_chrome_trace_shape(self):
        events = [
            _span_evt("root", "t1", "a1", None, seconds=0.5),
            _span_evt("child", "t1", "b2", "a1", seconds=0.2),
            {"event": "transient_retry", "seq": 2, "ts_mono": 100.1,
             "trace_id": "t1", "span_id": "b2", "label": "x"},
        ]
        doc = obs.chrome_trace(events)
        json.loads(json.dumps(doc))  # loads cleanly
        evs = doc["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        inst = [e for e in evs if e["ph"] == "i"]
        assert len(xs) == 2 and len(inst) == 1
        root = next(e for e in xs if e["name"] == "root")
        child = next(e for e in xs if e["name"] == "child")
        assert root["ts"] == pytest.approx(100.0 * 1e6)
        assert root["dur"] == pytest.approx(0.5 * 1e6)
        # same trace -> same timeline row; args carry the linkage
        assert root["tid"] == child["tid"] == inst[0]["tid"]
        assert child["args"]["parent_id"] == "a1"
        for e in xs:
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)

    def test_trace_summary_flags_orphans_and_roots(self):
        events = [
            _span_evt("root", "t1", "a1", None),
            _span_evt("ok_child", "t1", "b2", "a1"),
            _span_evt("orphan", "t1", "c3", "missing"),
            _span_evt("other_root", "t2", "d4", None),
        ]
        summ = obs.trace_summary(events)
        assert summ["t1"]["spans"] == 3
        assert summ["t1"]["roots"] == 1
        assert summ["t1"]["orphans"] == ["orphan"]
        assert summ["t2"]["roots"] == 1 and not summ["t2"]["orphans"]


# ------------------------------------------------- telemetry satellites


class _FormatProbe:
    def __init__(self):
        self.formats = 0

    def __repr__(self):
        self.formats += 1
        return "probe"

    __str__ = __repr__


class TestRecordHotPath:
    def test_disabled_logging_does_no_formatting(self):
        """With no sinks and the runtime logger quiet, record() must not
        format anything — and must NOT force-install a handler the way
        utils.get_logger does (the old hot-path tax)."""
        logger = logging.getLogger("mosaic_tpu.runtime")
        saved = (logger.level, logger.handlers[:])
        logger.handlers[:] = []
        logger.setLevel(logging.WARNING)
        try:
            probe = _FormatProbe()
            evt = telemetry.record("hot_path_unit", payload=probe)
            assert evt["payload"] is probe
            assert probe.formats == 0
            assert logger.handlers == []  # record() never configures it
            assert logger.level == logging.WARNING
        finally:
            logger.setLevel(saved[0])
            logger.handlers[:] = saved[1]

    def test_enabled_logging_still_formats(self):
        import io

        logger = logging.getLogger("mosaic_tpu.runtime")
        saved = (logger.level, logger.handlers[:], logger.propagate)
        buf = io.StringIO()
        logger.handlers[:] = [logging.StreamHandler(buf)]
        logger.setLevel(logging.INFO)
        logger.propagate = False
        try:
            probe = _FormatProbe()
            telemetry.record("hot_path_unit", payload=probe)
            assert probe.formats >= 1
            assert "hot_path_unit" in buf.getvalue()
        finally:
            logger.setLevel(saved[0])
            logger.handlers[:] = saved[1]
            logger.propagate = saved[2]

    def test_micro_benchmark_disabled_record_is_cheap(self):
        """20k no-sink, logging-off events well under a second — the
        guard keeps record() out of the formatting business entirely."""
        logger = logging.getLogger("mosaic_tpu.runtime")
        saved = logger.level
        logger.setLevel(logging.ERROR)
        try:
            t0 = time.perf_counter()
            for _ in range(20_000):
                telemetry.record("hot_path_bench", a=1, b="x")
            elapsed = time.perf_counter() - t0
        finally:
            logger.setLevel(saved)
        assert elapsed < 2.0, f"record() too slow: {elapsed:.3f}s / 20k"


class TestTimedErrorStamp:
    def test_exception_stamps_error_type_and_reraises(self):
        with telemetry.capture() as events:
            with pytest.raises(KeyError):
                with telemetry.timed("stage_unit", stage="s"):
                    raise KeyError("gone")
        (e,) = [x for x in events if x["event"] == "stage_unit"]
        assert e["error"] == "KeyError"
        assert e["seconds"] >= 0.0

    def test_success_has_no_error_field(self):
        with telemetry.capture() as events:
            with telemetry.timed("stage_unit", stage="s"):
                pass
        (e,) = [x for x in events if x["event"] == "stage_unit"]
        assert "error" not in e


class TestSummarizeNearestRank:
    """Exact nearest-rank (ceil(q*n)-1) values — the old banker's-
    rounding spelling drifted p50 at n=4 (to the 3rd value) and n=100
    (to the 51st)."""

    @pytest.mark.parametrize(
        "n,p50,p90,p99",
        [
            (1, 1.0, 1.0, 1.0),
            (2, 1.0, 2.0, 2.0),
            (3, 2.0, 3.0, 3.0),
            (10, 5.0, 9.0, 10.0),
            (100, 50.0, 90.0, 99.0),
        ],
    )
    def test_exact_ranks(self, n, p50, p90, p99):
        events = [
            {"event": "e", "seconds": float(v)} for v in range(1, n + 1)
        ]
        s = telemetry.summarize(events, event="e")
        assert s["count"] == n
        assert (s["p50"], s["p90"], s["p99"]) == (p50, p90, p99)
        assert s["max"] == float(n)

    def test_n4_regression_pin(self):
        # banker's rounding gave index round(1.5)=2 (the 3rd value);
        # nearest-rank gives ceil(2)-1=1 (the 2nd)
        s = telemetry.summarize(
            [{"event": "e", "seconds": float(v)} for v in (1, 2, 3, 4)],
            event="e",
        )
        assert s["p50"] == 2.0


# --------------------------------------------- durable stream: one trace


@pytest.fixture(scope="module")
def stream_setup():
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index
    from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    col = wkt.from_wkt(["POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))"])
    index = build_chip_index(
        tessellate(col, grid, 3, keep_core_geoms=False)
    )
    rng = np.random.default_rng(0)
    sj = StreamJoin(index, grid, 3, prefetch=True)
    ring = ring_from_host(
        [rng.uniform((-25, -25), (35, 20), (2048, 2)) for _ in range(3)]
    )
    return sj, ring


class TestDurableStreamTrace:
    def test_run_durable_is_one_connected_trace(self, stream_setup, tmp_path):
        sj, ring = stream_setup
        with telemetry.capture() as events:
            sj.run_durable(ring, 6, run_dir=str(tmp_path), snapshot_every=2)
        spans = [e for e in events if e["event"] == "span"]
        summ = obs.trace_summary(events)
        assert len(summ) == 1, summ
        ((tid, t),) = summ.items()
        assert t["roots"] == 1 and not t["orphans"], t
        names = t["names"]
        assert "stream.durable_run" in names
        assert names.count("stream.segment") == 3
        assert names.count("stream.snapshot") == 3
        # snapshot_saved events attach to their snapshot span's trace
        saved = [e for e in events if e["event"] == "snapshot_saved"]
        assert saved and all(e["trace_id"] == tid for e in saved)
        root = next(
            s for s in spans if s["name"] == "stream.durable_run"
        )
        segs = [s for s in spans if s["name"] == "stream.segment"]
        assert all(s["parent_id"] == root["span_id"] for s in segs)

    def test_kill_and_resume_join_one_trace(self, stream_setup, tmp_path):
        """A killed durable run and its resume read as ONE trace: the
        resume's root parents to the interrupted run's root (persisted
        through the snapshot sidecar), and the stats stay bit-identical
        to the clean run."""
        sj, ring = stream_setup
        clean = sj.run(ring, 9)
        d = str(tmp_path / "run")
        with telemetry.capture() as events:
            with pytest.raises(RuntimeError):
                with faults.inject(
                    fail_first=99, skip_first=2,
                    sites=("stream.scan_step",),
                    exc_factory=lambda s: RuntimeError("device loss"),
                ):
                    sj.run_durable(ring, 9, run_dir=d, snapshot_every=2)
            r = sj.resume(d, ring)
        assert (r.checksum, r.matches, r.overflow) == (
            clean.checksum, clean.matches, clean.overflow
        )
        roots = [
            e for e in events
            if e["event"] == "span" and e["name"] == "stream.durable_run"
        ]
        assert len(roots) == 2
        killed, resumed = roots
        assert killed["error"] == "RuntimeError"
        assert resumed["trace_id"] == killed["trace_id"]
        assert resumed["parent_id"] == killed["span_id"]
        assert resumed["resumed_from"] == 4
        summ = obs.trace_summary(events)
        assert len(summ) == 1
        (t,) = summ.values()
        assert t["roots"] == 1 and not t["orphans"], t


# ------------------------------------------------------------- perf gate


def _mk_trail(tmp_path, name, stages):
    """stages: {stage_name: (seconds, count)} -> trail file path."""
    events = []
    for stage, (seconds, count) in stages.items():
        for _ in range(count):
            events.append({
                "event": "bench_stage", "stage": stage,
                "seconds": seconds / count, "seq": 0, "ts_mono": 0.0,
            })
    p = tmp_path / name
    obs.write_jsonl(events, str(p))
    return str(p)


BASE_STAGES = {
    "compile": (4.0, 2),
    "join_loop": (2.0, 2),
    "dispatch": (0.5, 10),
}


class TestPerfGate:
    def test_green_on_identical_and_uniformly_slower_runs(self, tmp_path):
        import perf_gate

        trail = _mk_trail(tmp_path, "a.jsonl", BASE_STAGES)
        fresh = perf_gate.stage_odds(obs.read_trail(trail))
        golden = {
            "tolerance": 3.0, "odds_floor": 0.02,
            "stages": {
                k: {"odds": v["odds"], "require": True}
                for k, v in fresh.items()
            },
        }
        ok, verdicts = perf_gate.evaluate(fresh, golden)
        assert ok, verdicts
        # a uniformly 5x slower machine keeps every odds identical
        slow = _mk_trail(tmp_path, "slow.jsonl", {
            k: (s * 5, c) for k, (s, c) in BASE_STAGES.items()
        })
        ok, verdicts = perf_gate.evaluate(
            perf_gate.stage_odds(obs.read_trail(slow)), golden
        )
        assert ok, verdicts

    def test_red_on_10x_single_stage_slowdown(self, tmp_path):
        import perf_gate

        trail = _mk_trail(tmp_path, "a.jsonl", BASE_STAGES)
        fresh = perf_gate.stage_odds(obs.read_trail(trail))
        golden = {
            "tolerance": 3.0, "odds_floor": 0.02,
            "stages": {
                k: {"odds": v["odds"], "require": True}
                for k, v in fresh.items()
            },
        }
        for stage in ("compile", "join_loop", "dispatch"):
            bad = _mk_trail(tmp_path, f"bad_{stage}.jsonl", {
                k: ((s * 10 if k == stage else s), c)
                for k, (s, c) in BASE_STAGES.items()
            })
            ok, verdicts = perf_gate.evaluate(
                perf_gate.stage_odds(obs.read_trail(bad)), golden
            )
            assert not ok, (stage, verdicts)
            assert verdicts[f"bench_stage.{stage}"]["status"] == "SLOW"

    def test_trail_pools_isolate_odds(self, tmp_path, monkeypatch,
                                      capsys):
        """Each --trail is its own odds pool: a huge unrelated bench in
        another trail must not dilute a small stage's odds below the
        point where a 10x slowdown can escape odds_floor."""
        import perf_gate

        small = _mk_trail(tmp_path, "small.jsonl", {
            "light": (0.02, 3), "heavy": (0.04, 3),
        })
        # 1000x the small trail's total: pooled odds would sink
        # heavy to ~0.0007, where 10x stays under 3*odds + 0.02
        huge = _mk_trail(tmp_path, "huge.jsonl", {"compile": (60.0, 1)})
        golden = str(tmp_path / "golden.json")
        monkeypatch.setattr(sys, "argv", [
            "perf_gate.py", "--update", "--golden", golden,
            "--trail", small, "--trail", huge,
        ])
        assert perf_gate.main() == 0
        capsys.readouterr()
        monkeypatch.setattr(sys, "argv", [
            "perf_gate.py", "--golden", golden,
            "--trail", small, "--trail", huge,
            "--inject-slowdown", "bench_stage.heavy:10",
        ])
        assert perf_gate.main() == 1
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["stages"]["bench_stage.heavy"]["status"] == "SLOW"
        # and the huge trail's own stage still gates green
        assert out["stages"]["bench_stage.compile"]["ok"] is True

    def test_missing_required_stage_is_red(self, tmp_path):
        import perf_gate

        golden = {
            "tolerance": 3.0, "odds_floor": 0.02,
            "stages": {
                "bench_stage.vanished": {"odds": 0.5, "require": True},
            },
        }
        trail = _mk_trail(tmp_path, "a.jsonl", {"other": (1.0, 1)})
        ok, verdicts = perf_gate.evaluate(
            perf_gate.stage_odds(obs.read_trail(trail)), golden
        )
        assert not ok
        assert (
            verdicts["bench_stage.vanished"]["status"]
            == "MISSING_REQUIRED"
        )

    def test_cli_update_then_gate_and_inject(self, tmp_path, monkeypatch,
                                             capsys):
        import perf_gate

        trail = _mk_trail(tmp_path, "a.jsonl", BASE_STAGES)
        golden = str(tmp_path / "golden.json")
        monkeypatch.setattr(sys, "argv", [
            "perf_gate.py", "--update", "--golden", golden,
            "--trail", trail,
        ])
        assert perf_gate.main() == 0
        capsys.readouterr()
        monkeypatch.setattr(sys, "argv", [
            "perf_gate.py", "--golden", golden, "--trail", trail,
        ])
        assert perf_gate.main() == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["pass"] is True
        monkeypatch.setattr(sys, "argv", [
            "perf_gate.py", "--golden", golden, "--trail", trail,
            "--inject-slowdown", "bench_stage.join_loop:10",
        ])
        assert perf_gate.main() == 1
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["pass"] is False

    def test_committed_golden_parses_and_gates_its_own_stages(self):
        """The committed golden is well-formed: stage odds positive,
        tolerance sane, and every stage key names a real bench stage."""
        with open(REPO / "tests" / "goldens" / "perf_gate.json") as f:
            golden = json.load(f)
        assert 1.0 < golden["tolerance"] <= 10.0
        assert golden["stages"], "empty golden gates nothing"
        for key, g in golden["stages"].items():
            assert g["odds"] > 0, key
            assert key.split(".")[0] in (
                "serve_stage", "stream_stage", "serve_request",
                "recheck_narrow", "quarantine_stage", "snapshot_saved",
                "probe_stage", "raster_stage", "multichip_stage",
                "expr_stage", "tune_stage", "router_stage",
                "overlay_stage", "epoch_stage", "knn_stage",
                "ops_stage",
            ), key


# ----------------------------------------------------------- trace report


class TestTraceReport:
    def test_stage_keys(self):
        import trace_report

        assert trace_report.stage_key(
            {"event": "stream_stage", "stage": "x", "seconds": 1.0}
        ) == "stream_stage.x"
        assert trace_report.stage_key(
            {"event": "span", "name": "serve.request", "seconds": 1.0}
        ) == "span.serve.request"
        assert trace_report.stage_key(
            {"event": "serve_request", "seconds": 1.0}
        ) == "serve_request"
        assert trace_report.stage_key({"event": "no_seconds"}) is None

    def test_cli_report_and_diff(self, tmp_path, monkeypatch, capsys):
        import trace_report

        a = _mk_trail(tmp_path, "a.jsonl", BASE_STAGES)
        b = _mk_trail(tmp_path, "b.jsonl", {
            k: (s * 2 if k == "compile" else s, c)
            for k, (s, c) in BASE_STAGES.items()
        })
        monkeypatch.setattr(sys, "argv", ["trace_report.py", a])
        trace_report.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["metric"] == "trace_report"
        assert out["stages"]["bench_stage.compile"]["count"] == 2
        assert sum(
            s["share"] for s in out["stages"].values()
        ) == pytest.approx(1.0, abs=0.01)
        monkeypatch.setattr(
            sys, "argv", ["trace_report.py", b, "--against", a]
        )
        trace_report.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        d = out["diff"]["bench_stage.compile"]
        assert d["total_ratio"] == pytest.approx(2.0, abs=0.01)
        assert d["share_delta"] > 0

    def test_diff_tolerates_one_sided_stages(self, tmp_path, monkeypatch,
                                             capsys):
        """New lanes (e.g. the adaptive probe's probe_stage.* keys) diff
        cleanly against a historical trail that never emitted them: no
        throw, null deltas, and an explicit only_in tag each way."""
        import trace_report

        old = _mk_trail(tmp_path, "old.jsonl", BASE_STAGES)
        new = _mk_trail(tmp_path, "new.jsonl", {
            **BASE_STAGES,
            "probe_light": (0.2, 1),
            "probe_heavy": (0.4, 1),
        })
        monkeypatch.setattr(
            sys, "argv", ["trace_report.py", new, "--against", old]
        )
        trace_report.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        d = out["diff"]["bench_stage.probe_heavy"]
        assert d["only_in"] == "fresh"
        assert d["share_delta"] is None and d["total_ratio"] is None
        # and the reverse direction: a stage that vanished
        monkeypatch.setattr(
            sys, "argv", ["trace_report.py", old, "--against", new]
        )
        trace_report.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        d = out["diff"]["bench_stage.probe_heavy"]
        assert d["only_in"] == "base"
        assert out["diff"]["bench_stage.compile"].get("only_in") is None

    def test_diff_against_summary_only_artifact(self, tmp_path,
                                                monkeypatch, capsys):
        """A bench artifact whose detail.stages is a DICT of per-stage
        summaries (the perf_gate golden shape) must yield a real base
        breakdown, not a silently-empty one."""
        import trace_report

        fresh = _mk_trail(tmp_path, "fresh.jsonl", BASE_STAGES)
        art = tmp_path / "hist.json"
        art.write_text(json.dumps({
            "metric": "m", "value": 1,
            "detail": {"stages": {
                "bench_stage.compile": {"total_s": 2.0, "count": 2},
            }},
        }) + "\n")
        monkeypatch.setattr(
            sys, "argv", ["trace_report.py", fresh, "--against", str(art)]
        )
        trace_report.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        d = out["diff"]["bench_stage.compile"]
        assert d.get("only_in") is None
        assert d["total_ratio"] == pytest.approx(2.0, abs=0.01)

    def test_stage_key_skips_non_dict_and_non_numeric(self):
        import trace_report

        assert trace_report.stage_key("bench_stage.seconds") is None
        assert trace_report.stage_key({"seconds": None}) is None
        assert trace_report.stage_key(
            {"stage_key": "x", "seconds": 1.0}
        ) == "x"


# ------------------------------------- prometheus label-value escaping


_LABEL_RE = re.compile(r'="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    # inverse of the exposition-format escaping, applied left to right
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class TestPrometheusLabelEscaping:
    HOSTILE = [
        'a\\b"c\nd',                      # all three escapes at once
        "C:\\temp\\trail.jsonl",          # windows path (backslashes)
        'say "hi"',                       # embedded quotes
        "line1\nline2",                   # embedded newline
        "\\n",                            # literal backslash-n, NOT \n
        'trailing\\',                     # trailing backslash
    ]

    @pytest.mark.parametrize("value", HOSTILE)
    def test_hostile_value_round_trips(self, value):
        reg = obs_metrics.Registry()
        reg.counter("hostile").inc(site=value)
        snap = {n: m.snapshot() for n, m in reg._metrics.items()}
        text = obs.prometheus_text(snap)
        line = next(
            ln for ln in text.splitlines() if ln.startswith("hostile{")
        )
        # exactly one series line, one value capture, lossless inverse
        (escaped,) = _LABEL_RE.findall(line)
        assert "\n" not in line
        assert _unescape_label(escaped) == value

    def test_distinct_hostile_values_stay_distinct(self):
        # the raw f-string rendering collapsed 'a\nb' and 'a\\nb' into
        # ambiguous text; escaped rendering must keep them apart
        reg = obs_metrics.Registry()
        reg.counter("h2").inc(site="a\nb")
        reg.counter("h2").inc(2, site="a\\nb")
        snap = {n: m.snapshot() for n, m in reg._metrics.items()}
        text = obs.prometheus_text(snap)
        lines = [
            ln for ln in text.splitlines() if ln.startswith("h2{")
        ]
        assert len(lines) == 2
        vals = {
            _unescape_label(_LABEL_RE.findall(ln)[0]) for ln in lines
        }
        assert vals == {"a\nb", "a\\nb"}


# ------------------------------------------ chrome trace class tracks


class TestChromeTraceClassTracks:
    def test_classified_spans_land_on_named_tracks(self):
        events = [
            _span_evt(
                "dispatch.transfer.h2d", "t1", "a1", None,
                seconds=0.1, nbytes=4096,
            ),
            _span_evt("stream.segment", "t1", "b2", None, seconds=0.5),
            {"event": "serve_stage", "stage": "queue_wait",
             "seconds": 0.02, "ts_mono": 100.5, "seq": 3,
             "trace_id": "t1"},
        ]
        doc = obs.chrome_trace(events)
        evs = doc["traceEvents"]
        track = [e for e in evs if e.get("cat") == "mosaic.timeline"]
        # transfer span + queue_wait interval get track rows; the
        # device-class segment stays on its trace row only
        assert {e["args"]["class"] for e in track} == {
            "transfer", "queue_wait",
        }
        xfer = next(e for e in track if e["args"]["class"] == "transfer")
        assert xfer["ph"] == "X" and xfer["tid"] == 1002
        qw = next(e for e in track if e["args"]["class"] == "queue_wait")
        assert qw["ph"] == "X" and qw["tid"] == 1003
        # the flat interval is anchored at ts_mono - seconds
        assert qw["ts"] == pytest.approx((100.5 - 0.02) * 1e6)
        names = {
            (e["tid"], e["args"]["name"]) for e in evs
            if e.get("ph") == "M" and e["name"] == "thread_name"
        }
        assert (1002, "mosaic:transfer") in names
        assert (1003, "mosaic:queue_wait") in names
        # the original trace rows are still intact alongside
        assert any(
            e["ph"] == "X" and e.get("cat") == "mosaic"
            and e["name"] == "dispatch.transfer.h2d"
            for e in evs
        )
        json.loads(json.dumps(doc))

    def test_unclassified_trails_emit_no_tracks(self):
        doc = obs.chrome_trace(
            [_span_evt("custom.thing", "t1", "a1", None)]
        )
        assert not [
            e for e in doc["traceEvents"]
            if e.get("cat") == "mosaic.timeline" or e.get("ph") == "M"
        ]
