"""SLO registry + multi-window burn-rate monitor (PR 20).

The acceptance contract of `mosaic_tpu/obs/slo.py`:

- :class:`WindowRing` / :class:`WindowHistogram` give exact-at-bucket
  sliding-window totals and percentiles in O(buckets) memory;
- a breach requires the burn rate over BOTH the short and the long
  window (a short-window blip never pages);
- the healthy→breached transition emits exactly ONE ``slo_violation``
  on the spine per breach episode (hysteresis re-arm below
  ``clear_factor x threshold``), trace-stamped like any event;
- ``count_zero`` (cold compiles after freeze) and ``rate_min``
  (sustained stream rate) kinds breach on their own rules;
- :func:`evaluate_trail` replays a captured trail through a fresh
  monitor and returns the benches' ``--slo`` verdict;
- default specs are registered only under ``MOSAIC_SLO_ENABLE``, with
  thresholds from the ``MOSAIC_SLO_*`` knobs.
"""

import pytest

from mosaic_tpu.obs import slo
from mosaic_tpu.runtime import telemetry


# ---------------------------------------------------------- window ring


class TestWindowRing:
    def test_totals_within_window_and_expiry(self):
        r = slo.WindowRing(10.0, n_buckets=10)  # 1 s buckets
        r.add(100.2, a=1.0)
        r.add(100.7, a=1.0)
        r.add(105.5, b=1.0)
        assert r.totals(106.0) == (2.0, 1.0)
        # a narrower window excludes the old bucket
        assert r.totals(106.0, window_s=2.0) == (0.0, 1.0)
        # sliding forward expires old buckets without any sweep: at
        # 114.9 only the 105 bucket survives; at 115.0 the window edge
        # (exclusive at lo) drops it too
        assert r.totals(114.9) == (0.0, 1.0)
        assert r.totals(115.0) == (0.0, 0.0)

    def test_slot_reuse_invalidates_stale_bucket(self):
        r = slo.WindowRing(10.0, n_buckets=10)
        r.add(100.5, a=5.0)
        # 110.5 maps to the SAME slot (10 buckets x 1 s): the stale
        # value must be dropped, not accumulated into
        r.add(110.5, b=1.0)
        assert r.totals(110.9) == (0.0, 1.0)

    def test_reset(self):
        r = slo.WindowRing(10.0, n_buckets=4)
        r.add(1.0, a=1.0, b=2.0)
        r.reset()
        assert r.totals(1.0) == (0.0, 0.0)


class TestWindowHistogram:
    def test_windowed_percentile(self):
        h = slo.WindowHistogram(10.0, n_buckets=10)
        for _ in range(99):
            h.observe(100.0, 0.004)
        h.observe(100.0, 5.0)
        # bucket-edge resolution: 0.004 lands in the 0.005 bucket
        assert h.percentile(100.5, 0.5) == 0.005
        assert h.percentile(100.5, 0.999) == 5.0
        # outside the window the samples are gone
        assert h.percentile(200.0, 0.5) is None

    def test_empty_is_none(self):
        h = slo.WindowHistogram(10.0)
        assert h.percentile(0.0, 0.99) is None


# ------------------------------------------------------ burn-rate rules


def _ratio_monitor(short=10.0, long=50.0, **spec_kw):
    m = slo.SLOMonitor(
        short_window_s=short, long_window_s=long, burn_threshold=1.0,
    )
    kw = {"min_events": 1, **spec_kw}
    spec = m.register(slo.SLOSpec(
        name="unit.ratio", kind="ratio", objective=0.95, **kw,
    ))
    m.wire_good(spec, "unit_good")
    m.wire_bad(spec, "unit_bad")
    return m


def _feed(m, event, n, t, **fields):
    hs = m._handlers[event]
    for _ in range(n):
        m._ingest(hs, {"event": event, **fields}, t)


class TestBurnRate:
    def test_short_window_blip_alone_does_not_breach(self):
        """The multi-window rule: a burst that torches the short window
        while the long window still holds budget does NOT page."""
        m = _ratio_monitor()
        _feed(m, "unit_good", 400, 1000.0)  # long-window ballast
        _feed(m, "unit_bad", 10, 1035.0)    # short-window burst
        with telemetry.capture() as events:
            statuses = m.evaluate(1040.0)
        (s,) = statuses
        assert s["burn_short"] == pytest.approx(20.0)   # 100% bad / 5%
        assert s["burn_long"] < 1.0
        assert not s["breached"]
        assert not [e for e in events if e["event"] == "slo_violation"]

    def test_both_windows_over_threshold_breaches_once(self):
        m = _ratio_monitor()
        _feed(m, "unit_good", 400, 1000.0)
        _feed(m, "unit_bad", 30, 1035.0)  # 30/430 long > 5% budget
        with telemetry.capture() as events:
            m.evaluate(1040.0)
            m.evaluate(1041.0)  # still breached: no second violation
            m.evaluate(1042.0)
        violations = [e for e in events if e["event"] == "slo_violation"]
        assert len(violations) == 1
        v = violations[0]
        assert v["slo"] == "unit.ratio" and v["kind"] == "ratio"
        assert v["burn_rate"] >= 1.0 and v["burn_rate_long"] >= 1.0
        assert v["window_s"] == 10.0 and v["long_window_s"] == 50.0

    def test_hysteresis_rearms_only_below_clear_floor(self):
        """Clear (window slides past the burst) then re-breach: a NEW
        episode, a second violation — but never one per evaluation."""
        m = _ratio_monitor(short=10.0, long=10.0)
        _feed(m, "unit_bad", 10, 1000.0)
        with telemetry.capture() as events:
            m.evaluate(1000.0)
            m.evaluate(1005.0)          # breached, no new event
            m.evaluate(1050.0)          # empty window -> clears, re-arms
            _feed(m, "unit_bad", 10, 1100.0)
            m.evaluate(1100.0)          # new episode
        violations = [e for e in events if e["event"] == "slo_violation"]
        assert len(violations) == 2
        (s,) = m.evaluate(1100.5)
        assert s["violations"] == 2 and s["breached"]

    def test_min_events_gate_holds_fire(self):
        m = _ratio_monitor(min_events=10)
        _feed(m, "unit_bad", 3, 1000.0)  # 100% bad but only 3 events
        with telemetry.capture() as events:
            (s,) = m.evaluate(1000.0)
        assert s["burn_short"] is None and not s["breached"]
        assert not [e for e in events if e["event"] == "slo_violation"]

    def test_count_zero_breaches_on_any_event(self):
        m = slo.SLOMonitor(short_window_s=10.0, long_window_s=10.0)
        spec = m.register(slo.SLOSpec(name="unit.cold", kind="count_zero"))
        m.wire_bad(spec, "serve_compile")
        (s,) = m.evaluate(1000.0)
        assert not s["breached"]
        _feed(m, "serve_compile", 1, 1001.0)
        with telemetry.capture() as events:
            (s,) = m.evaluate(1001.0)
        assert s["breached"] and s["burn_short"] == 1.0
        assert [e for e in events if e["event"] == "slo_violation"]

    def test_rate_min_breaches_below_floor(self):
        m = slo.SLOMonitor(short_window_s=10.0, long_window_s=10.0)
        spec = m.register(slo.SLOSpec(
            name="unit.rate", kind="rate_min", rate_min=100.0,
            min_events=1,
        ))
        m.wire_rate(spec, "stream_stage", "points_per_sec",
                    stage="join_loop")
        hs = m._handlers["stream_stage"]
        # wrong stage is ignored entirely
        m._ingest(hs, {"event": "stream_stage", "stage": "compile",
                       "points_per_sec": 1.0}, 1000.0)
        (s,) = m.evaluate(1000.0)
        assert s["burn_short"] is None
        m._ingest(hs, {"event": "stream_stage", "stage": "join_loop",
                       "points_per_sec": 50.0}, 1001.0)
        (s,) = m.evaluate(1001.0)
        assert s["breached"]  # mean 50 under the 100 floor: burn 2.0
        assert s["burn_short"] == pytest.approx(2.0)
        # rate recovers far above the floor -> burn < clear floor,
        # re-arms
        for _ in range(20):
            m._ingest(hs, {"event": "stream_stage", "stage": "join_loop",
                           "points_per_sec": 5000.0}, 1002.0)
        (s,) = m.evaluate(1002.0)
        assert not s["breached"]


# ----------------------------------------------------- observer wiring


class TestObserver:
    def test_observer_routes_and_evaluates_on_cadence(self):
        """Feeding the observer directly (as the spine would) both
        ingests matching events and trips evaluation without any manual
        evaluate() call — eval piggybacks on event arrival."""
        m = _ratio_monitor(short=1.0, long=1.0)
        with telemetry.capture() as events:
            for i in range(10):
                m.observer({"event": "unit_bad", "ts_mono": 1000.0 + i * 0.5})
            # unknown events are a no-op, not an error
            m.observer({"event": "who_knows", "ts_mono": 1001.0})
        assert [e for e in events if e["event"] == "slo_violation"]

    def test_snapshot_shape(self):
        m = _ratio_monitor()
        snap = m.snapshot(1000.0)
        assert snap["short_window_s"] == 10.0
        assert snap["long_window_s"] == 50.0
        assert set(snap["slos"]) == {"unit.ratio"}
        assert snap["slos"]["unit.ratio"]["kind"] == "ratio"


# ----------------------------------------------------- default specs


class TestDefaultSpecs:
    def test_latency_spec_classifies_against_knob(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_SLO_LATENCY_S", "0.01")
        m = slo.SLOMonitor(short_window_s=10.0, long_window_s=10.0)
        specs = slo.register_default_specs(m)
        names = {s.name for s in specs}
        assert {"serve.latency", "serve.shed", "runtime.degraded",
                "serve.cold_compile"} <= names
        hs = m._handlers["serve_request"]
        for i in range(20):
            m._ingest(hs, {"event": "serve_request", "seconds": 0.5,
                           "ts_mono": 1000.0}, 1000.0)
        (lat,) = [
            s for s in m.evaluate(1000.0) if s["slo"] == "serve.latency"
        ]
        assert lat["breached"]  # every request over the 10 ms threshold
        assert lat["p99_s"] is not None

    def test_stream_rate_spec_is_knob_gated(self, monkeypatch):
        m = slo.SLOMonitor(short_window_s=10.0)
        assert not any(
            s.name == "stream.sustained_rate"
            for s in slo.register_default_specs(m)
        )
        monkeypatch.setenv("MOSAIC_SLO_STREAM_RATE_MIN", "1000")
        m2 = slo.SLOMonitor(short_window_s=10.0)
        assert any(
            s.name == "stream.sustained_rate"
            for s in slo.register_default_specs(m2)
        )

    def test_window_and_burn_knobs(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_SLO_WINDOW_S", "30")
        monkeypatch.setenv("MOSAIC_SLO_BURN", "2.5")
        m = slo.SLOMonitor()
        assert m.short_window_s == 30.0
        assert m.long_window_s == 150.0  # 5x the short window
        assert m.burn_threshold == 2.5
        monkeypatch.setenv("MOSAIC_SLO_WINDOW_S", "not-a-number")
        assert slo.SLOMonitor().short_window_s == slo.DEFAULT_WINDOW_S


# ----------------------------------------------------- trail replay


def _trail(n_good, n_bad, t0=100.0):
    events = [
        {"event": "serve_request", "seconds": 0.001,
         "ts_mono": t0 + i * 0.01, "seq": i}
        for i in range(n_good)
    ]
    events += [
        {"event": "serve_shed", "reason": "deadline",
         "ts_mono": t0 + 1.0 + i * 0.01, "seq": n_good + i}
        for i in range(n_bad)
    ]
    return events


class TestEvaluateTrail:
    def test_clean_trail_is_ok(self):
        verdict = slo.evaluate_trail(_trail(50, 0))
        assert verdict["ok"] and verdict["breached"] == []
        assert not verdict["verdicts"]["serve.shed"]["breached"]

    def test_shed_storm_breaches_and_lands_in_capture(self):
        """The --slo lane contract: a breach during replay emits a real
        slo_violation INSIDE the caller's capture, so the bench trail
        itself records the verdict."""
        with telemetry.capture() as events:
            verdict = slo.evaluate_trail(_trail(50, 50))
        assert not verdict["ok"]
        assert verdict["breached"] == ["serve.shed"]
        v = [e for e in events if e["event"] == "slo_violation"]
        assert len(v) == 1 and v[0]["slo"] == "serve.shed"

    def test_non_dict_rows_are_tolerated(self):
        events = _trail(20, 0) + ["garbage", None]
        assert slo.evaluate_trail(events)["ok"]
