"""docs/API.md must match the live registry (regenerate on drift)."""

import sys
from pathlib import Path

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))


def test_api_doc_is_current():
    import generate_api_docs

    want = generate_api_docs.generate()
    got = (REPO / "docs" / "API.md").read_text()
    assert got == want, (
        "docs/API.md is stale - run: python tools/generate_api_docs.py"
    )
