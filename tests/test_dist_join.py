"""Distributed join parity on the virtual 8-device CPU mesh.

The multi-chip correctness evidence: `distributed_join_step` on a
``(dp, cell)`` mesh must produce exactly the single-device
`pip_join_points` result, for several mesh shapes, with uneven shard
padding, and for both the sharded- and replicated-hash-table layouts.
Reference semantics: `sql/join/PointInPolygonJoin.scala:68-84` (equi-join
on cell + ``is_core || st_contains``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.datasets import random_points, synthetic_zones
from mosaic_tpu.parallel import (
    distributed_join_step,
    make_mesh,
    pad_index_for_shards,
)
from mosaic_tpu.parallel.dist_join import pad_points
from mosaic_tpu.sql.join import build_chip_index, pip_join_points

RES = 7
BBOX = (-74.05, 40.60, -73.85, 40.78)


@pytest.fixture(scope="module")
def problem():
    h3 = H3IndexSystem()
    zones = synthetic_zones(3, 3, bbox=BBOX)
    table = tessellate(zones, h3, RES, keep_core_geoms=False)
    index = build_chip_index(table)
    pts = random_points(301, bbox=BBOX, seed=5)  # odd: forces point padding
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), RES))
    shift = np.asarray(index.border.shift, dtype=np.float64)
    shifted = (pts - shift).astype(np.asarray(index.border.verts).dtype)
    single = np.asarray(pip_join_points(jnp.asarray(shifted), jnp.asarray(cells), index))
    return h3, index, shifted, cells, single, len(zones)


def _run(mesh, index, shifted, cells, num_zones, table_size):
    index = pad_index_for_shards(index, mesh.shape["cell"])
    p, c = pad_points(shifted, cells, mesh.size)
    step = distributed_join_step(mesh, num_zones, table_size=table_size)
    match, counts = step(jnp.asarray(p), jnp.asarray(c), index)
    return np.asarray(match)[: shifted.shape[0]], np.asarray(counts)


@pytest.mark.parametrize("cell_axis", [1, 2, 4, 8])
def test_mesh_shapes_match_single_device(problem, devices, cell_axis):
    h3, index, shifted, cells, single, nz = problem
    mesh = make_mesh(8, cell_axis=cell_axis)
    T = int(index.table_cell.shape[0])
    match, counts = _run(mesh, index, shifted, cells, nz, T)
    np.testing.assert_array_equal(match, single)
    # psum'd per-zone histogram == host bincount of the single-device match
    expect = np.bincount(single[single >= 0], minlength=nz)
    np.testing.assert_array_equal(counts, expect)


def test_replicated_table_path(problem, devices):
    """table_size=None keeps the hash table replicated — same answer."""
    h3, index, shifted, cells, single, nz = problem
    mesh = make_mesh(8, cell_axis=2)
    match, _ = _run(mesh, index, shifted, cells, nz, None)
    np.testing.assert_array_equal(match, single)


def test_indivisible_table_falls_back_to_replicated(problem, devices):
    """A table size the cell axis doesn't divide must still be correct."""
    h3, index, shifted, cells, single, nz = problem
    mesh = make_mesh(8, cell_axis=4)
    # claim a non-divisible T: the step must choose the replicated layout
    match, _ = _run(mesh, index, shifted, cells, nz, int(index.table_cell.shape[0]) + 1)
    np.testing.assert_array_equal(match, single)


def test_pad_index_roundtrip(problem):
    """Padding preserves the single-device join result exactly."""
    h3, index, shifted, cells, single, nz = problem
    padded = pad_index_for_shards(index, 8)
    assert int(padded.cells.shape[0]) % 8 == 0
    assert int(padded.chip_geom.shape[0]) % 8 == 0
    out = np.asarray(
        pip_join_points(jnp.asarray(shifted), jnp.asarray(cells), padded)
    )
    np.testing.assert_array_equal(out, single)


def test_pad_points_sentinels_never_match(problem, devices):
    h3, index, shifted, cells, single, nz = problem
    p, c = pad_points(shifted, cells, 8)
    assert p.shape[0] % 8 == 0
    mesh = make_mesh(8, cell_axis=2)
    idx = pad_index_for_shards(index, 2)
    step = distributed_join_step(mesh, nz, table_size=int(idx.table_cell.shape[0]))
    match, _ = step(jnp.asarray(p), jnp.asarray(c), idx)
    match = np.asarray(match)
    assert (match[shifted.shape[0] :] == -1).all()
