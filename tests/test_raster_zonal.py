"""Zonal statistics: bit-identity against the f64 host oracles.

The contract under test (ISSUE 10): every zonal fold — grid cells,
vector zones, both kernel lanes, and the durable scan through any
kill/resume point — is bit-identical to a pure-host f64 oracle that
mirrors the tile decomposition, on adversarial fixtures: NaN nodata,
zone edges crossing tile boundaries, pixel centers landing EXACTLY on
zone edges, pad tiles from non-divisible shapes.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.kernels.pip import TilingError
from mosaic_tpu.kernels.zonal import zonal_fold, zonal_tiled
from mosaic_tpu.raster import Raster
from mosaic_tpu.raster.zonal import (
    ZonalEngine,
    host_zonal_grid_oracle,
    host_zonal_zones_oracle,
    resolve_zonal_lane,
    zonal_grid,
    zonal_zones,
)
from mosaic_tpu.runtime import checkpoint, faults, telemetry
from mosaic_tpu.runtime.retry import RetryPolicy
from mosaic_tpu.sql import RasterStream
from mosaic_tpu.sql.join import build_chip_index

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3

#: zone edges cross the (32, 32) tile boundaries (x = 32/64, rows
#: 32/64), and the vertical x=6 / horizontal y=8 edges run EXACTLY
#: through pixel centers of the fixture raster (centers at integer
#: coordinates); zone 0 carries a hole
ZONES = [
    "POLYGON ((6 -20, 50 -25, 70 10, 40 8, 6 8, 6 -20), "
    "(20 -10, 30 -10, 30 -2, 20 -2, 20 -10))",
    "POLYGON ((55 -50, 85 -50, 85 -20, 70 -35, 55 -20, 55 -50))",
    "POLYGON ((2 -55, 20 -55, 20 -40, 2 -40, 2 -55))",
]

FAST = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)


@pytest.fixture(scope="module")
def index():
    col = wkt.from_wkt(ZONES)
    return build_chip_index(
        tessellate(col, CUSTOM, RES, keep_core_geoms=False)
    )


def _mk_raster(h=75, w=90, nodata=-9.0, seed=5, integer=False):
    """75x90 @ (32,32) tiles -> 3x3 grid, both axes padded; pixel
    centers at integer world coordinates (x = col, y = 15 - row)."""
    rng = np.random.default_rng(seed)
    if integer:
        data = rng.integers(0, 100, (1, h, w)).astype(np.float64)
    else:
        data = rng.uniform(0, 100, (1, h, w))
    speck = rng.random((h, w)) < 0.1
    if nodata is not None:
        data[0][speck] = nodata
    return Raster(
        data=data,
        gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0),
        srid=0,
        nodata=nodata,
    )


def _assert_result_equal(got, want):
    np.testing.assert_array_equal(got.keys, want.keys)
    np.testing.assert_array_equal(got.count, want.count)
    np.testing.assert_array_equal(got.sum, want.sum)  # bitwise: f64 fold
    np.testing.assert_array_equal(got.min, want.min)
    np.testing.assert_array_equal(got.max, want.max)
    assert got.pixels == want.pixels


# ------------------------------------------------------------------ kernels


def test_zonal_fold_matches_sequential_numpy():
    rng = np.random.default_rng(0)
    vals = rng.uniform(-50, 50, 4096)
    seg = rng.integers(-1, 37, 4096).astype(np.int32)
    cnt, s, mn, mx = (
        np.asarray(a) for a in zonal_fold(vals, seg, 37)
    )
    want_c = np.zeros(37, np.int64)
    want_s = np.zeros(37)
    want_mn = np.full(37, np.inf)
    want_mx = np.full(37, -np.inf)
    for g, v in zip(seg, vals):  # sequential: the fold's order contract
        if g >= 0:
            want_c[g] += 1
            want_s[g] += v
            want_mn[g] = min(want_mn[g], v)
            want_mx[g] = max(want_mx[g], v)
    np.testing.assert_array_equal(cnt, want_c)
    np.testing.assert_array_equal(s, want_s)
    live = want_c > 0
    np.testing.assert_array_equal(mn[live], want_mn[live])
    np.testing.assert_array_equal(mx[live], want_mx[live])


def test_zonal_tiled_matches_fold_on_exact_summable():
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 100, 5000).astype(np.float32)
    seg = rng.integers(-1, 19, 5000).astype(np.int32)
    cnt_t, s_t, mn_t, mx_t = (
        np.asarray(a)
        for a in zonal_tiled(vals, seg, 19, interpret=True)
    )
    cnt_f, s_f, mn_f, mx_f = (
        np.asarray(a)
        for a in zonal_fold(
            vals, seg, 19, acc_dtype=jnp.float32
        )
    )
    np.testing.assert_array_equal(cnt_t, cnt_f)
    np.testing.assert_array_equal(s_t, s_f)  # integer-valued: exact
    live = cnt_f > 0
    np.testing.assert_array_equal(mn_t[live], mn_f[live])
    np.testing.assert_array_equal(mx_t[live], mx_f[live])


def test_zonal_tiled_rejects_bad_tiling():
    vals = np.zeros(256, np.float32)
    seg = np.zeros(256, np.int32)
    with pytest.raises(TilingError):
        zonal_tiled(vals, seg, 4, tile_n=100, interpret=True)
    with pytest.raises(TilingError):
        zonal_tiled(vals, seg, 4, tile_s=64, interpret=True)


# --------------------------------------------------------------- lane knob


def test_lane_knob(monkeypatch):
    monkeypatch.delenv("MOSAIC_RASTER_LANE", raising=False)
    assert resolve_zonal_lane("auto") == "fold"
    monkeypatch.setenv("MOSAIC_RASTER_LANE", "tiled")
    assert resolve_zonal_lane("auto") == "tiled"
    assert resolve_zonal_lane("fold") == "fold"  # explicit beats env
    monkeypatch.setenv("MOSAIC_RASTER_LANE", "warp")
    with pytest.raises(ValueError, match="zonal lane"):
        resolve_zonal_lane("auto")


# ------------------------------------------------------------- grid oracle


def test_grid_bit_identical_to_oracle():
    r = _mk_raster()
    got = zonal_grid(r, RES, index_system=CUSTOM, tile=(32, 32))
    want = host_zonal_grid_oracle(r, RES, CUSTOM, tile=(32, 32))
    _assert_result_equal(got, want)
    # counts cover exactly the valid pixels
    assert got.pixels == int(r.band(1).mask.sum())


def test_grid_oracle_nan_nodata():
    r = _mk_raster(nodata=np.nan)
    got = zonal_grid(r, RES, index_system=CUSTOM, tile=(32, 32))
    want = host_zonal_grid_oracle(r, RES, CUSTOM, tile=(32, 32))
    _assert_result_equal(got, want)
    assert np.isfinite(got.sum).all()


def test_grid_tile_shape_invariant_keys():
    # sums are tile-order-dependent (documented), but keys/counts/min/
    # max are not: any tile shape must agree on those
    r = _mk_raster()
    a = zonal_grid(r, RES, index_system=CUSTOM, tile=(32, 32))
    b = zonal_grid(r, RES, index_system=CUSTOM, tile=(64, 128))
    np.testing.assert_array_equal(a.keys, b.keys)
    np.testing.assert_array_equal(a.count, b.count)
    np.testing.assert_array_equal(a.min, b.min)
    np.testing.assert_array_equal(a.max, b.max)
    np.testing.assert_allclose(a.sum, b.sum, rtol=1e-12)
    # mean/stat view
    st = a.stat("mean")
    assert st[int(a.keys[0])] == pytest.approx(a.sum[0] / a.count[0])


# ------------------------------------------------------------ zones oracle


def test_zones_bit_identical_to_oracle(index):
    r = _mk_raster()
    got = zonal_zones(r, index, CUSTOM, RES, tile=(32, 32))
    want = host_zonal_zones_oracle(r, index, CUSTOM, RES, tile=(32, 32))
    _assert_result_equal(got, want)
    assert set(got.keys) <= {0, 1, 2}
    assert len(got.keys) == 3  # every zone is hit by this fixture


def test_zones_oracle_nan_nodata_and_edge_centers(index):
    # NaN nodata + centers exactly on the x=6 / y=8 zone edges: device
    # probe and f64 host join must classify every such pixel identically
    r = _mk_raster(nodata=np.nan, seed=11)
    got = zonal_zones(r, index, CUSTOM, RES, tile=(32, 32))
    want = host_zonal_zones_oracle(r, index, CUSTOM, RES, tile=(32, 32))
    _assert_result_equal(got, want)


def test_zones_engine_reuse_and_hole(index):
    # hole pixels (zone 0's interior ring) fold nowhere: count over the
    # hole bbox interior must be absent from zone 0's pixels
    eng = ZonalEngine(CUSTOM, RES, chip_index=index)
    r = _mk_raster(nodata=None, seed=13)
    got = eng.zones(r, tile=(32, 32))
    want = host_zonal_zones_oracle(r, index, CUSTOM, RES, tile=(32, 32))
    _assert_result_equal(got, want)
    # engine reuse across rasters (same tile shape -> same executables)
    r2 = _mk_raster(seed=17)
    _assert_result_equal(
        eng.zones(r2, tile=(32, 32)),
        host_zonal_zones_oracle(r2, index, CUSTOM, RES, tile=(32, 32)),
    )


def test_zones_tiled_lane_agrees_on_integer_data(index):
    # the f32 Pallas lane holds bit-identity on exact-summable values
    r = _mk_raster(integer=True, seed=23)
    fold = ZonalEngine(
        CUSTOM, RES, chip_index=index, lane="fold"
    ).zones(r, tile=(32, 32))
    tiled = ZonalEngine(
        CUSTOM, RES, chip_index=index, lane="tiled"
    ).zones(r, tile=(32, 32))
    np.testing.assert_array_equal(tiled.keys, fold.keys)
    np.testing.assert_array_equal(tiled.count, fold.count)
    np.testing.assert_array_equal(tiled.sum, fold.sum)
    np.testing.assert_array_equal(tiled.min, fold.min)
    np.testing.assert_array_equal(tiled.max, fold.max)


def test_zones_requires_chip_index():
    eng = ZonalEngine(CUSTOM, RES)
    with pytest.raises(ValueError, match="chip_index"):
        eng.zones(_mk_raster())


def test_zonal_emits_stage_telemetry(index):
    with telemetry.capture() as ev:
        zonal_zones(_mk_raster(), index, CUSTOM, RES, tile=(32, 32))
    stages = [
        e.get("stage") for e in ev if e["event"] == "raster_stage"
    ]
    assert "tile" in stages and "zonal" in stages


# ------------------------------------------------------------ durable scan


@pytest.fixture(scope="module")
def stream(index):
    return RasterStream(index, CUSTOM, RES)


@pytest.fixture(scope="module")
def raster():
    return _mk_raster(seed=29)


@pytest.fixture(scope="module")
def clean(stream, raster):
    return stream.scan(raster, tile=(32, 32))


def test_scan_matches_engine_and_oracle(stream, raster, clean, index):
    want = host_zonal_zones_oracle(
        raster, index, CUSTOM, RES, tile=(32, 32)
    )
    _assert_result_equal(clean.stats, want)
    assert clean.ntiles == 9
    assert clean.pixels == 75 * 90


def test_durable_scan_equals_plain(stream, raster, clean, tmp_path):
    r = stream.scan(
        raster, tile=(32, 32), run_dir=str(tmp_path), snapshot_every=2,
    )
    _assert_result_equal(r.stats, clean.stats)
    # 9 tiles, every-2 boundaries: 2, 4, 6, 8, 9
    assert r.metrics["snapshots"] == 5
    assert checkpoint.list_snapshots(str(tmp_path)) == [2, 4, 6, 8, 9]


@pytest.mark.parametrize("kill_after", [2, 4, 6])
def test_scan_kill_and_resume_bit_identical(
    stream, raster, clean, tmp_path, kill_after
):
    """Fatal device loss after ``kill_after`` tiles; resume() from the
    newest snapshot converges to the clean fold bit for bit."""
    d = str(tmp_path / f"kill{kill_after}")
    with faults.inject(
        fail_first=99, skip_first=kill_after,
        sites=("raster.zonal",),
        exc_factory=lambda s: RuntimeError(f"simulated device loss @ {s}"),
    ):
        with pytest.raises(RuntimeError, match="simulated device loss"):
            stream.scan(
                raster, tile=(32, 32), run_dir=d, snapshot_every=2,
                retry_policy=FAST,
            )
    assert checkpoint.list_snapshots(d)
    r = stream.resume(d, raster, retry_policy=FAST)
    _assert_result_equal(r.stats, clean.stats)
    assert r.metrics["resumed_from"] == kill_after  # boundary == kill pt


def test_scan_transient_faults_retry_to_clean(stream, raster, clean, tmp_path):
    with telemetry.capture() as ev:
        with faults.transient_errors(2, sites=("raster.zonal",)):
            r = stream.scan(
                raster, tile=(32, 32), run_dir=str(tmp_path / "t"),
                snapshot_every=4, retry_policy=FAST,
            )
    _assert_result_equal(r.stats, clean.stats)
    assert r.metrics["degraded"] is False
    assert [e["event"] for e in ev].count("transient_retry") == 2


def test_scan_exhausted_tile_degrades_to_host(stream, raster, clean, tmp_path):
    """A tile whose retry budget exhausts is answered by the f64 host
    twin — bit-identical, so the final fold still equals clean."""
    with telemetry.capture() as ev:
        with faults.transient_errors(
            3, sites=("raster.zonal",)
        ):  # == FAST.max_attempts: tile 0's budget exhausts
            r = stream.scan(
                raster, tile=(32, 32), run_dir=str(tmp_path / "d"),
                snapshot_every=4, retry_policy=FAST,
            )
    assert r.metrics["degraded"] is True
    assert r.metrics["degraded_tiles"] == 1
    _assert_result_equal(r.stats, clean.stats)
    assert "degraded" in [e["event"] for e in ev]


def test_scan_snapshot_failure_does_not_kill_run(
    stream, raster, clean, tmp_path
):
    with telemetry.capture() as ev:
        with faults.transient_errors(999, sites=("raster.snapshot",)):
            # snapshot site is guarded by save_snapshot itself; simulate
            # a sick disk instead by pointing run_dir at a file
            p = tmp_path / "not_a_dir"
            p.write_text("x")
            r = stream.scan(
                raster, tile=(32, 32), run_dir=str(p), snapshot_every=4,
            )
    _assert_result_equal(r.stats, clean.stats)
    assert r.metrics["snapshots"] == 0
    assert "snapshot_skipped" in [e["event"] for e in ev]


def test_resume_rejects_wrong_raster(stream, raster, tmp_path):
    stream.scan(
        raster, tile=(32, 32), run_dir=str(tmp_path), snapshot_every=4,
    )
    other = _mk_raster(seed=99)
    with pytest.raises(ValueError, match="fingerprint"):
        stream.resume(str(tmp_path), other)


def test_resume_without_snapshots_raises(stream, raster, tmp_path):
    with pytest.raises(FileNotFoundError, match="nothing to resume"):
        stream.resume(str(tmp_path / "empty"), raster)


def test_scan_joins_trace_on_resume(stream, raster, tmp_path):
    d = str(tmp_path)
    with faults.inject(
        fail_first=99, skip_first=4, sites=("raster.zonal",),
        exc_factory=lambda s: RuntimeError("boom"),
    ):
        with telemetry.capture() as ev1:
            with pytest.raises(RuntimeError):
                stream.scan(
                    raster, tile=(32, 32), run_dir=d, snapshot_every=2,
                )
    with telemetry.capture() as ev2:
        stream.resume(d, raster)

    def scan_span(evs):
        return next(
            e for e in evs
            if e["event"] == "span" and e["name"] == "raster.scan"
        )

    first, second = scan_span(ev1), scan_span(ev2)
    # the resumed run joins the killed run's trace, not a fresh one
    assert second["trace_id"] == first["trace_id"]
    assert second["resumed_from"] == 4
    assert first["error"] == "RuntimeError"
