"""Geo-expression compiler: fusion, bit-identity, and cache hygiene.

The contract under test (ISSUE 13): an expression tree — band math,
masking, zonal terminal — lowered by `mosaic_tpu.expr` runs as ONE
device program per tile-bucket signature, and its per-zone results are
bit-identical to (a) the staged pipeline of existing rst_*/zonal ops
and (b) a pure-numpy f64 interpreter of the same tree, on adversarial
fixtures: NaN-nodata speckle, pixel centers landing EXACTLY on zone
edges, multi-band planar tiles. Structurally equal trees share one
compiled program; after ``freeze()`` a novel signature trips the
cold-compile tripwire; durable expression scans refuse to resume
against a different tree.
"""

import numpy as np
import pytest

from mosaic_tpu import expr as E
from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.dispatch import core as dispatch
from mosaic_tpu.expr import compile as expr_compile
from mosaic_tpu.functions.raster import rst_mapbands, rst_ndvi
from mosaic_tpu.raster import Raster
from mosaic_tpu.raster.zonal import ZonalEngine, zonal_zones
from mosaic_tpu.runtime import checkpoint, faults, telemetry
from mosaic_tpu.runtime.retry import RetryPolicy
from mosaic_tpu.sql import RasterStream
from mosaic_tpu.sql.join import build_chip_index

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3

#: same adversarial zone set as test_raster_zonal.py: edges cross the
#: (32, 32) tile boundaries and the x=6 / y=8 edges run EXACTLY through
#: pixel centers of the fixture raster; zone 0 carries a hole
ZONES = [
    "POLYGON ((6 -20, 50 -25, 70 10, 40 8, 6 8, 6 -20), "
    "(20 -10, 30 -10, 30 -2, 20 -2, 20 -10))",
    "POLYGON ((55 -50, 85 -50, 85 -20, 70 -35, 55 -20, 55 -50))",
    "POLYGON ((2 -55, 20 -55, 20 -40, 2 -40, 2 -55))",
]

FAST = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)


@pytest.fixture(scope="module")
def index():
    col = wkt.from_wkt(ZONES)
    return build_chip_index(
        tessellate(col, CUSTOM, RES, keep_core_geoms=False)
    )


@pytest.fixture(scope="module")
def engine(index):
    return ZonalEngine(CUSTOM, RES, chip_index=index)


def _mk_raster(h=75, w=90, bands=3, seed=5):
    """Multi-band 75x90 @ (32, 32) -> 3x3 padded tile grid; pixel
    centers at integer world coordinates (x = col, y = 15 - row); NaN
    nodata with ~8% speckle per band (NaN pixels are INVALID — the
    bit-identity contract masks NaN out, it never reaches a fold)."""
    rng = np.random.default_rng(seed)
    data = rng.uniform(0.0, 100.0, (bands, h, w))
    for b in range(bands):
        speck = rng.random((h, w)) < 0.08
        data[b][speck] = np.nan
    return Raster(
        data=data,
        gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0),
        srid=0,
        nodata=float("nan"),
    )


def _planar_raster(h=75, w=90, bands=3):
    """Multi-band planar tiles: each band constant per (32, 32) tile,
    adversarial for min == max == mean collapses and for any lowering
    that confuses band rows."""
    data = np.zeros((bands, h, w))
    for b in range(bands):
        for ti, r0 in enumerate(range(0, h, 32)):
            for tj, c0 in enumerate(range(0, w, 32)):
                data[b, r0:r0 + 32, c0:c0 + 32] = (
                    10.0 * (b + 1) + ti + 0.5 * tj
                )
    return Raster(
        data=data, gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0), srid=0,
        nodata=float("nan"),
    )


@pytest.fixture(scope="module")
def raster():
    return _mk_raster()


#: the acceptance pipeline: NDVI, cloud mask, zonal fold
def _pipeline():
    return (
        E.ndvi(nir=2, red=1)
        .mask_where(E.band(3) < 80.0)
        .zonal(by="zones")
    )


def _assert_result_equal(got, want):
    np.testing.assert_array_equal(got.keys, want.keys)
    np.testing.assert_array_equal(got.count, want.count)
    np.testing.assert_array_equal(got.sum, want.sum)  # bitwise: f64
    np.testing.assert_array_equal(got.min, want.min)
    np.testing.assert_array_equal(got.max, want.max)


# --------------------------------------------------------------- ast


class TestAst:
    def test_structural_equality_and_hash(self):
        a = _pipeline()
        b = _pipeline()
        assert a == b
        assert E.structure_key(a) == E.structure_key(b)
        assert E.tree_hash(a) == E.tree_hash(b)
        assert E.tree_hash(a) != E.tree_hash(
            E.ndvi(nir=3, red=1).zonal(by="zones")
        )

    def test_eq_is_a_method_not_dunder(self):
        # __eq__ stays structural (dataclass) so trees are dict keys;
        # pixel equality is spelled .eq()/.ne()
        node = E.band(1).eq(E.band(2))
        assert isinstance(node, E.Compare)
        assert node.op == "eq"

    def test_bands_of_and_terminal(self):
        e = _pipeline()
        value, kind, by, stats = E.terminal_of(e)
        assert kind == "zonal" and by == "zones"
        assert list(E.bands_of(value)) == [1, 2, 3]
        assert set(stats) == {"count", "sum", "min", "max", "mean"}

    def test_validation_errors(self):
        with pytest.raises(ValueError, match="out of range"):
            E.validate(E.band(4).zonal(), 3)
        with pytest.raises(TypeError, match="numeric"):
            E.validate(E.band(1) + (E.band(2) < 1.0), 3)
        with pytest.raises(ValueError, match="grid"):
            E.validate(
                (E.band(1) + E.zone_data((1.0,))).zonal(by="grid"), 3
            )
        with pytest.raises(ValueError, match="terminal"):
            E.validate(E.band(1).zonal() + E.band(2), 3)
        with pytest.raises(ValueError, match="vector side"):
            E.validate(
                (E.band(1) + E.zone_data((1.0,))).zonal(), 3,
                has_zones=False,
            )
        with pytest.raises(TypeError, match="numeric value tree"):
            E.validate((E.band(1) < 2.0).zonal(), 3)


# ------------------------------------------------- fused == staged == oracle


class TestBitIdentity:
    def test_fused_equals_staged_and_oracle(self, engine, raster, index):
        """The acceptance pipeline, three ways: (1) fused — one program
        per tile does NDVI + mask + fold; (2) staged — NDVI computed
        into a NaN-nodata raster by numpy, masked by numpy, folded by
        the pre-existing zonal path; (3) the f64 host interpreter."""
        e = _pipeline()
        fused = engine.map(e, raster, tile=(32, 32))

        nir = raster.data[1]
        red = raster.data[0]
        cloud = raster.data[2]
        staged_px = (nir - red) / (nir + red)
        keep = np.isfinite(cloud) & (cloud < 80.0)
        staged_px = np.where(keep, staged_px, np.nan)
        staged_r = Raster(
            data=staged_px[None], gt=raster.gt, srid=0,
            nodata=float("nan"),
        )
        staged = zonal_zones(
            staged_r, index, CUSTOM, RES, tile=(32, 32)
        )
        _assert_result_equal(fused, staged)

        oracle = E.host_expr_zonal_oracle(
            raster, e, index_system=CUSTOM, resolution=RES,
            chip_index=index, tile=(32, 32),
        )
        _assert_result_equal(fused, oracle)

    def test_edge_pixels_fold_identically(self, engine, raster, index):
        """Pixel centers exactly on the x=6 / y=8 zone edges go through
        the epsilon-band host re-join in BOTH lanes — membership of the
        fused fold must match the staged path bit for bit (counts too,
        not just sums)."""
        e = (E.band(1) * 2.0 - E.band(2)).zonal(by="zones")
        fused = engine.map(e, raster, tile=(32, 32))
        staged_px = raster.data[0] * 2.0 - raster.data[1]
        staged = zonal_zones(
            Raster(
                data=staged_px[None], gt=raster.gt, srid=0,
                nodata=float("nan"),
            ),
            index, CUSTOM, RES, tile=(32, 32),
        )
        _assert_result_equal(fused, staged)

    def test_planar_tiles(self, engine, index):
        """Per-tile-constant bands: min == max per zone-tile overlap,
        and any band-row confusion in the lowering shows instantly."""
        r = _planar_raster()
        e = E.norm_diff(E.band(2), E.band(1)).zonal(by="zones")
        fused = engine.map(e, r, tile=(32, 32))
        oracle = E.host_expr_zonal_oracle(
            r, e, index_system=CUSTOM, resolution=RES,
            chip_index=index, tile=(32, 32),
        )
        _assert_result_equal(fused, oracle)

    def test_where_and_boolean_ops(self, engine, raster, index):
        e = E.where(
            (E.band(1) < 30.0) | (E.band(2) > 70.0),
            E.band(3),
            E.band(1) - E.band(2),
        ).zonal(by="zones")
        fused = engine.map(e, raster, tile=(32, 32))
        oracle = E.host_expr_zonal_oracle(
            raster, e, index_system=CUSTOM, resolution=RES,
            chip_index=index, tile=(32, 32),
        )
        _assert_result_equal(fused, oracle)

    def test_grid_mode(self, engine, raster):
        """by="grid": the fused program folds by index cell; oracle is
        the numpy interpreter + sequential dict fold."""
        e = E.ndvi(nir=2, red=1).zonal(by="grid")
        fused = engine.map(e, raster, tile=(32, 32))
        oracle = E.host_expr_zonal_oracle(
            raster, e, index_system=CUSTOM, resolution=RES,
            tile=(32, 32), by="grid",
        )
        _assert_result_equal(fused, oracle)

    def test_nan_detectable_in_tree(self, engine, raster, index):
        """band.ne(band) is the in-tree NaN probe — on a NaN-nodata
        raster every valid pixel is finite, so the probe is all-False
        and where() keeps the first branch everywhere."""
        e = E.where(
            E.band(1).ne(E.band(1)), E.const(-1.0), E.band(1)
        ).zonal(by="zones")
        fused = engine.map(e, raster, tile=(32, 32))
        plain = engine.map(E.band(1).zonal(by="zones"), raster,
                           tile=(32, 32))
        _assert_result_equal(fused, plain)


# ----------------------------------------------------- one-program fusion


class TestFusion:
    def test_warm_map_compiles_nothing(self, engine, raster):
        """THE acceptance criterion: after warmup the 3-op pipeline is
        exactly one device program per tile bucket — a warm map adds
        ZERO backend compiles."""
        e = _pipeline()
        engine.warmup_expr(e, raster, tile=(32, 32))
        n0 = dispatch.backend_compiles()
        engine.map(e, raster, tile=(32, 32))
        assert dispatch.backend_compiles() == n0

    def test_structural_sharing_one_compile(self, engine, raster):
        """Two independently-built equal trees key the same cached
        program: the second map is a pure cache hit."""
        a = (E.band(1) + E.band(2) * 0.25).mask_where(
            E.band(3) < 99.0
        ).zonal(by="zones")
        b = (E.band(1) + E.band(2) * 0.25).mask_where(
            E.band(3) < 99.0
        ).zonal(by="zones")
        assert a is not b and a == b
        engine.map(a, raster, tile=(32, 32))
        before = dispatch.cache_view("expr_programs")
        n0 = dispatch.backend_compiles()
        got_b = engine.map(b, raster, tile=(32, 32))
        after = dispatch.cache_view("expr_programs")
        assert after["misses"] == before["misses"]  # no new program
        assert after["hits"] > before["hits"]
        assert dispatch.backend_compiles() == n0
        _assert_result_equal(
            got_b, engine.map(a, raster, tile=(32, 32))
        )

    def test_post_freeze_cold_compile_tripwire(self, engine, raster):
        """freeze() arms the tripwire: a NOVEL tree after it increments
        cold_compiles and emits an ``expr_compile`` event."""
        sigs = expr_compile.signatures()
        frozen = expr_compile._frozen
        try:
            engine.warmup_expr(_pipeline(), raster, tile=(32, 32))
            expr_compile.freeze()
            cold0 = expr_compile.cold_compiles()
            # warm tree: no trip
            engine.map(_pipeline(), raster, tile=(32, 32))
            assert expr_compile.cold_compiles() == cold0
            novel = (E.band(1) * 7.75 - E.band(3)).zonal(by="zones")
            with telemetry.capture() as ev:
                engine.map(novel, raster, tile=(32, 32))
            assert expr_compile.cold_compiles() == cold0 + 1
            trips = [e for e in ev if e["event"] == "expr_compile"]
            assert len(trips) == 1 and trips[0]["after_freeze"]
        finally:
            expr_compile._frozen = frozen
            expr_compile._signatures.update(sigs)

    def test_first_build_opens_compile_span(self, engine, raster):
        """Satellite 2: the first execution of a signature sits under a
        ``dispatch.compile`` span (site=expr) that timeline attribution
        classifies as *compile*, with a backend_compiles delta."""
        from mosaic_tpu.obs import timeline

        novel = (E.band(2) / (E.band(1) + 123.25)).zonal(by="zones")
        with telemetry.capture() as ev:
            engine.map(novel, raster, tile=(32, 32))
        comp = [
            e for e in ev
            if e["event"] == "span" and e["name"] == "dispatch.compile"
            and e.get("site") == "expr"
        ]
        assert len(comp) == 1
        assert comp[0]["backend_compiles"] >= 1
        assert (
            timeline.classify_key("span.dispatch.compile") == "compile"
        )
        # warm repeat: no compile span at all
        with telemetry.capture() as ev2:
            engine.map(novel, raster, tile=(32, 32))
        assert not [
            e for e in ev2
            if e["event"] == "span" and e["name"] == "dispatch.compile"
        ]

    def test_map_emits_expr_stage(self, engine, raster):
        with telemetry.capture() as ev:
            engine.map(_pipeline(), raster, tile=(32, 32))
        stages = [e for e in ev if e["event"] == "expr_stage"]
        assert len(stages) == 1
        st = stages[0]
        assert st["stage"] == "map" and st["mode"] == "zones"
        assert st["pixels"] > 0 and st["pixels_per_sec"] > 0


# -------------------------------------------------------- guarded path


class TestDegradation:
    def test_exhausted_tile_degrades_bit_identically(
        self, engine, raster
    ):
        e = _pipeline()
        clean = engine.map(e, raster, tile=(32, 32))
        with telemetry.capture() as ev:
            with faults.transient_errors(
                3, sites=("expr.map",)
            ):
                got = engine.map(
                    e, raster, tile=(32, 32), retry_policy=FAST
                )
        _assert_result_equal(got, clean)
        degr = [e2 for e2 in ev if e2["event"] == "degraded"]
        assert degr and degr[0]["label"] == "expr.map"

    def test_transient_faults_retry_to_clean(self, engine, raster):
        e = _pipeline()
        clean = engine.map(e, raster, tile=(32, 32))
        with telemetry.capture() as ev:
            with faults.transient_errors(2, sites=("expr.map",)):
                got = engine.map(
                    e, raster, tile=(32, 32), retry_policy=FAST
                )
        _assert_result_equal(got, clean)
        assert [
            e2["event"] for e2 in ev
        ].count("transient_retry") == 2


# ------------------------------------------------------- pixel frontends


class TestPixelFrontends:
    def test_rst_ndvi_matches_numpy(self, raster):
        out = rst_ndvi([raster])[0]
        assert out.num_bands == 1 and out.data.shape == (1, 75, 90)
        nir, red = raster.data[1], raster.data[0]
        want = (nir - red) / (nir + red)
        valid = np.isfinite(nir) & np.isfinite(red)
        np.testing.assert_array_equal(
            out.data[0][valid], want[valid]
        )
        assert np.isnan(out.data[0][~valid]).all()

    def test_rst_mapbands_mask_where(self, raster):
        e = E.band(1).mask_where(E.band(2) < 50.0)
        out = rst_mapbands([raster], e)[0].data[0]
        b1, b2 = raster.data[0], raster.data[1]
        keep = np.isfinite(b1) & np.isfinite(b2) & (b2 < 50.0)
        np.testing.assert_array_equal(out[keep], b1[keep])
        assert np.isnan(out[~keep]).all()

    def test_rst_mapbands_cell_of_needs_resolution(self, raster):
        with pytest.raises(ValueError, match="resolution"):
            rst_mapbands([raster], E.cell_of(), index=CUSTOM)

    def test_map_join_zones_raster(self, engine, raster):
        zones, vals, valid = engine.map(
            E.ndvi(nir=2, red=1).join(), raster, tile=(32, 32)
        )
        assert zones.shape == (75, 90) and vals.shape == (75, 90)
        assert (zones[~valid] == -1).all()
        assert set(np.unique(zones)) <= {-1, 0, 1, 2}


# ---------------------------------------------------------- durable scan


class TestExprScan:
    @pytest.fixture(scope="class")
    def stream(self, index):
        return RasterStream(index, CUSTOM, RES)

    def test_fused_scan_matches_map_and_oracle(
        self, stream, engine, raster, index
    ):
        e = _pipeline()
        fused = stream.scan(r := raster, expr=e, tile=(32, 32))
        _assert_result_equal(
            fused.stats, engine.map(e, r, tile=(32, 32))
        )
        _assert_result_equal(
            fused.stats,
            E.host_expr_zonal_oracle(
                r, e, index_system=CUSTOM, resolution=RES,
                chip_index=index, tile=(32, 32),
            ),
        )

    def test_kill_resume_and_expr_hash_refusals(
        self, stream, raster, tmp_path
    ):
        e = _pipeline()
        clean = stream.scan(raster, expr=e, tile=(32, 32))
        d = str(tmp_path / "fused")
        with faults.inject(
            fail_first=99, skip_first=4, sites=("raster.zonal",),
            exc_factory=lambda s: RuntimeError("simulated device loss"),
        ):
            with pytest.raises(RuntimeError, match="device loss"):
                stream.scan(
                    raster, expr=e, tile=(32, 32), run_dir=d,
                    snapshot_every=2, retry_policy=FAST,
                )
        assert checkpoint.list_snapshots(d) == [2, 4]
        # a durable expression scan snapshots the tree hash: resuming
        # with a different tree (or none) must refuse, not fold garbage
        with pytest.raises(ValueError, match="expression mismatch"):
            stream.resume(
                d, raster, expr=E.ndvi(nir=3, red=1).zonal(),
                retry_policy=FAST,
            )
        with pytest.raises(ValueError, match="expression mismatch"):
            stream.resume(d, raster, retry_policy=FAST)
        r = stream.resume(d, raster, expr=e, retry_policy=FAST)
        _assert_result_equal(r.stats, clean.stats)
        assert r.metrics["resumed_from"] == 4

    def test_plain_snapshot_refuses_expr_resume(
        self, stream, raster, tmp_path
    ):
        d = str(tmp_path / "plain")
        with faults.inject(
            fail_first=99, skip_first=2, sites=("raster.zonal",),
            exc_factory=lambda s: RuntimeError("boom"),
        ):
            with pytest.raises(RuntimeError):
                stream.scan(
                    raster, tile=(32, 32), run_dir=d,
                    snapshot_every=2, retry_policy=FAST,
                )
        with pytest.raises(ValueError, match="expression mismatch"):
            stream.resume(
                d, raster, expr=_pipeline(), retry_policy=FAST
            )

    def test_scan_rejects_non_zonal_terminals(self, stream, raster):
        with pytest.raises(ValueError, match="zones"):
            stream.scan(
                raster, expr=E.ndvi().zonal(by="grid"), tile=(32, 32)
            )
