"""Viz fallback renderer + observability utils."""

import numpy as np

from mosaic_tpu import functions as F
from mosaic_tpu import viz
from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.utils import benchmark, get_logger, timer


def test_feature_collection_props():
    fc = viz.to_feature_collection(
        ["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))", "POINT (2 2)"],
        properties={"name": np.array(["a", "b"], dtype=object)},
    )
    assert fc["type"] == "FeatureCollection"
    assert len(fc["features"]) == 2
    assert fc["features"][0]["properties"]["name"] == "a"
    assert fc["features"][1]["geometry"]["type"] == "Point"


def test_plot_cells_html(tmp_path):
    idx = H3IndexSystem()
    cells = np.asarray(
        F.grid_longlatascellid(np.array([-0.1, -0.2]), np.array([51.5, 51.6]), 7, index=idx)
    )
    out = viz.plot_cells(cells, index=idx, values=[1.0, 2.0], path=str(tmp_path / "m.html"))
    html = (tmp_path / "m.html").read_text()
    assert "FeatureCollection" in html and "canvas" in html
    assert out.endswith("m.html")


def test_mosaic_kepler_dispatch(tmp_path):
    p = viz.mosaic_kepler(
        ["POINT (0 0)"], kind="geometry", path=str(tmp_path / "g.html")
    )
    assert p.endswith("g.html")


def test_timer_and_benchmark(caplog):
    with timer("unit") as t:
        sum(range(1000))
    assert t["seconds"] >= 0
    import jax.numpy as jnp

    stats = benchmark(lambda x: jnp.sum(x * 2), jnp.arange(1000.0), trials=3)
    assert stats["min_s"] <= stats["median_s"]
    assert get_logger().name == "mosaic_tpu"
