"""Viz fallback renderer + observability utils."""

import numpy as np

from mosaic_tpu import functions as F
from mosaic_tpu import viz
from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.utils import benchmark, get_logger, timer


def test_feature_collection_props():
    fc = viz.to_feature_collection(
        ["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))", "POINT (2 2)"],
        properties={"name": np.array(["a", "b"], dtype=object)},
    )
    assert fc["type"] == "FeatureCollection"
    assert len(fc["features"]) == 2
    assert fc["features"][0]["properties"]["name"] == "a"
    assert fc["features"][1]["geometry"]["type"] == "Point"


def test_plot_cells_html(tmp_path):
    idx = H3IndexSystem()
    cells = np.asarray(
        F.grid_longlatascellid(np.array([-0.1, -0.2]), np.array([51.5, 51.6]), 7, index=idx)
    )
    out = viz.plot_cells(cells, index=idx, values=[1.0, 2.0], path=str(tmp_path / "m.html"))
    html = (tmp_path / "m.html").read_text()
    assert "FeatureCollection" in html and "canvas" in html
    assert out.endswith("m.html")


def test_mosaic_kepler_dispatch(tmp_path):
    p = viz.mosaic_kepler(
        ["POINT (0 0)"], kind="geometry", path=str(tmp_path / "g.html")
    )
    assert p.endswith("g.html")


def test_timer_and_benchmark(caplog):
    with timer("unit") as t:
        sum(range(1000))
    assert t["seconds"] >= 0
    import jax.numpy as jnp

    stats = benchmark(lambda x: jnp.sum(x * 2), jnp.arange(1000.0), trials=3)
    assert stats["min_s"] <= stats["median_s"]
    assert get_logger().name == "mosaic_tpu"


def test_kepler_cell_magic(tmp_path, monkeypatch):
    """The registered %%mosaic_kepler magic resolves notebook variables
    and renders through the same plot paths (reference:
    `python/mosaic/utils/kepler_magic.py:18-70`)."""
    import os

    from mosaic_tpu.readers.vector import VectorTable
    from mosaic_tpu.core.geometry import wkt as W

    monkeypatch.chdir(tmp_path)
    table = VectorTable(
        geometry=W.from_wkt(
            ["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))", "POINT (2 2)"]
        ),
        columns={
            "cell": np.asarray(
                F.grid_longlatascellid(
                    np.array([-0.1, -0.2]), np.array([51.5, 51.6]), 7,
                    index=H3IndexSystem(),
                )
            )
        },
    )
    ns = {"t": table}
    out = viz._magic_render(ns, "t geometry geometry")
    assert str(out).endswith(".html") and os.path.exists(out)
    out = viz._magic_render(ns, "t cell h3 1")
    assert str(out).endswith(".html")
    # grammar + namespace errors are loud
    import pytest as _pytest

    with _pytest.raises(ValueError, match="usage"):
        viz._magic_render(ns, "t geometry")
    with _pytest.raises(ValueError, match="no variable"):
        viz._magic_render(ns, "missing geometry geometry")
    with _pytest.raises(ValueError, match="feature type"):
        viz._magic_render(ns, "t cell hexes")
    # case-insensitive kind; cell/cells aliases accepted like mosaic_kepler
    assert str(viz._magic_render(ns, "t cell CELLS 1")).endswith(".html")


def test_kepler_magic_registration(tmp_path, monkeypatch):
    """register_kepler_magic wires the cell magic into a live IPython
    shell; MosaicContext.build auto-registers it (enable_mosaic parity)."""
    pytest_ipython = __import__("pytest").importorskip("IPython")
    from IPython.core.interactiveshell import InteractiveShell

    monkeypatch.chdir(tmp_path)
    shell = InteractiveShell.instance()
    try:
        from mosaic_tpu import viz as _viz

        fn = _viz.register_kepler_magic(shell)
        assert fn is not None
        shell.user_ns["col"] = ["POINT (1 1)"]
        # args may continue into the cell body (IPython rejects an empty one)
        out = shell.run_cell_magic("mosaic_kepler", "col x", "geometry")
        assert str(out).endswith(".html")
    finally:
        InteractiveShell.clear_instance()
