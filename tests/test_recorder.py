"""Flight recorder: bounded ring, auto-dump on typed failures, the
pinned overhead budget, and concurrency over the telemetry spine.

The acceptance contract of `mosaic_tpu/obs/recorder.py`:

- the ring is ALWAYS on (installed at ``mosaic_tpu.obs`` import) and
  hard-bounded (``MOSAIC_RECORDER_N``);
- a typed failure crossing the spine (``retry_exhausted`` from
  RetryExhausted, ``watchdog_stall``, ``degraded``) freezes a snapshot
  without anyone having set up a capture first;
- the observer costs ≤ 1.15× the bare ``record()`` path (pinned
  microbenchmark, best-of-N against best-of-N);
- concurrent recorders (serve submit threads + the batcher, watchdog
  workers) never lose events or corrupt the ring: ``seq`` stays
  strictly increasing and unique, the metrics bridge counts every
  event, the ring length never exceeds its bound.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

import mosaic_tpu.obs as obs
from mosaic_tpu.obs import export, metrics, recorder
from mosaic_tpu.runtime import telemetry
from mosaic_tpu.runtime.errors import RetryExhausted, TransientDeviceError
from mosaic_tpu.runtime.retry import RetryPolicy, call_with_retry

FAST = RetryPolicy(
    max_attempts=2, base_delay_s=0.0, max_delay_s=0.0,
    timeout_s=5.0, jitter=0.0,
)


def test_process_recorder_is_installed_by_obs_import():
    assert obs.RECORDER is recorder.RECORDER
    before = len(recorder.RECORDER.events())
    telemetry.record("dispatch_cache_stats", probe="recorder-install")
    ring = recorder.RECORDER.events()
    assert len(ring) >= min(before + 1, recorder.RECORDER.maxlen)
    assert any(
        e.get("probe") == "recorder-install" for e in ring[-5:]
    )


def test_ring_is_bounded_and_keeps_newest():
    r = recorder.FlightRecorder(maxlen=16)
    for i in range(100):
        r({"event": "x", "seq": i})
    ring = r.events()
    assert len(ring) == 16
    assert [e["seq"] for e in ring] == list(range(84, 100))


def test_zero_capacity_disables_recording():
    r = recorder.FlightRecorder(maxlen=0)
    assert not r.enabled
    r({"event": "retry_exhausted", "seq": 1})
    assert r.events() == []
    assert r.auto_dumps == 0


def test_env_knob_sizes_the_ring(monkeypatch):
    monkeypatch.setenv("MOSAIC_RECORDER_N", "7")
    assert recorder.FlightRecorder().maxlen == 7
    monkeypatch.setenv("MOSAIC_RECORDER_N", "not-a-number")
    assert recorder.FlightRecorder().maxlen == recorder.DEFAULT_N


def test_dump_writes_a_readable_jsonl_trail(tmp_path):
    r = recorder.FlightRecorder(maxlen=8)
    r({"event": "span", "seq": 1, "name": "x", "seconds": 0.5})
    r({"event": "transient_retry", "seq": 2, "label": "y"})
    path = str(tmp_path / "dump.jsonl")
    snap = r.dump(path)
    assert len(snap) == 2
    rows = export.read_trail(path)
    # dumps open with the incarnation header (fleet-stitchable)
    assert rows[0]["event"] == "incarnation"
    assert [e["seq"] for e in rows[1:]] == [1, 2]


def test_auto_dump_fires_on_injected_retry_exhausted():
    """The acceptance lane: a real RetryExhausted (no capture scope set
    up beforehand) leaves a frozen snapshot on the PROCESS recorder."""
    r = recorder.RECORDER
    before = r.auto_dumps

    def always_down():
        raise TransientDeviceError("injected: device went away")

    with pytest.raises(RetryExhausted):
        call_with_retry(
            always_down, policy=FAST, label="test.injected",
            sleep=lambda s: None,
        )
    assert r.auto_dumps == before + 1
    assert r.last_dump is not None
    trigger = [
        e for e in r.last_dump if e["event"] == "retry_exhausted"
    ]
    assert trigger and trigger[-1]["label"] == "test.injected"
    # the retries leading up to the failure are IN the snapshot —
    # post-hoc diagnosis without a re-run is the whole point
    assert any(
        e["event"] == "transient_retry"
        and e.get("label") == "test.injected"
        for e in r.last_dump
    )


def test_auto_dump_fires_on_each_trigger_event():
    for ev in sorted(recorder.TRIGGER_EVENTS):
        r = recorder.FlightRecorder(maxlen=8)
        r({"event": "x", "seq": 0})
        r({"event": ev, "seq": 1})
        assert r.auto_dumps == 1, ev
        assert [e["seq"] for e in r.last_dump] == [0, 1], ev


def test_auto_dump_writes_trail_file_when_dir_set(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("MOSAIC_RECORDER_DIR", str(tmp_path))
    r = recorder.FlightRecorder(maxlen=8)
    r({"event": "watchdog_stall", "seq": 42, "site": "stream.scan_step"})
    assert r.last_dump_path is not None
    rows = export.read_trail(r.last_dump_path)
    assert rows[-1]["event"] == "watchdog_stall"
    # the dump announces itself on the spine (recorder_dump) without
    # re-triggering a dump of the dump
    assert r.auto_dumps == 1


def test_auto_dump_file_writes_are_debounced(tmp_path, monkeypatch):
    monkeypatch.setenv("MOSAIC_RECORDER_DIR", str(tmp_path))
    r = recorder.FlightRecorder(maxlen=8, min_dump_interval_s=60.0)
    r({"event": "degraded", "seq": 1})
    r({"event": "degraded", "seq": 2})
    # both triggers snapshot in memory; only the first hits the disk
    assert r.auto_dumps == 2
    assert len(list(tmp_path.iterdir())) == 1


def test_slo_violation_triggers_dump_named_after_the_slo(
    tmp_path, monkeypatch
):
    """An SLO burn-rate breach is a first-class dump trigger, and the
    dump file names the violated SLO and its window — a directory of
    dumps reads as an incident log without opening any file."""
    import os

    monkeypatch.setenv("MOSAIC_RECORDER_DIR", str(tmp_path))
    r = recorder.FlightRecorder(maxlen=8)
    r({"event": "serve_shed", "seq": 6, "reason": "deadline"})
    r({
        "event": "slo_violation", "seq": 7, "slo": "serve.shed",
        "window_s": 60.0, "burn_rate": 10.0,
    })
    assert r.auto_dumps == 1
    assert r.last_dump_path is not None
    name = os.path.basename(r.last_dump_path)
    assert "slo_violation" in name
    assert "serve.shed" in name and "w60s" in name
    # the evidence leading up to the breach is IN the snapshot
    assert any(
        e["event"] == "serve_shed" for e in r.last_dump
    )


def test_one_dump_per_breach_episode(tmp_path, monkeypatch):
    """A breached SLO that stays breached emits ONE violation — so one
    dump — until the burn clears below the hysteresis floor; the flap
    back up is a NEW episode and a new dump."""
    from mosaic_tpu.obs import slo as obs_slo

    monkeypatch.setenv("MOSAIC_RECORDER_DIR", str(tmp_path))
    r = recorder.FlightRecorder(maxlen=64)
    telemetry.add_observer(r.observer)
    try:
        m = obs_slo.SLOMonitor(
            short_window_s=10.0, long_window_s=10.0,
        )
        spec = m.register(obs_slo.SLOSpec(
            name="unit.shed", kind="ratio", objective=0.95,
            min_events=1,
        ))
        m.wire_good(spec, "unit_good")
        m.wire_bad(spec, "unit_bad")
        t0 = 1000.0
        for i in range(10):
            m._ingest(m._handlers["unit_bad"], {"event": "unit_bad"}, t0)
        m.evaluate(t0)          # breach: one violation, one dump
        m.evaluate(t0 + 0.1)    # still breached: no new violation
        m.evaluate(t0 + 0.2)
        assert r.auto_dumps == 1
        # burn clears (window slides past the bad burst) -> re-arm
        m.evaluate(t0 + 50.0)
        for i in range(10):
            m._ingest(
                m._handlers["unit_bad"], {"event": "unit_bad"},
                t0 + 100.0,
            )
        m.evaluate(t0 + 100.0)  # new episode, second dump
        assert r.auto_dumps == 2
    finally:
        telemetry.remove_observer(r.observer)


def test_recorder_dump_event_rides_the_spine():
    r = recorder.FlightRecorder(maxlen=8)
    with telemetry.capture() as events:
        r({"event": "degraded", "seq": 1})
    dumps = [e for e in events if e["event"] == "recorder_dump"]
    assert len(dumps) == 1
    assert dumps[0]["trigger"] == "degraded"
    assert dumps[0]["n_events"] == 1


def test_micro_benchmark_recorder_overhead_within_budget():
    """Installed ``record()`` ≤ 1.15× the bare path (the pinned
    budget). Measured as INTERLEAVED best-of-pairs — alternating
    bare/installed samples so load drift on a shared box hits both
    sides equally instead of biasing whichever phase ran second; the
    recorder's per-event cost is one function call, one deque append,
    one dict getitem, one frozenset test."""
    n = 20_000

    def once() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            telemetry.record("dispatch_cache_stats", hits=1)
        return time.perf_counter() - t0

    def measure() -> tuple[float, float]:
        bare = installed = float("inf")
        try:
            for _ in range(10):
                recorder.uninstall()
                bare = min(bare, once())
                recorder.install()
                installed = min(installed, once())
        finally:
            recorder.install()
        return bare, installed

    # one full re-measure before failing: a CI neighbor's burst can
    # still skew a single round; a REAL >15% regression fails both
    bare, installed = measure()
    if installed / bare > 1.15:
        b2, i2 = measure()
        if i2 / b2 < installed / bare:
            bare, installed = b2, i2
    ratio = installed / bare
    assert ratio <= 1.15, (
        f"recorder overhead {ratio:.3f}x exceeds the 1.15x budget "
        f"(bare {bare:.4f}s, installed {installed:.4f}s)"
    )


def test_concurrent_record_no_lost_events_and_monotonic_seq():
    """Serve submit threads + the batcher record concurrently: every
    event reaches the observers exactly once, ``seq`` is unique and
    strictly increasing, and the bounded ring survives the load."""
    n_threads, per_thread = 4, 2000
    r = recorder.FlightRecorder(maxlen=512)
    got: list = []
    observers = [r, got.append]
    for o in observers:
        telemetry.add_observer(o)
    label = f"conc-{id(got):x}"
    try:
        barrier = threading.Barrier(n_threads)

        def worker(tid: int) -> None:
            barrier.wait()
            for i in range(per_thread):
                telemetry.record(
                    "transient_retry", label=label, attempt=i,
                    worker=tid,
                )

        threads = [
            threading.Thread(target=worker, args=(t,))  # lint: thread-context-adoption-ok (probes RAW concurrent record() via process-wide observers; adopting sinks would defeat the test)
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        for o in observers:
            telemetry.remove_observer(o)

    mine = [e for e in got if e.get("label") == label]
    assert len(mine) == n_threads * per_thread
    seqs = [e["seq"] for e in mine]
    assert len(set(seqs)) == len(seqs), "seq collision under threads"
    per_worker = {}
    for e in mine:
        per_worker.setdefault(e["worker"], []).append(e["seq"])
    for w, ws in per_worker.items():
        assert ws == sorted(ws), f"worker {w} saw reordered seqs"
    assert len(r.events()) == 512
    # the metrics bridge (installed at obs import) counted every one
    snap = metrics.snapshot()["runtime.transient_retries"]
    total = sum(
        s["value"] for s in snap["series"]
        if s["labels"].get("label") == label
    )
    assert total == n_threads * per_thread


def test_dump_is_json_serializable_with_hostile_payloads(tmp_path):
    r = recorder.FlightRecorder(maxlen=4)
    r({"event": "x", "seq": 1, "payload": object()})
    path = str(tmp_path / "h.jsonl")
    r.dump(path)
    with open(path) as f:
        rows = [json.loads(line) for line in f]
    row = rows[-1]  # rows[0] is the incarnation header
    assert row["seq"] == 1 and "object" in row["payload"]
