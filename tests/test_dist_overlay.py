"""Mesh-sharded overlay predicate vs the single-device and oracle paths.

Runs on the virtual 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8) — the same evidence standard as
tests/test_dist_join.py for the point join.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.functions import geometry as F
from mosaic_tpu.functions.geometry import _pair_pack
from mosaic_tpu.parallel.dist_join import make_mesh
from mosaic_tpu.parallel.dist_overlay import distributed_pair_intersects


def _pairs(n, seed):
    rng = np.random.default_rng(seed)
    a, b = [], []
    for _ in range(n):
        x, y = rng.uniform(0, 10, 2)
        s1, s2 = rng.uniform(0.5, 2.0, 2)
        dx, dy = rng.uniform(-2.0, 2.0, 2)
        a.append(
            f"POLYGON (({x} {y}, {x + s1} {y}, {x + s1} {y + s1},"
            f" {x} {y + s1}, {x} {y}))"
        )
        b.append(
            f"POLYGON (({x + dx} {y + dy}, {x + dx + s2} {y + dy},"
            f" {x + dx + s2} {y + dy + s2}, {x + dx} {y + dy + s2},"
            f" {x + dx} {y + dy}))"
        )
    return wkt.from_wkt(a), wkt.from_wkt(b)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_dist_pair_intersects_matches_single_device(devices, n_devices):
    a, b = _pairs(37, seed=5)  # 37: deliberately not a mesh multiple
    mesh = make_mesh(n_devices)
    da, db = _pair_pack(a, b)
    got = distributed_pair_intersects(mesh, da, db)
    want = np.asarray(F.st_intersects(a, b))
    np.testing.assert_array_equal(got, want)
    oracle = np.asarray(F.st_intersects(a, b, backend="oracle"))
    np.testing.assert_array_equal(got, oracle)
    assert got.any() and not got.all()  # the layout mixes hits and misses


def test_pad_preserves_shift_invariant(devices):
    # padding the pair axis to a mesh multiple must not touch the shared
    # (2,) shift leaf (advisor r3: shape-based padding grew it to (2+pad,)
    # whenever the pair count was exactly 2)
    from mosaic_tpu.parallel.dist_overlay import _pad_pair_axis

    a, b = _pairs(2, seed=7)  # n == 2 collides with shift's length
    da, _ = _pair_pack(a, b)
    padded = _pad_pair_axis(da, 6)
    assert padded.shift.shape == (2,)
    assert padded.verts.shape[0] == 8
    assert padded.geom_type.shape[0] == 8
    got = distributed_pair_intersects(make_mesh(8), *_pair_pack(a, b))
    np.testing.assert_array_equal(got, np.asarray(F.st_intersects(a, b)))
