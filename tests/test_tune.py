"""Self-tuning workload optimizer (`mosaic_tpu/tune/`): the contracts.

1. **Knob precedence** — explicit arg > env knob > TuningProfile >
   built-in default, per knob at the resolver and per frontend at the
   entry point: every profile-consumed knob of all five ``profile=``
   frontends (`pip_join`, `StreamJoin`, `ServeEngine`, `ZonalEngine`,
   `RasterStream`) is asserted through the ``tune_resolve`` telemetry
   event its host entry records.
2. **Profile store refusal matrix** — corrupt versions skip
   newest-valid-wins with telemetry; all-corrupt/empty raises the typed
   `ProfileStoreCorrupt`; a tessellation-fingerprint mismatch on the
   newest valid version is a typed REFUSAL (never a silent fallback to
   an older matching version).
3. **Hot swap** — `ServeEngine.hot_swap` to a different-resolution
   recommended index introduces ZERO cold compiles and keeps answers
   equal to the device-path reference join.
4. Profiler statistics are sane and round-trip; recommendations are
   measurement-backed with machine-checkable rationales.
5. Satellites: `SampleStrategy` typed empty-input errors;
   `overlay.candidate_pairs` candidate-statistics telemetry.
"""

import json
import os

import numpy as np
import pytest

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.raster import Raster
from mosaic_tpu.raster.zonal import ZonalEngine
from mosaic_tpu.runtime import telemetry
from mosaic_tpu.serve import BucketLadder, ServeEngine
from mosaic_tpu.sql.analyzer import SampleStrategy
from mosaic_tpu.sql.join import build_chip_index, pip_join
from mosaic_tpu.sql.overlay import candidate_pairs
from mosaic_tpu.sql.raster_stream import RasterStream
from mosaic_tpu.sql.stream import StreamJoin, ring_from_host
from mosaic_tpu.tune import (
    KNOBS,
    ProfileFingerprintMismatch,
    ProfileStore,
    ProfileStoreCorrupt,
    TuningProfile,
    WorkloadProfile,
    index_fingerprint,
    profile_overlay,
    profile_points,
    profile_polygons,
    profile_raster,
    recommend,
    resolve_knob,
)

CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3
ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), "
    "(5 5, 5 8, 8 8, 8 5, 5 5))",
    "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
    "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
]
BBOX = (-25.0, -25.0, 35.0, 20.0)

ALL_TUNE_ENV = (
    "MOSAIC_TUNE_PROBE", "MOSAIC_TUNE_WRITEBACK", "MOSAIC_TUNE_LOOKUP",
    "MOSAIC_TUNE_BATCH", "MOSAIC_TUNE_BUCKET_MIN", "MOSAIC_TUNE_BUCKET_MAX",
    "MOSAIC_STREAM_WINDOW", "MOSAIC_STREAM_PIPELINE",
    "MOSAIC_RASTER_TILE", "MOSAIC_RASTER_LANE",
)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for name in ALL_TUNE_ENV:
        monkeypatch.delenv(name, raising=False)
    yield


@pytest.fixture(scope="module")
def zones():
    return wkt.from_wkt(ZONES)


@pytest.fixture(scope="module")
def index(zones):
    return build_chip_index(
        tessellate(zones, CUSTOM, RES, keep_core_geoms=False)
    )


@pytest.fixture(scope="module")
def points():
    rng = np.random.default_rng(11)
    return rng.uniform(BBOX[:2], BBOX[2:], (2048, 2))


def _mk_raster(h=64, w=64, nodata=-9.0, seed=5):
    rng = np.random.default_rng(seed)
    data = rng.uniform(0, 100, (1, h, w))
    data[0][rng.random((h, w)) < 0.5] = nodata
    return Raster(
        data=data, gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0),
        srid=0, nodata=nodata,
    )


def resolve_events(events, entry):
    return [
        e for e in events
        if e.get("event") == "tune_resolve" and e.get("entry") == entry
    ]


# --------------------------------------------------------------- resolver


class TestResolveKnob:
    def test_explicit_beats_everything(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "adaptive")
        prof = TuningProfile(probe="mxu")
        assert resolve_knob("probe", "scatter", prof, "x") == (
            "scatter", "explicit"
        )

    def test_env_beats_profile(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "adaptive")
        prof = TuningProfile(probe="scatter")
        assert resolve_knob("probe", None, prof, "x") == ("adaptive", "env")

    def test_profile_beats_default(self):
        prof = TuningProfile(probe="adaptive")
        assert resolve_knob("probe", None, prof, "scatter") == (
            "adaptive", "profile"
        )

    def test_default_when_nothing_set(self):
        assert resolve_knob("probe", None, None, "scatter") == (
            "scatter", "default"
        )

    def test_empty_env_is_unset(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "")
        assert resolve_knob("probe", None, None, "d") == ("d", "default")

    def test_env_parsers(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_BATCH", "4096")
        assert resolve_knob("batch_size", None, None, None) == (4096, "env")
        monkeypatch.setenv("MOSAIC_RASTER_TILE", "64x128")
        assert resolve_knob("raster_tile", None, None, None) == (
            (64, 128), "env"
        )
        # "0" must WIN with value False (force-off), not fall through
        monkeypatch.setenv("MOSAIC_STREAM_PIPELINE", "0")
        prof = TuningProfile(stream_pipeline=True)
        assert resolve_knob("stream_pipeline", None, prof, None) == (
            False, "env"
        )

    def test_malformed_env_raises(self, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_BATCH", "many")
        with pytest.raises(ValueError, match="malformed env value"):
            resolve_knob("batch_size", None, None, None)

    def test_resolution_has_no_env_layer(self, monkeypatch):
        # resolution changes the tessellation artifact, not the schedule:
        # no env spelling exists, so even a lookalike var is inert
        monkeypatch.setenv("MOSAIC_TUNE_RESOLUTION", "9")
        prof = TuningProfile(resolution=4)
        assert resolve_knob("resolution", None, prof, 3) == (4, "profile")

    def test_unknown_knob_rejected(self):
        with pytest.raises(KeyError, match="unknown tune knob"):
            resolve_knob("warp_factor", None, None, None)

    def test_every_knob_resolves_through_all_layers(self, monkeypatch):
        """The full matrix at the resolver: each knob accepts each layer."""
        profile_values = {
            "resolution": 5, "probe": "adaptive", "writeback": "sort",
            "lookup": "gather", "batch_size": 2048, "bucket_min": 128,
            "bucket_max": 1024, "stream_window": 6, "stream_pipeline": True,
            "raster_tile": (64, 64), "zonal_lane": "tiled",
            "knn_lane": "voronoi",
        }
        env_values = {
            "probe": ("MOSAIC_TUNE_PROBE", "scatter", "scatter"),
            "writeback": ("MOSAIC_TUNE_WRITEBACK", "scatter", "scatter"),
            "lookup": ("MOSAIC_TUNE_LOOKUP", "mxu", "mxu"),
            "batch_size": ("MOSAIC_TUNE_BATCH", "512", 512),
            "bucket_min": ("MOSAIC_TUNE_BUCKET_MIN", "64", 64),
            "bucket_max": ("MOSAIC_TUNE_BUCKET_MAX", "256", 256),
            "stream_window": ("MOSAIC_STREAM_WINDOW", "2", 2),
            "stream_pipeline": ("MOSAIC_STREAM_PIPELINE", "1", True),
            "raster_tile": ("MOSAIC_RASTER_TILE", "32x32", (32, 32)),
            "zonal_lane": ("MOSAIC_RASTER_LANE", "fold", "fold"),
            "knn_lane": ("MOSAIC_TUNE_KNN_LANE", "ring", "ring"),
        }
        assert set(KNOBS) == set(profile_values)
        prof = TuningProfile(**profile_values)
        for knob in KNOBS:
            # profile layer
            assert resolve_knob(knob, None, prof, "dflt") == (
                profile_values[knob], "profile"
            ), knob
            # default layer
            assert resolve_knob(knob, None, None, "dflt") == (
                "dflt", "default"
            ), knob
            # env layer (where one exists) beats profile
            if knob in env_values:
                var, raw, parsed = env_values[knob]
                monkeypatch.setenv(var, raw)
                assert resolve_knob(knob, None, prof, "dflt") == (
                    parsed, "env"
                ), knob
                monkeypatch.delenv(var)
            # explicit beats all
            assert resolve_knob(knob, "xx", prof, "dflt") == (
                "xx", "explicit"
            ), knob


# ------------------------------------------------- frontend entry points


class TestPipJoinPrecedence:
    PROFILE = TuningProfile(
        resolution=RES, probe="adaptive", writeback="scatter",
        lookup="gather", batch_size=1024,
    )

    def run(self, points, index, **kw):
        with telemetry.capture() as events:
            out = pip_join(points, None, CUSTOM, kw.pop("resolution", None),
                           chip_index=index, **kw)
        (ev,) = resolve_events(events, "pip_join")
        return np.asarray(out), ev

    def test_profile_layer(self, points, index):
        out, ev = self.run(points, index, profile=self.PROFILE)
        for knob in ("resolution", "probe", "writeback", "lookup",
                     "batch_size"):
            assert ev[f"{knob}_source"] == "profile", (knob, ev)
        assert ev["probe"] == "adaptive" and ev["batch_size"] == 1024
        base, _ = self.run(points, index, resolution=RES)
        np.testing.assert_array_equal(out, base)

    def test_env_layer_beats_profile(self, points, index, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "scatter")
        monkeypatch.setenv("MOSAIC_TUNE_BATCH", "512")
        _, ev = self.run(points, index, profile=self.PROFILE)
        assert ev["probe_source"] == "env" and ev["probe"] == "scatter"
        assert ev["batch_size_source"] == "env" and ev["batch_size"] == 512
        # resolution has no env layer: still the profile's
        assert ev["resolution_source"] == "profile"

    def test_explicit_beats_env_and_profile(self, points, index, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "adaptive")
        _, ev = self.run(
            points, index, resolution=RES, probe="scatter",
            writeback="scatter", lookup="gather", batch_size=256,
            profile=self.PROFILE,
        )
        for knob in ("resolution", "probe", "writeback", "lookup",
                     "batch_size"):
            assert ev[f"{knob}_source"] == "explicit", (knob, ev)

    def test_no_resolution_anywhere_is_typed(self, points, index):
        with pytest.raises(ValueError, match="resolution"):
            pip_join(points, None, CUSTOM, None, chip_index=index)


class TestStreamJoinPrecedence:
    def test_constructor_knobs(self, index, monkeypatch):
        prof = TuningProfile(probe="adaptive", lookup="gather")
        with telemetry.capture() as events:
            StreamJoin(index, CUSTOM, RES, profile=prof)
        (ev,) = resolve_events(events, "stream_join")
        assert ev["probe_source"] == "profile"
        assert ev["lookup_source"] == "profile"

        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "scatter")
        with telemetry.capture() as events:
            StreamJoin(index, CUSTOM, RES, profile=prof)
        (ev,) = resolve_events(events, "stream_join")
        assert ev["probe_source"] == "env" and ev["probe"] == "scatter"

        with telemetry.capture() as events:
            StreamJoin(index, CUSTOM, RES, probe="scatter", profile=prof)
        (ev,) = resolve_events(events, "stream_join")
        assert ev["probe_source"] == "explicit"

    def test_durable_run_knobs(self, index, tmp_path, monkeypatch):
        """stream_window / stream_pipeline resolve per durable run."""
        rng = np.random.default_rng(3)
        ring = ring_from_host(
            [rng.uniform(BBOX[:2], BBOX[2:], (512, 2)) for _ in range(2)]
        )
        prof = TuningProfile(stream_window=2, stream_pipeline=True)
        sj = StreamJoin(index, CUSTOM, RES, profile=prof)

        with telemetry.capture() as events:
            sj.run_durable(ring, 2, run_dir=str(tmp_path / "a"))
        (ev,) = resolve_events(events, "stream_join.run_durable")
        assert ev["stream_pipeline_source"] == "profile"
        assert ev["stream_window_source"] == "profile"
        assert ev["stream_pipeline"] is True and ev["stream_window"] == 2

        monkeypatch.setenv("MOSAIC_STREAM_PIPELINE", "0")
        monkeypatch.setenv("MOSAIC_STREAM_WINDOW", "3")
        with telemetry.capture() as events:
            sj.run_durable(ring, 2, run_dir=str(tmp_path / "b"))
        (ev,) = resolve_events(events, "stream_join.run_durable")
        assert ev["stream_pipeline_source"] == "env"
        assert ev["stream_pipeline"] is False  # "0" forces OFF over profile
        assert ev["stream_window_source"] == "env"
        assert ev["stream_window"] == 3

        with telemetry.capture() as events:
            sj.run_durable(
                ring, 2, run_dir=str(tmp_path / "c"),
                pipeline=True, window=4,
            )
        (ev,) = resolve_events(events, "stream_join.run_durable")
        assert ev["stream_pipeline_source"] == "explicit"
        assert ev["stream_window_source"] == "explicit"
        assert ev["stream_window"] == 4


class TestServeEnginePrecedence:
    def test_profile_builds_ladder(self, index):
        prof = TuningProfile(
            probe="adaptive", writeback="scatter", lookup="gather",
            bucket_min=64, bucket_max=256,
        )
        with telemetry.capture() as events:
            with ServeEngine(index, CUSTOM, RES, profile=prof) as eng:
                assert eng.ladder.buckets == (64, 128, 256)
        (ev,) = resolve_events(events, "serve_engine")
        for knob in ("probe", "writeback", "lookup", "bucket_min",
                     "bucket_max"):
            assert ev[f"{knob}_source"] == "profile", (knob, ev)

    def test_env_beats_profile(self, index, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_BUCKET_MIN", "128")
        monkeypatch.setenv("MOSAIC_TUNE_BUCKET_MAX", "512")
        monkeypatch.setenv("MOSAIC_TUNE_WRITEBACK", "scatter")
        prof = TuningProfile(bucket_min=64, bucket_max=256, writeback="sort")
        with telemetry.capture() as events:
            with ServeEngine(index, CUSTOM, RES, profile=prof) as eng:
                assert eng.ladder.buckets == (128, 256, 512)
        (ev,) = resolve_events(events, "serve_engine")
        assert ev["bucket_min_source"] == "env"
        assert ev["bucket_max_source"] == "env"
        assert ev["writeback_source"] == "env"

    def test_explicit_ladder_bypasses_bucket_knobs(self, index, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_BUCKET_MIN", "128")
        prof = TuningProfile(bucket_min=64, bucket_max=256)
        with ServeEngine(
            index, CUSTOM, RES, ladder=BucketLadder(32, 64), profile=prof
        ) as eng:
            assert eng.ladder.buckets == (32, 64)

    def test_explicit_probe_beats_all(self, index, monkeypatch):
        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "adaptive")
        prof = TuningProfile(probe="adaptive")
        with telemetry.capture() as events:
            with ServeEngine(
                index, CUSTOM, RES, probe="scatter", profile=prof
            ):
                pass
        (ev,) = resolve_events(events, "serve_engine")
        assert ev["probe_source"] == "explicit" and ev["probe"] == "scatter"


class TestZonalEnginePrecedence:
    def test_all_layers(self, index, monkeypatch):
        prof = TuningProfile(probe="adaptive", lookup="gather",
                             zonal_lane="tiled")
        with telemetry.capture() as events:
            eng = ZonalEngine(CUSTOM, RES, chip_index=index, profile=prof)
        (ev,) = resolve_events(events, "zonal_engine")
        for knob in ("probe", "lookup", "zonal_lane"):
            assert ev[f"{knob}_source"] == "profile", (knob, ev)
        assert eng.lane == "tiled"

        monkeypatch.setenv("MOSAIC_RASTER_LANE", "fold")
        monkeypatch.setenv("MOSAIC_TUNE_LOOKUP", "gather")
        with telemetry.capture() as events:
            eng = ZonalEngine(CUSTOM, RES, chip_index=index, profile=prof)
        (ev,) = resolve_events(events, "zonal_engine")
        assert ev["zonal_lane_source"] == "env" and eng.lane == "fold"
        assert ev["lookup_source"] == "env"

        with telemetry.capture() as events:
            eng = ZonalEngine(
                CUSTOM, RES, chip_index=index, lane="tiled",
                probe="scatter", profile=prof,
            )
        (ev,) = resolve_events(events, "zonal_engine")
        assert ev["zonal_lane_source"] == "explicit" and eng.lane == "tiled"
        assert ev["probe_source"] == "explicit"


class TestRasterStreamPrecedence:
    def test_constructor_knobs(self, index, monkeypatch):
        prof = TuningProfile(probe="scatter", lookup="gather")
        with telemetry.capture() as events:
            RasterStream(index, CUSTOM, RES, profile=prof)
        (ev,) = resolve_events(events, "raster_stream")
        assert ev["probe_source"] == "profile"
        assert ev["lookup_source"] == "profile"

        monkeypatch.setenv("MOSAIC_TUNE_PROBE", "adaptive")
        with telemetry.capture() as events:
            RasterStream(index, CUSTOM, RES, probe="scatter", profile=prof)
        (ev,) = resolve_events(events, "raster_stream")
        assert ev["probe_source"] == "explicit"

    def test_scan_knobs(self, index, monkeypatch):
        raster = _mk_raster()
        prof = TuningProfile(raster_tile=(32, 32), stream_window=2)
        rs = RasterStream(index, CUSTOM, RES, profile=prof)

        with telemetry.capture() as events:
            out_prof = rs.scan(raster)
        (ev,) = resolve_events(events, "raster_stream.scan")
        assert ev["raster_tile_source"] == "profile"
        assert ev["stream_window_source"] == "profile"

        monkeypatch.setenv("MOSAIC_RASTER_TILE", "16x16")
        with telemetry.capture() as events:
            rs.scan(raster)
        (ev,) = resolve_events(events, "raster_stream.scan")
        assert ev["raster_tile_source"] == "env"

        with telemetry.capture() as events:
            out_expl = rs.scan(raster, tile=(32, 32))
        (ev,) = resolve_events(events, "raster_stream.scan")
        assert ev["raster_tile_source"] == "explicit"
        # the tile shape is a schedule knob: answers are tile-invariant
        np.testing.assert_array_equal(
            np.asarray(out_prof.stats.keys), np.asarray(out_expl.stats.keys)
        )


# ---------------------------------------------------------- profile store


class TestProfileStore:
    PROF = TuningProfile(resolution=5, probe="adaptive", batch_size=2048)

    def test_roundtrip(self, tmp_path):
        store = ProfileStore(str(tmp_path))
        store.save(self.PROF, fingerprint="abc123")
        store.save(TuningProfile(resolution=6), fingerprint="abc123")
        assert store.versions() == [1, 2]
        prof, payload = store.load_latest()
        assert prof.resolution == 6
        assert payload["profile_version"] == 2
        assert payload["fingerprint"] == "abc123"

    def test_empty_store_is_typed(self, tmp_path):
        with pytest.raises(ProfileStoreCorrupt, match="no tuning profile"):
            ProfileStore(str(tmp_path / "nope")).load_latest()

    def test_corrupt_newest_skips_to_older_valid(self, tmp_path):
        store = ProfileStore(str(tmp_path))
        store.save(self.PROF)
        p2 = store.save(TuningProfile(resolution=9))
        with open(p2, "w") as f:
            f.write("{ not json")
        with telemetry.capture() as events:
            prof, payload = store.load_latest()
        assert prof.resolution == 5 and payload["profile_version"] == 1
        skipped = [
            e for e in events
            if e.get("event") == "tune_profile_corrupt_skipped"
        ]
        assert len(skipped) == 1 and skipped[0]["profile_version"] == 2

    def test_checksum_tamper_is_corrupt(self, tmp_path):
        store = ProfileStore(str(tmp_path))
        path = store.save(self.PROF)
        payload = json.loads(open(path).read())
        payload["profile"]["batch_size"] = 4  # tamper without re-hashing
        with open(path, "w") as f:
            json.dump(payload, f)
        with pytest.raises(ProfileStoreCorrupt, match="failed validation"):
            store.load_latest()

    def test_unknown_format_version_is_corrupt(self, tmp_path):
        store = ProfileStore(str(tmp_path))
        path = store.save(self.PROF)
        payload = json.loads(open(path).read())
        payload["version"] = 99
        with open(path, "w") as f:
            json.dump(payload, f)
        with pytest.raises(ProfileStoreCorrupt):
            store.load_latest()

    def test_fingerprint_mismatch_is_refusal_not_fallback(self, tmp_path):
        """An older version DOES match the expected fingerprint — the
        store must still refuse: versions are one index's history, not a
        candidate pool."""
        store = ProfileStore(str(tmp_path))
        store.save(self.PROF, fingerprint="good")
        store.save(TuningProfile(resolution=9), fingerprint="stale")
        with pytest.raises(
            ProfileFingerprintMismatch, match="re-profile"
        ):
            store.load_latest(expect_fingerprint="good")

    def test_fingerprint_match_loads(self, tmp_path, index):
        store = ProfileStore(str(tmp_path))
        fp = index_fingerprint(index)
        store.save(self.PROF, fingerprint=fp)
        prof, payload = store.load_latest(expect_fingerprint=fp)
        assert prof.resolution == 5 and payload["fingerprint"] == fp

    def test_orphan_tmp_never_shadows(self, tmp_path):
        store = ProfileStore(str(tmp_path))
        store.save(self.PROF)
        # a kill mid-write leaves only the temp name behind
        open(os.path.join(str(tmp_path), "profile-v0002.json.tmp"),
             "w").close()
        assert store.versions() == [1]
        prof, _ = store.load_latest()
        assert prof.resolution == 5


# -------------------------------------------------------------- hot swap


class TestHotSwap:
    def test_swap_changes_resolution_without_cold_compiles(
        self, zones, index, points
    ):
        fine = build_chip_index(
            tessellate(zones, CUSTOM, RES + 1, keep_core_geoms=False)
        )
        prof = TuningProfile(
            resolution=RES + 1, probe="scatter",
            bucket_min=64, bucket_max=512,
        )
        q = points[:400]
        with ServeEngine(
            index, CUSTOM, RES, ladder=BucketLadder(64, 512),
            max_wait_s=0.001,
        ) as eng:
            eng.warmup()
            eng.join(q, timeout=30.0)  # traffic on the old core
            with telemetry.capture() as events:
                stats = eng.hot_swap(fine, profile=prof)
            assert stats["buckets"] == len(eng.ladder.buckets)
            assert eng.resolution == RES + 1
            assert [
                e for e in events if e.get("event") == "serve_swap"
            ], "hot_swap must record a serve_swap event"
            post = np.asarray(eng.join(q, timeout=30.0))
            assert eng.metrics()["cold_compiles"] == 0
        want = np.asarray(pip_join(
            q, None, CUSTOM, RES + 1, chip_index=fine, recheck=False,
            probe="scatter",
        ))
        np.testing.assert_array_equal(post.astype(np.int64),
                                      want.astype(np.int64))

    def test_profileless_swap_keeps_tuning(self, index):
        with ServeEngine(
            index, CUSTOM, RES, ladder=BucketLadder(64, 256),
            probe="scatter",
        ) as eng:
            eng.warmup()
            eng.hot_swap(index)
            assert eng.resolution == RES
            assert eng.probe == "scatter"
            assert eng.ladder.buckets == (64, 128, 256)
            assert eng.metrics()["cold_compiles"] == 0


# ------------------------------------------------- profiler + recommend


class TestProfiler:
    def test_points_profile_sane(self, index, points):
        with telemetry.capture() as events:
            prof = profile_points(points, index, CUSTOM, RES, sample=512)
        assert prof.kind == "points" and prof.n_sampled == 512
        assert 0.0 < prof.match_rate <= 1.0
        shares = prof.class_shares
        assert abs(sum(shares.values()) - 1.0) < 1e-9
        assert prof.chip_density["p50"] >= 1.0
        assert prof.band_fraction is not None
        assert 0.0 <= prof.band_fraction <= 1.0
        assert [e for e in events if e.get("event") == "tune_profile"]
        back = WorkloadProfile.from_dict(prof.as_dict())
        assert back == prof

    def test_polygons_profile_sane(self, zones):
        prof = profile_polygons(zones, CUSTOM)
        assert prof.kind == "polygons"
        assert isinstance(prof.optimal_resolution, int)
        assert prof.cells_per_geom["mean"] > 0

    def test_raster_profile_sane(self):
        prof = profile_raster(_mk_raster(), tile=(32, 32))
        assert prof.kind == "raster"
        assert 0.0 <= prof.tile_occupancy <= 1.0
        assert 0.4 < prof.nodata_fraction < 0.6  # 50% speckle by seed


class TestRecommend:
    def test_rationale_is_machine_checkable(self, zones, index, points):
        poly = recommend(profile_polygons(zones, CUSTOM), priors={})
        pts = recommend(
            profile_points(points, index, CUSTOM, RES), priors={}
        )
        merged = TuningProfile.merged(poly, pts)
        assert merged.resolution == poly.resolution
        assert merged.probe == pts.probe
        assert merged.rationale and all(
            {"knob", "value", "rule", "evidence"} <= set(r)
            for r in merged.rationale
        )
        # every recommended knob has exactly its rationale entries
        recommended = {
            k for k, v in merged.as_dict().items()
            if k not in ("rationale", "source") and v is not None
        }
        assert {r["knob"] for r in merged.rationale} == recommended

    def test_dense_share_routes_adaptive(self):
        prof = WorkloadProfile(
            kind="points", n_sampled=4096,
            class_shares={"heavy": 0.3, "convex": 0.1, "light": 0.6},
        )
        rec = recommend(prof, priors={})
        assert rec.probe == "adaptive"
        (rule,) = [r for r in rec.rationale if r["knob"] == "probe"]
        assert rule["rule"] == "dense-share-router"

    def test_light_share_routes_scatter(self):
        prof = WorkloadProfile(
            kind="points", n_sampled=4096,
            class_shares={"heavy": 0.05, "convex": 0.05, "light": 0.9},
        )
        assert recommend(prof, priors={}).probe == "scatter"

    def test_band_fraction_pins_fold_lane(self):
        prof = WorkloadProfile(
            kind="points", n_sampled=64, band_fraction=0.2
        )
        assert recommend(prof, priors={}).zonal_lane == "fold"

    def test_sparse_raster_shrinks_tiles(self):
        sparse = WorkloadProfile(
            kind="raster", n_sampled=9, tile_occupancy=0.2
        )
        dense = WorkloadProfile(
            kind="raster", n_sampled=9, tile_occupancy=0.9
        )
        assert recommend(sparse, priors={}).raster_tile == (128, 128)
        assert recommend(dense, priors={}).raster_tile == (256, 256)

    def test_stream_prior_sets_window(self):
        priors = {"artifacts": {"STREAM_CPU_r99.json": {
            "detail": {"pipeline": {"window": 6, "speedup_vs_sync": 1.2}}
        }}}
        rec = recommend(
            WorkloadProfile(kind="points", n_sampled=0), priors=priors
        )
        assert rec.stream_window == 6 and rec.stream_pipeline is True

    def test_stream_prior_can_disable_pipeline(self):
        priors = {"artifacts": {"STREAM_CPU_r99.json": {
            "detail": {"pipeline": {"window": 4, "speedup_vs_sync": 0.8}}
        }}}
        rec = recommend(
            WorkloadProfile(kind="points", n_sampled=0), priors=priors
        )
        assert rec.stream_pipeline is False


# ------------------------------------------------------------ satellites


class TestSampleStrategyErrors:
    def test_zero_rows_typed(self):
        rng = np.random.default_rng(0)
        with pytest.raises(ValueError, match="empty geometry column"):
            SampleStrategy(fraction=1.0).apply(0, rng)
        with pytest.raises(ValueError, match="empty geometry column"):
            SampleStrategy(fraction=1.0).apply(-3, rng)

    def test_zero_fraction_typed(self):
        with pytest.raises(ValueError, match="fraction"):
            SampleStrategy(fraction=0.0)

    def test_overrange_fraction_typed(self):
        with pytest.raises(ValueError, match="fraction"):
            SampleStrategy(fraction=1.5)

    def test_zero_limit_typed(self):
        with pytest.raises(ValueError, match="limit"):
            SampleStrategy(fraction=1.0, limit=0)


class TestOverlayCandidateTelemetry:
    def test_stats_recorded(self, zones):
        left = tessellate(zones, CUSTOM, RES, keep_core_geoms=False)
        with telemetry.capture() as events:
            lrows, rrows, sure = candidate_pairs(left, left)
        (ev,) = [
            e for e in events if e.get("event") == "overlay_candidates"
        ]
        assert ev["candidates"] == int(lrows.shape[0]) > 0
        assert 0.0 <= ev["sure_fraction"] <= 1.0
        assert abs(
            ev["sure_fraction"] + ev["border_fraction"] - 1.0
        ) < 1e-6
        assert ev["sure_fraction"] == pytest.approx(
            float(sure.sum()) / sure.shape[0], abs=1e-6
        )

    def test_disjoint_tables_record_zeros(self, zones):
        left = tessellate(zones, CUSTOM, RES, keep_core_geoms=False)
        far = wkt.from_wkt(
            ["POLYGON ((100 50, 110 50, 110 60, 100 60, 100 50))"]
        )
        right = tessellate(far, CUSTOM, RES, keep_core_geoms=False)
        with telemetry.capture() as events:
            lrows, _, _ = candidate_pairs(left, right)
        assert lrows.shape[0] == 0
        (ev,) = [
            e for e in events if e.get("event") == "overlay_candidates"
        ]
        assert ev["candidates"] == 0
        assert ev["sure_fraction"] == 0.0


class TestOverlayProfile:
    def test_overlay_profile_consumes_span_stats(self, zones):
        """PR 16 satellite: `profile_overlay` reads the sure/border
        split straight off the ``overlay.candidates`` span — no second
        pass over the tables."""
        with telemetry.capture() as events:
            prof = profile_overlay(zones, zones, CUSTOM, RES)
        assert prof.kind == "overlay" and prof.n_sampled > 0
        assert prof.resolution == RES
        assert 0.0 <= prof.sure_fraction <= 1.0
        assert abs(prof.sure_fraction + prof.border_fraction - 1.0) < 1e-6
        assert [e for e in events if e.get("event") == "tune_profile"]
        assert WorkloadProfile.from_dict(prof.as_dict()) == prof

    def test_border_dominated_recommends_finer_tessellation(self):
        prof = WorkloadProfile(
            kind="overlay", n_sampled=100, resolution=3,
            sure_fraction=0.2, border_fraction=0.8,
        )
        rec = recommend(prof, priors={})
        assert rec.resolution == 4
        (rule,) = [r for r in rec.rationale if r["knob"] == "resolution"]
        assert rule["rule"] == "border-dominated-finer-tessellation"
        assert rule["evidence"]["border_fraction"] == 0.8
        assert rule["evidence"]["threshold"] == 0.5

    def test_sure_dominated_keeps_resolution(self):
        prof = WorkloadProfile(
            kind="overlay", n_sampled=100, resolution=3,
            sure_fraction=0.9, border_fraction=0.1,
        )
        rec = recommend(prof, priors={})
        assert rec.resolution is None
        assert not [r for r in rec.rationale if r["knob"] == "resolution"]
