"""mosaic-lint framework tests: per-rule positive/negative fixtures on
synthetic projects, suppression semantics, baseline round-trip, and the
driver's JSON contract (reference analog: the scalastyle gate's own
rule tests in the reference build)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from mosaic_tpu.analysis import (
    Finding,
    all_rules,
    analyze,
    load_baseline,
    save_baseline,
    split_baselined,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def project(tmp_path, **files):
    """Write ``{relative path: source}`` under a tmp root and return it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def run_rule(tmp_path, rule, **files):
    res = analyze(project(tmp_path, **files), rule_names=[rule])
    return res.findings, res.suppressed


def test_rule_catalog_has_the_semantic_rules():
    rules = all_rules()
    for name in (
        "jit-purity", "env-read-after-staging", "thread-context-adoption",
        "registry-drift", "broad-except", "unbounded-cache",
    ):
        assert name in rules, name
        assert rules[name].doc  # one-line catalog doc
    assert len(rules) >= 6


def test_jit_purity_flags_effects_in_decorated_fn(tmp_path):
    found, _ = run_rule(
        tmp_path, "jit-purity",
        **{"mosaic_tpu/m.py": """\
            import jax
            from . import telemetry

            @jax.jit
            def f(x):
                print(x)
                telemetry.record("ev", n=1)
                return x.sum().item()
            """},
    )
    msgs = {(f.line, f.message.split()[0]) for f in found}
    assert any(line == 6 for line, _ in msgs)          # print
    assert any("telemetry" in f.message for f in found)
    assert any(".item()" in f.message for f in found)


def test_jit_purity_follows_scan_body_and_local_calls(tmp_path):
    found, _ = run_rule(
        tmp_path, "jit-purity",
        **{"mosaic_tpu/m.py": """\
            import time
            import jax
            import numpy as np

            def helper(c):
                time.perf_counter()
                return c

            def body(c, x):
                np.asarray(x)
                return helper(c), x

            def outer(c, xs):
                return jax.lax.scan(body, c, xs)
            """},
    )
    lines = {f.line for f in found}
    assert 10 in lines  # np.asarray in the scan body
    assert 6 in lines   # time.* reached transitively via helper


def test_jit_purity_ignores_untraced_code(tmp_path):
    found, _ = run_rule(
        tmp_path, "jit-purity",
        **{"mosaic_tpu/m.py": """\
            import time

            def host_only(x):
                print(x)
                return time.time()
            """},
    )
    assert found == []


def test_env_read_after_staging(tmp_path):
    found, _ = run_rule(
        tmp_path, "env-read-after-staging",
        **{"mosaic_tpu/m.py": """\
            import os
            import jax

            @jax.jit
            def f(x):
                if os.environ.get("MOSAIC_X"):
                    return x + 1
                return x

            def host(x):
                return os.environ.get("MOSAIC_X")  # host-side: fine
            """},
    )
    assert [f.line for f in found] == [6]


def test_thread_adoption_missing_and_satisfied(tmp_path):
    src_bad = """\
        import threading

        def worker():
            pass

        def launch():
            threading.Thread(target=worker).start()
        """
    src_good = """\
        import threading
        from mosaic_tpu.runtime import telemetry, faults
        from mosaic_tpu import obs

        def launch(ctx, sinks, plans):
            def worker():
                telemetry.adopt_sinks(sinks)
                obs.adopt_context(ctx)
                faults.adopt_plans(plans)
            threading.Thread(target=worker).start()
        """
    found, _ = run_rule(tmp_path, "thread-context-adoption",
                        **{"mosaic_tpu/bad.py": src_bad})
    assert len(found) == 1 and found[0].line == 7
    assert "adopt" in found[0].message
    found, _ = run_rule(tmp_path / "g", "thread-context-adoption",
                        **{"mosaic_tpu/good.py": src_good})
    assert found == []


def test_thread_adoption_walks_nested_calls(tmp_path):
    # adoption two hops below the thread target (the serve batcher shape)
    found, _ = run_rule(
        tmp_path, "thread-context-adoption",
        **{"mosaic_tpu/m.py": """\
            import threading
            from mosaic_tpu.runtime import telemetry, faults
            from mosaic_tpu import obs

            class B:
                def _loop(self):
                    self._process()

                def _process(self):
                    telemetry.adopt_sinks(self.sinks)
                    obs.adopt_trace(self.ctx)
                    faults.adopt_plans(self.plans)

                def start(self):
                    threading.Thread(target=self._loop).start()
            """},
    )
    assert found == []


def test_broad_except_swallow_reraise_suppress(tmp_path):
    found, silenced = run_rule(
        tmp_path, "broad-except",
        **{"mosaic_tpu/m.py": """\
            def f():
                try:
                    work()
                except Exception:
                    pass

            def g():
                try:
                    work()
                except Exception as e:
                    raise RuntimeError("ctx") from e

            def h():
                try:
                    work()
                except Exception:  # lint: broad-except-ok (best-effort probe)
                    pass
            """},
    )
    assert [f.line for f in found] == [4]
    assert [f.line for f in silenced] == [16]


def test_unbounded_cache_library_scope(tmp_path):
    lib = """\
        import functools

        @functools.lru_cache(maxsize=None)
        def bad(x):
            return x

        @functools.lru_cache
        def fine_default(x):  # maxsize=128
            return x

        @functools.lru_cache(maxsize=8)
        def fine_bounded(x):
            return x

        @functools.cache
        def also_bad(x):
            return x
        """
    found, _ = run_rule(tmp_path, "unbounded-cache",
                        **{"mosaic_tpu/m.py": lib})
    assert sorted(f.line for f in found) == [3, 15]
    # tool scripts are out of scope for this rule
    found, _ = run_rule(tmp_path / "t", "unbounded-cache",
                        **{"tools/m.py": lib})
    assert found == []


def test_registry_drift_reports_missing_registry(tmp_path):
    found, _ = run_rule(
        tmp_path, "registry-drift",
        **{"mosaic_tpu/m.py": """\
            from mosaic_tpu.runtime import telemetry

            def f():
                telemetry.record("some_event", stage="s1")
            """},
    )
    assert any("committed registry missing" in f.message for f in found)


def test_malformed_suppressions_are_findings(tmp_path):
    # the marker is spliced in via format so THIS file's raw source does
    # not itself carry a malformed suppression comment
    res = analyze(project(
        tmp_path,
        **{"mosaic_tpu/m.py": """\
            def f():
                try:
                    work()
                except Exception:  # {m1}
                    pass

            def g():
                try:
                    work()
                except Exception:  # {m2}
                    pass
            """.format(
                m1="lint: no-such-rule-ok (reason)",
                m2="lint: broad-except-ok",
            )},
    ))
    sup = [f for f in res.findings if f.rule == "suppression"]
    assert len(sup) == 2
    assert any("no-such-rule" in f.message for f in sup)
    # an empty reason does not silence: the broad-except stays active
    assert any(
        f.rule == "broad-except" and f.line == 10 for f in res.findings
    )


def test_suppression_silences_exactly_its_rule(tmp_path):
    res = analyze(project(
        tmp_path,
        **{"mosaic_tpu/m.py": """\
            import functools

            @functools.lru_cache(maxsize=None)  # lint: broad-except-ok (wrong rule)
            def f(x):
                return x
            """},
    ))
    assert any(f.rule == "unbounded-cache" for f in res.findings)


def test_baseline_round_trip(tmp_path):
    f1 = Finding(rule="r", path="a.py", line=3, message="m1")
    f2 = Finding(rule="r", path="a.py", line=9, message="m1")  # same key
    f3 = Finding(rule="r", path="b.py", line=1, message="m2")
    path = str(tmp_path / "baseline.json")
    save_baseline(path, [f1, f2, f3])
    baseline = load_baseline(path)
    assert baseline == {f1.key(): 2, f3.key(): 1}

    # all grandfathered while nothing changed
    active, grand, stale = split_baselined([f1, f2, f3], baseline)
    assert (active, len(grand), stale) == ([], 3, [])

    # fixing findings leaves their unconsumed allowance stale — a
    # partially-consumed count must shrink too (shrink-only policy)
    active, grand, stale = split_baselined([f1], baseline)
    assert active == [] and len(grand) == 1
    assert stale == sorted([f1.key(), f3.key()])

    # a third identical finding overflows the count and stays active
    f4 = Finding(rule="r", path="a.py", line=20, message="m1")
    active, grand, stale = split_baselined([f1, f2, f4], baseline)
    assert len(active) == 1 and len(grand) == 2

    assert load_baseline(str(tmp_path / "missing.json")) == {}


def _run_driver(*argv, cwd=None):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lint.py"), *argv],
        capture_output=True, text=True, cwd=cwd or ROOT,
    )


def test_driver_repo_is_clean_and_json_terminated():
    r = _run_driver("--json-only")
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["tool"] == "mosaic-lint"
    assert summary["clean"] is True
    assert summary["findings"] == 0
    assert summary["rules_run"] >= 6
    assert summary["stale_baseline"] == []


def test_driver_fails_on_injected_violation(tmp_path):
    # the CI negative lane's logic: a synthetic violation in a copy must
    # turn the gate red
    project(tmp_path, **{"mosaic_tpu/bad.py": """\
        import functools

        @functools.lru_cache(maxsize=None)
        def f(x):
            return x
        """})
    r = _run_driver("--root", str(tmp_path), "--rule", "unbounded-cache")
    assert r.returncode == 1, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["clean"] is False and summary["findings"] == 1
    assert summary["rules"] == {"unbounded-cache": 1}


def test_driver_list_rules():
    r = _run_driver("--list-rules")
    assert r.returncode == 0
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert "jit-purity" in summary["rules"]


def test_driver_rejects_unknown_rule():
    with pytest.raises(KeyError):
        analyze(ROOT, targets=(), rule_names=["no-such-rule"])
