"""Test harness: virtual 8-device CPU mesh + float64 enabled.

Mirrors the reference's `SparkSuite` local[4] harness
(`src/test/scala/.../test/SparkSuite.scala:44`): distribution semantics are
exercised without real hardware by forcing 8 XLA host-platform devices.

Note: this environment's sitecustomize imports jax at interpreter startup
(axon TPU plugin), so JAX_PLATFORMS must be overridden through jax.config,
not os.environ. XLA_FLAGS is still read lazily at first backend init, which
has not happened yet when conftest loads.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {devs}"
    return devs
