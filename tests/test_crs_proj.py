"""Parameter-driven CRS engine: PROJ-string parsing, the built-in EPSG
table, runtime registration, and parity with the reference's bounds table.

Reference analogs: proj4j arbitrary-EPSG reprojection
(`core/geometry/MosaicGeometry.scala:102-128`) and `CRSBounds.csv`
(`core/crs/CRSBoundsProvider.scala:18-100`); the spot values below are
that CSV's rows for codes the table implements.
"""

import numpy as np
import pytest

from mosaic_tpu.core import crs
from mosaic_tpu.core.crs_proj import (
    lookup,
    parse_proj,
    register_crs,
)

# (geo area, reprojected bounds) per reference CRSBounds.csv
_CSV_ROWS = {
    3067: (50199.4814, 6582464.0358, 761274.6247, 7799839.8902),
    3301: (370753.1145, 6382922.7769, 739245.6000, 6624811.0577),
    3763: (-121656.5849, -294200.8899, 172945.8815, 277430.8421),
    2039: (123979.2782, 378130.9791, 265568.0471, 797585.3732),
    2177: (6390979.5111, 5466989.5093, 6609020.4889, 6078869.0066),
    2248: (593655.7373, 84146.0734, 1895381.6422, 757391.3704),
    2263: (909126.0155, 110626.2880, 1610215.3590, 424498.0529),
    26985: (180946.6307, 25647.7745, 577713.4801, 230853.3514),
    31370: (17736.0314, 23697.0977, 297289.9391, 245375.4223),
    31466: (2490547.1867, 5440321.7879, 2609576.6008, 5958700.0208),
    28992: (12628.0541, 308179.0423, 283594.4779, 611063.1429),
    2065: (-951370.4446, -1352211.7003, -159556.3438, -912234.3486),
    29101: (2786482.4389, 5670041.9266, 8077014.5748, 10896215.6624),
    2056: (2485869.5728, 1076443.1884, 2837076.5648, 1299941.7864),
    32198: (-886251.0296, 180252.9126, 897177.3418, 2106143.8139),
    32118: (277102.1637, 33718.9600, 490794.6230, 129387.2653),
}

_ROUNDTRIP_CODES = sorted(_CSV_ROWS) + [
    28355, 31983, 7855, 31970, 3395, 3435, 21781, 5514, 5880,
    # round-5 families: omerc A/B, cass, eqdc, south-orientated tmerc
    26931, 3375, 3376, 29873, 28191, 24500, 102031, 102026, 2048, 2053,
    # round-5 additions: NZMG, sphere-LAEA, POSGAR south-pole-origin GK
    27200, 2163, 5343, 5345, 5349,
    # round-5 breadth: world eqc/cea grids, Pulkovo GK (incl. the wrapped
    # antimeridian zone 32), WGS72/NAD27/ED50 UTM, AGD66/84 AMG, SAD69
    # UTM, Japan zones (all three datum generations), Irish grids, Greek
    4087, 4088, 6933, 3410, 28407, 28422, 28432, 32230, 32330, 26710,
    23031, 20255, 20355, 29171, 29193, 30169, 2451, 6677, 29902, 2157,
    2100, 54008, 54009, 6974,
]


def _interior_grid(srid, n=7, margin=0.25):
    x0, y0, x1, y1 = crs.crs_bounds(srid, reprojected=False)
    m = min(margin, (x1 - x0) / 5, (y1 - y0) / 5)  # tiny areas (Singapore)
    xs = np.linspace(x0 + m, x1 - m, n)
    ys = np.linspace(y0 + m, y1 - m, n)
    return np.stack(np.meshgrid(xs, ys), -1).reshape(-1, 2)


@pytest.mark.parametrize("srid", _ROUNDTRIP_CODES)
def test_roundtrip_below_microdegree(srid):
    ll = _interior_grid(srid)
    rt = crs.to_wgs84(crs.from_wgs84(ll, srid), srid)
    # 5e-7 deg ~ 5 cm: headroom over the sign-flip Helmert inverse
    # approximation for codes with larger datum parameters
    assert np.abs(rt - ll).max() < 5e-7
    assert crs.supported(srid)


@pytest.mark.parametrize("srid", sorted(_CSV_ROWS))
def test_reprojected_bounds_match_reference_csv(srid):
    """Computed projected envelopes vs the reference's static rows.

    The computed envelope densifies the area boundary, so it may exceed
    the CSV (which under-covers conic edge extrema — e.g. 32198's bottom
    parallel bulges below both corners) but must contain it and stay
    within 6% of the span on every side.
    """
    want = np.array(_CSV_ROWS[srid])
    got = np.array(crs.crs_bounds(srid, reprojected=True))
    span = np.array([want[2] - want[0], want[3] - want[1]] * 2)
    slack = 0.005 * span
    assert (got[:2] <= want[:2] + slack[:2]).all(), (got, want)
    assert (got[2:] >= want[2:] - slack[2:]).all(), (got, want)
    assert (np.abs(got - want) <= 0.06 * span).all(), (got, want)


def test_bng_proj_string_matches_native_path():
    """27700 built from its PROJ string (+datum=OSGB36 Helmert) must agree
    with the hand-written OSGB36 path to sub-mm."""
    from mosaic_tpu.core.crs_proj import crs_from_wgs84, crs_to_wgs84

    p = parse_proj(
        "+proj=tmerc +lat_0=49 +lon_0=-2 +k=0.9996012717 "
        "+x_0=400000 +y_0=-100000 +datum=OSGB36"
    )
    ll = np.array([[-1.5, 52.0], [0.1, 51.5], [-5.0, 50.1], [-3.2, 58.6]])
    native = crs.from_wgs84(ll, 27700)
    via = crs_from_wgs84(p, ll)
    assert np.abs(native - via).max() < 1e-3
    back = crs_to_wgs84(p, via)
    assert np.abs(back - ll).max() < 1e-7


def test_ellipsoidal_vs_spherical_mercator():
    # 3395 (ellipsoidal) northing differs from 3857 (spherical) by ~0.3%
    ll = np.array([[10.0, 45.0]])
    y_sph = crs.from_wgs84(ll, 3857)[0, 1]
    y_ell = crs.from_wgs84(ll, 3395)[0, 1]
    assert abs(y_sph - y_ell) / y_sph > 0.002
    # eastings agree exactly (same a, k0=1, lon_0=0)
    assert np.isclose(crs.from_wgs84(ll, 3395)[0, 0], ll[0, 0] / 180 * np.pi * 6378137)


def test_lcc_one_sp_center_and_scale():
    p = parse_proj(
        "+proj=lcc +lat_1=18 +lat_0=18 +lon_0=-77 +k_0=0.9995 "
        "+x_0=250000 +y_0=150000 +ellps=clrk66"
    )
    from mosaic_tpu.core.crs_proj import crs_from_wgs84, crs_to_wgs84

    # the natural origin maps exactly to the false origin
    en = crs_from_wgs84(p, np.array([[-77.0, 18.0]]))
    assert np.allclose(en, [[250000.0, 150000.0]], atol=1e-6)
    # k_0 scales distances: 1 degree of longitude at lat0 spans ~0.9995 *
    # the k_0=1 width
    p1 = parse_proj(
        "+proj=lcc +lat_1=18 +lat_0=18 +lon_0=-77 +k_0=1 "
        "+x_0=250000 +y_0=150000 +ellps=clrk66"
    )
    w = crs_from_wgs84(p, np.array([[-76.0, 18.0]]))[0, 0] - 250000.0
    w1 = crs_from_wgs84(p1, np.array([[-76.0, 18.0]]))[0, 0] - 250000.0
    assert np.isclose(w / w1, 0.9995, atol=1e-9)
    ll = np.array([[-78.2, 17.7], [-76.2, 18.4]])
    assert np.abs(crs_to_wgs84(p, crs_from_wgs84(p, ll)) - ll).max() < 1e-9


def test_us_survey_foot_units():
    # 2248 is 26985 expressed in US survey feet
    ll = np.array([[-76.6, 39.3]])
    m = crs.from_wgs84(ll, 26985)
    ft = crs.from_wgs84(ll, 2248)
    assert np.allclose(ft * 1200.0 / 3937.0, m, atol=1e-6)


def test_register_crs_runtime_and_functions_api():
    from mosaic_tpu.functions import formats as FF
    from mosaic_tpu.functions import geometry as F

    srid = 990001  # not a real EPSG code: runtime registration only
    with pytest.raises(ValueError):
        crs.to_wgs84(np.zeros((1, 2)), srid)
    register_crs(
        srid,
        "+proj=aea +lat_1=34 +lat_2=40.5 +lat_0=0 +lon_0=-120 "
        "+x_0=0 +y_0=-4000000 +ellps=GRS80",
        area=(-124.45, 32.53, -114.12, 42.01),
    )
    assert crs.supported(srid)
    # matches the hand-registered California Albers (3310) bit for bit
    ll = _interior_grid(3310, n=5)
    assert np.allclose(
        crs.from_wgs84(ll, srid), crs.from_wgs84(ll, 3310), atol=1e-9
    )
    wkt_pt = ["POINT (-120.5 37.2)"]
    moved = FF.st_astext(F.st_updatesrid(wkt_pt, 4326, srid))
    assert "POINT" in moved[0]
    ok = F.st_hasvalidcoordinates(wkt_pt, srid, which="bounds")
    assert ok.tolist() == [True]


def test_register_crs_overrides_builtin_codes():
    """A runtime registration must take precedence over the native path
    (e.g. swapping a null datum shift for a real one)."""
    from mosaic_tpu.core import crs_proj

    ll = np.array([[15.0, 52.0]])
    builtin = crs.from_wgs84(ll, 32633)
    try:
        register_crs(
            32633, "+proj=utm +zone=33 +ellps=WGS84 +towgs84=100,0,0"
        )
        overridden = crs.from_wgs84(ll, 32633)
        assert np.abs(overridden - builtin).max() > 10.0  # shift applied
        assert crs.crs_bounds(32633, reprojected=False)[1] == -80.0
    finally:
        del crs_proj._REGISTERED[32633]
        crs._PROJ_BOUNDS_CACHE.pop(32633, None)
    assert np.allclose(crs.from_wgs84(ll, 32633), builtin)


def test_oblique_stereographic_epsg_worked_example():
    """EPSG Guidance Note 7-2, Oblique Stereographic (Amersfoort / RD
    New) worked example: 53N 6E (Bessel) -> E 196105.283, N 557057.739.

    Projection-only (the guidance example is on the source datum), so the
    family forward is called directly with the parsed parameters."""
    from mosaic_tpu.core.crs import _FAMILY_FNS
    from mosaic_tpu.core.crs_proj import lookup

    rd = lookup(28992)
    en = _FAMILY_FNS["sterea"][0](rd.params, np.radians([[6.0, 53.0]]))
    np.testing.assert_allclose(
        en, [[196105.283, 557057.739]], atol=2e-3
    )


def test_rd_datum_point_end_to_end():
    """The Amersfoort fundamental point in ETRS89/WGS84 coordinates must
    land on the RD false origin (E 155000, N 463000) through the full
    chain incl. the 7-parameter Bessel datum shift — this catches
    arc-second/microradian rotation-unit mixups that the self-inverse
    round-trip test cannot see."""
    en = crs.from_wgs84(np.array([[5.3872035, 52.1551744]]), 28992)
    np.testing.assert_allclose(en, [[155000.0, 463000.0]], atol=0.5)


def test_polyconic_defining_properties():
    """American Polyconic (Snyder 18): the central meridian is true
    length (y == meridian arc) and every parallel is an arc of true
    scale — the projection's defining properties, checked directly."""
    import math

    from mosaic_tpu.core.crs import (
        _FAMILY_FNS,
        _poly_arc_params,
        _tm_meridional_arc,
    )
    from mosaic_tpu.core.crs_proj import lookup

    br = lookup(5880)
    a, e = br.params[0], br.params[1]
    tmp = _poly_arc_params(a, e)
    fwd = _FAMILY_FNS["poly"][0]
    for latd in (-30.0, -10.0, 5.0):
        en = fwd(br.params, np.radians([[-54.0, latd]]))
        M = _tm_meridional_arc(tmp, np.radians(latd), np)
        assert abs(en[0, 0] - 5e6) < 1e-6
        assert abs(en[0, 1] - 1e7 - M) < 1e-6
    for latd in (-25.0, -5.0):
        lat = math.radians(latd)
        N = a / math.sqrt(1 - e * e * math.sin(lat) ** 2)
        dl = math.radians(0.01)
        p1 = fwd(br.params, np.array([[math.radians(-60.0), lat]]))
        p2 = fwd(br.params, np.array([[math.radians(-60.0) + dl, lat]]))
        chord = np.linalg.norm(p2 - p1)
        assert abs(chord - N * math.cos(lat) * dl) / chord < 1e-9


def test_polyconic_inverse_contract_far_field():
    """Outside the usable domain the polyconic forward is non-injective;
    the inverse must return a principal-domain pre-image (forward of the
    result reproduces the input) or NaN — never a silent wrong answer."""
    from mosaic_tpu.core.crs import _FAMILY_FNS
    from mosaic_tpu.core.crs_proj import lookup

    br = lookup(5880)
    fwd, inv = _FAMILY_FNS["poly"]
    lons = np.radians(np.linspace(-170, 170, 12))
    lats = np.radians(np.linspace(-80, 80, 11))
    g = np.stack(np.meshgrid(lons, lats), -1).reshape(-1, 2)
    en = fwd(br.params, g)
    rt = inv(br.params, en, iters=25)
    ok = ~np.isnan(rt).any(axis=1)
    assert ok.any()  # plenty of the plane inverts
    back = fwd(br.params, rt[ok])
    np.testing.assert_allclose(back, en[ok], atol=1e-3)


def test_krovak_epsg_worked_example():
    """EPSG Guidance Note 7-2, Krovak worked example: 50d12'32.442"N
    16d50'59.179"E (Bessel) -> southing 1050538.643, westing 568991.017
    (proj axis convention negates both)."""
    from mosaic_tpu.core.crs import _FAMILY_FNS
    from mosaic_tpu.core.crs_proj import lookup

    kr = lookup(5514)
    ll = np.radians([[16 + 50 / 60 + 59.179 / 3600,
                      50 + 12 / 60 + 32.442 / 3600]])
    en = _FAMILY_FNS["krovak"][0](kr.params, ll)
    np.testing.assert_allclose(
        en, [[-568991.017, -1050538.643]], atol=0.05
    )


def test_swiss_oblique_mercator_origin_and_conformality():
    from mosaic_tpu.core.crs import _FAMILY_FNS
    from mosaic_tpu.core.crs_proj import lookup

    sw = lookup(21781)
    # Bern (the projection origin) maps exactly to the false origin
    en = _FAMILY_FNS["somerc"][0](
        sw.params,
        np.radians([[7.439583333333333, 46.952405555555565]]),
    )
    np.testing.assert_allclose(en, [[600000.0, 200000.0]], atol=1e-6)
    # LV95 is LV03 shifted by exactly (+2_000_000, +1_000_000)
    ll = np.array([[8.54, 47.38], [6.63, 46.52]])  # Zurich, Lausanne
    e03 = crs.from_wgs84(ll, 21781)
    e95 = crs.from_wgs84(ll, 2056)
    np.testing.assert_allclose(e95 - e03, [[2e6, 1e6]] * 2, atol=1e-6)


@pytest.mark.parametrize("srid", [28992, 21781])
def test_oblique_projections_are_conformal(srid):
    """A conformal projection's Jacobian (in ellipsoidal-metric terms:
    east = nu cos(lat) dlon, north = rho dlat) is a scaled rotation —
    a strong whole-formula property check."""
    import math

    p = np.array([[6.3, 52.2]]) if srid == 28992 else np.array([[8.5, 46.8]])
    h = 1e-6
    J = np.zeros((2, 2))
    for k in range(2):
        dp = np.zeros((1, 2))
        dp[0, k] = h
        J[:, k] = (crs.from_wgs84(p + dp, srid) - crs.from_wgs84(p - dp, srid))[
            0
        ] / (2 * h)
    lat = math.radians(p[0, 1])
    a, f = 6377397.155, 1 / 299.1528128  # Bessel (both codes)
    e2 = f * (2 - f)
    s = math.sin(lat)
    nu = a / math.sqrt(1 - e2 * s * s)
    rho = a * (1 - e2) / (1 - e2 * s * s) ** 1.5
    J[:, 0] /= nu * math.cos(lat)  # per-meter east on the ellipsoid
    J[:, 1] /= rho  # per-meter north
    resid = (abs(J[0, 0] - J[1, 1]) + abs(J[0, 1] + J[1, 0])) / np.abs(J).max()
    assert resid < 2e-4, (J, resid)


def test_parse_errors_are_loud():
    with pytest.raises(ValueError, match="implemented families"):
        parse_proj("+proj=robin +lon_0=0")
    with pytest.raises(ValueError, match="prime meridian"):
        parse_proj("+proj=lcc +lat_1=49 +lat_2=44 +pm=paris")
    with pytest.raises(ValueError, match="towgs84"):
        parse_proj("+proj=tmerc +towgs84=1,2")
    with pytest.raises(ValueError, match="ellps"):
        parse_proj("+proj=tmerc +ellps=marsoid")
    with pytest.raises(ValueError, match="polar"):
        parse_proj("+proj=stere +lat_0=52.15616055555555 +ellps=bessel")
    with pytest.raises(ValueError, match="zone"):
        parse_proj("+proj=utm +zone=61")


def test_unknown_code_still_raises():
    assert lookup(999999) is None
    with pytest.raises(ValueError, match="unsupported SRID"):
        crs.transform_points(np.zeros((1, 2)), 4326, 999999)


def test_proj_table_code_under_jit():
    import jax
    import jax.numpy as jnp

    ll = _interior_grid(3067, n=4)
    want = crs.from_wgs84(ll, 3067)
    got = jax.jit(lambda x: crs.from_wgs84(x, 3067, xp=jnp))(
        jnp.asarray(ll)
    )
    assert np.abs(np.asarray(got) - want).max() < 1e-6


def test_polyconic_inverse_under_jit():
    # regression (round-4 advisor): poly_inverse materialized the tracer
    # via np.asarray to pick its finite-difference step, so jitted
    # to_wgs84 for polyconic codes (5880/29101) raised
    # TracerArrayConversionError despite the 'jit-safe' docstring
    import jax
    import jax.numpy as jnp

    ll = _interior_grid(5880, n=4)
    en = crs.from_wgs84(ll, 5880)
    want = crs.to_wgs84(en, 5880)
    got = jax.jit(lambda x: crs.to_wgs84(x, 5880, xp=jnp))(
        jnp.asarray(en)
    )
    assert np.abs(np.asarray(got) - want).max() < 1e-5


def test_omerc_epsg_worked_example():
    """EPSG Guidance Note 7-2 worked example for Hotine oblique Mercator
    variant B: Timbalai 1948 / RSO Borneo (m). The projected coordinates
    must reproduce to centimetres (reference: proj4j resolves 29873
    through the same registry parameters)."""
    import math

    from mosaic_tpu.core.crs import omerc_forward

    a, rf = 6377298.556, 300.8017
    f = 1 / rf
    e = math.sqrt(f * (2 - f))
    d = math.radians
    p = (
        a, e, d(4.0), d(115.0),
        d(53 + 18 / 60 + 56.9537 / 3600),  # azimuth alpha_c
        d(53 + 7 / 60 + 48.3685 / 3600),   # rectified grid angle gamma_c
        0.99984, 590476.87, 442857.65, "B",
    )
    lat = d(5 + 23 / 60 + 14.1129 / 3600)
    lon = d(115 + 48 / 60 + 19.8196 / 3600)
    en = omerc_forward(p, np.array([[lon, lat]]))
    np.testing.assert_allclose(en[0], [679245.73, 596562.78], atol=0.02)


def test_omerc_variant_a_differs_from_b():
    # +no_uoff (variant A) shifts the grid by u_c along the skew axis
    va = parse_proj(
        "+proj=omerc +lat_0=4 +lonc=115 +alpha=53.31582047222222 "
        "+gamma=53.13010236111111 +k=0.99984 +no_uoff +ellps=GRS80"
    )
    vb = parse_proj(
        "+proj=omerc +lat_0=4 +lonc=115 +alpha=53.31582047222222 "
        "+gamma=53.13010236111111 +k=0.99984 +ellps=GRS80"
    )
    from mosaic_tpu.core.crs_proj import crs_from_wgs84

    pt = np.array([[115.0, 4.0]])
    ea = crs_from_wgs84(va, pt)
    eb = crs_from_wgs84(vb, pt)
    assert np.abs(ea - eb).max() > 1000.0  # u_c is hundreds of km here
    # each variant round-trips on its own
    from mosaic_tpu.core.crs_proj import crs_to_wgs84

    for v, en in ((va, ea), (vb, eb)):
        np.testing.assert_allclose(crs_to_wgs84(v, en), pt, atol=1e-9)


def test_tm_south_orientation():
    """Lo grids: westing grows west, southing grows south (EPSG 9808)."""
    en = crs.from_wgs84(np.array([[18.5, -33.9]]), 2048)  # west+south of L019 origin
    assert en[0, 0] > 0 and en[0, 1] > 0
    east = crs.from_wgs84(np.array([[19.5, -33.9]]), 2048)
    assert east[0, 0] < 0  # east of lon_0 -> negative westing


def test_eqdc_distance_property():
    """Equidistant conic: meridian arcs project with true length."""
    p = parse_proj(
        "+proj=eqdc +lat_0=30 +lon_0=95 +lat_1=15 +lat_2=65 +ellps=WGS84"
    )
    from mosaic_tpu.core.crs_proj import crs_from_wgs84

    lats = np.linspace(20.0, 60.0, 41)
    ll = np.stack([np.full_like(lats, 95.0), lats], -1)
    en = crs_from_wgs84(p, ll)
    seg = np.hypot(np.diff(en[:, 0]), np.diff(en[:, 1])).sum()
    from mosaic_tpu.core.crs import _marc

    e2 = 0.00669437999014132
    arc = float(
        _marc(6378137.0, e2, np.radians(60.0), np)
        - _marc(6378137.0, e2, np.radians(20.0), np)
    )
    assert abs(seg - arc) / arc < 1e-6


def test_nzmg_roundtrip_and_conformality():
    """NZMG (EPSG 27200, complex polynomial): the projection origin maps
    to (FE, FN) exactly; the grid round-trips to fp precision; and the
    map is CONFORMAL — equal-length isometric steps project to equal-
    length orthogonal steps, an independent check of the published
    Reilly coefficients."""
    import math

    from mosaic_tpu.core.crs import nzmg_forward

    d = math.radians
    p = (6378388.0, d(-41.0), d(173.0), 2510000.0, 6023150.0)
    np.testing.assert_allclose(
        nzmg_forward(p, np.array([[d(173.0), d(-41.0)]]))[0],
        [2510000.0, 6023150.0],
        atol=1e-6,
    )
    # intrinsic series check: the published inverse series must compose
    # with the forward series to identity (catches any transcription
    # error in either tail — a 10x slip in A5 moves this by ~1e-4)
    from mosaic_tpu.core.crs import _NZMG_A, _NZMG_D

    x = np.array([0.236, -0.2, 0.1, 0.3])
    psi = np.zeros_like(x)
    for A in reversed(_NZMG_A):
        psi = (psi + A) * x
    back = np.zeros_like(psi)
    for D in reversed(_NZMG_D):
        back = (back + D) * psi
    assert np.abs(back - x).max() < 1e-9
    # LINZ worked example (NZGD49 lat/lon -> NZMG): 5 m tolerance covers
    # the quoted-precision uncertainty while catching coefficient errors
    # (which show up as hundreds of metres)
    lat = -d(34 + 26 / 60 + 38.727 / 3600)
    lon = d(172 + 44 / 60 + 21.099 / 3600)
    en = nzmg_forward(p, np.array([[lon, lat]]))[0]
    np.testing.assert_allclose(en, [2487100.638, 6751049.719], atol=5.0)
    # public-API roundtrip (incl. the NZGD49 Helmert)
    ll = _interior_grid(27200)
    rt = crs.to_wgs84(crs.from_wgs84(ll, 27200), 27200)
    assert np.abs(rt - ll).max() < 5e-7
    # conformality: tight near the origin; NZMG is a FITTED nearly-
    # conformal map, so the deviation legitimately grows to ~1e-3 at the
    # national edges (that bound is part of the projection's definition)
    f = 1 / 297.0
    e2 = 2 * f - f * f
    for (phi_d, lam_d, tol) in [(-41.5, 172.0, 1e-6), (-44.5, 169.0, 2e-3)]:
        phi0, lam0 = d(phi_d), d(lam_d)
        s, c = math.sin(phi0), math.cos(phi0)
        dq_dphi = (1 - e2) / ((1 - e2 * s * s) * c)
        dl = 1e-6
        base = nzmg_forward(p, np.array([[lam0, phi0]]))[0]
        dN = (
            nzmg_forward(p, np.array([[lam0, phi0 + dl / dq_dphi]]))[0] - base
        )
        dE = nzmg_forward(p, np.array([[lam0 + dl, phi0]]))[0] - base
        ratio = np.hypot(*dN) / np.hypot(*dE)
        ang = (
            math.degrees(
                math.atan2(dN[1], dN[0]) - math.atan2(dE[1], dE[0])
            ) % 360.0
        )
        assert abs(ratio - 1.0) < tol, (phi_d, lam_d, ratio)
        assert abs(ang - 90.0) < 1e-3


def test_datum_shift_geographic_crs():
    # 4277 (OSGB36 geographic): shifting Greenwich to WGS84 moves it ~100 m
    ll_osgb = np.array([[0.0, 51.4778]])
    ll_wgs = crs.to_wgs84(ll_osgb, 4277)
    d = np.abs(ll_wgs - ll_osgb)
    assert 1e-4 < d.max() < 3e-3  # offset is O(100 m), not 0, not huge
    back = crs.from_wgs84(ll_wgs, 4277)
    assert np.abs(back - ll_osgb).max() < 1e-7


def test_eqc_world_grid_anchors():
    """EPSG 4087 (method 1028): the antimeridian easting is the WGS84
    semi-circumference and the pole northing is the meridian quadrant —
    both published constants of the grid."""
    en = crs.from_wgs84(np.array([[180.0, 0.0], [0.0, 90.0]]), 4087)
    assert abs(en[0, 0] - 20037508.3428) < 0.01
    assert abs(en[1, 1] - 10001965.7293) < 0.01
    # spherical twin: both extents are just R*pi(/2)
    en_s = crs.from_wgs84(np.array([[180.0, 0.0], [0.0, 90.0]]), 4088)
    assert abs(en_s[0, 0] - 6371007 * np.pi) < 1e-6
    assert abs(en_s[1, 1] - 6371007 * np.pi / 2) < 1e-6


def test_cea_ease_grid2_extent_and_equal_area():
    """EASE-Grid 2.0 (EPSG 6933): the published grid half-width is
    17367530.45 m; equal-area means d(y)/d(q) is constant — assert the
    authalic northing spacing, not linear latitude spacing."""
    en = crs.from_wgs84(np.array([[180.0, 0.0]]), 6933)
    assert abs(en[0, 0] - 17367530.45) < 0.01
    # area preservation: strip [0,30]x[lat,lat+d] areas shrink with cos(lat)
    lats = np.array([[10.0, 20.0], [10.0, 21.0], [10.0, 60.0], [10.0, 61.0]])
    ys = crs.from_wgs84(lats[:, ::-1] * 0 + np.stack(
        [np.zeros(4), lats[:, 1]], -1), 6933)[:, 1]
    strip_low = ys[1] - ys[0]
    strip_high = ys[3] - ys[2]
    # cos(60.5)/cos(20.5) ~ 0.525 — equal-area compression with latitude
    assert 0.4 < strip_high / strip_low < 0.6


def test_japan_zone_origins_map_to_zero():
    """JGD2000/JGD2011 Plane Rectangular origins (no datum shift) project
    to exactly (0,0); the Tokyo-datum twin is offset by its Helmert."""
    origins = {2443: (129.5, 33.0), 2451: (139.0 + 5.0 / 6.0, 36.0),
               6687: (154.0, 26.0)}
    for srid, (lo, la) in origins.items():
        en = crs.from_wgs84(np.array([[lo, la]]), srid)
        assert np.abs(en).max() < 1e-6, (srid, en)
    en_tokyo = crs.from_wgs84(np.array([[139.0 + 5.0 / 6.0, 36.0]]), 30169)
    assert 200 < float(np.hypot(*en_tokyo[0])) < 1000  # Tokyo datum offset


def test_pulkovo_gk_false_easting_prefix():
    """Pulkovo GK zone N prefixes the false easting with N*1e6; a point on
    the central meridian lands near x = N*1e6 + 500000."""
    for zone, srid in ((7, 28407), (32, 28432)):
        lon0 = zone * 6 - 3 - (360 if zone * 6 - 3 > 180 else 0)
        en = crs.from_wgs84(np.array([[lon0, 55.0]]), srid)
        assert abs(en[0, 0] - (zone * 1e6 + 500000)) < 300  # datum shift


def test_sinusoidal_modis_grid_anchor():
    """The MODIS sinusoidal sphere grid (SR-ORG 6974): the published tile
    grid half-width is 20015109.354 m (R * pi); the equal-area property
    compresses x with cos(lat)."""
    en = crs.from_wgs84(np.array([[180.0, 0.0]]), 6974)
    assert abs(en[0, 0] - 20015109.354) < 2.0
    x60 = crs.from_wgs84(np.array([[10.0, 60.0]]), 6974)[0, 0]
    x00 = crs.from_wgs84(np.array([[10.0, 0.0]]), 6974)[0, 0]
    assert abs(x60 / x00 - 0.5) < 1e-9  # cos(60) exactly on the sphere


def test_mollweide_constants_and_poles():
    """Mollweide: x(90E, 0) = sqrt(2) R, the poles map to y = +-sqrt(2) R
    without NaN (the Newton seed handles the vanishing derivative), and
    near-pole round-trips stay tight."""
    R = 6378137.0
    en = crs.from_wgs84(
        np.array([[90.0, 0.0], [0.0, 90.0], [0.0, -90.0]]), 54009
    )
    assert abs(en[0, 0] - np.sqrt(2) * R) < 1e-6
    assert abs(en[1, 1] - np.sqrt(2) * R) < 1e-6
    assert abs(en[2, 1] + np.sqrt(2) * R) < 1e-6
    assert np.isfinite(en).all()
    ll = np.array([[12.3, 89.2], [-45.0, -88.5], [179.0, -89.99]])
    rt = crs.to_wgs84(crs.from_wgs84(ll, 54009), 54009)
    assert np.abs(rt - ll).max() < 1e-7
