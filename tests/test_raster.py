"""Raster subsystem: native GeoTIFF reader, model, rst_ functions, pipeline.

Validation targets: (a) round-trips through our own writer, (b) the real
MODIS GeoTIFFs from the reference's test resources (tiled + deflate +
predictor-2 int16 — decoded with an independent implementation, compared on
internal consistency: sizes, geotransform arithmetic, nodata stats).
"""

import os

import numpy as np
import pytest

from mosaic_tpu import functions as F
from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.raster import Raster, read_raster, write_geotiff
from mosaic_tpu.readers import read

MODIS = (
    "/root/reference/src/test/resources/modis/"
    "MCD43A4.A2018185.h10v07.006.2018194033728_B01.TIF"
)


@pytest.fixture(scope="session")
def modis_path(tmp_path_factory):
    """Path to a MODIS GeoTIFF: the real reference tile when the
    checkout is present, else a synthetic twin with the same on-disk
    shape (tiled + deflate + predictor-2 int16, planar-2, 463.31 m
    sinusoidal pixels, 32767 nodata) written by tests/modis_fixture.py."""
    if os.path.exists(MODIS):
        return MODIS
    from tests.modis_fixture import write_modis_like

    p = tmp_path_factory.mktemp("modis") / "synthetic_modis_b01.tif"
    return write_modis_like(str(p))


def _toy_raster(bands=2, h=10, w=12, dtype=np.float32, nodata=-9.0):
    rng = np.random.default_rng(7)
    data = rng.uniform(0, 100, (bands, h, w)).astype(dtype)
    data[:, :2, :3] = nodata
    return Raster(
        data=data,
        gt=(-74.05, 0.01, 0.0, 40.78, 0.0, -0.01),
        srid=4326,
        nodata=float(nodata),
    )


def test_writer_reader_roundtrip(tmp_path):
    r = _toy_raster()
    p = tmp_path / "toy.tif"
    write_geotiff(str(p), r)
    back = read_raster(str(p))
    np.testing.assert_array_equal(back.data, r.data)
    np.testing.assert_allclose(back.gt, r.gt, atol=1e-12)
    assert back.srid == 4326
    assert back.nodata == -9.0


@pytest.mark.parametrize("dtype", [np.uint8, np.uint16, np.int16, np.int32, np.float64])
def test_roundtrip_dtypes(tmp_path, dtype):
    r = _toy_raster(bands=1, dtype=dtype, nodata=0)
    p = tmp_path / "t.tif"
    write_geotiff(str(p), r)
    back = read_raster(str(p))
    np.testing.assert_array_equal(back.data, r.data)
    assert back.data.dtype == dtype


def test_modis_decode(modis_path):
    r = read_raster(modis_path)
    assert (r.width, r.height, r.num_bands) == (2400, 2400, 1)
    assert r.data.dtype == np.int16
    # MODIS sinusoidal 463.3127m pixels
    assert r.gt[1] == pytest.approx(463.3127, abs=1e-3)
    assert r.nodata == 32767
    b = r.band(1)
    assert 0.05 < b.mask.mean() < 0.2  # mostly-ocean tile
    assert b.min() >= 0
    assert r.metadata().get("_FillValue") == "32767"


def test_rst_accessors():
    r = _toy_raster()
    assert F.rst_width([r])[0] == 12 and F.rst_height([r])[0] == 10
    assert F.rst_numbands([r])[0] == 2
    assert F.rst_scalex([r])[0] == pytest.approx(0.01)
    assert F.rst_scaley([r])[0] == pytest.approx(-0.01)
    assert F.rst_upperleftx([r])[0] == pytest.approx(-74.05)
    assert F.rst_upperlefty([r])[0] == pytest.approx(40.78)
    assert F.rst_skewx([r])[0] == 0 == F.rst_skewy([r])[0]
    assert F.rst_pixelwidth([r])[0] == pytest.approx(0.01)
    assert F.rst_rotation([r])[0] == 0
    assert F.rst_srid([r])[0] == 4326
    assert F.rst_memsize([r])[0] == r.data.nbytes
    assert not F.rst_isempty([r])[0]
    assert F.rst_georeference([r])[0]["scaleX"] == pytest.approx(0.01)
    assert F.rst_summary([r])[0]["bands"] == 2
    assert F.rst_subdatasets([r])[0] == {}


def test_rst_coord_transforms():
    r = _toy_raster()
    # pixel (0,0) corner is the upper-left anchor
    xy = F.rst_rastertoworldcoord([r], 0, 0)[0]
    np.testing.assert_allclose(xy, [-74.05, 40.78])
    assert F.rst_rastertoworldcoordx([r], 3, 2)[0] == pytest.approx(-74.05 + 0.03)
    assert F.rst_rastertoworldcoordy([r], 3, 2)[0] == pytest.approx(40.78 - 0.02)
    # world -> raster floors to the containing pixel
    cr = F.rst_worldtorastercoord([r], -74.05 + 0.035, 40.78 - 0.025)[0]
    np.testing.assert_array_equal(cr, [3, 2])
    # mid-pixel probe (exact pixel edges are fp-boundary-sensitive, as in GDAL)
    assert F.rst_worldtorastercoordx([r], -74.0 + 0.005, 40.7)[0] == 5
    roundtrip = r.world_to_raster(*r.raster_to_world(7.25, 4.5))
    np.testing.assert_allclose(roundtrip, (7.25, 4.5), atol=1e-9)


def test_retile():
    r = _toy_raster(bands=1, h=10, w=12)
    tiles = F.rst_retile([r], 5, 4)
    assert len(tiles) == 3 * 3
    assert tiles[0].data.shape == (1, 4, 5)
    assert tiles[-1].data.shape == (1, 2, 2)  # edge crop
    # tile origin must map to the same world point as the parent pixel
    t = tiles[4]  # second row, second col -> pixel (5, 4)
    wx, wy = r.raster_to_world(5, 4)
    assert t.gt[0] == pytest.approx(wx) and t.gt[3] == pytest.approx(wy)
    # reassembled stats match
    total = sum(t.data.sum() for t in tiles)
    assert total == pytest.approx(r.data.sum(), rel=1e-6)


def test_raster_to_grid_combiners():
    idx = H3IndexSystem()
    r = _toy_raster(bands=1, h=16, w=16)
    avg = F.rst_rastertogridavg([r], 7, index=idx)[0][0]
    cnt = F.rst_rastertogridcount([r], 7, index=idx)[0][0]
    mn = F.rst_rastertogridmin([r], 7, index=idx)[0][0]
    mx = F.rst_rastertogridmax([r], 7, index=idx)[0][0]
    med = F.rst_rastertogridmedian([r], 7, index=idx)[0][0]
    assert set(avg) == set(cnt) == set(mn) == set(mx) == set(med)
    assert len(avg) >= 1
    # counts total = number of valid pixels
    valid = int(r.band(1).mask.sum())
    assert int(sum(cnt.values())) == valid
    for c in avg:
        assert mn[c] <= med[c] <= mx[c]
        assert mn[c] <= avg[c] <= mx[c]
    # oracle recompute for one cell
    cells = np.asarray(
        idx.point_to_cell(
            np.stack(r.pixel_centers(), axis=-1), 7
        )
    )
    vals = r.band(1).values.ravel().astype(np.float64)
    mask = r.band(1).mask.ravel()
    c0 = next(iter(avg))
    sel = (cells == c0) & mask
    assert avg[c0] == pytest.approx(vals[sel].mean())


def test_checkpoint_save(tmp_path):
    r = _toy_raster(bands=1)
    p = r.save_checkpoint(str(tmp_path / "ckpt"))
    back = read_raster(p)
    np.testing.assert_array_equal(back.data, r.data)


def test_reader_registry_gdal_and_grid(modis_path):
    meta = read("gdal").load(modis_path)
    assert meta[0]["xSize"] == 2400 and meta[0]["bandCount"] == 1
    idx = H3IndexSystem()
    # MODIS srid is user-defined (32767) -> treat coordinates as-is would be
    # wrong; pass rasterSrid override skipping transform is not meaningful
    # for sinusoidal, so use the toy raster through the full pipeline:
    r = _toy_raster(bands=1, h=16, w=16)
    import tempfile, os

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "t.tif")
        write_geotiff(path, r)
        grid = read("raster_to_grid").option("resolution", 7).option(
            "index", idx
        ).load(path)
        assert 1 in grid and len(grid[1]) >= 1
        ref = F.rst_rastertogridavg([r], 7, index=idx)[0][0]
        for c, v in grid[1].items():
            assert v == pytest.approx(ref[c], rel=1e-6)
        smoothed = read("raster_to_grid").option("resolution", 7).option(
            "index", idx
        ).option("kRingInterpolate", 1).load(path)
        assert set(smoothed[1]) >= set(grid[1])  # ring extends coverage
        for c, v in grid[1].items():
            assert smoothed[1][c] == pytest.approx(v)  # measured cells kept


def test_shapefile_reader(tmp_path):
    # build a tiny shapefile by hand (spec-conformant) and read it back
    import struct

    shp = tmp_path / "poly.shp"
    # one polygon record: CW square shell
    ring = [(0.0, 0.0), (0.0, 4.0), (4.0, 4.0), (4.0, 0.0), (0.0, 0.0)]
    rec = struct.pack("<i", 5)  # polygon
    rec += struct.pack("<4d", 0, 0, 4, 4)  # bbox
    rec += struct.pack("<ii", 1, len(ring))
    rec += struct.pack("<i", 0)
    for x, y in ring:
        rec += struct.pack("<dd", x, y)
    content = struct.pack(">ii", 1, len(rec) // 2) + rec
    header = struct.pack(">i", 9994) + b"\0" * 20
    header += struct.pack(">i", (100 + len(content)) // 2)
    header += struct.pack("<ii", 1000, 5)
    header += struct.pack("<8d", 0, 0, 4, 4, 0, 0, 0, 0)
    shp.write_bytes(header + content)
    t = read("shapefile").load(str(shp))
    assert len(t) == 1
    assert F.st_area(t.geometry, backend="oracle")[0] == pytest.approx(16.0)


def test_points_csv_reader(tmp_path):
    p = tmp_path / "pts.csv"
    p.write_text(
        "id,pickup_longitude,pickup_latitude\n1,-73.99,40.75\n2,-73.98,40.76\n"
    )
    t = read("csv_points").load(str(p))
    assert len(t) == 2
    np.testing.assert_allclose(
        F.st_x(t.geometry), [-73.99, -73.98]
    )


def test_multistrip_short_final_strip(tmp_path):
    # hand-built striped TIFF: height 10, RowsPerStrip 4 -> strips 4,4,2
    import struct

    h, w = 10, 6
    data = np.arange(h * w, dtype=np.uint8).reshape(h, w)
    strips = [data[0:4], data[4:8], data[8:10]]
    ifd_off = 8
    ntags = 8
    val_off = ifd_off + 2 + 12 * ntags + 4
    offsets_blob_off = val_off
    counts_blob_off = offsets_blob_off + 12
    pix_off = counts_blob_off + 12
    offs, cnts, cursor = [], [], pix_off
    for s in strips:
        offs.append(cursor)
        cnts.append(s.nbytes)
        cursor += s.nbytes
    out = bytearray(b"II*\0" + struct.pack("<I", ifd_off))
    out += struct.pack("<H", ntags)
    for tag, typ, cnt, val in [
        (256, 4, 1, w), (257, 4, 1, h), (258, 3, 1, 8), (259, 3, 1, 1),
        (262, 3, 1, 1), (273, 4, 3, offsets_blob_off), (278, 4, 1, 4),
        (279, 4, 3, counts_blob_off),
    ]:
        out += struct.pack("<HHII", tag, typ, cnt, val)
    out += struct.pack("<I", 0)
    out += struct.pack("<3I", *offs) + struct.pack("<3I", *cnts)
    for s in strips:
        out += s.tobytes()
    p = tmp_path / "strips.tif"
    p.write_bytes(bytes(out))
    r = read_raster(str(p))
    np.testing.assert_array_equal(r.data[0], data)


def test_southup_skew_roundtrip(tmp_path):
    # south-up + skewed geotransform must survive the checkpoint write
    r = _toy_raster(bands=1)
    r.gt = (100.0, 2.0, 0.5, 50.0, -0.25, 3.0)
    p = tmp_path / "skew.tif"
    write_geotiff(str(p), r)
    back = read_raster(str(p))
    np.testing.assert_allclose(back.gt, r.gt, atol=1e-12)


def test_raster_to_grid_tile_boundary_weighted_avg(tmp_path):
    import os

    from mosaic_tpu.readers import read
    from mosaic_tpu.core.index.h3 import H3IndexSystem

    idx = H3IndexSystem()
    r = _toy_raster(bands=1, h=16, w=16)
    p = tmp_path / "t.tif"
    write_geotiff(str(p), r)
    whole = read("raster_to_grid").option("resolution", 7).option(
        "index", idx
    ).option("retileSize", 1024).load(str(p))
    tiled = read("raster_to_grid").option("resolution", 7).option(
        "index", idx
    ).option("retileSize", 5).load(str(p))
    assert set(whole[1]) == set(tiled[1])
    for c, v in whole[1].items():
        assert tiled[1][c] == pytest.approx(v, rel=1e-9)


def test_unsupported_crs_raises():
    r = _toy_raster(bands=1)
    r.srid = 32767  # user-defined (e.g. sinusoidal)
    idx = H3IndexSystem()
    with pytest.raises(ValueError, match="SRID"):
        F.rst_rastertogridavg([r], 7, index=idx)


def test_lowercase_ext_listing(tmp_path):
    from mosaic_tpu.readers import read

    r = _toy_raster(bands=1)
    write_geotiff(str(tmp_path / "a.tif"), r)  # lowercase
    meta = read("gdal").load(str(tmp_path))
    assert len(meta) == 1 and meta[0]["bandCount"] == 1
