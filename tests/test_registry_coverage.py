"""Registry coverage: the committed registry golden, the docs, and the
perf_gate golden all agree with what the code actually emits — the
invariant the `registry-drift` rule enforces at lint time, pinned here
in the suite with explicit known names so a silent scanner regression
(e.g. the AST scan finding nothing) cannot pass as "no drift"."""

import json
import os

from mosaic_tpu.analysis import analyze, build_registry
from mosaic_tpu.analysis.project_registry import name_matches
from mosaic_tpu.analysis.rules.drift import span_table_names

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REGISTRY = os.path.join(ROOT, "tests", "goldens", "registry.json")


def load(path):
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def docs_text():
    chunks = [open(os.path.join(ROOT, "README.md"), encoding="utf-8").read()]
    docs = os.path.join(ROOT, "docs")
    for name in sorted(os.listdir(docs)):
        if name.endswith(".md"):
            chunks.append(
                open(os.path.join(docs, name), encoding="utf-8").read()
            )
    return "\n".join(chunks)


def test_committed_registry_matches_fresh_scan():
    fresh = build_registry(ROOT)
    committed = load(REGISTRY)
    for cat in (
        "fault_sites", "spans", "spans_tools", "events", "stages",
        "env_knobs",
    ):
        assert committed[cat] == fresh[cat], f"stale category {cat!r}"


def test_known_fault_sites_are_registered_and_documented():
    reg = load(REGISTRY)
    docs = docs_text()
    for site in (
        "pip_join.device", "stream.scan_step", "stream.snapshot",
        "stream.prefetch", "stream.admit", "serve.admit", "serve.batch",
        "serve.dispatch", "overlay.predicate", "dist_join.step",
        "knn.pair_distances",
    ):
        assert site in reg["fault_sites"], site
        assert site in docs, f"fault site {site!r} undocumented"


def test_known_dynamic_families_registered_as_wildcards():
    reg = load(REGISTRY)
    assert "join.probe.*" in reg["spans"]           # f-string span
    assert "MOSAIC_WATCHDOG_*" in reg["env_knobs"]  # per-site deadline
    assert "probe_stage.*" in reg["stages"]         # per-lane stage kwarg


def test_perf_gate_stages_are_registered_names():
    reg = load(REGISTRY)
    known = (
        reg["stages"] + reg["events"] + reg["spans"] + reg["spans_tools"]
    )
    gate = load(os.path.join(ROOT, "tests", "goldens", "perf_gate.json"))
    stages = sorted(gate["stages"])
    assert stages, "perf_gate golden has no stages"
    for stage in stages:
        assert name_matches(stage, known), f"unregistered gate stage {stage}"


def test_span_taxonomy_table_matches_code_both_ways():
    reg = load(REGISTRY)
    arch = open(
        os.path.join(ROOT, "docs", "ARCHITECTURE.md"), encoding="utf-8"
    ).read()
    table = span_table_names(arch)
    assert len(table) >= 10, "span table parse came back near-empty"
    for row in table:
        assert name_matches(row, reg["spans"]), f"stale table row {row!r}"
    for span in reg["spans"]:
        if span.endswith("*"):
            assert any(
                name_matches(row, [span]) for row in table
            ), f"span family {span!r} has no documented member"
        else:
            assert span in table, f"span {span!r} missing from the table"


def test_env_knobs_are_documented():
    reg = load(REGISTRY)
    docs = docs_text()
    assert reg["env_knobs"], "scan found no env knobs"
    for knob in reg["env_knobs"]:
        probe = knob[:-1] if knob.endswith("*") else knob
        assert probe in docs, f"env knob {knob!r} undocumented"


def test_registry_drift_rule_is_green_on_the_repo():
    res = analyze(ROOT, rule_names=["registry-drift"])
    assert res.findings == [], [f.render() for f in res.findings]
