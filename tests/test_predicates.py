"""Predicates: jnp vs host oracle, and Pallas kernel vs jnp reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core.geometry import oracle, predicates, wkt
from mosaic_tpu.core.geometry.device import pack_to_device
from mosaic_tpu.kernels import pip

import fixtures as fx


@pytest.fixture(scope="module")
def polys():
    return wkt.from_wkt(fx.POLY_WKT + fx.MULTIPOLY_WKT)


@pytest.fixture(scope="module")
def dev(polys):
    return pack_to_device(polys, dtype=jnp.float64)


def test_contains_matches_oracle(polys, dev):
    pts = fx.random_points(500, bbox=(-1, -2, 11, 11), seed=1)
    got = np.asarray(predicates.contains_xy(jnp.asarray(pts), dev))
    for g in range(len(polys)):
        want = oracle.contains_points(polys, g, pts)
        np.testing.assert_array_equal(got[:, g], want)


def test_contains_hole(dev):
    pts = jnp.array([[3.0, 3.0], [5.0, 5.0], [1.0, 1.0]])
    got = np.asarray(predicates.contains_xy(pts, dev))
    # geometry 1 is the square with a hole at [2,4]x[2,4]
    assert not got[0, 1]  # inside hole
    assert got[1, 1]
    assert got[2, 1]


def test_contains_multipolygon(dev):
    pts = jnp.array([[0.5, 0.5], [6.0, 6.0], [3.0, 3.0]])
    got = np.asarray(predicates.contains_xy(pts, dev))
    assert got[0, 3] and got[1, 3] and not got[2, 3]


def test_contains_gather(polys, dev):
    pts = fx.random_points(200, bbox=(-1, -2, 11, 11), seed=2)
    idx = np.random.default_rng(0).integers(0, len(polys), 200)
    got = np.asarray(
        predicates.contains_xy_gather(jnp.asarray(pts), jnp.asarray(idx), dev)
    )
    dense = np.asarray(predicates.contains_xy(jnp.asarray(pts), dev))
    np.testing.assert_array_equal(got, dense[np.arange(200), idx])


def test_bbox_prefilter_consistent(polys, dev):
    pts = fx.random_points(300, bbox=(-1, -2, 11, 11), seed=3)
    plain = np.asarray(predicates.contains_xy(jnp.asarray(pts), dev))
    pre = np.asarray(predicates.contains_xy_bbox(jnp.asarray(pts), dev))
    np.testing.assert_array_equal(plain, pre)


def test_intersects(dev):
    got = np.asarray(predicates.intersects(dev, dev))
    assert got.diagonal().all()
    # square [0,4]^2 vs 10x10-with-hole overlap
    assert got[0, 1]


def test_disjoint_squares():
    col = wkt.from_wkt(
        ["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))", "POLYGON ((5 5, 6 5, 6 6, 5 6, 5 5))"]
    )
    dev = pack_to_device(col, dtype=jnp.float64)
    got = np.asarray(predicates.intersects(dev, dev))
    assert not got[0, 1] and not got[1, 0]
    d = np.asarray(predicates.min_distance(dev, dev))
    np.testing.assert_allclose(d[0, 1], np.sqrt(32), rtol=1e-9)


def test_point_distance(dev):
    pts = jnp.array([[2.0, 2.0], [-3.0, 0.0]])
    d = np.asarray(predicates.points_min_dist(pts, dev))
    assert d[0, 0] == 0.0  # inside square
    np.testing.assert_allclose(d[1, 0], 3.0)  # 3 left of x=0 edge


# ------------------------------------------------------------------- pallas
def test_pallas_pip_matches_reference(polys, dev):
    pts = jnp.asarray(fx.random_points(777, bbox=(-1, -2, 11, 11), seed=4))
    planes, n_g = pip.edge_planes(dev)
    got = np.asarray(
        pip.pip_zone(pts, planes, n_g, tile_n=1024, tile_e=8, interpret=True)
    )
    want = np.asarray(pip.pip_zone_reference(pts, dev))
    np.testing.assert_array_equal(got, want)


def test_pallas_pip_unaligned_n(dev):
    pts = jnp.asarray(fx.random_points(100, bbox=(-1, -2, 11, 11), seed=5))
    planes, n_g = pip.edge_planes(dev)
    got = np.asarray(
        pip.pip_zone(pts, planes, n_g, tile_n=1024, tile_e=8, interpret=True)
    )
    assert got.shape == (100,)
    want = np.asarray(pip.pip_zone_reference(pts, dev))
    np.testing.assert_array_equal(got, want)


def test_pallas_pip_multiblock_g(dev):
    """More polygons than one g-block: min-accumulation across g blocks.

    tile_g=128 with G padded to 256 forces two g blocks in interpret mode.
    """
    pts = jnp.asarray(fx.random_points(512, bbox=(-1, -2, 11, 11), seed=6))
    planes, n_g = pip.edge_planes(dev, g_pad=256)
    got = np.asarray(
        pip.pip_zone(
            pts, planes, n_g, tile_n=1024, tile_e=8, tile_g=128, interpret=True
        )
    )
    want = np.asarray(pip.pip_zone_reference(pts, dev))
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="compiled Pallas path needs a real TPU",
)
def test_pallas_pip_compiled_tpu(polys, dev):
    """The kernel must COMPILE on TPU (not interpret) and agree."""
    pts = jnp.asarray(fx.random_points(2048, bbox=(-1, -2, 11, 11), seed=7))
    planes, n_g = pip.edge_planes(dev)
    got = np.asarray(pip.pip_zone(pts, planes, n_g))
    want = np.asarray(pip.pip_zone_reference(pts, dev))
    np.testing.assert_array_equal(got, want)
