"""Streaming join pipeline (sql/stream.py): the CPU-provable contracts.

The TPU numbers live in STREAM_1B artifacts; what must hold on any
backend is bit-identity and accounting:

1. cycling an HBM-resident ring through the scanned loop returns exactly
   the per-batch path's rows and stats (ring reuse changes nothing);
2. the double-buffered prefetch path equals the non-prefetch path (cell
   assignment is deterministic — pipelining changes scheduling, never
   values);
3. every pipeline stage emits a `stream_stage` telemetry event with a
   non-negative measured duration;
4. memory accounting never reports zero (the STREAM_1B_r05
   ``peak_hbm_bytes: 0`` artifact bug): when the backend exposes no
   memory stats, the live-buffer census lower-bounds the peak.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mosaic_tpu.core.geometry import wkt
from mosaic_tpu.core.index import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.runtime import telemetry
from mosaic_tpu.sql.join import build_chip_index, pip_join_points
from mosaic_tpu.sql.stream import (
    StreamJoin,
    fold_stats,
    generator_rate,
    hbm_peak,
    ring_from_host,
)

# the custom grid's cell pipeline is pure arithmetic — it keeps the
# scanned loop's compile cheap on CPU (the H3 digit pipeline costs
# minutes to compile here; the contracts are index-system-agnostic)
CUSTOM = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
RES = 3
ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), "
    "(5 5, 5 8, 8 8, 8 5, 5 5))",
    "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
    "MULTIPOLYGON (((-20 -20, -12 -20, -12 -12, -20 -12, -20 -20)), "
    "((-8 -8, -2 -8, -2 -2, -8 -2, -8 -8)))",
]
K, BATCH, NB = 3, 4096, 7  # NB > K: the ring must cycle


@pytest.fixture(scope="module")
def index():
    col = wkt.from_wkt(ZONES)
    return build_chip_index(
        tessellate(col, CUSTOM, RES, keep_core_geoms=False)
    )


@pytest.fixture(scope="module")
def ring():
    rng = np.random.default_rng(0)
    return ring_from_host(
        [rng.uniform((-25, -25), (35, 20), (BATCH, 2)) for _ in range(K)]
    )


@pytest.fixture(scope="module")
def sj(index):
    return StreamJoin(index, CUSTOM, RES, prefetch=True)


def test_ring_cycling_bit_identical_to_per_batch(index, ring, sj):
    """Scanned ring loop == one pip_join_points call per batch, row for
    row — including cycled slots (iterations K..NB-1 re-visit ring
    rows)."""
    res = sj.run(ring, NB, collect=True)
    assert res.outs.shape == (NB, BATCH)
    shift = np.asarray(index.border.shift, dtype=np.float64)
    dtype = index.border.verts.dtype
    for i in range(NB):
        pts = np.asarray(ring[i % K])
        cells = CUSTOM.point_to_cell(
            jnp.asarray(pts, dtype=jnp.float32), RES
        ).astype(jnp.int64)
        want = np.asarray(
            pip_join_points(
                jnp.asarray(pts - shift, dtype=dtype), cells, index
            )
        )
        np.testing.assert_array_equal(res.outs[i], want)
    assert res.matches == int((res.outs >= 0).sum())
    assert res.overflow == 0
    assert res.matches > 0  # the workload must actually hit polygons


def test_run_batched_matches_scanned_loop(ring, sj):
    rs = sj.run(ring, NB, collect=True)
    rb = sj.run_batched(ring, NB)
    np.testing.assert_array_equal(rs.outs, rb.outs)
    assert (rs.checksum, rs.matches, rs.overflow) == (
        rb.checksum, rb.matches, rb.overflow
    )


def test_prefetch_equals_non_prefetch(index, ring, sj):
    """Double-buffering the cell assignment must be invisible in the
    results (it only changes what overlaps what)."""
    sj0 = StreamJoin(index, CUSTOM, RES, prefetch=False)
    r1 = sj.run(ring, NB, collect=True)
    r0 = sj0.run(ring, NB, collect=True)
    np.testing.assert_array_equal(r1.outs, r0.outs)
    assert (r1.checksum, r1.matches, r1.overflow) == (
        r0.checksum, r0.matches, r0.overflow
    )
    assert r1.prefetch and not r0.prefetch


def test_step_stats_folds_step(ring, sj):
    out = sj.step(ring[0])
    want = np.asarray(fold_stats(out))
    got = np.asarray(sj.step_stats(ring[0]))
    np.testing.assert_array_equal(got, want)


def test_telemetry_stage_timings(index, ring):
    """Every stage event carries a non-negative measured duration."""
    with telemetry.capture() as events:
        sj = StreamJoin(index, CUSTOM, RES, prefetch=True)
        sj.compile(ring, 4)
        sj.run(ring, 4)
        generator_rate(
            lambda k: jax.random.uniform(k, (256, 2), dtype=jnp.float64),
            jax.random.PRNGKey(1), 3, 256,
        )
    stages = [e for e in events if e["event"] == "stream_stage"]
    names = {e["stage"] for e in stages}
    assert {"compile", "join_loop", "gen_compile", "gen_loop"} <= names
    for e in stages:
        assert e["seconds"] >= 0.0, e
    loop = [e for e in stages if e["stage"] == "join_loop"][0]
    assert loop["n_batches"] == 4 and loop["batch"] == BATCH
    assert loop["points_per_sec"] > 0


def test_ring_from_host_shape_and_residency(ring):
    assert ring.shape == (K, BATCH, 2)
    assert ring.dtype == jnp.float64


def test_hbm_peak_never_zero(ring):
    """The r05 artifact recorded peak_hbm_bytes: 0 — the census fallback
    must always see at least the resident ring."""
    peak, source = hbm_peak(fallback_arrays=[ring])
    assert peak > 0
    assert source  # a named source, never silent
