"""AOT TPU-platform lowering of the hot programs, runnable without a TPU.

`jax.jit(...).trace(...).lower(lowering_platforms=("tpu",))` runs the full
Mosaic/StableHLO lowering pipeline for the TPU target on any host — it is
the stage where round 2's Pallas kernel failed on hardware (invalid block
shapes) and where a stray f64 constant inside a kernel dies today. Keeping
these green on CPU CI means a TPU compile failure can only come from the
final XLA backend stage, not from our programs.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.core.index.h3 import H3IndexSystem
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.datasets import random_points, synthetic_zones
from mosaic_tpu.sql.join import build_chip_index, pip_join_points

BBOX = (-74.05, 40.60, -73.85, 40.78)


@pytest.fixture(scope="module")
def problem():
    h3 = H3IndexSystem()
    zones = synthetic_zones(4, 4, bbox=BBOX)
    table = tessellate(zones, h3, 7, keep_core_geoms=False)
    return h3, build_chip_index(table), len(zones)


def _tpu_lower(traced):
    return traced.lower(lowering_platforms=("tpu",)).as_text()


@pytest.mark.xfail(
    reason="this jax build's Mosaic lowering has no rule for integer "
    "min reductions inside the Pallas kernel (LoweringException in "
    "pallas/mosaic/lowering.py on the int32 jnp.min); lowers fine on "
    "newer jax — environment-bound, PR 3 triage",
    strict=False,
)
def test_pallas_pip_kernel_lowers_for_tpu():
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.geometry.device import pack_to_device
    from mosaic_tpu.kernels.pip import edge_planes, pip_zone

    polys = wkt.from_wkt(["POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))"] * 3)
    dev = pack_to_device(polys, dtype=jnp.float32)
    planes, n_g = edge_planes(dev)
    pts = jnp.zeros((2048, 2), jnp.float32)

    def f(points, planes):
        return pip_zone(points, planes, n_real_g=n_g)

    hlo = _tpu_lower(jax.jit(f).trace(pts, planes))
    assert "tpu_custom_call" in hlo  # the Pallas kernel actually lowered


def test_bench_step_lowers_for_tpu(problem):
    h3, index, _ = problem
    dtype = index.border.verts.dtype
    pts = jnp.asarray(random_points(16384, bbox=BBOX, seed=1))

    @functools.partial(jax.jit, static_argnames=("found_cap", "heavy_cap"))
    def step(points_f64, chip_index, found_cap, heavy_cap):
        cells = h3.point_to_cell(points_f64.astype(jnp.float32), 7)
        shifted = (points_f64 - chip_index.border.shift).astype(dtype)
        return pip_join_points(
            shifted,
            cells.astype(jnp.int64),
            chip_index,
            heavy_cap=heavy_cap,
            found_cap=found_cap,
        )

    hlo = _tpu_lower(step.trace(pts, index, 4096, 1024))
    assert len(hlo) > 1000


def test_dist_join_step_lowers_for_tpu(problem, devices):
    from mosaic_tpu.parallel import (
        distributed_join_step,
        make_mesh,
        pad_index_for_shards,
    )
    from mosaic_tpu.parallel.dist_join import pad_points

    h3, index, nz = problem
    mesh = make_mesh(8)
    idx = pad_index_for_shards(index, mesh.shape["cell"])
    pts = random_points(512, bbox=BBOX, seed=2)
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), 7))
    shifted = (pts - np.asarray(index.border.shift)).astype(
        np.asarray(index.border.verts).dtype
    )
    p, c = pad_points(shifted, cells, 8)
    step = distributed_join_step(
        mesh, nz, table_size=int(idx.table_cell.shape[0])
    )
    hlo = _tpu_lower(step.trace(jnp.asarray(p), jnp.asarray(c), idx))
    assert "all-gather" in hlo or "all_gather" in hlo  # ICI collective present
