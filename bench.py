"""North-star benchmark: NYC-style PIP join, points/sec on one chip.

Workload shape follows the reference Quickstart
(`notebooks/examples/scala/QuickstartNotebook.scala:149-216`): ~256 polygon
zones tiling the NYC bbox, tessellated to H3 chips; N random pickup points
get a cell id and join against the chip index (`is_core || contains`).

Prints ONE JSON line. ``vs_baseline`` is measured against a vectorized
NumPy implementation of the identical join (searchsorted + ray crossing) —
the stand-in for the reference's JTS codegen path on this machine, since the
reference publishes no numbers (SURVEY.md §6).
"""

from __future__ import annotations

import json
import time

import numpy as np

RES = 8
N_DEVICE = 4_000_000
N_BASE = 200_000
BATCH = 2_000_000


def _numpy_join(points, cells_sorted, rows, chip_geom, chip_core, verts, ring_len, pcells):
    """Pure-NumPy oracle of pip_join_points (vectorized over points)."""
    U = cells_sorted.shape[0]
    u = np.clip(np.searchsorted(cells_sorted, pcells), 0, U - 1)
    hit_cell = cells_sorted[u] == pcells
    cand = rows[u]  # (N, M)
    valid = hit_cell[:, None] & (cand >= 0)
    cand_safe = np.maximum(cand, 0)
    core = chip_core[cand_safe] & valid
    N, M = cand.shape
    G, R, V, _ = verts.shape
    inside = np.zeros((N, M), dtype=bool)
    px, py = points[:, 0], points[:, 1]
    for m in range(M):
        g = cand_safe[:, m]
        need = valid[:, m] & ~chip_core[cand_safe[:, m]]
        if not need.any():
            continue
        idx = np.nonzero(need)[0]
        gg = g[idx]
        x, y = px[idx], py[idx]
        cnt = np.zeros(idx.shape[0], dtype=np.int64)
        for r in range(R):
            L = ring_len[gg, r]  # (K,)
            for e in range(V - 1):
                live = e < L
                ax, ay = verts[gg, r, e, 0], verts[gg, r, e, 1]
                bx, by = verts[gg, r, e + 1, 0], verts[gg, r, e + 1, 1]
                cond = ((ay > y) != (by > y)) & (
                    x < ax + (y - ay) * (bx - ax) / np.where(by != ay, by - ay, 1.0)
                )
                cnt += (cond & live).astype(np.int64)
        inside[idx, m] = (cnt % 2).astype(bool)
    hit = core | (inside & valid)
    out = np.where(hit, chip_geom[cand_safe], np.iinfo(np.int32).max)
    best = out.min(axis=1)
    return np.where(best == np.iinfo(np.int32).max, -1, best)


def main():
    import jax
    import jax.numpy as jnp

    from mosaic_tpu.core.index.h3 import H3IndexSystem
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.datasets import random_points, synthetic_zones
    from mosaic_tpu.sql.join import build_chip_index, pip_join_points

    h3 = H3IndexSystem()
    zones = synthetic_zones(16, 16)
    t0 = time.perf_counter()
    table = tessellate(zones, h3, RES, keep_core_geoms=False)
    tess_s = time.perf_counter() - t0
    index = build_chip_index(table)

    pts = random_points(N_DEVICE, seed=11)
    shift = np.asarray(index.border.shift, dtype=np.float64)
    dtype = index.border.verts.dtype

    @jax.jit
    def step(points_f64, chip_index):
        cells = h3.point_to_cell(points_f64, RES)
        shifted = (points_f64 - chip_index.border.shift).astype(dtype)
        return pip_join_points(shifted, cells, chip_index)

    # warm up compile on one batch, then time steady-state batches
    first = jnp.asarray(pts[:BATCH])
    step(first, index).block_until_ready()
    t0 = time.perf_counter()
    outs = []
    for s in range(0, N_DEVICE, BATCH):
        outs.append(step(jnp.asarray(pts[s : s + BATCH]), index))
    for o in outs:
        o.block_until_ready()
    dev_s = time.perf_counter() - t0
    dev_rate = N_DEVICE / dev_s
    match = np.concatenate([np.asarray(o) for o in outs])

    # NumPy baseline on a subsample of the same workload
    sub = pts[:N_BASE]
    pcells = np.asarray(h3.point_to_cell(jnp.asarray(sub), RES))
    cells_sorted = np.asarray(index.cells)
    rows = np.asarray(index.chip_rows)
    verts = np.asarray(index.border.verts, dtype=np.float64)
    sub_shift = (sub - shift).astype(np.float64)
    t0 = time.perf_counter()
    base = _numpy_join(
        sub_shift,
        cells_sorted,
        rows,
        np.asarray(index.chip_geom),
        np.asarray(index.chip_core),
        verts,
        np.asarray(index.border.ring_len),
        pcells,
    )
    base_s = time.perf_counter() - t0
    base_rate = N_BASE / base_s
    agree = float((base == match[:N_BASE]).mean())

    print(
        json.dumps(
            {
                "metric": "nyc_pip_join_throughput",
                "value": round(dev_rate, 1),
                "unit": "points/sec/chip",
                "vs_baseline": round(dev_rate / base_rate, 2),
                "detail": {
                    "n_points": N_DEVICE,
                    "n_zones": len(zones),
                    "n_chips": len(table),
                    "h3_res": RES,
                    "device": str(jax.devices()[0]),
                    "device_s": round(dev_s, 3),
                    "numpy_points_per_sec": round(base_rate, 1),
                    "numpy_agreement": agree,
                    "tessellate_s": round(tess_s, 2),
                    "match_rate": round(float((match >= 0).mean()), 4),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
