"""North-star benchmark: NYC PIP join, points/sec on one chip.

Workload follows the reference Quickstart
(`notebooks/examples/scala/QuickstartNotebook.scala:149-216`): the
reference's own NYC taxi-zone fixture (when readable) is tessellated to H3
chips; N random pickup points get a cell id and join against the chip index
(`is_core || contains`). Falls back to synthetic zones of the same shape.

Prints ONE JSON line, always — including on backend failure (the TPU
tunnel on this rig can hang at init, so the backend is probed in a
subprocess with a timeout and the bench falls back to CPU rather than
recording nothing). ``vs_baseline`` compares against a vectorized NumPy
implementation of the identical join — the stand-in for the reference's
JTS codegen path, since the reference publishes no numbers (SURVEY.md §6).

Env knobs: MOSAIC_BENCH_PLATFORM=tpu|cpu (skip probe),
MOSAIC_BENCH_PROBE_TIMEOUT (s, default 120), MOSAIC_BENCH_POINTS.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

RES = 9
NYC_FIXTURE = "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"


def _numpy_join(points, cells_sorted, rows, chip_geom, chip_core, verts, ring_len, pcells):
    """Pure-NumPy oracle of pip_join_points (vectorized over points)."""
    U = cells_sorted.shape[0]
    u = np.clip(np.searchsorted(cells_sorted, pcells), 0, U - 1)
    hit_cell = cells_sorted[u] == pcells
    cand = rows[u]  # (N, M)
    valid = hit_cell[:, None] & (cand >= 0)
    cand_safe = np.maximum(cand, 0)
    core = chip_core[cand_safe] & valid
    N, M = cand.shape
    G, R, V, _ = verts.shape
    inside = np.zeros((N, M), dtype=bool)
    px, py = points[:, 0], points[:, 1]
    for m in range(M):
        g = cand_safe[:, m]
        need = valid[:, m] & ~chip_core[cand_safe[:, m]]
        if not need.any():
            continue
        idx = np.nonzero(need)[0]
        gg = g[idx]
        x, y = px[idx], py[idx]
        cnt = np.zeros(idx.shape[0], dtype=np.int64)
        for r in range(R):
            L = ring_len[gg, r]  # (K,)
            for e in range(V - 1):
                live = e < L
                ax, ay = verts[gg, r, e, 0], verts[gg, r, e, 1]
                bx, by = verts[gg, r, e + 1, 0], verts[gg, r, e + 1, 1]
                cond = ((ay > y) != (by > y)) & (
                    x < ax + (y - ay) * (bx - ax) / np.where(by != ay, by - ay, 1.0)
                )
                cnt += (cond & live).astype(np.int64)
        inside[idx, m] = (cnt % 2).astype(bool)
    hit = core | (inside & valid)
    out = np.where(hit, chip_geom[cand_safe], np.iinfo(np.int32).max)
    best = out.min(axis=1)
    return np.where(best == np.iinfo(np.int32).max, -1, best)


def _probe_platform() -> str:
    """Decide tpu vs cpu WITHOUT risking a hang in this process.

    The accelerator plugin on this rig can block indefinitely during
    backend init, so the probe runs in a subprocess with a hard timeout.
    """
    forced = os.environ.get("MOSAIC_BENCH_PLATFORM")
    if forced:
        return forced
    timeout = float(os.environ.get("MOSAIC_BENCH_PROBE_TIMEOUT", "120"))
    code = (
        "import jax, sys; d = jax.devices(); "
        "sys.exit(0 if d and d[0].platform not in ('cpu',) else 3)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout,
            capture_output=True,
        )
        return "tpu" if r.returncode == 0 else "cpu"
    except (subprocess.TimeoutExpired, OSError):
        return "cpu"


def _load_zones():
    """Reference NYC taxi-zone fixture if readable, else synthetic twins."""
    try:
        from mosaic_tpu.readers.vector import read_geojson

        col = read_geojson(NYC_FIXTURE).geometry
        if len(col):
            return col, "nyc_taxi_zones"
    except Exception:
        pass
    from mosaic_tpu.datasets import synthetic_zones

    return synthetic_zones(16, 16), "synthetic"


def main():
    detail: dict = {}
    t_start = time.perf_counter()
    try:
        platform = _probe_platform()
        if platform == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        import jax
        import jax.numpy as jnp

        from mosaic_tpu.core.index.h3 import H3IndexSystem
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.datasets import NYC_BBOX, random_points
        from mosaic_tpu.sql.join import build_chip_index, pip_join_points

        detail["device"] = str(jax.devices()[0])
        on_tpu = jax.devices()[0].platform not in ("cpu",)
        n_device = int(
            os.environ.get(
                "MOSAIC_BENCH_POINTS", 4_000_000 if on_tpu else 1_000_000
            )
        )
        batch = min(2_000_000, n_device)
        n_base = 200_000

        h3 = H3IndexSystem()
        zones, zones_src = _load_zones()
        b = zones.bounds()
        bbox = (
            float(np.nanmin(b[:, 0])),
            float(np.nanmin(b[:, 1])),
            float(np.nanmax(b[:, 2])),
            float(np.nanmax(b[:, 3])),
        )
        t0 = time.perf_counter()
        table = tessellate(zones, h3, RES, keep_core_geoms=False)
        detail["tessellate_s"] = round(time.perf_counter() - t0, 2)
        index = build_chip_index(table)
        detail.update(
            n_zones=len(zones), n_chips=len(table), h3_res=RES, zones=zones_src
        )

        pts = random_points(n_device, bbox=bbox, seed=11)
        shift = np.asarray(index.border.shift, dtype=np.float64)
        dtype = index.border.verts.dtype

        @jax.jit
        def step(points_f64, chip_index):
            cells = h3.point_to_cell(points_f64, RES)
            shifted = (points_f64 - chip_index.border.shift).astype(dtype)
            return pip_join_points(shifted, cells, chip_index)

        # warm up compile on one batch, then time steady-state batches
        first = jnp.asarray(pts[:batch])
        t0 = time.perf_counter()
        step(first, index).block_until_ready()
        detail["compile_s"] = round(time.perf_counter() - t0, 2)
        t0 = time.perf_counter()
        outs = []
        for s in range(0, n_device, batch):
            outs.append(step(jnp.asarray(pts[s : s + batch]), index))
        for o in outs:
            o.block_until_ready()
        dev_s = time.perf_counter() - t0
        dev_rate = n_device / dev_s
        match = np.concatenate([np.asarray(o) for o in outs])
        detail.update(
            n_points=n_device,
            device_s=round(dev_s, 3),
            match_rate=round(float((match >= 0).mean()), 4),
        )

        # Pallas zone-level kernel lane (the BASELINE.json north-star
        # kernel): brute-force PIP against every zone polygon, compiled
        # (not interpret) — only meaningful on a real TPU
        if on_tpu:
            try:
                from mosaic_tpu.core.geometry.device import pack_to_device
                from mosaic_tpu.kernels.pip import edge_planes, pip_zone

                zdev = pack_to_device(zones, dtype=jnp.float32, recenter=True)
                planes, n_real = edge_planes(zdev)
                zshift = np.asarray(zdev.shift, dtype=np.float64)
                n_pal = min(500_000, n_device)
                ppts = jnp.asarray((pts[:n_pal] - zshift).astype(np.float32))
                out = pip_zone(ppts, planes, n_real_g=n_real)
                out.block_until_ready()  # compile
                t0 = time.perf_counter()
                out = pip_zone(ppts, planes, n_real_g=n_real)
                out.block_until_ready()
                pal_s = time.perf_counter() - t0
                detail["pallas_points_per_sec"] = round(n_pal / pal_s, 1)
                detail["pallas_match_rate"] = round(
                    float((np.asarray(out) >= 0).mean()), 4
                )
            except Exception as e:  # kernel failure must not kill the bench
                detail["pallas_error"] = repr(e)[:200]

        # NumPy baseline on a subsample of the same workload
        sub = pts[:n_base]
        pcells = np.asarray(h3.point_to_cell(jnp.asarray(sub), RES))
        t0 = time.perf_counter()
        base = _numpy_join(
            (sub - shift).astype(np.float64),
            np.asarray(index.cells),
            np.asarray(index.chip_rows),
            np.asarray(index.chip_geom),
            np.asarray(index.chip_core),
            np.asarray(index.border.verts, dtype=np.float64),
            np.asarray(index.border.ring_len),
            pcells,
        )
        base_s = time.perf_counter() - t0
        base_rate = n_base / base_s
        detail["numpy_points_per_sec"] = round(base_rate, 1)
        detail["numpy_agreement"] = float((base == match[:n_base]).mean())

        print(
            json.dumps(
                {
                    "metric": "nyc_pip_join_throughput",
                    "value": round(dev_rate, 1),
                    "unit": "points/sec/chip",
                    "vs_baseline": round(dev_rate / base_rate, 2),
                    "detail": detail,
                }
            )
        )
    except Exception as e:  # always emit a parseable line
        detail["error"] = repr(e)[:500]
        detail["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(
            json.dumps(
                {
                    "metric": "nyc_pip_join_throughput",
                    "value": 0.0,
                    "unit": "points/sec/chip",
                    "vs_baseline": 0.0,
                    "detail": detail,
                }
            )
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
