"""North-star benchmark: NYC PIP join, points/sec on one chip.

Workload follows the reference Quickstart
(`notebooks/examples/scala/QuickstartNotebook.scala:149-216`): the
reference's own NYC taxi-zone fixture (when readable) is tessellated to H3
chips; N random pickup points get a cell id and join against the chip index
(`is_core || contains`). Falls back to synthetic zones of the same shape.

Prints ONE JSON line, always — including on backend failure (the TPU
tunnel on this rig can hang at init, so the backend is probed in a
subprocess with a timeout and the bench falls back to CPU rather than
recording nothing). If device compilation fails at the chosen batch size,
the batch is halved and retried (at least two fallback attempts) so a
number is always recorded. ``vs_baseline`` compares against a vectorized
NumPy implementation of the identical flat-edge join — the stand-in for the
reference's JTS codegen path, since the reference publishes no numbers
(SURVEY.md §6).

Env knobs: MOSAIC_BENCH_PLATFORM=tpu|cpu (skip probe),
MOSAIC_BENCH_PROBE_TIMEOUT (s, default 120), MOSAIC_BENCH_POINTS,
MOSAIC_BENCH_CELL_DTYPE=f32|f64 (default f32 — the fast H3 cell-assignment
path; ~0.2% of points within ~10cm of a res-9 cell edge may land in the
neighbor cell).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

RES = 9
NYC_FIXTURE = "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"
_I32_MAX = np.iinfo(np.int32).max


def _np_parity(px, py, e, bits):
    ax, ay, bx, by = e[..., 0], e[..., 1], e[..., 2], e[..., 3]
    st = (ay > py[:, None]) != (by > py[:, None])
    den = np.where(by == ay, 1.0, by - ay)
    xc = ax + (py[:, None] - ay) * (bx - ax) / den
    cr = st & (px[:, None] < xc)
    return np.bitwise_xor.reduce(
        np.where(cr, bits, np.uint32(0)).astype(np.uint32), axis=1
    )


def _numpy_join(points, index, pcells):
    """Pure-NumPy oracle of pip_join_points over the flat-edge layout."""
    cells_sorted = np.asarray(index.cells)
    cell_edges = np.asarray(index.cell_edges, dtype=np.float64)
    cell_ebits = np.asarray(index.cell_ebits)
    slot_geom = np.asarray(index.cell_slot_geom)
    slot_core = np.asarray(index.cell_slot_core)
    cell_heavy = np.asarray(index.cell_heavy)
    heavy_edges = np.asarray(index.heavy_edges, dtype=np.float64)
    heavy_ebits = np.asarray(index.heavy_ebits)
    heavy_geom = np.asarray(index.heavy_slot_geom)

    U = cells_sorted.shape[0]
    u = np.clip(np.searchsorted(cells_sorted, pcells), 0, U - 1)
    fidx = np.nonzero(cells_sorted[u] == pcells)[0]  # only found points pay
    uf = u[fidx]
    px, py = points[fidx, 0], points[fidx, 1]
    par = _np_parity(px, py, cell_edges[uf], cell_ebits[uf])
    M = slot_geom.shape[1]
    inside = ((par[:, None] >> np.arange(M, dtype=np.uint32)) & 1).astype(bool)
    g = slot_geom[uf]
    hit = (g >= 0) & (slot_core[uf] | inside)
    bestf = np.where(hit, g, _I32_MAX).min(axis=1)
    if heavy_edges.shape[0]:
        hs = cell_heavy[uf]
        rows = np.nonzero(hs >= 0)[0]
        if rows.size:
            h = hs[rows]
            par2 = _np_parity(px[rows], py[rows], heavy_edges[h], heavy_ebits[h])
            M2 = heavy_geom.shape[1]
            in2 = ((par2[:, None] >> np.arange(M2, dtype=np.uint32)) & 1).astype(
                bool
            )
            g2 = heavy_geom[h]
            b2 = np.where((g2 >= 0) & in2, g2, _I32_MAX).min(axis=1)
            bestf[rows] = np.minimum(bestf[rows], b2)
    best = np.full(points.shape[0], _I32_MAX, dtype=np.int64)
    best[fidx] = bestf
    return np.where(best == _I32_MAX, -1, best).astype(np.int32)


def _probe_platform() -> str:
    """Decide tpu vs cpu WITHOUT risking a hang in this process.

    The accelerator plugin on this rig can block indefinitely during
    backend init, so the probe runs in a subprocess with a hard timeout.
    """
    forced = os.environ.get("MOSAIC_BENCH_PLATFORM")
    if forced:
        return forced
    timeout = float(os.environ.get("MOSAIC_BENCH_PROBE_TIMEOUT", "120"))
    code = (
        "import jax, sys; d = jax.devices(); "
        "sys.exit(0 if d and d[0].platform not in ('cpu',) else 3)"
    )
    # a hung probe (tunnel hiccup) gets one retry after a pause — a CPU
    # fallback records a misleading number for the whole round; a clean
    # CPU verdict (rc != 0) or a deterministic spawn failure is final.
    # Worst case 2 * timeout + 20s.
    for attempt in range(2):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=timeout,
                capture_output=True,
            )
            return "tpu" if r.returncode == 0 else "cpu"
        except subprocess.TimeoutExpired:
            if attempt == 0:
                time.sleep(20)
        except OSError:
            break
    return "cpu"


_CACHE_VERSION = 4  # bump when ChipIndex layout changes


def _load_or_build_index(zones, zones_src: str, h3):
    """Tessellation is pure host work recomputed identically every run
    (~3s, ~20% of bench wall-clock noise): cache the built ChipIndex."""
    import jax.numpy as jnp

    from mosaic_tpu.core.geometry.device import DeviceGeometry
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import ChipIndex, build_chip_index

    import zlib

    xy = np.ascontiguousarray(np.asarray(zones.xy, dtype=np.float64))
    fp = zlib.crc32(xy.tobytes()) ^ zlib.crc32(bytes(str(len(zones)), "ascii"))
    key = f"{zones_src}-{RES}-v{_CACHE_VERSION}-{fp:08x}"
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache", key + ".npz")
    import dataclasses as _dc

    border_names = [f.name for f in _dc.fields(DeviceGeometry)]
    index_names = [
        f.name for f in _dc.fields(ChipIndex) if f.name != "border"
    ]
    if os.path.exists(cache):
        try:
            z = np.load(cache)
            border = DeviceGeometry(
                **{n: jnp.asarray(z[f"b_{n}"]) for n in border_names}
            )
            ix = ChipIndex(
                border=border,
                **{n: jnp.asarray(z[n]) for n in index_names},
            )
            return ix, True, None
        except Exception:
            pass  # stale/corrupt cache: rebuild
    t0 = time.perf_counter()
    table = tessellate(zones, h3, RES, keep_core_geoms=False)
    tess_only_s = time.perf_counter() - t0
    index = build_chip_index(table)
    try:
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        np.savez_compressed(
            cache,
            **{n: np.asarray(getattr(index, n)) for n in index_names},
            **{f"b_{n}": np.asarray(getattr(index.border, n))
               for n in border_names},
        )
    except OSError:
        pass
    return index, False, tess_only_s


def _load_zones():
    """Reference NYC taxi-zone fixture if readable, else synthetic twins."""
    try:
        from mosaic_tpu.readers.vector import read_geojson

        col = read_geojson(NYC_FIXTURE).geometry
        if len(col):
            return col, "nyc_taxi_zones"
    except Exception:
        pass
    from mosaic_tpu.datasets import synthetic_zones

    return synthetic_zones(16, 16), "synthetic"


def main():
    detail: dict = {}
    t_start = time.perf_counter()
    try:
        platform = _probe_platform()
        if platform == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        import jax
        import jax.numpy as jnp

        from mosaic_tpu.core.index.h3 import H3IndexSystem
        from mosaic_tpu.datasets import random_points
        from mosaic_tpu.sql.join import pip_join_points

        detail["device"] = str(jax.devices()[0])
        on_tpu = jax.devices()[0].platform not in ("cpu",)
        n_device = int(
            os.environ.get(
                "MOSAIC_BENCH_POINTS", 4_000_000 if on_tpu else 1_000_000
            )
        )
        n_base = 200_000
        cell_dtype = (
            jnp.float32
            if os.environ.get("MOSAIC_BENCH_CELL_DTYPE", "f32") == "f32"
            else jnp.float64
        )

        h3 = H3IndexSystem()
        zones, zones_src = _load_zones()
        b = zones.bounds()
        bbox = (
            float(np.nanmin(b[:, 0])),
            float(np.nanmin(b[:, 1])),
            float(np.nanmax(b[:, 2])),
            float(np.nanmax(b[:, 3])),
        )
        t0 = time.perf_counter()
        index, cache_hit, tess_only_s = _load_or_build_index(
            zones, zones_src, h3
        )
        # on a hit this is npz-load time, NOT tessellation speed — the
        # flag keeps cross-round comparisons honest
        tess_s = time.perf_counter() - t0
        detail["tessellate_s"] = round(tess_s, 2)
        detail["tessellate_cache_hit"] = cache_hit
        if tess_only_s:
            # BASELINE's secondary metric: H3 tessellate chips/sec —
            # timed around tessellate() alone (not index build or the
            # cache write), and only when actually computed
            detail["tessellate_chips_per_sec"] = round(
                int(index.chip_geom.shape[0]) / tess_only_s, 1
            )
        detail.update(
            n_zones=len(zones),
            n_chips=int(index.chip_geom.shape[0]),
            h3_res=RES,
            zones=zones_src,
            n_heavy_cells=index.num_heavy_cells,
            edge_cap=int(index.cell_edges.shape[1]),
        )

        pts = random_points(n_device, bbox=bbox, seed=11)
        shift = np.asarray(index.border.shift, dtype=np.float64)
        dtype = index.border.verts.dtype

        import functools

        index_cells = np.asarray(index.cells)

        @jax.jit
        def cells_of(points_f64):
            c = h3.point_to_cell(points_f64.astype(cell_dtype), RES)
            return c.astype(jnp.int64)

        @functools.partial(jax.jit, static_argnames=("found_cap", "heavy_cap"))
        def step(points_f64, chip_index, found_cap, heavy_cap):
            cells = h3.point_to_cell(points_f64.astype(cell_dtype), RES)
            shifted = (points_f64 - chip_index.border.shift).astype(dtype)
            return pip_join_points(
                shifted,
                cells.astype(jnp.int64),
                chip_index,
                heavy_cap=heavy_cap,
                found_cap=found_cap,
            )

        def bucket(n):
            """128k-multiple buckets above 128k (pow2 below): tighter than
            pure pow2 — a 530k estimate caps at 640k, not 1M, and cap size
            directly scales the tier-1 gather and scatter-back cost."""
            if n <= 131072:
                return max(16, 1 << int(np.ceil(np.log2(n + 1))))
            return (n + 131071) // 131072 * 131072

        def caps_for(cnp, margin, clamp):
            """Bucketed compaction caps from host-side counts, with a
            safety margin so one presample sizes every batch (an overflow
            (-2) in any output triggers a redo at doubled caps)."""
            pos = np.clip(
                np.searchsorted(index_cells, cnp), 0, index_cells.size - 1
            )
            fnp = index_cells[pos] == cnp
            n_found = int(fnp.sum() * margin)
            fcap = min(bucket(n_found), clamp)
            hcap = None
            if index.num_heavy_cells:
                hmask = np.asarray(index.cell_heavy) >= 0
                n_heavy = int(np.isin(cnp[fnp], index_cells[hmask]).sum() * margin)
                hcap = min(bucket(n_heavy), fcap)
            return fcap, hcap, float(fnp.mean())

        # size the compaction caps once from a host presample (the timed
        # loop then runs sync-free); scale counts to the batch size
        batch = min(4_000_000, n_device)
        pre = np.asarray(cells_of(jnp.asarray(pts[:n_base])))
        fcap, hcap, ffrac = caps_for(
            pre, margin=1.5 * batch / n_base, clamp=batch
        )

        # warm up compile on one batch; on compile failure halve the batch
        # and retry so the bench always records a real number
        attempts = []
        while True:
            try:
                first = jnp.asarray(pts[:batch])
                t0 = time.perf_counter()
                step(first, index, fcap, hcap).block_until_ready()
                detail["compile_s"] = round(time.perf_counter() - t0, 2)
                break
            except Exception as e:
                attempts.append({"batch": batch, "error": repr(e)[:200]})
                if batch <= 125_000:
                    raise
                batch //= 2
                fcap = min(fcap, batch)
                hcap = min(hcap, fcap) if hcap else hcap
        if attempts:
            detail["compile_attempts"] = attempts
        detail["batch"] = batch
        detail["caps"] = [fcap, hcap]

        # pre-stage input batches in HBM (a real pipeline overlaps host
        # ingest with device compute; the metric is the join itself)
        staged = [
            jax.device_put(jnp.asarray(pts[s : s + batch]))
            for s in range(0, n_device, batch)
        ]
        for sbatch in staged:
            sbatch.block_until_ready()

        def run_all():
            outs = [step(sb, index, fcap, hcap) for sb in staged]
            for o in outs:
                o.block_until_ready()
            return outs

        def timed_run():
            t0 = time.perf_counter()
            outs = run_all()
            return time.perf_counter() - t0, outs

        # best of two passes: single-dispatch runs carry ~±10% of rig
        # noise (tunnel RTT, host scheduling) that min() strips
        dev_s, outs = timed_run()
        dev_s2, outs = timed_run()
        dev_s = min(dev_s, dev_s2)
        match = np.concatenate([np.asarray(o) for o in outs])
        if (match == -2).any():  # compaction cap overflow: redo, larger caps
            fcap = min(fcap * 2, batch)
            hcap = min((hcap or 16) * 2, fcap)
            detail["caps_redo"] = [fcap, hcap]
            timed_run()  # discard: the changed static caps recompile here
            dev_s, outs = timed_run()
            dev_s2, outs = timed_run()
            dev_s = min(dev_s, dev_s2)
            match = np.concatenate([np.asarray(o) for o in outs])
        dev_rate = n_device / dev_s
        # probe traffic: found points pay the tier-1 flat edge gather
        # (20 B/edge), heavy-cell points additionally the tier-2 row — the
        # HBM roofline of the join (misses stop at the 96 B hash bucket)
        e1 = int(index.cell_edges.shape[1])
        e2 = int(index.heavy_edges.shape[1]) if index.num_heavy_cells else 0
        hfrac = float((np.asarray(index.cell_heavy) >= 0).mean())
        bpp = 96 + 20.0 * (e1 + e2 * hfrac) * ffrac
        detail.update(
            n_points=n_device,
            device_s=round(dev_s, 3),
            match_rate=round(float((match >= 0).mean()), 4),
            found_rate=round(ffrac, 4),
            overflow=int((match == -2).sum()),
            roofline=(
                f"~{bpp:.0f} B/pt probe traffic -> "
                f"{bpp * dev_rate / 1e9:.0f} GB/s achieved vs ~800 GB/s "
                f"v5e HBM; heavy cells {hfrac:.1%} of {index.num_cells}"
            ),
        )

        # Pallas zone-level kernel lane (the BASELINE.json north-star
        # kernel): brute-force PIP against every zone polygon, compiled
        # (not interpret) — only meaningful on a real TPU
        if on_tpu:
            try:
                from mosaic_tpu.core.geometry.device import pack_to_device
                from mosaic_tpu.kernels.pip import edge_planes, pip_zone

                zdev = pack_to_device(zones, dtype=jnp.float32, recenter=True)
                planes, n_real = edge_planes(zdev)
                zshift = np.asarray(zdev.shift, dtype=np.float64)
                n_pal = min(500_000, n_device)
                ppts = jnp.asarray((pts[:n_pal] - zshift).astype(np.float32))
                out = pip_zone(ppts, planes, n_real_g=n_real)
                out.block_until_ready()  # compile
                t0 = time.perf_counter()
                out = pip_zone(ppts, planes, n_real_g=n_real)
                out.block_until_ready()
                pal_s = time.perf_counter() - t0
                detail["pallas_points_per_sec"] = round(n_pal / pal_s, 1)
                detail["pallas_match_rate"] = round(
                    float((np.asarray(out) >= 0).mean()), 4
                )
            except Exception as e:  # kernel failure must not kill the bench
                detail["pallas_error"] = repr(e)[:200]

        # NumPy baseline on a subsample of the same workload (same flat
        # layout, same cell assignment — the single-core competitor)
        sub = pts[:n_base]
        pcells = np.asarray(
            h3.point_to_cell(jnp.asarray(sub, dtype=cell_dtype), RES)
        ).astype(np.int64)
        t0 = time.perf_counter()
        base = _numpy_join((sub - shift).astype(np.float64), index, pcells)
        base_s = time.perf_counter() - t0
        base_rate = n_base / base_s
        detail["numpy_points_per_sec"] = round(base_rate, 1)
        agree = base == match[:n_base]
        detail["numpy_agreement"] = float(agree.mean())

        print(
            json.dumps(
                {
                    "metric": "nyc_pip_join_throughput",
                    "value": round(dev_rate, 1),
                    "unit": "points/sec/chip",
                    "vs_baseline": round(dev_rate / base_rate, 2),
                    "detail": detail,
                }
            )
        )
    except Exception as e:  # always emit a parseable line
        detail["error"] = repr(e)[:500]
        detail["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(
            json.dumps(
                {
                    "metric": "nyc_pip_join_throughput",
                    "value": 0.0,
                    "unit": "points/sec/chip",
                    "vs_baseline": 0.0,
                    "detail": detail,
                }
            )
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
