"""North-star benchmark: NYC PIP join, points/sec on one chip.

Workload follows the reference Quickstart
(`notebooks/examples/scala/QuickstartNotebook.scala:149-216`): the
reference's own NYC taxi-zone fixture (when readable) is tessellated to H3
chips; N random pickup points get a cell id and join against the chip index
(`is_core || contains`). Falls back to synthetic zones of the same shape.

Prints ONE JSON line, always — including on backend failure.

Acquisition protocol (the TPU tunnel on this rig can hang at init for
many minutes):
- the platform probe runs in a subprocess that must COMPILE AND RUN a tiny
  jit op on the accelerator, not just list devices;
- a hung or transiently-failing probe retries with exponential backoff
  inside a total budget (default 480 s, per-attempt timeout 120 s);
  every attempt and its outcome is recorded in ``detail.probe``;
- a clean CPU verdict (no accelerator registered) is final, no retries;
- after a CPU-fallback measurement completes, ONE late probe runs; if the
  TPU came back meanwhile the whole bench re-executes on it and that line
  is printed instead (``detail.late_retry_from_cpu`` marks it).

Timing protocol (see docs/ARCHITECTURE.md measurement doctrine —
``block_until_ready`` is leaky on this rig and identical (fn, input)
re-executions can return cached results):
- N passes (default 3) each over DISTINCT pre-staged input batches;
- completion of every batch is forced by a device-side full-bit XOR-fold
  to one scalar whose value is pulled with ``float(...)``;
- the fixed sync round-trip (measured as the min of three scalar pulls of
  precomputed values, ~28 ms over the tunnel) is subtracted from each pass;
- the reported time is the min over the N non-identical passes; raw pass
  times are recorded in ``detail.passes_s``.

``vs_baseline`` compares against the single-thread C++ host join
(`native/src/evalgeom.cpp mg_eval_pip_join`, detail.baseline_kind =
native_cpp_single_thread) — the honest analog of the reference's JTS
codegen row path, since the reference publishes no numbers (SURVEY.md
§6); the vectorized NumPy lane is also reported
(detail.numpy_points_per_sec), and is the fallback baseline when the
native toolchain is unavailable.

Env knobs: MOSAIC_BENCH_PLATFORM=tpu|cpu (skip probe),
MOSAIC_BENCH_PROBE_TIMEOUT (s/attempt, default 120),
MOSAIC_BENCH_PROBE_BUDGET (s total, default 480), MOSAIC_BENCH_POINTS,
MOSAIC_BENCH_PASSES (default 3), MOSAIC_BENCH_SCALE_POINTS (default 16M,
TPU only), MOSAIC_BENCH_CELL_DTYPE=f32|f64 (default f32 — the fast H3
cell-assignment path; every run quantifies its cost end to end:
``detail.cell_f32_f64_agreement`` counts points assigned a different cell
than the f64 path, ``detail.join_f32_f64_agreement`` counts join results
that actually differ, with a 0.998 floor flagged on violation).
"""

from __future__ import annotations

import datetime
import functools
import json
import os
import subprocess
import sys
import time

import numpy as np

RES = 9
NYC_FIXTURE = "/root/reference/src/test/resources/NYC_Taxi_Zones.geojson"
_I32_MAX = np.iinfo(np.int32).max

_T0 = time.perf_counter()


_PARTIAL_PATH = os.environ.get("MOSAIC_BENCH_PARTIAL")


class _QuickSkip(Exception):
    """Raised inside optional lanes when MOSAIC_BENCH_QUICK is set."""


def _prog(msg: str) -> None:
    """Stderr progress mark (stdout carries only the JSON line). The
    tunnel makes some compiles minutes-long; without these marks a slow
    lane is indistinguishable from a hang.

    When MOSAIC_BENCH_PARTIAL names a file, the current ``detail`` dict is
    also checkpointed there at every mark — the tunnel can die mid-bench
    (observed 2026-07-31: alive at 01:01, hung at 01:33), and a partial
    artifact with the main-lane number beats losing the whole run."""
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)
    detail = getattr(_prog, "detail", None)
    if _PARTIAL_PATH and detail is not None:
        try:
            tmp = _PARTIAL_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"stage": msg, "detail": detail}, f,
                          indent=1, default=str)
            os.replace(tmp, _PARTIAL_PATH)
        except Exception:  # noqa: BLE001 — best-effort: a salvage helper
            pass           # must never be what kills the bench


def _np_parity(px, py, e, bits):
    # single source of truth for the host parity lives in the library
    from mosaic_tpu.sql.join import _np_parity as lib_parity

    return lib_parity(px, py, e, bits)


def _numpy_join(points, index, pcells):
    """Pure-NumPy oracle of pip_join_points over the flat-edge layout."""
    cells_sorted = np.asarray(index.cells)
    cell_edges = np.asarray(index.cell_edges, dtype=np.float64)
    cell_ebits = np.asarray(index.cell_ebits)
    slot_geom = np.asarray(index.cell_slot_geom)
    slot_core = np.asarray(index.cell_slot_core)
    cell_heavy = np.asarray(index.cell_heavy)
    heavy_edges = np.asarray(index.heavy_edges, dtype=np.float64)
    heavy_ebits = np.asarray(index.heavy_ebits)
    heavy_geom = np.asarray(index.heavy_slot_geom)

    U = cells_sorted.shape[0]
    u = np.clip(np.searchsorted(cells_sorted, pcells), 0, U - 1)
    fidx = np.nonzero(cells_sorted[u] == pcells)[0]  # only found points pay
    uf = u[fidx]
    px, py = points[fidx, 0], points[fidx, 1]
    par = _np_parity(px, py, cell_edges[uf], cell_ebits[uf])
    M = slot_geom.shape[1]
    inside = ((par[:, None] >> np.arange(M, dtype=np.uint32)) & 1).astype(bool)
    g = slot_geom[uf]
    hit = (g >= 0) & (slot_core[uf] | inside)
    bestf = np.where(hit, g, _I32_MAX).min(axis=1)
    if heavy_edges.shape[0]:
        hs = cell_heavy[uf]
        rows = np.nonzero(hs >= 0)[0]
        if rows.size:
            h = hs[rows]
            par2 = _np_parity(px[rows], py[rows], heavy_edges[h], heavy_ebits[h])
            M2 = heavy_geom.shape[1]
            in2 = ((par2[:, None] >> np.arange(M2, dtype=np.uint32)) & 1).astype(
                bool
            )
            g2 = heavy_geom[h]
            b2 = np.where((g2 >= 0) & in2, g2, _I32_MAX).min(axis=1)
            bestf[rows] = np.minimum(bestf[rows], b2)
    best = np.full(points.shape[0], _I32_MAX, dtype=np.int64)
    best[fidx] = bestf
    return np.where(best == _I32_MAX, -1, best).astype(np.int32)


# the probe must exercise the full accelerator path — devices() alone can
# succeed while compilation hangs (observed round 2: HTTP 500 at compile)
_PROBE_CODE = """
import json, sys, time
t0 = time.time()
import jax, jax.numpy as jnp
devs = jax.devices()
t1 = time.time()
if devs[0].platform in ("cpu",):
    print(json.dumps({"platform": "cpu", "devices_s": round(t1 - t0, 2)}))
    sys.exit(3)
x = jnp.arange(1024, dtype=jnp.int32)
r = int(jax.jit(lambda v: ((v * v + 1) ^ (v >> 7)).sum())(x))
t2 = time.time()
print(json.dumps({
    "platform": str(devs[0].platform), "device": str(devs[0]),
    "devices_s": round(t1 - t0, 2), "compile_run_s": round(t2 - t1, 2),
}))
sys.exit(0 if r == int(((x * x + 1) ^ (x >> 7)).sum()) else 4)
"""


def _probe_once(timeout: float, rec: dict) -> str | None:
    """One subprocess probe attempt; returns a platform verdict or None
    (None = inconclusive, worth retrying)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            timeout=timeout,
            capture_output=True,
            text=True,
        )
        lines = r.stdout.strip().splitlines()
        if lines:
            try:
                rec.update(json.loads(lines[-1]))
            except ValueError:
                rec["stdout"] = lines[-1][:120]
        if r.returncode == 0:
            rec["outcome"] = "tpu"
            return "tpu"
        if r.returncode == 3:
            # deterministic: jax has no accelerator registered — final
            rec["outcome"] = "cpu_verdict"
            return "cpu"
        rec["outcome"] = f"error_rc{r.returncode}"
        rec["stderr"] = r.stderr[-200:]
        return None  # plugin error (e.g. compile HTTP 500) may be transient
    except subprocess.TimeoutExpired:
        rec["outcome"] = f"hang_timeout_{timeout:.0f}s"
        return None
    except OSError as e:
        rec["outcome"] = f"spawn_error:{e!r}"[:120]
        return "cpu"


def _probe_platform(detail: dict) -> str:
    """Decide tpu vs cpu WITHOUT risking a hang in this process.

    Retries hung/erroring probes with exponential backoff (the shared
    `mosaic_tpu.runtime.retry` schedule) inside a total budget; the full
    attempt trail lands in ``detail["probe"]``.
    """
    from mosaic_tpu.runtime.retry import RetryPolicy, backoff_delays

    trail: list[dict] = []
    detail["probe"] = trail
    forced = os.environ.get("MOSAIC_BENCH_PLATFORM")
    if forced:
        trail.append({"outcome": f"forced:{forced}"})
        return forced
    per = float(os.environ.get("MOSAIC_BENCH_PROBE_TIMEOUT", "120"))
    budget = float(os.environ.get("MOSAIC_BENCH_PROBE_BUDGET", "480"))
    t_start = time.monotonic()
    delays = backoff_delays(
        RetryPolicy(
            max_attempts=1 << 30, base_delay_s=15.0, max_delay_s=120.0,
            timeout_s=budget, jitter=0.25,
        )
    )
    attempt = 0
    while True:
        attempt += 1
        rec = {"attempt": attempt, "t_s": round(time.monotonic() - t_start, 1)}
        trail.append(rec)
        verdict = _probe_once(per, rec)
        if verdict is not None:
            return verdict
        backoff = next(delays)
        if time.monotonic() - t_start + backoff + per > budget:
            trail.append(
                {"outcome": "budget_exhausted", "budget_s": budget}
            )
            return "cpu"
        time.sleep(backoff)


def _maybe_late_tpu_retry(obj: dict) -> dict:
    """After a CPU fallback caused by a hung tunnel, probe once more; if
    the TPU came back, re-run the whole bench on it and return that line."""
    detail = obj.get("detail", {})
    if os.environ.get("MOSAIC_BENCH_NO_REEXEC") or os.environ.get(
        "MOSAIC_BENCH_PLATFORM"
    ):
        return obj
    trail = detail.get("probe", [])
    fell_back = any(
        str(r.get("outcome", "")).startswith(("hang_timeout", "error_rc", "budget"))
        for r in trail
    )
    if not fell_back or detail.get("platform") == "tpu":
        return obj
    rec: dict = {}
    verdict = _probe_once(
        float(os.environ.get("MOSAIC_BENCH_PROBE_TIMEOUT", "120")), rec
    )
    detail["late_probe"] = rec
    if verdict != "tpu":
        return obj
    env = dict(os.environ)
    env.update(MOSAIC_BENCH_PLATFORM="tpu", MOSAIC_BENCH_NO_REEXEC="1")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            timeout=900,
            capture_output=True,
            text=True,
        )
        line = json.loads(r.stdout.strip().splitlines()[-1])
        if line.get("value", 0) > 0:
            line.setdefault("detail", {})["late_retry_from_cpu"] = True
            line["detail"]["cpu_fallback_value"] = obj.get("value")
            return line
        detail["late_retry_error"] = "tpu rerun emitted no usable number"
    except Exception as e:
        detail["late_retry_error"] = repr(e)[:200]
    return obj


#: nominal HBM bandwidth per chip, GB/s, keyed by device_kind substring
#: (checked in order — "v5p" before "v5" matters)
_HBM_PEAK_GBPS = (
    ("v6e", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
)


def _hbm_peak_gbps():
    """Peak HBM GB/s of device 0, or None off-TPU / unknown kind — the
    roofline then reports achieved GB/s without a %-of-peak figure."""
    import jax

    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for pat, peak in _HBM_PEAK_GBPS:
        if pat in kind:
            return peak
    return None


_CACHE_VERSION = 7  # bump when ChipIndex/HostRecheck layout changes


def _load_or_build_index(zones, zones_src: str, h3):
    """Tessellation is pure host work recomputed identically every run
    (~3s, ~20% of bench wall-clock noise): cache the built ChipIndex."""
    import jax.numpy as jnp

    from mosaic_tpu.core.geometry.device import DeviceGeometry
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import ChipIndex, HostRecheck, build_chip_index

    import zlib

    xy = np.ascontiguousarray(np.asarray(zones.xy, dtype=np.float64))
    fp = zlib.crc32(xy.tobytes()) ^ zlib.crc32(bytes(str(len(zones)), "ascii"))
    key = f"{zones_src}-{RES}-v{_CACHE_VERSION}-{fp:08x}"
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache", key + ".npz")
    import dataclasses as _dc

    border_names = [f.name for f in _dc.fields(DeviceGeometry)]
    index_names = [
        f.name for f in _dc.fields(ChipIndex) if f.name != "border"
    ]
    if os.path.exists(cache):
        try:
            z = np.load(cache)
            border = DeviceGeometry(
                **{n: jnp.asarray(z[f"b_{n}"]) for n in border_names}
            )
            ix = ChipIndex(
                border=border,
                **{n: jnp.asarray(z[n]) for n in index_names},
            )
            ix.host = HostRecheck.from_arrays(z)  # f64 recheck companion
            return ix, True, None
        except Exception:
            pass  # stale/corrupt cache: rebuild
    t0 = time.perf_counter()
    table = tessellate(zones, h3, RES, keep_core_geoms=False)
    tess_only_s = time.perf_counter() - t0
    index = build_chip_index(table)
    try:
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        np.savez_compressed(
            cache,
            **{n: np.asarray(getattr(index, n)) for n in index_names},
            **{f"b_{n}": np.asarray(getattr(index.border, n))
               for n in border_names},
            **index.host.save_arrays(),
        )
    except OSError:
        pass
    return index, False, tess_only_s


def _load_zones():
    """Reference NYC taxi-zone fixture if readable, else synthetic twins."""
    try:
        from mosaic_tpu.readers.vector import read_geojson

        col = read_geojson(NYC_FIXTURE).geometry
        if len(col):
            return col, "nyc_taxi_zones"
    except Exception:
        pass
    from mosaic_tpu.datasets import synthetic_zones

    return synthetic_zones(16, 16), "synthetic"


def main():
    detail: dict = {
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        )
    }
    t_start = time.perf_counter()
    _prog.detail = detail  # type: ignore[attr-defined] — partial checkpoints

    # artifact hygiene (BENCH_r05 "parsed: null"): the metric JSON must be
    # the LAST stdout line, single-line, always. Anything any library
    # prints to stdout mid-run (probe/retry chatter, backend warnings)
    # diverts to stderr; only _emit writes to the real stdout.
    emit_to = sys.stdout
    sys.stdout = sys.stderr

    # MOSAIC_BENCH_TRAIL=/path.jsonl captures the full telemetry trail
    # (join.pip spans, recheck/escalation/retry events, stage timings)
    # and exports it at emit — feed it to tools/trace_report.py or
    # tools/perf_gate.py
    trail_path = os.environ.get("MOSAIC_BENCH_TRAIL")
    trail_events: list = []
    if trail_path:
        from mosaic_tpu.runtime import telemetry as _telemetry

        _telemetry.current_sinks().append(trail_events)

    def _emit(obj: dict) -> None:
        if trail_path:
            try:
                from mosaic_tpu.obs import write_jsonl as _write_jsonl

                _write_jsonl(trail_events, trail_path)
                obj.setdefault("detail", {})["trail"] = trail_path
            except Exception as e:  # the artifact line must still emit
                obj.setdefault("detail", {})["trail_error"] = repr(e)[:200]
        obj.setdefault("detail", {}).setdefault("device", "unknown")
        emit_to.write(json.dumps(obj) + "\n")
        emit_to.flush()
    try:
        platform = _probe_platform(detail)
        _prog(f"platform verdict: {platform}")
        if platform == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        import jax
        import jax.numpy as jnp

        from mosaic_tpu.core.index.h3 import H3IndexSystem
        from mosaic_tpu.datasets import random_points
        from mosaic_tpu.sql.join import pip_join_points

        detail["device"] = str(jax.devices()[0])
        _prog(f"device: {detail['device']}")
        on_tpu = jax.devices()[0].platform not in ("cpu",)
        # the measured platform, recorded explicitly: device strings on this
        # rig ('axon') need not contain 'TPU', so the late-retry guard keys
        # on this instead of a substring match
        detail["platform"] = "tpu" if on_tpu else "cpu"
        # MOSAIC_BENCH_FORCE_TPU_LANES exercises the TPU-only lanes on CPU
        # (code-path testing; the numbers are meaningless there)
        force_lanes = bool(os.environ.get("MOSAIC_BENCH_FORCE_TPU_LANES"))
        # quick mode: headline + writeback autotune + pallas + baselines
        # only — the watcher banks a number inside a short tunnel window
        # before attempting the full lane set (scale defaults off in quick
        # mode; an explicit MOSAIC_BENCH_SCALE_POINTS still enables it)
        quick = bool(os.environ.get("MOSAIC_BENCH_QUICK"))
        if quick:
            detail["quick"] = True
        n_device = int(
            os.environ.get(
                "MOSAIC_BENCH_POINTS", 4_000_000 if on_tpu else 1_000_000
            )
        )
        n_passes = max(1, int(os.environ.get("MOSAIC_BENCH_PASSES", "3")))
        n_base = 200_000
        cell_dtype = (
            jnp.float32
            if os.environ.get("MOSAIC_BENCH_CELL_DTYPE", "f32") == "f32"
            else jnp.float64
        )

        h3 = H3IndexSystem()
        zones, zones_src = _load_zones()
        b = zones.bounds()
        bbox = (
            float(np.nanmin(b[:, 0])),
            float(np.nanmin(b[:, 1])),
            float(np.nanmax(b[:, 2])),
            float(np.nanmax(b[:, 3])),
        )
        t0 = time.perf_counter()
        index, cache_hit, tess_only_s = _load_or_build_index(
            zones, zones_src, h3
        )
        _prog(f"index ready (cache_hit={cache_hit})")
        # on a hit this is npz-load time, NOT tessellation speed — the
        # flag keeps cross-round comparisons honest
        tess_s = time.perf_counter() - t0
        detail["tessellate_s"] = round(tess_s, 2)
        detail["tessellate_cache_hit"] = cache_hit
        if tess_only_s:
            # BASELINE's secondary metric: H3 tessellate chips/sec —
            # timed around tessellate() alone (not index build or the
            # cache write), and only when actually computed
            detail["tessellate_chips_per_sec"] = round(
                int(index.chip_geom.shape[0]) / tess_only_s, 1
            )
        detail.update(
            n_zones=len(zones),
            n_chips=int(index.chip_geom.shape[0]),
            h3_res=RES,
            zones=zones_src,
            n_heavy_cells=index.num_heavy_cells,
            edge_cap=int(index.cell_edges.shape[1]),
        )

        # one contiguous host pool sliced into n_passes DISTINCT point
        # sets — identical (fn, input) re-execution is untrustworthy on
        # this rig (results can come back cached)
        _prog("generating host point pool")
        all_pts = random_points(n_passes * n_device, bbox=bbox, seed=11)
        shift = np.asarray(index.border.shift, dtype=np.float64)
        dtype = index.border.verts.dtype

        index_cells = np.asarray(index.cells)

        @jax.jit
        def cells_of(points_f64):
            c = h3.point_to_cell(points_f64.astype(cell_dtype), RES)
            return c.astype(jnp.int64)

        @functools.partial(
            jax.jit,
            static_argnames=(
                "found_cap", "heavy_cap", "writeback", "lookup", "compaction"
            ),
        )
        def step(points_f64, chip_index, found_cap, heavy_cap,
                 writeback="scatter", lookup="gather",
                 compaction="scatter"):
            cells = h3.point_to_cell(points_f64.astype(cell_dtype), RES)
            shifted = (points_f64 - chip_index.border.shift).astype(dtype)
            return pip_join_points(
                shifted,
                cells.astype(jnp.int64),
                chip_index,
                heavy_cap=heavy_cap,
                found_cap=found_cap,
                writeback=writeback,
                lookup=lookup,
                compaction=compaction,
            )

        # full-bit XOR-shift fold: every result bit stays live (a masked
        # sum lets XLA dead-code the high half); int32 end to end
        _fold = jax.jit(lambda m: (m ^ (m >> 16)).sum())
        # device-side stats so the 4M-row match array never crosses the
        # ~10 MB/s tunnel
        _stats = jax.jit(lambda m: ((m >= 0).sum(), (m == -2).sum()))

        def bucket(n):
            """128k-multiple buckets above 128k (pow2 below): tighter than
            pure pow2 — a 530k estimate caps at 640k, not 1M, and cap size
            directly scales the tier-1 gather and scatter-back cost."""
            if n <= 131072:
                return max(16, 1 << int(np.ceil(np.log2(n + 1))))
            return (n + 131071) // 131072 * 131072

        def caps_for(cnp, margin, clamp):
            """Bucketed compaction caps from host-side counts, with a
            safety margin so one presample sizes every batch (an overflow
            (-2) in any output triggers a redo at doubled caps)."""
            pos = np.clip(
                np.searchsorted(index_cells, cnp), 0, index_cells.size - 1
            )
            fnp = index_cells[pos] == cnp
            n_found = int(fnp.sum() * margin)
            fcap = min(bucket(n_found), clamp)
            hcap = None
            if index.num_heavy_cells:
                hmask = np.asarray(index.cell_heavy) >= 0
                n_heavy = int(np.isin(cnp[fnp], index_cells[hmask]).sum() * margin)
                hcap = min(bucket(n_heavy), fcap)
            return fcap, hcap, float(fnp.mean())

        # size the compaction caps once from a host presample (the timed
        # loop then runs sync-free); scale counts to the batch size
        batch = min(4_000_000, n_device)
        pre = np.asarray(cells_of(jnp.asarray(all_pts[:n_base])))
        fcap, hcap, ffrac = caps_for(
            pre, margin=1.5 * batch / n_base, clamp=batch
        )

        # warm up compile on one batch; on compile failure halve the batch
        # and retry so the bench always records a real number
        attempts = []
        _prog(f"compiling main step (batch={batch})")
        while True:
            try:
                first = jnp.asarray(all_pts[:batch])
                t0 = time.perf_counter()
                float(_fold(step(first, index, fcap, hcap)))
                detail["compile_s"] = round(time.perf_counter() - t0, 2)
                break
            except Exception as e:
                attempts.append({"batch": batch, "error": repr(e)[:200]})
                if batch <= 125_000:
                    raise
                batch //= 2
                fcap = min(fcap, batch)
                hcap = min(hcap, fcap) if hcap else hcap
        if attempts:
            detail["compile_attempts"] = attempts
        _prog(f"main step compiled in {detail.get('compile_s')}s")
        detail["batch"] = batch
        detail["caps"] = [fcap, hcap]

        # pre-stage every pass's batches in HBM (a real pipeline overlaps
        # host ingest with device compute; the metric is the join itself)
        def stage(pts):
            sp = [
                jax.device_put(jnp.asarray(pts[s : s + batch]))
                for s in range(0, len(pts), batch)
            ]
            for sb in sp:
                sb.block_until_ready()
            return sp

        _prog("staging passes to device")
        staged_passes = [
            stage(all_pts[p * n_device : (p + 1) * n_device])
            for p in range(n_passes)
        ]
        _prog("staging done")

        # fixed sync round-trip: min of three scalar pulls of values that
        # are already computed — subtracted from every timed pass
        _bump = jax.jit(lambda s: s + 1)
        readies = [_bump(jnp.int32(i)) for i in range(3)]
        for r_ in readies:
            r_.block_until_ready()
        rtts = []
        for r_ in readies:
            t0 = time.perf_counter()
            float(r_)
            rtts.append(time.perf_counter() - t0)
        rtt = min(rtts)
        detail["sync_rtt_s"] = round(rtt, 4)

        def run_pass(sp, fc, hc, wb="scatter", lk="gather", cp="scatter"):
            """Time one pass: dispatch every batch, force completion via
            the device fold of each output pulled as one chained scalar."""
            t0 = time.perf_counter()
            outs = [
                step(sb, index, fc, hc, writeback=wb, lookup=lk,
                     compaction=cp)
                for sb in sp
            ]
            tot = None
            for o in outs:
                s = _fold(o)
                tot = s if tot is None else tot + s
            float(tot)
            return time.perf_counter() - t0, outs

        def measure(fc, hc):
            # overflow is checked on EVERY pass (each pass joins a distinct
            # point set, so a cap overflow may appear only in a later one
            # — the min-time pass must not be reported with invalid outputs)
            times, outs0, n_match, n_over = [], None, 0, 0
            for p, sp in enumerate(staged_passes):
                dt, outs = run_pass(sp, fc, hc)
                times.append(round(dt, 4))
                for o in outs:
                    m, v = _stats(o)
                    n_over += int(v)
                    if p == 0:
                        n_match += int(m)
                if p == 0:
                    outs0 = outs
            return times, outs0, n_match, n_over

        _prog("measuring scatter writeback")
        times, outs0, n_match, n_over = measure(fcap, hcap)
        if n_over:  # compaction cap overflow: redo at doubled caps
            fcap = min(fcap * 2, batch)
            hcap = min((hcap or 16) * 2, fcap)
            detail["caps_redo"] = [fcap, hcap]
            run_pass(staged_passes[0], fcap, hcap)  # discard: recompile
            times, outs0, n_match, n_over = measure(fcap, hcap)
        detail["passes_s"] = times
        dev_s = max(min(times) - rtt, 1e-9)
        dev_rate = n_device / dev_s
        detail["writeback"] = {"scatter": round(dev_rate, 1)}
        detail["main_points_per_sec"] = round(dev_rate, 1)

        # TPU autotune: A/B the probe plumbing variants and headline the
        # winner. (writeback, lookup) pairs — "mxu" replaces the tier-1
        # row gather with a bit-exact one-hot MXU matmul (measured
        # 2026-07-31 on v5e: scatter+mxu 63.4M vs scatter+gather 34.9M
        # pts/s). Each variant has its own try: one failure (the direct
        # lane has hit tpu_compile_helper crashes) must not lose the rest.
        win_wb, win_lk, win_cp = "scatter", "gather", "scatter"
        if on_tpu or force_lanes:
            variants = [
                ("scatter", "mxu", "scatter"),
                ("scatter", "mxu", "mxu"),
                ("scatter", "mxu2", "scatter"),
                ("gather", "gather", "scatter"),
                ("gather", "mxu", "mxu"),
                ("direct", "gather", "scatter"),
            ]
            detail["writeback"]["winner"] = "scatter"
            for wb, lk, cp in variants:
                name = wb if lk == "gather" else f"{wb}+{lk}"
                if cp != "scatter":
                    name += "+cmxu"
                try:
                    _prog(f"{name} variant lane")
                    run_pass(staged_passes[0], fcap, hcap, wb=wb, lk=lk,
                             cp=cp)
                    v_times = [
                        round(
                            run_pass(sp, fcap, hcap, wb=wb, lk=lk, cp=cp)[0],
                            4,
                        )
                        for sp in staged_passes
                    ]
                    v_s = max(min(v_times) - rtt, 1e-9)
                    detail["writeback"][name] = round(n_device / v_s, 1)
                    detail["writeback"][f"{name}_passes_s"] = v_times
                    if v_s < dev_s:
                        dev_s, dev_rate = v_s, n_device / v_s
                        detail["writeback"]["winner"] = name
                        win_wb, win_lk, win_cp = wb, lk, cp
                except Exception as e:
                    detail["writeback"][f"{name}_error"] = repr(e)[:200]
            detail["main_points_per_sec"] = round(dev_rate, 1)
        # probe traffic roofline, computed from the arrays one probe
        # actually touches (never hand-written): a miss stops at one hash
        # bucket row, a found point adds its cell's tier-1 edge row,
        # heavy-cell points additionally the tier-2 row. Emitted per
        # writeback variant so a lane-plumbing change shows up as a
        # bandwidth delta, not just a pts/s delta.
        bucket_b = int(index.table_cell.shape[1]) * (
            index.table_cell.dtype.itemsize + index.table_slot.dtype.itemsize
        )
        edge_b = (
            int(index.cell_edges.shape[-1]) * index.cell_edges.dtype.itemsize
            + index.cell_ebits.dtype.itemsize
        )
        e1 = int(index.cell_edges.shape[1])
        e2 = int(index.heavy_edges.shape[1]) if index.num_heavy_cells else 0
        e3 = (
            int(index.convex_edges.shape[2])
            if index.num_convex_cells
            else 0
        )
        hfrac = float((np.asarray(index.cell_heavy) >= 0).mean())
        bpp = bucket_b + edge_b * (e1 + e2 * hfrac) * ffrac
        peak = _hbm_peak_gbps()
        roofline = {
            "bytes_per_point": round(bpp, 1),
            "bucket_bytes": bucket_b,
            "edge_bytes": edge_b,
            "hbm_peak_gbps": peak,
            "heavy_cell_frac": round(hfrac, 4),
            # what the adaptive router's lanes each cost per routed point
            # (light = tier-1 row, heavy adds the tier-2 row, convex reads
            # the y-bucketed reduced row instead of the tier-1 row)
            "per_lane_bytes_per_point": {
                "light": bucket_b + edge_b * e1,
                "heavy": bucket_b + edge_b * (e1 + e2),
                "convex": bucket_b + edge_b * e3,
            },
            "per_writeback": {},
        }
        for vname, vrate in detail["writeback"].items():
            if not isinstance(vrate, (int, float)):
                continue  # "winner" tag, pass-time lists, error strings
            v_gbps = bpp * vrate / 1e9
            entry = {
                "points_per_sec": vrate,
                "achieved_gbps": round(v_gbps, 2),
            }
            if peak:
                entry["pct_hbm_peak"] = round(100.0 * v_gbps / peak, 2)
            roofline["per_writeback"][vname] = entry
        detail.update(
            n_points=n_device,
            device_s=round(dev_s, 3),
            match_rate=round(n_match / n_device, 4),
            found_rate=round(ffrac, 4),
            overflow=n_over,
            roofline=roofline,
        )

        # Pallas zone-level kernel lane (the BASELINE.json north-star
        # kernel): brute-force PIP against every zone polygon, compiled
        # (not interpret). Runs unconditionally on TPU; elsewhere the skip
        # is recorded loudly instead of silently dropping the lane.
        if on_tpu or force_lanes:
            try:
                _prog("pallas lane")
                from mosaic_tpu.core.geometry.device import pack_to_device
                from mosaic_tpu.kernels.pip import edge_planes, pip_zone

                zdev = pack_to_device(zones, dtype=jnp.float32, recenter=True)
                planes, n_real = edge_planes(zdev)
                zshift = np.asarray(zdev.shift, dtype=np.float64)
                n_pal = min(500_000, n_device)
                pal_jit = jax.jit(
                    functools.partial(pip_zone, n_real_g=n_real)
                )
                # two DISTINCT staged slices when the point pool allows
                # (one otherwise); compile on the first
                n_sl = 2 if 2 * n_pal <= len(all_pts) else 1
                pslices = [
                    jnp.asarray(
                        (all_pts[i * n_pal : (i + 1) * n_pal] - zshift).astype(
                            np.float32
                        )
                    )
                    for i in range(n_sl)
                ]
                out0 = pal_jit(pslices[0], planes)
                float(_fold(out0))  # compile + force
                pal_times = []
                for ps in pslices:
                    t0 = time.perf_counter()
                    out = pal_jit(ps, planes)
                    float(_fold(out))
                    pal_times.append(time.perf_counter() - t0)
                pal_s = max(min(pal_times) - rtt, 1e-9)
                detail["pallas_points_per_sec"] = round(n_pal / pal_s, 1)
                # pts/s alone misreads: this kernel is BRUTE FORCE
                # (every point x every zone x every edge — no index), so
                # also report the arithmetic rate it sustains. ~8 VPU
                # flops per (point, zone-slot, edge) crossing test.
                E_pal, G_pal = int(planes.shape[1]), int(planes.shape[2])
                detail["pallas_brute_force_work"] = (
                    f"{n_pal} pts x {G_pal} zone slots x {E_pal} edges"
                )
                detail["pallas_achieved_gflops"] = round(
                    8.0 * n_pal * G_pal * E_pal / pal_s / 1e9, 1
                )
                m, _ = _stats(out0)
                detail["pallas_match_rate"] = round(int(m) / n_pal, 4)
            except Exception as e:  # kernel failure must not kill the bench
                detail["pallas_error"] = repr(e)[:200]
        else:
            detail["pallas_error"] = (
                f"not measured: device is {detail['device']} (TPU required;"
                " see detail.probe for the acquisition trail)"
            )

        # scale lane (TPU only): ≥16M points generated ON DEVICE (no
        # tunnel transfer), same compiled step — quantifies achieved HBM
        # bandwidth headroom toward the 1B-point north star
        # quick mode defaults the slowest lane OFF, but an explicit env
        # override always wins (matches the comment at the quick flag)
        n_scale = int(
            os.environ.get(
                "MOSAIC_BENCH_SCALE_POINTS", "0" if quick else "16000000"
            )
        )
        if (on_tpu or force_lanes) and n_scale >= n_device:
            try:
                _prog(f"scale lane ({n_scale} pts, device-generated)")
                nb = (n_scale + batch - 1) // batch
                lo = jnp.asarray(bbox[:2], dtype=jnp.float32)
                span = jnp.asarray(
                    [bbox[2] - bbox[0], bbox[3] - bbox[1]], dtype=jnp.float32
                )

                @functools.partial(jax.jit, static_argnames=("n",))
                def gen_batch(key, n):
                    u = jax.random.uniform(key, (n, 2), dtype=jnp.float32)
                    return (lo + u * span).astype(jnp.float64)

                key = jax.random.PRNGKey(1234)
                scale_passes = []
                for p in range(2):  # two distinct generated sets
                    sp = [
                        gen_batch(jax.random.fold_in(key, p * nb + i), batch)
                        for i in range(nb)
                    ]
                    for sb in sp:
                        sb.block_until_ready()
                    scale_passes.append(sp)
                stimes = []
                souts0: list = []
                for p, sp in enumerate(scale_passes):
                    t0 = time.perf_counter()
                    outs = [
                        step(sb, index, fcap, hcap, writeback=win_wb,
                             lookup=win_lk, compaction=win_cp)
                        for sb in sp
                    ]
                    tot = None
                    for o in outs:
                        s = _fold(o)
                        tot = s if tot is None else tot + s
                    float(tot)
                    stimes.append(round(time.perf_counter() - t0, 4))
                    if p == 0:
                        souts0 = outs  # reuse for overflow stats below
                s_dev = max(min(stimes) - rtt, 1e-9)
                s_rate = nb * batch / s_dev
                n_sover = sum(int(_stats(o)[1]) for o in souts0)
                detail["scale"] = {
                    "n_points": nb * batch,
                    "passes_s": stimes,
                    "points_per_sec": round(s_rate, 1),
                    "achieved_gb_per_s": round(bpp * s_rate / 1e9, 1),
                    "hbm_frac_of_800": round(bpp * s_rate / 800e9, 3),
                    "overflow": n_sover,
                }
            except Exception as e:
                detail["scale_error"] = repr(e)[:200]

        # NumPy baseline on a subsample of the same workload (same flat
        # layout, same cell assignment — the single-core competitor)
        _prog("numpy baseline lane")
        sub = all_pts[:n_base]
        pcells = np.asarray(
            h3.point_to_cell(jnp.asarray(sub, dtype=cell_dtype), RES)
        ).astype(np.int64)
        t0 = time.perf_counter()
        base = _numpy_join((sub - shift).astype(np.float64), index, pcells)
        base_s = time.perf_counter() - t0
        base_rate = n_base / base_s
        detail["numpy_points_per_sec"] = round(base_rate, 1)
        # device agreement on the shared prefix — slice on device first so
        # only n_base rows cross the tunnel
        nb0 = min(n_base, int(outs0[0].shape[0]))  # batch may have shrunk
        dev_prefix = np.asarray(outs0[0][:nb0])
        detail["numpy_agreement"] = float((base[:nb0] == dev_prefix).mean())

        # single-thread C++ reference-path lane (VERDICT r4 #4): binary-
        # search equi-join + per-chip `is_core || contains` over clipped
        # chip rings — the honest JTS-codegen analog this environment can
        # run. ``vs_baseline`` is measured against THIS lane when the
        # native library builds (numpy otherwise).
        _prog("native C++ baseline lane")
        base_kind = "numpy"
        try:
            from mosaic_tpu.core.geometry.second import (
                chip_index_csr,
                eval_pip_join,
            )

            csr_xy, csr_ro, csr_cro = chip_index_csr(
                np.asarray(index.border.verts),
                np.asarray(index.border.ring_len),
            )
            nat_args = (
                csr_xy, csr_ro, csr_cro,
                np.asarray(index.chip_core), np.asarray(index.chip_geom),
                np.asarray(index.cells), np.asarray(index.chip_rows),
                (sub - shift).astype(np.float64), pcells,
            )
            native = eval_pip_join(*nat_args)  # warm (may build the .so)
            t0 = time.perf_counter()
            native = eval_pip_join(*nat_args)
            nat_s = time.perf_counter() - t0
            detail["native_points_per_sec"] = round(n_base / nat_s, 1)
            detail["native_agreement"] = float(
                (native[:nb0] == dev_prefix).mean()
            )
            base_rate = n_base / nat_s
            base_kind = "native_cpp_single_thread"
        except Exception as e:  # missing toolchain: keep the numpy lane
            detail["native_error"] = repr(e)[:200]
        detail["baseline_kind"] = base_kind

        # f32 cell assignment knowingly trades near-edge points for
        # throughput — quantify the END-TO-END effect every run: same
        # NumPy join fed f64-assigned cells, floor 0.998 on join results
        # (cell-level disagreement overstates it: a moved cell only flips
        # the answer when the point also sits near a zone boundary)
        if cell_dtype == jnp.float32:
            from mosaic_tpu.runtime.retry import RetryPolicy, call_with_retry

            try:
                # transient tunnel-compile failures (observed 2026-07-31:
                # remote_compile HTTP 500 here zeroed a 34M pts/s TPU run)
                # retry via the shared runtime policy before the lane is
                # abandoned — a salvaged retry keeps the lane's numbers
                c64 = np.asarray(
                    call_with_retry(
                        lambda: jax.jit(
                            lambda p: h3.point_to_cell(p, RES).astype(
                                jnp.int64
                            )
                        )(jnp.asarray(sub, dtype=jnp.float64)),
                        policy=RetryPolicy(
                            max_attempts=3, base_delay_s=2.0,
                            max_delay_s=30.0, timeout_s=120.0,
                        ),
                        label="bench.agreement_lane",
                    )
                )
                detail["cell_f32_f64_agreement"] = round(
                    float((pcells == c64).mean()), 6
                )
                base64 = _numpy_join(
                    (sub - shift).astype(np.float64), index, c64
                )
                jagree = float((base == base64).mean())
                detail["join_f32_f64_agreement"] = round(jagree, 6)
                if jagree < 0.998:
                    detail["join_f32_f64_floor_violated"] = True
            except Exception as e:  # non-transient: the headline already
                # measured; record and keep the bench line
                detail["agreement_error"] = repr(e)[:200]

        # epsilon-band borderline recheck lane (SURVEY §7, VERDICT r4 #3):
        # band sizes, corrected agreement vs the exact f64 host oracle
        # (the bar is EXACTLY 1.0), and the throughput cost of the band-
        # instrumented step. On TPU the full fused step is timed over the
        # same staged passes; on CPU a 60k eager-path subsample checks
        # correctness only (the fused compile costs minutes there).
        _prog("recheck lane" + (" (skipped: quick)" if quick else ""))
        try:
            if quick:
                raise _QuickSkip()
            from mosaic_tpu.sql.join import (
                CELL_MARGIN_K,
                EDGE_BAND_K,
                _compact,
                host_join,
                pip_join,
            )

            rc: dict = {}
            detail["recheck"] = rc
            host = index.host
            cell_np = np.float32 if cell_dtype == jnp.float32 else np.float64
            km_val = CELL_MARGIN_K * float(np.finfo(cell_np).eps)
            eps2_val = (
                EDGE_BAND_K * float(np.finfo(np.dtype(dtype)).eps)
                * host.coord_scale
            ) ** 2
            if on_tpu or force_lanes:
                # band-compacted narrow recheck: size the flag cap from
                # the presample's measured band fraction (1.25x margin +
                # floor) instead of a flat batch//8 — the alt re-join's
                # cost is linear in this cap, and the r05 lane paid a
                # 12.5%-of-batch re-join for a ~4.7% band. The margin is
                # ~50 sigma of the binomial count at 4M; band points
                # beyond the cap escalate to the host oracle via overF
                # (exact, just slower), never a wrong answer.
                _, m_pre = jax.jit(
                    lambda p: h3.point_to_cell_margin(p, RES)
                )(jnp.asarray(all_pts[:n_base], dtype=cell_dtype))
                band_pre = float(
                    (np.asarray(m_pre)[:, 0] < km_val).mean()
                )
                flag_cap = min(
                    bucket(int(1.25 * band_pre * batch) + 2048), batch
                )
                rc["band_frac_presample"] = round(band_pre, 5)
                rc["flag_cap"] = flag_cap

                @jax.jit
                def step_rc(points_f64, chip_index):
                    cells, margins = h3.point_to_cell_margin(
                        points_f64.astype(cell_dtype), RES
                    )
                    cells = cells.astype(jnp.int64)
                    shifted = (
                        points_f64 - chip_index.border.shift
                    ).astype(dtype)
                    out, near = pip_join_points(
                        shifted, cells, chip_index,
                        heavy_cap=hcap, found_cap=fcap,
                        edge_eps2=jnp.asarray(eps2_val, dtype),
                        writeback=win_wb, lookup=win_lk,
                        compaction=win_cp,
                    )
                    flagged = margins[..., 0] < km_val
                    srcF, validF, overF, _ = _compact(flagged, flag_cap)
                    alt = h3.point_to_cell_alt(
                        points_f64[srcF].astype(cell_dtype), RES
                    ).astype(jnp.int64)
                    # the single narrow re-join over the compacted band,
                    # on the autotuned winner's probe plumbing
                    r_alt = pip_join_points(
                        shifted[srcF], alt, chip_index,
                        lookup=win_lk, compaction=win_cp,
                    )
                    tie = validF & (
                        (r_alt != out[srcF])
                        | (margins[srcF, 1] < km_val)
                        | (alt < 0)
                    )
                    esc = (near | overF).at[srcF].max(tie)
                    return out, esc, flagged

                # compile + timed passes over the same staged batches
                float(_fold(step_rc(staged_passes[0][0], index)[0]))
                rc_times = []
                outs_rc0 = None
                for p, sp in enumerate(staged_passes):
                    t0 = time.perf_counter()
                    outs = [step_rc(sb, index) for sb in sp]
                    tot = None
                    for o, e, f in outs:
                        s = _fold(o) + e.sum() + f.sum()
                        tot = s if tot is None else tot + s
                    float(tot)
                    rc_times.append(round(time.perf_counter() - t0, 4))
                    if p == 0:
                        outs_rc0 = outs
                rc_dev_s = max(min(rc_times) - rtt, 1e-9)
                rc["passes_s"] = rc_times
                rc["device_cost_frac"] = round(rc_dev_s / dev_s - 1.0, 4)
                # correctness on pass-0 batch 0 vs the exact host oracle
                o0, e0, f0 = outs_rc0[0]
                out_np = np.asarray(o0)
                esc_np = np.asarray(e0)
                flag_np = np.asarray(f0)
                pts0 = all_pts[:batch]
                rows = np.nonzero(esc_np)[0]
                t0 = time.perf_counter()
                corrected = np.array(out_np)
                if rows.size:
                    corrected[rows] = host_join(pts0[rows], host, h3, RES)
                host_s = time.perf_counter() - t0
                rc["host_recheck_s"] = round(host_s, 4)
                rc["host_cost_frac"] = round(host_s / max(rc_dev_s, 1e-9), 4)
                t0 = time.perf_counter()
                truth = host_join(pts0, host, h3, RES)
                detail["host_oracle_points_per_sec"] = round(
                    batch / (time.perf_counter() - t0), 1
                )
                rc["band_frac"] = round(float(flag_np.mean()), 5)
                rc["esc_frac"] = round(float(esc_np.mean()), 5)
                rc["join_agreement_before"] = round(
                    float((out_np == truth).mean()), 6
                )
                rc["join_agreement_after"] = float(
                    (corrected == truth).mean()
                )
                # cell-level closure: flagged rows take the f64 cell
                c32 = np.asarray(cells_of(jnp.asarray(pts0)))
                c64h = np.asarray(h3.point_to_cell(pts0, RES))
                rc["cell_agreement_after"] = float(
                    ((c32 == c64h) | flag_np).mean()
                )
            else:
                sub = all_pts[:60_000]
                got = pip_join(
                    sub, None, h3, RES, chip_index=index,
                    recheck=True, cell_dtype=jnp.float32,
                )
                truth = host_join(sub, host, h3, RES)
                rc["join_agreement_after"] = float((got == truth).mean())
                import jax.numpy as _jnp

                _, m = h3.point_to_cell_margin(
                    _jnp.asarray(sub, dtype=_jnp.float32), RES
                )
                m = np.asarray(m)
                rc["band_frac"] = round(
                    float((m[:, 0] < km_val).mean()), 5
                )
                rc["mode"] = "cpu_subsample_60k"
        except _QuickSkip:
            detail["recheck"] = {"skipped": "quick"}
        except Exception as e:  # the lane must not kill the bench
            detail["recheck_error"] = repr(e)[:300]

        # secondary micro-lanes: the row-wise ST_Intersects pair predicate
        # (the compute core of the overlay-join config; NOT the full BNG
        # indexed join) and a small SpatialKNN transform. Same timing
        # doctrine as the main lane: warm compile, then min over passes
        # with DISTINCT inputs (identical re-execution can return cached
        # results on this rig), dispatch RTT subtracted.
        _prog("secondary lanes" + (" (skipped: quick)" if quick else ""))
        try:
            if quick:
                raise _QuickSkip()
            sec: dict = {}
            from mosaic_tpu import functions as Fn
            from mosaic_tpu.datasets import synthetic_zones
            from mosaic_tpu.functions.formats import st_point
            from mosaic_tpu.models.knn import SpatialKNN

            bbox_b = (
                bbox[0], bbox[1],
                bbox[0] + 0.7 * (bbox[2] - bbox[0]),
                bbox[1] + 0.7 * (bbox[3] - bbox[1]),
            )
            pairs = [
                (
                    synthetic_zones(16, 16, bbox=bbox, seed=s),
                    synthetic_zones(16, 16, bbox=bbox_b, seed=s + 1),
                )
                for s in (7, 21)
            ]
            hits = np.asarray(Fn.st_intersects(*pairs[0]))  # compile/warm
            ov_times = []
            for za, zb_arr in pairs:
                t0 = time.perf_counter()
                hits = np.asarray(Fn.st_intersects(za, zb_arr))
                ov_times.append(time.perf_counter() - t0)
            ov_s = max(min(ov_times) - rtt, 1e-9)
            sec["overlay_pairs_per_sec"] = round(len(hits) / ov_s, 1)
            sec["overlay_hit_frac"] = round(float(hits.mean()), 3)

            rng_k = np.random.default_rng(5)

            def knn_inputs():
                return (
                    st_point(*rng_k.uniform(bbox[:2], bbox[2:], (8, 2)).T),
                    st_point(*rng_k.uniform(bbox[:2], bbox[2:], (4096, 2)).T),
                )

            knn = SpatialKNN(
                index=h3, resolution=RES - 2, k_neighbours=4,
                max_iterations=8,
            )
            knn.transform(*knn_inputs())  # warm/compile
            kn_times = []
            for _ in range(2):
                lm, cd = knn_inputs()  # distinct draws per pass
                t0 = time.perf_counter()
                r_knn = knn.transform(lm, cd)
                kn_times.append(time.perf_counter() - t0)
            sec["knn_transform_s"] = round(max(min(kn_times) - rtt, 1e-9), 3)
            sec["knn_matches"] = int(r_knn.landmark_id.shape[0])

            # ship2ship core: buffered-track corridors -> indexed
            # intersects join. This is a HOST lane (tessellation +
            # oracle refinement are host work by design; the device
            # backend would recompile per distinct pair-list shape), so
            # no RTT subtraction applies; warm-up uses a set that is
            # never measured
            from mosaic_tpu.core.geometry import wkt as Wk
            from mosaic_tpu.sql.overlay import intersects_join

            def tracks(n, seed):
                rg = np.random.default_rng(seed)
                out = []
                for _ in range(n):
                    x, y = rg.uniform(bbox[0], bbox[2]), rg.uniform(
                        bbox[1], bbox[3]
                    )
                    hd = rg.uniform(0, 2 * np.pi)
                    pts = []
                    for _k in range(6):
                        pts.append(f"{x:.6f} {y:.6f}")
                        x += 0.02 * np.cos(hd) + rg.normal(0, 0.003)
                        y += 0.02 * np.sin(hd) + rg.normal(0, 0.003)
                    out.append("LINESTRING (" + ", ".join(pts) + ")")
                return Wk.from_wkt(out)

            s2s_sets = [
                (
                    Fn.st_buffer(tracks(24, s), 0.004),
                    Fn.st_buffer(tracks(24, s + 1), 0.004),
                )
                for s in (3, 31, 57)
            ]
            intersects_join(*s2s_sets[0], h3, RES - 2)  # warm caches
            s2s_times = []
            for ba, bb in s2s_sets[1:]:
                t0 = time.perf_counter()
                prs = intersects_join(ba, bb, h3, RES - 2)
                s2s_times.append(time.perf_counter() - t0)
            sec["ship2ship_join_host_s"] = round(min(s2s_times), 3)
            sec["ship2ship_pairs"] = int(np.asarray(prs).shape[0])
            detail["secondary"] = sec  # only a complete record is exposed
        except _QuickSkip:
            detail["secondary"] = {"skipped": "quick"}
        except Exception as e:
            detail["secondary_error"] = repr(e)[:200]

        _prog("all lanes done")
        obj = {
            "metric": "nyc_pip_join_throughput",
            "value": round(dev_rate, 1),
            "unit": "points/sec/chip",
            "vs_baseline": round(dev_rate / base_rate, 2),
            "detail": detail,
        }
        # a retry-guard failure must not reroute a fully successful bench
        # into the error path (which would misattribute detail['error']
        # and re-run the probe)
        try:
            obj = _maybe_late_tpu_retry(obj)
        except Exception as e:
            detail["late_retry_error"] = repr(e)[:200]
        _emit(obj)
    except Exception as e:  # always emit a parseable line
        detail["error"] = repr(e)[:500]
        detail["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        # Salvage: if the headline lane already measured, report it — a
        # failure in a LATER optional lane must not zero the artifact
        # (observed 2026-07-31: transient remote_compile HTTP 500 in the
        # agreement lane zeroed a 34M pts/s TPU quick bench).
        rate = float(detail.get("main_points_per_sec") or 0.0)
        base = float(
            detail.get("native_points_per_sec")
            or detail.get("numpy_points_per_sec")
            or 0.0
        )
        obj = {
            "metric": "nyc_pip_join_throughput",
            "value": round(rate, 1),
            "unit": "points/sec/chip",
            "vs_baseline": round(rate / base, 2) if base else 0.0,
            "detail": detail,
        }
        if rate > 0:
            try:
                obj = _maybe_late_tpu_retry(obj)
            except Exception:  # salvage must never die in the retry guard
                pass
        _emit(obj)
        sys.exit(0 if rate > 0 else 1)


if __name__ == "__main__":
    main()
