"""Per-stage latency breakdown (and diff) from a telemetry trail.

The read side of `mosaic_tpu/obs/`: benches export their captured event
trail with ``--trail FILE`` (JSONL, one event per line — spans
included), and this CLI renders what the run actually spent its time
on:

- per stage (``stream_stage.join_loop``, ``serve_stage.dispatch``,
  ``span.serve.request``, ...): count, total seconds, share of the
  trail's total, p50/p99 via the shared ``telemetry.summarize`` helper;
- trace connectivity: traces, spans, roots, orphans
  (`obs.trace_summary`) — the "is one request one trace?" check at a
  glance;
- ``--against OTHER``: per-stage share/total deltas between two trails
  — the human twin of `tools/perf_gate.py`'s enforced comparison.

Accepts JSONL trails or a bench artifact whose last line is one JSON
object with ``detail.stages``/``detail.trail``. The human-readable
report goes to stderr; the LAST stdout line is always one
machine-parseable JSON object (the repo-wide bench contract).

``--fleet`` accepts MANY trails (different processes' exports, flight-
recorder dumps) and stitches them onto one wall-clock axis via their
incarnation headers (`tools/fleet_report.py` does the merging) before
reporting — the breakdown then covers the whole storm, not one child.

Usage:
  python tools/serve_bench.py ... --trail /tmp/serve.jsonl
  python tools/trace_report.py /tmp/serve.jsonl
  python tools/trace_report.py fresh.jsonl --against golden.jsonl
  python tools/trace_report.py --fleet /tmp/storm/*.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def stage_key(event: dict) -> str | None:
    """The gate/report stage identity of one event, or None.

    Timed stage events (``*_stage`` with a ``stage`` field) key as
    ``<event>.<stage>``; span events as ``span.<name>``; any other
    event carrying a numeric ``seconds`` keys as its event name.
    Pre-keyed summary pseudo-events (``stage_key``, from summary-only
    artifacts) pass their key through. Non-dict rows are skipped.
    """
    if not isinstance(event, dict):
        return None
    if not isinstance(event.get("seconds"), (int, float)):
        return None
    if "stage_key" in event:
        return str(event["stage_key"])
    ev = event.get("event", "")
    if ev == "span":
        return f"span.{event.get('name', '')}"
    if "stage" in event:
        return f"{ev}.{event['stage']}"
    return ev


def stage_breakdown(events) -> dict:
    """``{stage_key: {"count", "total_s", "share", "p50", "p99"}}``,
    shares over the summed seconds of all keyed events."""
    from mosaic_tpu.runtime import telemetry

    groups: dict[str, list] = {}
    for e in events:
        key = stage_key(e)
        if key:
            groups.setdefault(key, []).append(e)
    total = sum(
        e["seconds"] for evs in groups.values() for e in evs
    )
    out = {}
    for key, evs in sorted(groups.items()):
        s = telemetry.summarize(evs)
        out[key] = {
            "count": s["count"],
            "total_s": s["sum"],
            "share": round(s["sum"] / total, 4) if total else 0.0,
            "p50": s["p50"],
            "p99": s["p99"],
        }
    return out


def diff_breakdown(fresh: dict, base: dict) -> dict:
    """Per-stage comparison: share delta and total ratio (None when the
    stage is missing on either side). One-sided stages — a lane that
    exists in only one trail, e.g. new probe spans diffed against a
    historical trail — are tolerated and tagged ``only_in`` so
    consumers need not infer sidedness from null deltas."""
    out = {}
    for key in sorted(set(fresh) | set(base)):
        f, b = fresh.get(key), base.get(key)
        entry = {
            "share": f["share"] if f else None,
            "base_share": b["share"] if b else None,
            "share_delta": (
                round(f["share"] - b["share"], 4) if f and b else None
            ),
            "total_ratio": (
                round(f["total_s"] / b["total_s"], 3)
                if f and b and b["total_s"] > 0
                else None
            ),
        }
        if f is None or b is None:
            entry["only_in"] = "base" if f is None else "fresh"
        out[key] = entry
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trail", nargs="+",
                    help="JSONL trail or bench artifact (several with "
                         "--fleet)")
    ap.add_argument("--against", default=None,
                    help="second trail to diff against")
    ap.add_argument("--fleet", action="store_true",
                    help="stitch MANY trails by incarnation header "
                         "(fleet_report) and report over the merge")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report here")
    args = ap.parse_args()

    from mosaic_tpu.obs import export, trace_summary

    if args.fleet:
        import fleet_report as _fleet

        events, fleet = _fleet.stitch(args.trail)
        trail_name = ",".join(args.trail)
    elif len(args.trail) > 1:
        ap.error("multiple trails require --fleet")
    else:
        events = export.read_trail(args.trail[0])
        fleet = None
        trail_name = args.trail[0]
    stages = stage_breakdown(events)
    traces = trace_summary(events)
    report = {
        "metric": "trace_report",
        "trail": trail_name,
        "events": len(events),
        "spans": sum(t["spans"] for t in traces.values()),
        "traces": len(traces),
        "connected_traces": sum(
            1 for t in traces.values()
            if t["roots"] == 1 and not t["orphans"]
        ),
        "stages": stages,
    }
    if fleet is not None:
        report["fleet"] = {
            "incarnations": len(fleet["incarnations"]),
            "chain": fleet["chain"],
            "cross_incarnation_traces": fleet["cross_incarnation_traces"],
        }

    w = sys.stderr.write
    w(f"trail: {trail_name} ({len(events)} events, "
      f"{report['spans']} spans in {report['traces']} traces, "
      f"{report['connected_traces']} fully connected)\n")
    if fleet is not None:
        for link in fleet["chain"]:
            gap = (
                f"  (+{link['gap_s']:.3f}s after {link['prev']})"
                if "prev" in link else ""
            )
            w(f"  {link['incarnation']}: {link['events']} events over "
              f"{link['span_s']:.3f}s{gap}\n")
    w(f"{'stage':<38} {'count':>6} {'total_s':>9} {'share':>6} "
      f"{'p50':>9} {'p99':>9}\n")
    for key, s in sorted(
        stages.items(), key=lambda kv: -kv[1]["total_s"]
    ):
        w(f"{key:<38} {s['count']:>6} {s['total_s']:>9.4f} "
          f"{s['share']:>6.1%} {s['p50']:>9.4f} {s['p99']:>9.4f}\n")

    if args.against:
        base = stage_breakdown(export.read_trail(args.against))
        report["against"] = args.against
        report["diff"] = diff_breakdown(stages, base)
        w(f"\nvs {args.against}:\n")
        w(f"{'stage':<38} {'share':>7} {'base':>7} {'delta':>8} "
          f"{'ratio':>7}\n")
        for key, d in sorted(
            report["diff"].items(),
            key=lambda kv: -(abs(kv[1]["share_delta"] or 0)),
        ):
            fmt = lambda v, p: ("-" if v is None else f"{v:{p}}")  # noqa: E731
            tag = (
                f"  ({d['only_in']} only)" if d.get("only_in") else ""
            )
            w(f"{key:<38} {fmt(d['share'], '7.1%')} "
              f"{fmt(d['base_share'], '7.1%')} "
              f"{fmt(d['share_delta'], '+8.1%')} "
              f"{fmt(d['total_ratio'], '7.2f')}{tag}\n")

    line = json.dumps(report)
    sys.stdout.write(line + "\n")
    sys.stdout.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
