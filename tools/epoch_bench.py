"""Epochal-index bench: delta-patch speedup, kill-storm replay, publish latency.

The CI twin of `mosaic_tpu/index/epoch.py` — three lanes, one committed
`EPOCH_r*.json` artifact:

1. **churn** — a 1%-churn live-edit workload at vertex-heavy scale
   (dented 96-gon "blobs": tessellation, not index build, dominates a
   rebuild, which is exactly the regime mutable indexes exist for).
   Each round perturbs ``--churn-pct`` of the geometries, ``apply``\\ s
   the delta and ``publish``\\ es the epoch; the baseline is a warm
   from-scratch ``tessellate + build_chip_index`` of the same column.
   Headline = rebuild seconds / patch seconds (median over rounds),
   asserted ``>= --min-speedup``; every round's published index is
   asserted bit-identical to the from-scratch rebuild.
2. **kill-storm** — a synthetic kill at EVERY fault-site boundary of
   the epoch lifecycle (apply pre-tessellate / pre-append /
   post-append, publish pre-build / torn swap-vs-counter, compact
   pre-snapshot / pre-truncate / post-truncate), each followed by
   ``EpochalIndex.replay``; every survivor must be bit-identical to a
   from-scratch rebuild of the surviving epoch. ``identical`` MUST
   equal ``boundaries``.
3. **serve** — publishes driven through a live ``ServeEngine`` while a
   client thread keeps submitting joins: records publish p50/p99 and
   the worst request latency observed DURING a publish window, asserts
   traffic kept flowing (requests completed inside every publish
   window) and no request errored — the publish-never-blocks claim.

Every stage lands a timed ``epoch_stage.<stage>`` telemetry event
(tessellate / append / materialize / build / compact / replay) — the
keys `tools/perf_gate.py` gates, with the 10x ``--inject-slowdown``
negative lane in CI.

The final stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr.

Usage (CI epoch-smoke lane):
  python tools/epoch_bench.py --n-side 20 --reps 2 --min-speedup 1.5 \
      --trail /tmp/epoch.jsonl
  python tools/perf_gate.py --golden tests/goldens/perf_gate.json \
      --trail /tmp/epoch.jsonl --stages-prefix epoch_stage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the kill matrix the bench storms through: (site, boundaries let
#: through before the kill, epoch the log must replay to) — mirrors
#: tests/test_epoch.py::KILL_MATRIX
KILL_MATRIX = [
    ("epoch.apply", 0, 0),
    ("epoch.apply", 1, 0),
    ("epoch.apply", 2, 1),
    ("epoch.publish", 0, 1),
    ("epoch.publish", 1, 1),
    ("epoch.compact", 0, 1),
    ("epoch.compact", 1, 1),
    ("epoch.compact", 2, 1),
]


def blob_wkt(i: int, j: int, phase: float, cw: float, verts: int):
    """One dented ``verts``-gon around lattice site (i, j) — vertex-
    heavy enough that tessellation dominates, small enough (~0.8 cell
    across) that the chip table stays lean."""
    import numpy as np

    th = np.linspace(0, 2 * np.pi, verts, endpoint=False)
    cx, cy = -80.0 + i * 2.2 * cw, -84.0 + j * 2.2 * cw
    rr = 0.42 * cw * (1.0 + 0.22 * np.sin(7 * th + phase + 0.1 * (i + j)))
    xs, ys = cx + rr * np.cos(th), cy + rr * np.sin(th)
    pts = ", ".join(f"{x:.6f} {y:.6f}" for x, y in zip(xs, ys))
    return f"POLYGON (({pts}, {xs[0]:.6f} {ys[0]:.6f}))"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-side", type=int, default=60,
                    help="blobs per lattice side (geoms = n_side^2)")
    ap.add_argument("--verts", type=int, default=96)
    ap.add_argument("--res", type=int, default=4)
    ap.add_argument("--churn-pct", type=float, default=1.0)
    ap.add_argument("--reps", type=int, default=3,
                    help="churn rounds (speedup = median over rounds)")
    ap.add_argument("--min-speedup", type=float, default=1.5,
                    help="fail below this patch-vs-rebuild speedup; "
                    "CI smoke lanes keep a conservative floor, the "
                    "committed round is the measured claim")
    ap.add_argument("--serve-publishes", type=int, default=3)
    ap.add_argument("--log-dir", default=None,
                    help="delta-log directory for the churn lane "
                    "(default: a temp dir)")
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail as JSONL")
    args = ap.parse_args()

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    detail: dict = {}
    line = {"metric": "epoch_patch_speedup_vs_rebuild", "value": 0.0,
            "unit": "x", "detail": detail}
    stages: list = []
    root_span = None
    rc = 1
    try:
        import tempfile

        import jax
        import numpy as np

        from mosaic_tpu import obs
        from mosaic_tpu.core.geometry import wkt
        from mosaic_tpu.core.index import CustomIndexSystem, GridConf
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.index import EpochalIndex, chip_index_equal
        from mosaic_tpu.runtime import faults, telemetry
        from mosaic_tpu.serve import BucketLadder, ServeEngine
        from mosaic_tpu.sql.join import build_chip_index

        cap = telemetry.capture()
        stages = cap.__enter__()
        root_span = obs.start_span("epoch_bench", n_side=args.n_side,
                                   res=args.res)
        detail["platform"] = str(jax.devices()[0].platform)
        grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2,
                                          10.0, 10.0))
        cw, _ = grid.cell_size(args.res)
        n_geoms = args.n_side * args.n_side
        n_churn = max(1, int(round(n_geoms * args.churn_pct / 100.0)))
        detail["geoms"] = n_geoms
        detail["churn_geoms"] = n_churn

        def column(phase, only=None):
            gids = range(n_geoms) if only is None else only
            return wkt.from_wkt([
                blob_wkt(g % args.n_side, g // args.n_side, phase, cw,
                         args.verts)
                for g in gids
            ])

        # ------------------------------------------------ churn lane
        col = column(0.0)
        # warm the tessellation + build path so the rebuild baseline
        # measures work, not compiles
        warm = build_chip_index(
            tessellate(col, grid, args.res, keep_core_geoms=False)
        )
        detail["chips"] = int(np.asarray(warm.cells).shape[0])

        log_dir = args.log_dir or tempfile.mkdtemp(prefix="epoch-bench-")
        ep = EpochalIndex(col, grid, args.res, keep_core_geoms=False,
                          log_dir=log_dir)
        ep.publish()

        rng = np.random.default_rng(18)
        rounds = []
        for rep in range(args.reps):
            ids = np.sort(rng.choice(n_geoms, n_churn, replace=False))
            up = column(2.0 + rep, only=[int(g) for g in ids])
            t0 = time.perf_counter()
            ep.apply(upsert=up, ids=ids)
            apply_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            ep.publish()
            publish_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            scratch = build_chip_index(
                tessellate(ep.column(), grid, args.res,
                           keep_core_geoms=False)
            )
            rebuild_s = time.perf_counter() - t0
            if not chip_index_equal(ep.index, scratch):
                raise AssertionError(
                    f"round {rep}: patched epoch {ep.epoch} is NOT "
                    "bit-identical to the from-scratch rebuild"
                )
            rounds.append({
                "apply_s": round(apply_s, 6),
                "publish_s": round(publish_s, 6),
                "rebuild_s": round(rebuild_s, 6),
                "speedup": round(
                    rebuild_s / max(apply_s + publish_s, 1e-9), 3
                ),
            })
        detail["rounds"] = rounds
        speedup = float(np.median([r["speedup"] for r in rounds]))
        detail["speedup"] = round(speedup, 3)
        line["value"] = round(speedup, 3)

        # replay the whole churn log back: the durable story at scale
        t0 = time.perf_counter()
        replayed = EpochalIndex.replay(log_dir, grid)
        detail["replay_s"] = round(time.perf_counter() - t0, 6)
        if not chip_index_equal(replayed.index, ep.index):
            raise AssertionError(
                "replay of the churn log diverged from the live index"
            )
        detail["replay_epoch"] = replayed.epoch

        # ------------------------------------------- kill-storm lane
        small = wkt.from_wkt([
            blob_wkt(i, j, 0.0, cw, 24) for i in range(3) for j in range(3)
        ])
        edit = wkt.from_wkt([blob_wkt(1, 1, 9.0, cw, 24)])
        storm = {"boundaries": len(KILL_MATRIX), "identical": 0}
        for site, skip, survivor in KILL_MATRIX:
            d = tempfile.mkdtemp(prefix="epoch-storm-")
            sep = EpochalIndex(small, grid, args.res,
                               keep_core_geoms=False, log_dir=d)
            try:
                with faults.transient_errors(
                    1, sites=(site,), skip_first=skip,
                    exc_factory=lambda s: RuntimeError(f"kill @ {s}"),
                ):
                    sep.apply(upsert=edit, ids=[4])
                    if site == "epoch.publish":
                        sep.publish()
                    elif site == "epoch.compact":
                        sep.compact()
                raise AssertionError(
                    f"injected kill at {site}+{skip} did not fire"
                )
            except RuntimeError:
                pass
            r = EpochalIndex.replay(d, grid)
            want = build_chip_index(
                tessellate(r.column(), grid, args.res,
                           keep_core_geoms=False)
            )
            if r.epoch == survivor and chip_index_equal(r.index, want):
                storm["identical"] += 1
        detail["kill_storm"] = storm
        if storm["identical"] != storm["boundaries"]:
            raise AssertionError(
                f"kill storm: only {storm['identical']} of "
                f"{storm['boundaries']} boundaries replayed "
                "bit-identically"
            )

        # ------------------------------------------------ serve lane
        sep = EpochalIndex(small, grid, args.res, keep_core_geoms=False)
        sep.publish()
        bounds = (-81.0, -85.0, -74.0, -78.0)
        stop = threading.Event()
        lat: list = []
        errors: list = []
        with ServeEngine(
            sep.index, grid, args.res, ladder=BucketLadder(64, 256),
            bounds=bounds, max_wait_s=0.0,
        ) as eng:
            eng.warmup()
            prng = np.random.default_rng(7)
            pts = prng.uniform(bounds[:2], bounds[2:], (128, 2))

            def client():
                while not stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        eng.join(pts, deadline_s=60.0)
                        lat.append(time.perf_counter() - t0)
                    except Exception as e:  # lint: broad-except-ok (the lane's assertion IS that no request errors; collect, don't mask)
                        errors.append(repr(e)[:200])
                        return

            t = threading.Thread(target=client, daemon=True)  # lint: thread-context-adoption-ok (load generator: client-side latency only, no telemetry emitted on this thread)
            t.start()
            pub_s, during = [], []
            for rep in range(args.serve_publishes):
                sep.apply(upsert=wkt.from_wkt(
                    [blob_wkt(1, 1, 20.0 + rep, cw, 24)]), ids=[4])
                n0 = len(lat)
                t0 = time.perf_counter()
                sep.publish(eng)
                pub_s.append(time.perf_counter() - t0)
                during.append(len(lat) - n0)
            stop.set()
            t.join(timeout=30)
        if errors:
            raise AssertionError(
                f"serve traffic errored during publish: {errors[0]}"
            )
        if min(during) < 1:
            raise AssertionError(
                "no request completed inside a publish window — "
                "publish blocked in-flight traffic"
            )
        detail["serve"] = {
            "publishes": len(pub_s),
            "publish_p50_s": round(float(np.percentile(pub_s, 50)), 6),
            "publish_p99_s": round(float(np.percentile(pub_s, 99)), 6),
            "requests": len(lat),
            "requests_during_publish": during,
            "request_p99_s": round(float(np.percentile(lat, 99)), 6),
            "request_max_s": round(max(lat), 6),
        }

        if speedup < args.min_speedup:
            raise AssertionError(
                f"patch speedup {speedup:.2f}x < --min-speedup "
                f"{args.min_speedup}x on {args.churn_pct}% churn"
            )
        rc = 0
    except Exception as e:  # lint: broad-except-ok (bench must always emit its JSON line; rc carries failure)
        detail["error"] = repr(e)[:400]

    if root_span is not None:
        try:
            root_span.end()
        except Exception:  # lint: broad-except-ok (span cleanup must not mask the bench result)
            pass
    if args.trail and stages:
        try:
            from mosaic_tpu import obs as _obs

            _obs.write_jsonl(stages, args.trail)
        except Exception as e:  # lint: broad-except-ok (a sick trail disk degrades the trail, not the bench)
            detail["trail_error"] = repr(e)[:200]

    emit_to.write(json.dumps(line) + "\n")
    emit_to.flush()
    return rc


if __name__ == "__main__":
    sys.exit(main())
