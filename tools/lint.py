#!/usr/bin/env python
"""mosaic-lint driver over `mosaic_tpu/analysis/` (reference analog:
the scalastyle gate in the reference's Maven build, grown from unused-
import hygiene into project-aware semantic rules — jit purity, env
staging, cross-thread context adoption, registry drift, broad-except
discipline, unbounded caches).

Usage:
    python tools/lint.py                     # full repo, exit 0 clean
    python tools/lint.py --rule jit-purity   # one rule (repeatable)
    python tools/lint.py --list-rules        # the catalog
    python tools/lint.py --update-baseline   # grandfather current findings
    python tools/lint.py --update-registry   # regenerate registry golden
    python tools/lint.py --json-only         # machine mode (no per-line text)

Per repo convention the LAST stdout line is always one JSON object:
``{"tool": "mosaic-lint", "files": N, "rules_run": K, "findings": n,
"baselined": b, "suppressed": s, "stale_baseline": [...], "rules":
{rule: count}, "clean": bool}``. Exit 0 iff no active findings and no
stale baseline entries.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _import_analysis():
    """Import `mosaic_tpu.analysis` WITHOUT executing the package
    __init__ (which imports jax and the whole framework): the lint gate
    stays stdlib-only, so it runs in bare CI environments — same
    contract as the seed linter. The analysis subpackage itself imports
    nothing outside the standard library."""
    import types

    if "mosaic_tpu" not in sys.modules:
        pkg = types.ModuleType("mosaic_tpu")
        pkg.__path__ = [os.path.join(ROOT, "mosaic_tpu")]
        sys.modules["mosaic_tpu"] = pkg
    import mosaic_tpu.analysis as analysis

    return analysis

DEFAULT_BASELINE = os.path.join("tests", "goldens", "lint_baseline.json")
DEFAULT_REGISTRY = os.path.join("tests", "goldens", "registry.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--root", default=ROOT,
        help="repo root to analyze (default: this checkout)",
    )
    ap.add_argument(
        "--rule", action="append", default=None, metavar="NAME",
        help="run only this rule (repeatable)",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="PATH",
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="write current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--update-registry", action="store_true",
        help="regenerate tests/goldens/registry.json from the AST scan",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    ap.add_argument(
        "--json-only", action="store_true",
        help="suppress per-finding lines; print only the final JSON",
    )
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root)

    analysis = _import_analysis()
    all_rules = analysis.all_rules
    analyze = analysis.analyze
    build_registry = analysis.build_registry
    load_baseline = analysis.load_baseline
    save_baseline = analysis.save_baseline
    split_baselined = analysis.split_baselined
    REGISTRY_NOTE = analysis.project_registry.REGISTRY_NOTE

    if args.list_rules:
        for name, r in all_rules().items():
            print(f"{name:26s} [{r.scope:7s}] {r.doc}")
        print(json.dumps({
            "tool": "mosaic-lint", "rules": sorted(all_rules()),
        }))
        return 0

    if args.update_registry:
        reg = build_registry(root)
        path = os.path.join(root, DEFAULT_REGISTRY)
        reg["note"] = REGISTRY_NOTE
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(reg, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(json.dumps({
            "tool": "mosaic-lint", "updated_registry": DEFAULT_REGISTRY,
            **{k: len(v) for k, v in reg.items() if isinstance(v, list)},
        }))
        return 0

    result = analyze(root, rule_names=args.rule)
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)

    if args.update_baseline:
        counts = save_baseline(baseline_path, result.findings)
        print(json.dumps({
            "tool": "mosaic-lint",
            "updated_baseline": os.path.relpath(baseline_path, root),
            "entries": sum(counts.values()),
        }))
        return 0

    baseline = load_baseline(baseline_path)
    active, grandfathered, stale = split_baselined(
        result.findings, baseline
    )
    # a rule-filtered run only sees a slice of the findings, so unmatched
    # baseline entries are expected — never report them stale
    if args.rule:
        stale = []

    if not args.json_only:
        for f in active:
            print(f.render())
        if stale:
            for k in stale:
                print(f"baseline: stale entry (fixed? remove it): {k}")

    summary = {
        "tool": "mosaic-lint",
        "files": result.files,
        "rules_run": len(result.rules_run),
        "findings": len(active),
        "baselined": len(grandfathered),
        "suppressed": len(result.suppressed),
        "stale_baseline": stale,
        "rules": dict(sorted(_count(active).items())),
        "clean": not active and not stale,
    }
    print(json.dumps(summary))
    return 0 if summary["clean"] else 1


def _count(findings) -> dict:
    out: dict = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out


if __name__ == "__main__":
    sys.exit(main())
