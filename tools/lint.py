#!/usr/bin/env python
"""Dependency-free lint gate (reference analog: the scalastyle gate in the
reference's Maven build). Enforced rules, chosen to be high-signal and
false-positive-free on this codebase:

- every file parses (ast) and compiles (syntax floor);
- no unused imports (names imported at module top level that never appear
  in the module body; `# noqa` on the import line opts out);
- no tabs in indentation; no trailing whitespace;
- no bare `except:`;
- no `print(` in library code (mosaic_tpu/ only; tools/tests/bench may).

Run: python tools/lint.py  -> exit 0 clean, 1 with findings listed.
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = ["mosaic_tpu", "tests", "tools", "bench.py", "__graft_entry__.py"]


def _py_files():
    for t in TARGETS:
        p = os.path.join(ROOT, t)
        if os.path.isfile(p):
            yield p
        else:
            for base, _dirs, files in os.walk(p):
                if "__pycache__" in base:
                    continue
                for f in files:
                    if f.endswith(".py"):
                        yield os.path.join(base, f)


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    return used


def check_file(path: str) -> list[str]:
    rel = os.path.relpath(path, ROOT)
    src = open(path, encoding="utf-8").read()
    out = []
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: syntax error: {e.msg}"]
    lines = src.splitlines()
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            out.append(f"{rel}:{i}: trailing whitespace")
        if line.startswith("\t") or (line[: len(line) - len(line.lstrip())].count("\t")):
            out.append(f"{rel}:{i}: tab indentation")
    # unused top-level imports
    used = _used_names(tree)
    in_all = set()
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(getattr(t, "id", "") == "__all__" for t in node.targets)
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            in_all |= {
                e.value for e in node.value.elts if isinstance(e, ast.Constant)
            }
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue  # compiler directive, not a binding
            line = lines[node.lineno - 1]
            if "noqa" in line:
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = (alias.asname or alias.name).split(".")[0]
                if bound not in used and bound not in in_all:
                    out.append(
                        f"{rel}:{node.lineno}: unused import {bound!r}"
                    )
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(f"{rel}:{node.lineno}: bare except")
        if (
            rel.startswith("mosaic_tpu")
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(f"{rel}:{node.lineno}: print() in library code")
    return out


def main() -> int:
    findings: list[str] = []
    for path in sorted(_py_files()):
        findings += check_file(path)
    for f in findings:
        sys.stdout.write(f + "\n")
    sys.stdout.write(
        f"lint: {len(findings)} finding(s) in "
        f"{sum(1 for _ in _py_files())} files\n"
    )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
