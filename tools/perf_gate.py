"""Performance regression gate: enforce the committed stage-time shape.

The `BENCH_*.json` trajectory records how fast each round was; nothing
so far FAILED a build when a stage silently got slower. This gate turns
the bench trails (`--trail`, exported by serve_bench/stream_bench/
bench.py) into an enforced contract against a committed golden
(`tests/goldens/perf_gate.json`), MLPerf-style but CPU-safe:

**What is compared.** For every stage key (see
`tools/trace_report.py`: ``stream_stage.join_loop``,
``serve_stage.dispatch``, ...) the gate computes the stage's *odds* —
its total seconds over the total of every OTHER stage in the same
trail. Each ``--trail`` is its own odds pool: one bench's wall time
cannot dilute another bench's odds (pooling across benches would sink
small stages below the noise floor, where a 10x slowdown can no longer
escape ``odds_floor``); a stage that appears in several trails gates
on its worst pool. Odds are invariant under uniform machine speed (a
CI runner 3x slower than the golden machine scales every stage alike),
but a regression in ONE stage moves its odds by the regression factor
— so the tolerance can be modest (default 3x) while a genuine 10x
stage slowdown still fails loudly on any machine (the negative lane in
CI injects exactly that via ``--inject-slowdown``).

**Gate rule** per golden stage with recorded odds g: fresh odds must
satisfy ``odds <= g * tolerance + odds_floor`` (the floor forgives
sub-noise stages); a golden stage marked ``"require": true`` that is
absent from the fresh trails fails (a vanished stage is a coverage
regression, not a speedup). Optional per-stage ``"max_seconds"`` adds
an absolute ceiling for lanes where wall time itself is the contract.

``--update`` rewrites the golden from the fresh trails (commit the
result). The last stdout line is one JSON object; exit 0 = green.

Usage (CI obs-smoke lane):
  python tools/stream_bench.py ... --trail /tmp/stream.jsonl
  python tools/serve_bench.py ...  --trail /tmp/serve.jsonl
  python tools/perf_gate.py --golden tests/goldens/perf_gate.json \
      --trail /tmp/stream.jsonl --trail /tmp/serve.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

DEFAULT_GOLDEN = os.path.join(REPO, "tests", "goldens", "perf_gate.json")
DEFAULT_TOLERANCE = 3.0
DEFAULT_ODDS_FLOOR = 0.02
#: stage keys the gate ignores — spans double-count their timed events,
#: and one-off sub-ms bookkeeping events are pure noise
SKIP_PREFIXES = ("span.",)


def stage_odds(events) -> dict:
    """``{stage_key: {"seconds", "count", "odds"}}`` over ONE trail's
    events (one odds pool); odds = seconds / (total - seconds)."""
    from trace_report import stage_breakdown

    stages = {
        k: v
        for k, v in stage_breakdown(events).items()
        if not k.startswith(SKIP_PREFIXES)
    }
    total = sum(v["total_s"] for v in stages.values())
    out = {}
    for key, v in stages.items():
        rest = max(total - v["total_s"], 1e-9 * max(total, 1e-9))
        out[key] = {
            "seconds": v["total_s"],
            "count": v["count"],
            "odds": round(v["total_s"] / rest, 6),
        }
    return out


def apply_slowdown(pool: dict, stage: str, factor: float) -> dict:
    """Scale one stage's seconds within its pool and recompute every
    odds in that pool (what a real single-stage regression does)."""
    scaled = {
        k: dict(v, seconds=v["seconds"] * (factor if k == stage else 1.0))
        for k, v in pool.items()
    }
    total = sum(v["seconds"] for v in scaled.values())
    for v in scaled.values():
        rest = max(total - v["seconds"], 1e-9 * max(total, 1e-9))
        v["odds"] = round(v["seconds"] / rest, 6)
    return scaled


def merge_pools(pools) -> dict:
    """Union of per-trail pools: seconds/count sum across trails, odds
    gate on the worst (largest) pool — a stage must be healthy in every
    bench it appears in."""
    out: dict = {}
    for pool in pools:
        for k, v in pool.items():
            cur = out.get(k)
            if cur is None:
                out[k] = dict(v)
            else:
                cur["seconds"] = round(cur["seconds"] + v["seconds"], 6)
                cur["count"] += v["count"]
                cur["odds"] = max(cur["odds"], v["odds"])
    return out


def evaluate(
    fresh: dict, golden: dict
) -> tuple[bool, dict]:
    """Apply the gate rule; returns (green, per-stage verdicts)."""
    tol = float(golden.get("tolerance", DEFAULT_TOLERANCE))
    floor = float(golden.get("odds_floor", DEFAULT_ODDS_FLOOR))
    verdicts = {}
    green = True
    for key, g in sorted(golden.get("stages", {}).items()):
        f = fresh.get(key)
        if f is None:
            ok = not g.get("require", False)
            verdicts[key] = {
                "status": "missing" if ok else "MISSING_REQUIRED",
                "ok": ok,
            }
            green &= ok
            continue
        limit = float(g["odds"]) * tol + floor
        ok = f["odds"] <= limit
        v = {
            "status": "ok" if ok else "SLOW",
            "ok": ok,
            "odds": f["odds"],
            "golden_odds": g["odds"],
            "limit": round(limit, 6),
            "seconds": f["seconds"],
        }
        max_s = g.get("max_seconds")
        if max_s is not None and f["seconds"] > float(max_s):
            v.update(status="OVER_ABSOLUTE", ok=False)
            ok = False
        verdicts[key] = v
        green &= ok
    return green, verdicts


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trail", action="append", required=True,
                    help="trail file (repeatable; each trail is its "
                    "own odds pool)")
    ap.add_argument("--golden", default=DEFAULT_GOLDEN)
    ap.add_argument("--update", action="store_true",
                    help="rewrite the golden from these trails")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override the golden's odds tolerance")
    ap.add_argument("--inject-slowdown", default=None,
                    metavar="STAGE:FACTOR",
                    help="test knob: scale one fresh stage's seconds "
                    "(the CI negative lane proves the gate turns red)")
    ap.add_argument("--stages-prefix", action="append", default=None,
                    metavar="PREFIX",
                    help="gate only golden stages under these key "
                    "prefixes (repeatable) — a job that produces one "
                    "lane's trail (multichip-smoke) gates its own pool "
                    "without every other bench's trail on hand")
    args = ap.parse_args()

    from mosaic_tpu.obs import export

    pools = [stage_odds(export.read_trail(p)) for p in args.trail]

    if args.inject_slowdown:
        stage, factor = args.inject_slowdown.rsplit(":", 1)
        if not any(stage in pool for pool in pools):
            sys.stderr.write(f"inject-slowdown: no stage {stage!r}\n")
            return 2
        pools = [
            apply_slowdown(pool, stage, float(factor))
            if stage in pool else pool
            for pool in pools
        ]
    fresh = merge_pools(pools)

    if args.update:
        golden = {
            "tolerance": args.tolerance or DEFAULT_TOLERANCE,
            "odds_floor": DEFAULT_ODDS_FLOOR,
            "note": (
                "stage odds (seconds vs all other stages) from the CPU "
                "smoke lanes; regenerate: python tools/perf_gate.py "
                "--update --trail ... (commit the result)"
            ),
            "stages": {
                k: {
                    "odds": v["odds"],
                    "seconds": round(v["seconds"], 4),
                    "require": True,
                }
                for k, v in sorted(fresh.items())
            },
        }
        os.makedirs(os.path.dirname(args.golden), exist_ok=True)
        with open(args.golden, "w") as f:
            json.dump(golden, f, indent=2, sort_keys=True)
            f.write("\n")
        sys.stderr.write(
            f"wrote {args.golden} ({len(golden['stages'])} stages)\n"
        )
        sys.stdout.write(json.dumps(
            {"metric": "perf_gate", "updated": args.golden,
             "stages": len(golden["stages"])}
        ) + "\n")
        return 0

    with open(args.golden) as f:
        golden = json.load(f)
    if args.tolerance is not None:
        golden["tolerance"] = args.tolerance
    if args.stages_prefix:
        pref = tuple(args.stages_prefix)
        golden["stages"] = {
            k: v for k, v in golden["stages"].items()
            if k.startswith(pref)
        }
        if not golden["stages"]:
            sys.stderr.write(
                f"stages-prefix {pref} matches no golden stage\n"
            )
            return 2
    green, verdicts = evaluate(fresh, golden)

    for key, v in sorted(verdicts.items()):
        mark = "ok " if v["ok"] else "RED"
        extra = (
            f" odds {v['odds']:.4f} vs limit {v['limit']:.4f}"
            if "odds" in v else ""
        )
        sys.stderr.write(f"  [{mark}] {key}: {v['status']}{extra}\n")
    sys.stderr.write(
        f"perf gate: {'GREEN' if green else 'RED'} "
        f"({len(verdicts)} gated stages, "
        f"tolerance {golden.get('tolerance', DEFAULT_TOLERANCE)}x)\n"
    )
    sys.stdout.write(json.dumps({
        "metric": "perf_gate",
        "pass": green,
        "golden": args.golden,
        "stages": verdicts,
    }) + "\n")
    return 0 if green else 1


if __name__ == "__main__":
    sys.exit(main())
