"""Dependency-free line-coverage gate (reference analog: the 80%
scoverage floor in the reference's pom.xml `<minimum.coverage>`).

CI uses pytest-cov for the same floor; this tool exists so the gate is
verifiable in environments without coverage.py installed. It measures
line coverage of ``mosaic_tpu/`` while running the test suite in-process,
using PEP 669 ``sys.monitoring`` LINE events with per-location disable
(an event fires once per code location, then turns itself off — near-zero
steady-state overhead, the same trick coverage.py 7 uses on 3.12+).

Usage: python tools/coverage_gate.py [--fail-under 80] [pytest args...]
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "mosaic_tpu")


def executable_lines(path: str) -> set[int]:
    """All executable line numbers of a source file, from the compiled
    code objects' co_lines tables (the same denominator coverage.py
    uses), minus doc-only/constant lines compile() still attributes."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        code = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set[int] = set()
    stack = [code]
    while stack:
        co = stack.pop()
        for _, _, ln in co.co_lines():
            if ln is not None:
                lines.add(ln)
        for const in co.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fail-under", type=float, default=80.0)
    ap.add_argument("pytest_args", nargs="*", default=["tests/", "-q"])
    args = ap.parse_args()

    hit: dict[str, set[int]] = {}
    mon = sys.monitoring
    tool = mon.COVERAGE_ID
    mon.use_tool_id(tool, "mosaic-coverage-gate")

    def on_line(code, line):
        fn = code.co_filename
        if fn.startswith(PKG):
            hit.setdefault(fn, set()).add(line)
        return mon.DISABLE  # once per location is all coverage needs

    mon.register_callback(tool, mon.events.LINE, on_line)
    mon.set_events(tool, mon.events.LINE)

    os.chdir(REPO)
    sys.path.insert(0, REPO)  # `python -m pytest` would add cwd itself
    import pytest

    rc = pytest.main(args.pytest_args or ["tests/", "-q"])
    mon.set_events(tool, 0)
    mon.free_tool_id(tool)
    if rc != 0:
        print(f"coverage-gate: pytest failed (rc={rc})")
        return int(rc)

    total = covered = 0
    worst: list[tuple[float, str, int, int]] = []
    for root, _, files in os.walk(PKG):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            lines = executable_lines(path)
            if not lines:
                continue
            got = len(lines & hit.get(path, set()))
            total += len(lines)
            covered += got
            worst.append(
                (got / len(lines), os.path.relpath(path, REPO), got, len(lines))
            )
    pct = 100.0 * covered / max(total, 1)
    worst.sort()
    for frac, path, got, n in worst[:10]:
        print(f"  {frac * 100:5.1f}%  {path} ({got}/{n})")
    print(
        f"coverage-gate: {pct:.1f}% of {total} executable lines "
        f"(floor {args.fail_under}%)"
    )
    if pct < args.fail_under:
        print("coverage-gate: FAIL — below the floor")
        return 2
    print("coverage-gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
