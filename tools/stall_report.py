"""Stall attribution report: where a window of wall time actually went.

The answer to the ROADMAP's streaming question ("sustained is 0.26× of
single-batch — find where the 0.74 goes before rewriting"): given a
telemetry trail (`stream_bench --durable --trail ...`, a serve trail,
or a flight-recorder dump), reconstruct the interval timeline
(`mosaic_tpu/obs/timeline.py`), pick the attribution window (the
durable loop when present), and partition its wall time into the
closed stall-class set::

    {compile, transfer, queue_wait, host_callback, device, idle}

The partition is exact by construction (a priority boundary-sweep —
every instant has ONE owner), so the classes sum to the measured wall;
the CI lane asserts the 5% bound anyway as an end-to-end tripwire.

When the trail carries both the durable loop and a single-batch rate
(``stream_stage.single_batch``, emitted by `tools/stream_bench.py`),
the report additionally decomposes the sustained-vs-single loss:
``ideal_s`` is the wall the run WOULD take at the single-batch rate,
and the loss (``wall - ideal``) is split into the non-device classes
plus ``device_excess`` (device intervals beyond ideal — re-execution,
per-segment re-dispatch, scan overhead).

Conventions match `tools/trace_report.py`: human-readable report on
stderr, the LAST stdout line one machine-parseable JSON object;
``--against OTHER`` diffs class shares; ``--out`` also writes the JSON
to a file. ``--inject-slowdown KEY:FACTOR`` scales the ``seconds`` of
matching stage keys (fnmatch) before attribution — the CI negative
lane proves an injected stall surfaces in the RIGHT class.

Usage:
  python tools/stream_bench.py --durable --trail /tmp/stream.jsonl ...
  python tools/stall_report.py /tmp/stream.jsonl
  python tools/stall_report.py fresh.jsonl --against base.jsonl
  python tools/stall_report.py t.jsonl --inject-slowdown 'span.stream.snapshot:10'
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mosaic_tpu.obs import export, timeline  # noqa: E402


def inject_slowdown(events, spec: str) -> list[dict]:
    """Scale ``seconds`` of every event whose stage key fnmatches
    ``KEY`` by ``FACTOR``. The scaled interval is anchored at its
    COMPLETION stamp (``start_mono`` dropped, so the interval is
    re-derived as ``ts_mono - seconds``): the injected stall extends
    backward into the window, where attribution can see it, instead of
    overrunning the window's tail and getting clipped. Returns a new
    event list."""
    key_pat, factor_s = spec.rsplit(":", 1)
    factor = float(factor_s)
    out = []
    for e in events:
        key = timeline.event_key(e) if isinstance(e, dict) else None
        if (
            key is not None
            and fnmatch.fnmatchcase(key, key_pat)
            and isinstance(e.get("seconds"), (int, float))
        ):
            e = dict(e)
            e["seconds"] = round(float(e["seconds"]) * factor, 6)
            e.pop("start_mono", None)
        out.append(e)
    return out


def _find_stage(events, key: str) -> dict | None:
    for e in events:
        if isinstance(e, dict) and timeline.event_key(e) == key:
            return e
    return None


def build_report(events) -> dict | None:
    """The full stall report for one trail, or None when the trail has
    no usable window (no classified intervals at all)."""
    events = [e for e in events if isinstance(e, dict)]
    attr = timeline.attribute(events)
    if attr is None:
        return None
    wall = attr["wall_s"]
    classes = attr["classes"]
    loss_classes = {
        c: classes[c]["seconds"]
        for c in classes
        if c != "device"
    }
    report = {
        "metric": "stall_report",
        "window": attr["window"],
        "wall_s": wall,
        "classes": classes,
        "sum_s": attr["sum_s"],
        "sum_ok": abs(attr["sum_s"] - wall) <= 0.05 * max(wall, 1e-9),
        "segments": attr["segments"],
        "critical_path": attr["critical_path"],
        "top_stall": max(loss_classes, key=loss_classes.get),
    }

    # ---- sustained-vs-single decomposition (stream trails) ----------
    loop = _find_stage(events, "stream_stage.durable_loop")
    single = _find_stage(events, "stream_stage.single_batch")
    if loop is None:
        loop = _find_stage(events, "stream_stage.join_loop")
    if loop is not None and single is not None:
        single_rate = float(single.get("points_per_sec") or 0.0)
        sustained_rate = float(loop.get("points_per_sec") or 0.0)
        batch = int(loop.get("batch") or single.get("batch") or 0)
        n_batches = int(loop.get("n_batches") or loop.get("batches") or 0)
        resumed = int(loop.get("resumed_from") or 0)
        n_points = max(n_batches - resumed, 0) * batch
        if not n_points and sustained_rate:
            n_points = int(round(sustained_rate * wall))
        if single_rate > 0 and n_points > 0:
            ideal_s = n_points / single_rate
            loss = {
                "single_rate": round(single_rate, 1),
                "sustained_rate": round(sustained_rate, 1),
                "sustained_frac": round(
                    sustained_rate / single_rate, 4
                ),
                "n_points": n_points,
                "ideal_s": round(ideal_s, 6),
                "loss_s": round(wall - ideal_s, 6),
                "loss_classes": {
                    **{
                        c: round(s, 6)
                        for c, s in loss_classes.items()
                    },
                    "device_excess": round(
                        classes["device"]["seconds"] - ideal_s, 6
                    ),
                },
            }
            lc = loss["loss_classes"]
            loss["top_stall"] = max(lc, key=lc.get)
            report["loss"] = loss
            report["top_stall"] = loss["top_stall"]
    return report


def load_baseline(path: str) -> dict | None:
    """A baseline for ``--against``: either a raw trail (rebuilt into a
    report) or a committed ``stall_report`` artifact (used as-is), so
    cross-PR comparisons work from the repo-root JSON without the
    original trail."""
    rows = export.read_trail(path)
    if len(rows) == 1 and rows[0].get("metric") == "stall_report":
        return rows[0]
    return build_report(rows)


def diff_reports(fresh: dict, base: dict) -> dict:
    """Per-class share/seconds deltas between two reports, plus the
    sustained-vs-single loss deltas when both sides carry one."""
    out = {}
    keys = set(fresh["classes"]) | set(base["classes"])
    for c in sorted(keys):
        f = fresh["classes"].get(c, {"seconds": 0.0, "share": 0.0})
        b = base["classes"].get(c, {"seconds": 0.0, "share": 0.0})
        out[c] = {
            "seconds": round(f["seconds"] - b["seconds"], 6),
            "share": round(f["share"] - b["share"], 4),
        }
    fl, bl = fresh.get("loss"), base.get("loss")
    if fl and bl:
        out["loss"] = {
            "sustained_frac": round(
                fl["sustained_frac"] - bl["sustained_frac"], 4
            ),
            "sustained_frac_ratio": (
                round(fl["sustained_frac"] / bl["sustained_frac"], 3)
                if bl["sustained_frac"] else None
            ),
            "device_excess": round(
                fl["loss_classes"]["device_excess"]
                - bl["loss_classes"]["device_excess"], 6
            ),
        }
    return out


def render(report: dict) -> str:
    lines = [
        f"window: {report['window']['source']}  "
        f"wall {report['wall_s']:.4f}s  "
        f"({report['segments']} owner segments)",
        f"{'class':<14} {'seconds':>10} {'share':>8}",
    ]
    for c, v in sorted(
        report["classes"].items(),
        key=lambda kv: kv[1]["seconds"],
        reverse=True,
    ):
        lines.append(
            f"{c:<14} {v['seconds']:>10.4f} {v['share']:>7.1%}"
        )
    lines.append(
        f"sum {report['sum_s']:.4f}s vs wall {report['wall_s']:.4f}s "
        f"-> {'OK' if report['sum_ok'] else 'MISMATCH'}"
    )
    loss = report.get("loss")
    if loss:
        lines.append(
            f"sustained {loss['sustained_rate']:,.0f} pts/s = "
            f"{loss['sustained_frac']:.2%} of single-batch "
            f"{loss['single_rate']:,.0f}; ideal {loss['ideal_s']:.4f}s,"
            f" lost {loss['loss_s']:.4f}s:"
        )
        for c, s in sorted(
            loss["loss_classes"].items(),
            key=lambda kv: kv[1],
            reverse=True,
        ):
            lines.append(f"  {c:<16} {s:>10.4f}s")
    lines.append(f"top stall class: {report['top_stall']}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trail", help="JSONL trail or bench artifact")
    ap.add_argument(
        "--against", default=None,
        help="baseline to diff class shares (and loss decomposition) "
             "against: a trail, or a committed stall_report artifact",
    )
    ap.add_argument(
        "--out", default=None,
        help="also write the JSON report to this path",
    )
    ap.add_argument(
        "--inject-slowdown", default=None, metavar="KEY:FACTOR",
        help="scale seconds of matching stage keys before attribution "
             "(negative-lane self-test)",
    )
    args = ap.parse_args()

    events = export.read_trail(args.trail)
    if args.inject_slowdown:
        events = inject_slowdown(events, args.inject_slowdown)
    report = build_report(events)
    if report is None:
        print(
            "no classified intervals in trail; nothing to attribute",
            file=sys.stderr,
        )
        print(json.dumps({"metric": "stall_report", "error": "empty"}))
        return 1

    if args.against:
        base = load_baseline(args.against)
        if base is not None:
            report["diff"] = diff_reports(report, base)
            report["against"] = args.against

    print(render(report), file=sys.stderr)
    line = json.dumps(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
