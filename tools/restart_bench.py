"""Restart-storm bench: zero-cold-start serving over the AOT program store.

The claim under test (`mosaic_tpu/dispatch/programs.py`): once a serve
process has exported its compiled ladder, killing the process and
relaunching it against the same ``MOSAIC_PROGRAM_STORE`` must warm up by
LOADING serialized executables — ``cold_compiles == 0``, zero backend
compiles, admitted p99 within the deadline from the very first admitted
request — and every failure path must degrade to plain compilation with
bit-identical answers, never a wrong program, never a crash.

Lanes (parent process; each serve run is a REAL child process so jax's
in-memory executable cache cannot mask a store miss):

- **cold**: empty store, runs to completion — exports the ladder and
  records the compile-storm warmup cost the store amortizes;
- **storm**: ``--restarts`` relaunches, each SIGKILLed mid-load (after
  its early report flush) and each asserted to have warmed purely from
  the store (``aot.loaded > 0``, ``aot.exported == 0``,
  ``backend_compiles == 0``);
- **kill_mid_export**: a fresh store's child is SIGKILLed the moment the
  first payload lands — the atomic payload-before-sidecar write order
  means the relaunch sees at worst an orphaned payload (clean miss) and
  re-exports;
- **corrupt**: one payload's bytes are flipped in the populated store —
  the relaunch must record ``program_store_corrupt_skipped``, fall back
  to compilation, self-heal the entry, and still answer bit-identically.

Every lane's child answers a fixed probe set and reports its SHA-256;
the parent asserts ALL lanes hash identically. The last stdout line is
one machine-parseable JSON object (committed as ``SERVE_RESTART_r16``).

Fleet story: every child exports its telemetry trail (early flush
before the load phase, so a SIGKILLed child still leaves evidence;
final flush when it survives), each headed by the child's incarnation
id. The parent stitches ALL of them with `tools/fleet_report.py` into
one wall-clock timeline — ``detail.fleet`` carries the restart chain
(one link per incarnation, with the dark-gap seconds between a kill
and the relaunch's first event).

CPU CI smoke:
  JAX_PLATFORMS=cpu MOSAIC_BENCH_PLATFORM=cpu python tools/restart_bench.py \
      --restarts 2 --requests 120 --rate 120
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BBOX = (-25.0, -25.0, 35.0, 20.0)
RES = 3
PROBE_REQUESTS = 16
PROBE_ROWS = 96


def _build_index():
    """Deterministic synthetic workload: rebuildable identically in every
    child, so the tessellation fingerprint (the program-store key) is
    restart-stable by construction."""
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index

    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    col = wkt.from_wkt(
        [
            "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
            "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
            "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
        ]
    )
    index = build_chip_index(tessellate(col, grid, RES, keep_core_geoms=False))
    return index, grid


def _probe_set():
    rng = np.random.default_rng(123)
    return [
        rng.uniform(BBOX[:2], BBOX[2:], (PROBE_ROWS, 2))
        for _ in range(PROBE_REQUESTS)
    ]


def _write_report(path: str, report: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f)
    os.replace(tmp, path)


def child_main(args) -> None:
    """One serve lifetime: warm from the store, answer the probe set,
    flush an early report (the parent's kill gate), then serve open-loop
    until done or killed."""
    if os.environ.get("MOSAIC_BENCH_PLATFORM") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from mosaic_tpu.runtime import telemetry
    from mosaic_tpu.runtime.errors import Overloaded
    from mosaic_tpu.serve import BucketLadder, ServeEngine, backend_compiles

    t0 = time.perf_counter()
    index, grid = _build_index()
    bc0 = backend_compiles()
    with telemetry.capture() as events:
        engine = ServeEngine(
            index, grid, RES,
            ladder=BucketLadder(64, 1024),
            max_wait_s=0.002,
            queue_capacity=args.queue_cap,
            default_deadline_s=args.deadline_ms / 1e3,
            bounds=BBOX,
            program_store=args.store,
        )
        t_warm = time.perf_counter()
        warm = engine.warmup()
        warmup_wall = time.perf_counter() - t_warm

        # fixed probe set: the cross-lane bit-identity witness
        sha = hashlib.sha256()
        t_first = time.perf_counter()
        first_latency = None
        for pts in _probe_set():
            out = np.asarray(engine.join(pts, timeout=30.0))
            if first_latency is None:
                first_latency = time.perf_counter() - t_first
            sha.update(out.astype(np.int64).tobytes())

        def store_events():
            return {
                "corrupt_skipped": sum(
                    1 for e in events
                    if e.get("event") == "program_store_corrupt_skipped"
                ),
                "mismatch": sum(
                    1 for e in events
                    if e.get("event") == "program_store_mismatch"
                ),
                "loaded": sum(
                    1 for e in events
                    if e.get("event") == "program_store_loaded"
                ),
                "saved": sum(
                    1 for e in events
                    if e.get("event") == "program_store_saved"
                ),
            }

        bc1 = backend_compiles()
        report = {
            "phase": "serving",
            "incarnation": telemetry.INCARNATION,
            "warmup": warm,
            "warmup_wall_s": round(warmup_wall, 3),
            "startup_wall_s": round(time.perf_counter() - t0, 3),
            "first_latency_s": round(first_latency, 4),
            "backend_compiles": (
                bc1 - bc0 if bc0 is not None and bc1 is not None else None
            ),
            "cold_compiles": engine.metrics()["cold_compiles"],
            "answers_sha256": sha.hexdigest(),
            "store_events": store_events(),
        }
        # early flush BEFORE the load phase: a SIGKILLed child still
        # leaves its warmup/compile story for the parent to assert on
        _write_report(args.report, report)
        if args.trail:
            # same early-flush discipline for the trail: a SIGKILL
            # mid-load must still leave this incarnation's warmup
            # events for the parent's fleet stitch
            from mosaic_tpu import obs

            obs.write_jsonl(list(events), args.trail)

        rng = np.random.default_rng(args.seed)
        reqs = [
            rng.uniform(BBOX[:2], BBOX[2:], (int(n), 2))
            for n in rng.integers(1, args.rows_max + 1, args.requests)
        ]
        shed_submit = 0
        futures = []
        next_t = time.perf_counter()
        t_load = time.perf_counter()
        for pts in reqs:
            next_t += float(rng.exponential(1.0 / args.rate))
            lag = next_t - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            try:
                futures.append(engine.submit(pts))
            except Overloaded:
                shed_submit += 1
        for f in futures:
            try:
                f.result()
            except Overloaded:
                pass
        load_wall = time.perf_counter() - t_load

    m = engine.metrics()
    lat = telemetry.summarize(events, event="serve_request")
    bc2 = backend_compiles()
    report.update(
        phase="done",
        requests=args.requests,
        admitted=len(futures),
        shed_submit=shed_submit,
        shed_deadline=m["shed_deadline"],
        completed=m["completed"],
        load_wall_s=round(load_wall, 3),
        latency=lat,
        deadline_s=args.deadline_ms / 1e3,
        p99_under_deadline=bool(lat["p99"] <= args.deadline_ms / 1e3),
        cold_compiles=m["cold_compiles"],
        backend_compiles=(
            bc2 - bc0 if bc0 is not None and bc2 is not None else None
        ),
        store_events=store_events(),
    )
    engine.close()
    _write_report(args.report, report)
    if args.trail:
        from mosaic_tpu import obs

        obs.write_jsonl(events, args.trail)


# --------------------------------------------------------------- parent

def _spawn(store: str, report: str, args, extra=(), trail=None):
    if os.path.exists(report):
        os.remove(report)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--store", store, "--report", report,
        "--requests", str(args.requests), "--rate", str(args.rate),
        "--rows-max", str(args.rows_max), "--queue-cap", str(args.queue_cap),
        "--deadline-ms", str(args.deadline_ms), "--seed", str(args.seed),
        *(("--trail", trail) if trail else ()),
        *extra,
    ]
    return subprocess.Popen(cmd, stdout=sys.stderr, stderr=sys.stderr)


def _wait_report(proc, report: str, timeout: float) -> dict:
    """Block until the child's (early or final) report exists."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if os.path.exists(report):
            try:
                with open(report) as f:
                    return json.load(f)
            except ValueError:
                pass  # mid-replace; retry
        if proc.poll() is not None and not os.path.exists(report):
            raise RuntimeError(
                f"child exited rc={proc.returncode} without a report"
            )
        time.sleep(0.05)
    raise RuntimeError(f"no child report after {timeout}s")


def _run_to_completion(
    store: str, report: str, args, timeout=600.0, trail=None
) -> dict:
    proc = _spawn(store, report, args, trail=trail)
    rc = proc.wait(timeout=timeout)
    if rc != 0:
        raise RuntimeError(f"child failed rc={rc}")
    with open(report) as f:
        out = json.load(f)
    if out.get("phase") != "done":
        raise RuntimeError(f"child finished in phase {out.get('phase')!r}")
    return out


def _kill_mid_load(
    store: str, report: str, args, kill_after: float, trail=None
) -> dict:
    """Launch, wait for the early report (serving has begun), then
    SIGKILL mid-load and return the early report."""
    proc = _spawn(store, report, args, trail=trail)
    out = _wait_report(proc, report, timeout=600.0)
    time.sleep(kill_after)
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30.0)
    with open(report) as f:
        return json.load(f)


def _kill_mid_export(store: str, report: str, args, trail=None) -> int:
    """Launch against a fresh store and SIGKILL the instant the first
    payload file lands — the tightest window around the export write."""
    proc = _spawn(store, report, args, trail=trail)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 600.0:
        if glob.glob(os.path.join(store, "prog-*.bin")):
            break
        if proc.poll() is not None:
            break
        time.sleep(0.001)
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30.0)
    return len(glob.glob(os.path.join(store, "prog-*.bin")))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--store", default=None)
    ap.add_argument("--report", default=None)
    ap.add_argument("--restarts", type=int, default=3,
                    help="SIGKILL-mid-load relaunch count in the storm lane")
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--rate", type=float, default=150.0)
    ap.add_argument("--rows-max", type=int, default=256)
    ap.add_argument("--queue-cap", type=int, default=64)
    ap.add_argument("--deadline-ms", type=float, default=2000.0)
    ap.add_argument("--kill-after", type=float, default=0.4,
                    help="seconds into the load phase to SIGKILL")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--trail", default=None,
                    help="(child) export this lifetime's telemetry "
                    "trail as JSONL, incarnation-headed; the parent "
                    "sets this per child and stitches the fleet")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.child:
        child_main(args)
        return

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    t_all = time.perf_counter()
    detail: dict = {}
    line = {
        "metric": "restart_warmup_s",
        "value": 0.0,
        "unit": "s",
        "detail": detail,
    }
    failures: list = []

    def check(cond, what):
        if not cond:
            failures.append(what)
            print(f"FAIL: {what}", file=sys.stderr)

    try:
        work = tempfile.mkdtemp(prefix="restart_bench_")
        store = os.path.join(work, "programs")
        report = os.path.join(work, "report.json")
        fleet_dir = os.path.join(work, "fleet")
        os.makedirs(fleet_dir, exist_ok=True)
        fleet_trails: list[str] = []

        def _t(lane: str) -> str:
            path = os.path.join(fleet_dir, f"{lane}.jsonl")
            fleet_trails.append(path)
            return path

        # ---- cold: empty store, full run; exports the ladder
        cold = _run_to_completion(store, report, args, trail=_t("cold"))
        detail["cold"] = {
            k: cold[k] for k in (
                "warmup_wall_s", "startup_wall_s", "backend_compiles",
                "cold_compiles", "latency", "p99_under_deadline",
            )
        }
        detail["cold"]["aot"] = cold["warmup"].get("aot")
        check(cold["warmup"]["aot"]["exported"] > 0, "cold run exported programs")
        check(cold["cold_compiles"] == 0, "cold run cold_compiles == 0")
        ref_hash = cold["answers_sha256"]
        hashes = {"cold": ref_hash}

        # ---- storm: kill mid-load, relaunch; every relaunch must warm
        # purely from the store
        storm = []
        for i in range(max(args.restarts, 1)):
            final = i == args.restarts - 1
            if final:
                rep = _run_to_completion(
                    store, report, args, trail=_t(f"storm_{i}")
                )
            else:
                rep = _kill_mid_load(
                    store, report, args, args.kill_after,
                    trail=_t(f"storm_{i}"),
                )
            aot = rep["warmup"].get("aot") or {}
            entry = {
                "killed": not final,
                "warmup_wall_s": rep["warmup_wall_s"],
                "startup_wall_s": rep["startup_wall_s"],
                "first_latency_s": rep["first_latency_s"],
                "backend_compiles": rep["backend_compiles"],
                "cold_compiles": rep["cold_compiles"],
                "aot": aot,
            }
            if final:
                entry["latency"] = rep["latency"]
                entry["p99_under_deadline"] = rep["p99_under_deadline"]
                entry["admitted"] = rep["admitted"]
                entry["shed_submit"] = rep["shed_submit"]
                entry["shed_deadline"] = rep["shed_deadline"]
                check(
                    rep["p99_under_deadline"],
                    f"restart {i}: admitted p99 {rep['latency']['p99']} "
                    f"within deadline",
                )
            storm.append(entry)
            hashes[f"restart_{i}"] = rep["answers_sha256"]
            check(rep["cold_compiles"] == 0, f"restart {i}: cold_compiles == 0")
            check(
                rep["backend_compiles"] in (0, None),
                f"restart {i}: backend_compiles == 0 "
                f"(got {rep['backend_compiles']})",
            )
            check(aot.get("loaded", 0) > 0, f"restart {i}: warmed from store")
            check(aot.get("exported", 1) == 0, f"restart {i}: nothing re-exported")
        detail["storm"] = storm
        line["value"] = storm[-1]["warmup_wall_s"]
        detail["warmup_speedup"] = round(
            cold["warmup_wall_s"] / max(storm[-1]["warmup_wall_s"], 1e-9), 2
        )

        # ---- kill mid-export: fresh store, SIGKILL inside the export
        # window; the relaunch sees at worst an orphaned payload
        store2 = os.path.join(work, "programs_killed")
        payloads_at_kill = _kill_mid_export(
            store2, report, args, trail=_t("kill_mid_export")
        )
        sidecars_at_kill = len(glob.glob(os.path.join(store2, "prog-*.json")))
        rep = _run_to_completion(
            store2, report, args, trail=_t("relaunch")
        )
        detail["kill_mid_export"] = {
            "payloads_at_kill": payloads_at_kill,
            "sidecars_at_kill": sidecars_at_kill,
            "relaunch_aot": rep["warmup"].get("aot"),
            "relaunch_cold_compiles": rep["cold_compiles"],
            "store_events": rep["store_events"],
        }
        hashes["kill_mid_export"] = rep["answers_sha256"]
        check(rep["cold_compiles"] == 0, "kill_mid_export relaunch serves")

        # ---- corrupt: flip bytes in one payload of the GOOD store; the
        # relaunch must skip it (typed telemetry), recompile, self-heal
        victim = sorted(glob.glob(os.path.join(store, "prog-*.bin")))[0]
        blob = bytearray(open(victim, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        with open(victim, "wb") as f:
            f.write(blob)
        rep = _run_to_completion(store, report, args, trail=_t("corrupt"))
        detail["corrupt"] = {
            "aot": rep["warmup"].get("aot"),
            "cold_compiles": rep["cold_compiles"],
            "store_events": rep["store_events"],
        }
        hashes["corrupt"] = rep["answers_sha256"]
        check(
            rep["store_events"]["corrupt_skipped"] >= 1,
            "corrupt entry skipped with typed telemetry",
        )
        check(
            rep["warmup"]["aot"]["exported"] >= 1,
            "corrupt entry self-healed by re-export",
        )
        check(rep["cold_compiles"] == 0, "corrupt lane still serves")
        # self-heal proof: one more run loads everything cleanly
        rep = _run_to_completion(store, report, args, trail=_t("healed"))
        hashes["healed"] = rep["answers_sha256"]
        check(
            rep["store_events"]["corrupt_skipped"] == 0
            and rep["warmup"]["aot"]["exported"] == 0
            and rep["backend_compiles"] in (0, None),
            "store fully healed after corrupt-lane re-export",
        )

        # ---- fleet stitch: every child trail (killed children left
        # their early flush) merged onto one wall-clock timeline
        import fleet_report as _fleet

        live = [p for p in fleet_trails if os.path.exists(p)]
        _, fleet = _fleet.stitch(live)
        detail["fleet"] = {
            "trails": len(live),
            "incarnations": len(fleet["incarnations"]),
            "chain": fleet["chain"],
        }
        check(
            len(fleet["incarnations"]) == len(live),
            f"fleet stitch: one incarnation per child "
            f"({len(fleet['incarnations'])} vs {len(live)} trails)",
        )
        check(
            all("gap_s" in link for link in fleet["chain"][1:]),
            "fleet chain links every incarnation to its predecessor",
        )

        detail["answers_sha256"] = hashes
        check(
            len(set(hashes.values())) == 1,
            f"bit-identical answers across every lane ({hashes})",
        )
        detail["bit_identical"] = len(set(hashes.values())) == 1
        detail["restarts"] = args.restarts
        detail["requests"] = args.requests
        detail["deadline_s"] = args.deadline_ms / 1e3
        detail["failures"] = failures
        detail["passed"] = not failures
    except Exception as e:  # lint: broad-except-ok (the bench artifact line must still parse on ANY failure; the error lands in detail.failures and the exit code)
        detail["error"] = repr(e)[:400]
        detail["failures"] = failures + [f"exception: {e!r}"[:200]]
        detail["passed"] = False

    detail["total_wall_s"] = round(time.perf_counter() - t_all, 1)
    out = json.dumps(line)
    emit_to.write(out + "\n")
    emit_to.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if not detail.get("passed"):
        sys.exit(1)


if __name__ == "__main__":
    main()
