"""Self-diagnosis CLI: known failure signatures over committed evidence.

Dashboards show numbers; the doctor renders a VERDICT. Point it at any
mix of the repo's durable observability outputs — bench artifacts (one
JSON object with ``detail``), JSONL trails / flight-recorder dumps,
metrics snapshots, ops-server ``GET /`` documents — and it runs the
known-failure-signature checks this codebase has accumulated:

- **cold_compiles** — the zero-compile contract: every committed
  compile-after-warmup counter (``cold_compiles``,
  ``cold_compiles_after_swap``, ``relaunch_cold_compiles``,
  ``warm_backend_compiles``, ``relaunch_backend_compiles_serving``)
  must be 0, and a trail must contain no ``serve_compile`` event — a
  cold compile on the serve path after freeze means the AOT store or
  ladder freeze regressed;
- **snapshot_overlap** — any ``snapshot_overlap_fraction`` below 0.8
  means durable-stream snapshots stopped hiding behind compute;
- **shed_imbalance** — from TRAILS and METRIC SNAPSHOTS only (bench
  A/B artifacts shed on purpose): one tenant holding ≥ 90% of
  ``router_shed`` volume (≥ 50 sheds) while others admit is a noisy
  neighbor the router should have contained;
- **burn_rate** — any ``slo_violation`` event in a trail, breached SLO
  in an artifact's ``detail.slo``, or breached entry in an SLO
  snapshot is an active (or recorded) SLO breach;
- **cache_thrash** — ``dispatch_cache_stats`` events where a bounded
  cache sits full with misses outrunning hits 2:1, or an eviction
  counter past 100: the working set no longer fits.

Every check reports ``green`` or ``red`` with its findings; overall
``status`` is red when any check is. The LAST stdout line is one JSON
object (the repo-wide bench contract); exit code 1 on red. The scan
and checks run under timed ``ops_stage`` telemetry (``ops_stage.scan``,
``ops_stage.checks``), exportable with ``--trail`` — the doctor's own
work is gated by `tools/perf_gate.py` like every other stage.

Usage:
  python tools/doctor.py *.json                      # committed artifacts
  python tools/doctor.py /tmp/storm/*.jsonl          # live trails
  python tools/doctor.py SERVE_r16.json /tmp/t.jsonl --trail /tmp/doc.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: artifact detail keys whose committed value must be ZERO — each one
#: counts a compile that happened after the relevant warmup/freeze
ZERO_COMPILE_KEYS = frozenset({
    "cold_compiles",
    "cold_compiles_after_swap",
    "relaunch_cold_compiles",
    "warm_backend_compiles",
    "relaunch_backend_compiles_serving",
})

#: minimum acceptable snapshot_overlap_fraction (the durable-stream
#: lane commits ~0.96; below this, snapshots serialize behind compute)
OVERLAP_MIN = 0.8

#: shed_imbalance thresholds: one tenant with >= this share of >= this
#: many sheds, observed in a TRAIL or metrics snapshot
IMBALANCE_SHARE = 0.9
IMBALANCE_MIN_SHEDS = 50

#: cache_thrash thresholds
THRASH_MISS_RATIO = 2.0
THRASH_EVICTIONS = 100


def classify(path: str) -> tuple[str, object]:
    """``(kind, payload)`` for one input file: ``"trail"`` (list of
    event dicts — JSONL trails and recorder dumps), ``"artifact"``
    (bench JSON with ``detail``), ``"metrics"`` (a registry snapshot),
    ``"ops"`` (an ops-server ``GET /`` document), or ``"opaque"``."""
    with open(path) as f:
        text = f.read()
    try:
        # whole-file parse first: pretty-printed artifacts span lines
        rows = [json.loads(text)]
    except ValueError:
        rows = [
            json.loads(line)
            for line in text.splitlines() if line.strip()
        ]
    if not rows:
        return "opaque", None
    if len(rows) > 1:
        return "trail", rows
    doc = rows[0]
    if not isinstance(doc, dict):
        return "opaque", doc
    if "detail" in doc:
        return "artifact", doc
    if "metrics" in doc and ("health" in doc or "slo" in doc):
        return "ops", doc
    if doc and all(
        isinstance(v, dict) and "kind" in v and "series" in v
        for v in doc.values()
    ):
        return "metrics", doc
    return "opaque", doc


def _walk(obj, path=""):
    """Yield ``(dotted_path, key, value)`` for every dict key at any
    depth (lists descend with ``[i]`` segments)."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            p = f"{path}.{k}" if path else str(k)
            yield p, k, v
            yield from _walk(v, p)
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            yield from _walk(v, f"{path}[{i}]")


def check_cold_compiles(inputs) -> dict:
    findings = []
    for src, kind, payload in inputs:
        if kind == "artifact":
            for p, k, v in _walk(payload.get("detail")):
                if k in ZERO_COMPILE_KEYS and isinstance(v, (int, float)):
                    if v != 0:
                        findings.append({
                            "source": src, "where": p, "count": v,
                            "why": "compile after warmup/freeze",
                        })
        elif kind == "trail":
            n = sum(
                1 for e in payload
                if isinstance(e, dict) and e.get("event") == "serve_compile"
            )
            if n:
                findings.append({
                    "source": src, "where": "serve_compile events",
                    "count": n, "why": "cold compile on the serve path",
                })
    return _verdict("cold_compiles", findings)


def check_snapshot_overlap(inputs) -> dict:
    findings = []
    for src, kind, payload in inputs:
        if kind != "artifact":
            continue
        for p, k, v in _walk(payload.get("detail")):
            if k == "snapshot_overlap_fraction" and isinstance(
                v, (int, float)
            ) and v < OVERLAP_MIN:
                findings.append({
                    "source": src, "where": p,
                    "overlap": v, "min": OVERLAP_MIN,
                    "why": "snapshots no longer hide behind compute",
                })
    return _verdict("snapshot_overlap", findings)


def check_shed_imbalance(inputs) -> dict:
    findings = []
    for src, kind, payload in inputs:
        sheds: dict[str, float] = {}
        if kind == "trail":
            for e in payload:
                if isinstance(e, dict) and e.get("event") == "router_shed":
                    t = str(e.get("tenant", ""))
                    sheds[t] = sheds.get(t, 0) + 1
        elif kind in ("metrics", "ops"):
            snap = payload["metrics"] if kind == "ops" else payload
            m = snap.get("serve.router_shed")
            for s in (m or {}).get("series", []):
                t = s.get("labels", {}).get("tenant", "")
                sheds[t] = sheds.get(t, 0) + float(s.get("value", 0))
        else:
            continue  # bench A/B artifacts shed on purpose — excluded
        total = sum(sheds.values())
        if total < IMBALANCE_MIN_SHEDS or len(sheds) < 2:
            continue
        top_tenant, top = max(sheds.items(), key=lambda kv: kv[1])
        if top / total >= IMBALANCE_SHARE:
            findings.append({
                "source": src, "tenant": top_tenant,
                "sheds": top, "share": round(top / total, 4),
                "why": "one tenant holds nearly all shed volume",
            })
    return _verdict("shed_imbalance", findings)


def check_burn_rate(inputs) -> dict:
    findings = []
    for src, kind, payload in inputs:
        if kind == "trail":
            for e in payload:
                if isinstance(e, dict) and e.get("event") == "slo_violation":
                    findings.append({
                        "source": src, "slo": e.get("slo"),
                        "burn_rate": e.get("burn_rate"),
                        "window_s": e.get("window_s"),
                        "why": "burn-rate breach recorded in trail",
                    })
        elif kind == "artifact":
            slo = (payload.get("detail") or {}).get("slo") or {}
            for name in slo.get("breached", []):
                findings.append({
                    "source": src, "slo": name,
                    "why": "bench --slo lane verdict: breached",
                })
        elif kind == "ops":
            slos = (payload.get("slo") or {}).get("slos", {})
            for name, s in slos.items():
                if s.get("breached"):
                    findings.append({
                        "source": src, "slo": name,
                        "burn_rate": s.get("burn_short"),
                        "why": "live SLO currently breached",
                    })
    return _verdict("burn_rate", findings)


def check_cache_thrash(inputs) -> dict:
    findings = []
    for src, kind, payload in inputs:
        if kind == "trail":
            # last dispatch_cache_stats event wins — stats are cumulative
            last = None
            for e in payload:
                if isinstance(e, dict) and (
                    e.get("event") == "dispatch_cache_stats"
                ):
                    last = e
            if last is None:
                continue
            for name, st in last.items():
                if not isinstance(st, dict) or "maxsize" not in st:
                    continue
                maxsize = st.get("maxsize") or 0
                hits, misses = st.get("hits", 0), st.get("misses", 0)
                if (
                    maxsize > 0
                    and st.get("currsize", 0) >= maxsize
                    and misses > THRASH_MISS_RATIO * max(hits, 1)
                ):
                    findings.append({
                        "source": src, "cache": name,
                        "hits": hits, "misses": misses,
                        "why": "bounded cache full with misses "
                               "outrunning hits — working set too big",
                    })
        elif kind in ("metrics", "ops"):
            snap = payload["metrics"] if kind == "ops" else payload
            m = snap.get("dispatch.core_cache_evictions")
            total = sum(
                float(s.get("value", 0))
                for s in (m or {}).get("series", [])
            )
            if total >= THRASH_EVICTIONS:
                findings.append({
                    "source": src, "evictions": total,
                    "why": "core cache churning residents",
                })
    return _verdict("cache_thrash", findings)


def _verdict(check: str, findings: list) -> dict:
    return {
        "check": check,
        "status": "red" if findings else "green",
        "findings": findings,
    }


CHECKS = (
    check_cold_compiles,
    check_snapshot_overlap,
    check_shed_imbalance,
    check_burn_rate,
    check_cache_thrash,
)


def diagnose(paths) -> dict:
    """Scan ``paths``, run every signature check, return the report."""
    from mosaic_tpu.runtime import telemetry

    inputs, skipped = [], []
    with telemetry.timed("ops_stage", stage="scan", files=len(paths)):
        for path in paths:
            try:
                kind, payload = classify(path)
            except (OSError, ValueError) as e:
                skipped.append({"path": path, "error": repr(e)[:200]})
                continue
            if kind == "opaque":
                skipped.append({"path": path, "error": "unrecognized"})
            else:
                inputs.append((path, kind, payload))
    with telemetry.timed("ops_stage", stage="checks", inputs=len(inputs)):
        results = [check(inputs) for check in CHECKS]
    red = [r["check"] for r in results if r["status"] == "red"]
    return {
        "metric": "doctor",
        "status": "red" if red else "green",
        "red_checks": red,
        "inputs": {
            "scanned": len(inputs),
            "by_kind": _count_kinds(inputs),
            "skipped": skipped,
        },
        "checks": results,
    }


def _count_kinds(inputs) -> dict:
    out: dict[str, int] = {}
    for _, kind, _ in inputs:
        out[kind] = out.get(kind, 0) + 1
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="+",
                    help="bench artifacts (.json), JSONL trails / "
                         "recorder dumps, metrics or ops snapshots")
    ap.add_argument("--trail", default=None,
                    help="export the doctor's own telemetry trail "
                         "(ops_stage.scan / ops_stage.checks) as JSONL")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report here")
    args = ap.parse_args()

    from mosaic_tpu import obs
    from mosaic_tpu.runtime import telemetry

    with telemetry.capture() as events:
        report = diagnose(args.paths)
    if args.trail:
        obs.write_jsonl(events, args.trail)

    w = sys.stderr.write
    w(f"doctor: {report['status'].upper()} over "
      f"{report['inputs']['scanned']} input(s) "
      f"{report['inputs']['by_kind']}\n")
    for r in report["checks"]:
        mark = "OK " if r["status"] == "green" else "RED"
        w(f"  [{mark}] {r['check']}: {len(r['findings'])} finding(s)\n")
        for f_ in r["findings"]:
            w(f"        {json.dumps(f_)}\n")
    for s in report["inputs"]["skipped"]:
        w(f"  (skipped {s['path']}: {s['error']})\n")

    line = json.dumps(report)
    sys.stdout.write(line + "\n")
    sys.stdout.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 1 if report["status"] == "red" else 0


if __name__ == "__main__":
    sys.exit(main())
