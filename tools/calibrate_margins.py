"""Calibration sweep for the epsilon-band recheck constants.

The recheck band (`sql/join.py`) flags a point as borderline when its
cell-rounding margin is below ``CELL_MARGIN_K * eps`` or it lies within
``EDGE_BAND_K * eps * coord_scale`` of a probed chip edge. Those two
constants trade exactness risk against recheck cost: too narrow and an
f32-vs-f64 disagreement escapes the band (silent wrong answer); too wide
and the narrow re-join + host oracle see more points than they must.

This tool MEASURES the drift the constants must cover:

- **cell-margin drift** — over uniform global points at several H3
  resolutions (and a BNG lane), the largest margin (in units of
  ``eps(f32)``) at which the f32 cell assignment disagrees with the f64
  host path;
- **edge-band drift** — over a tessellated zone index, with cells pinned
  to the exact f64 assignment, the largest distance from a probed chip
  edge (in units of ``eps(f32) * coord_scale``) at which the f32
  ray-crossing parity path disagrees with the f64 host oracle.

Output: one JSON document (committed golden:
``tests/goldens/recheck_margins.json``); `tests/test_recheck.py` pins
that the shipped defaults keep >= 2x headroom over the recorded maxima.

Run: JAX_PLATFORMS=cpu python tools/calibrate_margins.py \
        [--n 200000] [--out tests/goldens/recheck_margins.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

EPS32 = float(np.finfo(np.float32).eps)


def global_points(n: int, seed: int) -> np.ndarray:
    """Area-uniform points over the sphere (degrees)."""
    rng = np.random.default_rng(seed)
    lng = rng.uniform(-180, 180, n)
    lat = np.degrees(np.arcsin(rng.uniform(-0.999, 0.999, n)))
    return np.stack([lng, lat], -1)


def measure_cell_drift(index_system, points: np.ndarray, res: int) -> dict:
    """Max margin (units of eps32) among f32-vs-f64 cell disagreements."""
    import jax.numpy as jnp

    c64 = np.asarray(index_system.point_to_cell(points, res))  # host f64
    c32, m = index_system.point_to_cell_margin(
        jnp.asarray(points, dtype=jnp.float32), res
    )
    c32, m = np.asarray(c32), np.asarray(m)
    dis = c32 != c64
    worst = float(m[dis, 0].max() / EPS32) if dis.any() else 0.0
    return {
        "resolution": res,
        "n_points": int(points.shape[0]),
        "n_disagreements": int(dis.sum()),
        "max_observed_k": round(worst, 4),
    }


def _seg_dist(px, py, e):
    """(R,) min f64 distance from each point to its row of segments.

    px, py: (R,); e: (R, E, 4) ax/ay/bx/by rows (pad rows are zero-length
    segments at the origin — masked by the caller via the parity bits).
    """
    ax, ay, bx, by = e[..., 0], e[..., 1], e[..., 2], e[..., 3]
    ex, ey = bx - ax, by - ay
    qx, qy = px[:, None] - ax, py[:, None] - ay
    dd = ex * ex + ey * ey
    t = np.clip((qx * ex + qy * ey) / np.where(dd == 0, 1.0, dd), 0.0, 1.0)
    rx, ry = qx - t * ex, qy - t * ey
    return rx * rx + ry * ry  # squared, per segment


def near_edge_points(host, n: int, seed: int, spread_k: float = 64.0
                     ) -> np.ndarray:
    """Adversarial probe set: points within ``spread_k * eps32 *
    coord_scale`` of random real chip edges — uniform points almost never
    land inside the drift band (1 disagreement per 200k observed), so the
    measured ceiling would be noise without concentrating samples where
    f32 parity can actually flip."""
    rng = np.random.default_rng(seed)
    u_idx, e_idx = np.nonzero(host.cell_ebits != 0)
    take = rng.integers(0, u_idx.size, n)
    e = host.cell_edges[u_idx[take], e_idx[take]]  # (n, 4) f64, shifted
    ax, ay, bx, by = e[:, 0], e[:, 1], e[:, 2], e[:, 3]
    t = rng.uniform(0.0, 1.0, n)
    px, py = ax + t * (bx - ax), ay + t * (by - ay)
    ex, ey = bx - ax, by - ay
    ln = np.hypot(ex, ey)
    ln = np.where(ln == 0, 1.0, ln)
    mag = rng.uniform(0.0, spread_k, n) * EPS32 * host.coord_scale
    sign = rng.choice([-1.0, 1.0], n)
    return np.stack(
        [px - sign * mag * ey / ln, py + sign * mag * ex / ln], 1
    ) + host.shift  # back to raw (unshifted) coordinates


def measure_edge_drift(
    zones, index_system, res: int, points: np.ndarray, seed: int = 0
) -> dict:
    """Max edge distance (units of eps32 * coord_scale) among f32-vs-f64
    parity disagreements, with the cell assignment pinned to f64.
    ``points`` is augmented with an equal-sized near-edge probe set."""
    import jax.numpy as jnp

    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import (
        build_chip_index,
        host_join_with_cells,
        pip_join_points,
    )

    idx = build_chip_index(
        tessellate(zones, index_system, res, keep_core_geoms=False)
    )
    host = idx.host
    points = np.concatenate(
        [points, near_edge_points(host, points.shape[0], seed + 1)]
    )
    # exact f64 cells for BOTH paths: any disagreement below is pure
    # probe-arithmetic drift, the band EDGE_BAND_K must cover
    cells = np.asarray(index_system.point_to_cell(points, res))
    want = host_join_with_cells(points, cells, host)
    shifted = jnp.asarray(points - host.shift, dtype=jnp.float32)
    got = np.asarray(pip_join_points(shifted, jnp.asarray(cells), idx))
    dis = np.nonzero(got != want)[0]
    worst = 0.0
    scale = EPS32 * host.coord_scale
    if dis.size:
        p = points[dis] - host.shift
        u = np.clip(
            np.searchsorted(host.cells, cells[dis]), 0, host.cells.size - 1
        )
        d2 = _seg_dist(p[:, 0], p[:, 1], host.cell_edges[u])
        d2 = np.where(host.cell_ebits[u] != 0, d2, np.inf).min(axis=1)
        hrow = host.cell_heavy[u]
        hv = np.nonzero(hrow >= 0)[0]
        if hv.size and host.heavy_edges.shape[0]:
            h = hrow[hv]
            d2h = _seg_dist(p[hv, 0], p[hv, 1], host.heavy_edges[h])
            d2h = np.where(
                host.heavy_ebits[h] != 0, d2h, np.inf
            ).min(axis=1)
            d2[hv] = np.minimum(d2[hv], d2h)
        worst = float(np.sqrt(d2.max()) / scale)
    return {
        "resolution": res,
        "n_points": int(points.shape[0]),
        "n_disagreements": int(dis.size),
        "max_observed_k": round(worst, 4),
        "coord_scale": round(float(host.coord_scale), 6),
    }


def run_sweep(n: int, seeds=(3, 11)) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from mosaic_tpu.core.index import BNG, H3
    from mosaic_tpu.datasets import synthetic_zones
    from mosaic_tpu.sql.join import CELL_MARGIN_K, EDGE_BAND_K

    cell_sweep = []
    for res in (5, 7, 9, 11):
        for seed in seeds:
            r = measure_cell_drift(H3, global_points(n, seed), res)
            r["system"] = "h3"
            r["seed"] = seed
            cell_sweep.append(r)
            print(f"[calibrate] h3 cell res={res} seed={seed}: "
                  f"max_k={r['max_observed_k']}", file=sys.stderr)
    # BNG margins are exact binning distances — drift only at the binning
    # boundary itself; measured for completeness, not the binding max
    rng = np.random.default_rng(9)
    bng_pts = np.column_stack(
        [rng.uniform(0, 700000, n // 2), rng.uniform(0, 1300000, n // 2)]
    )
    rb = measure_cell_drift(BNG, bng_pts, 4)
    rb["system"] = "bng"
    rb["seed"] = 9
    cell_sweep.append(rb)

    edge_sweep = []
    bbox = (-74.05, 40.60, -73.85, 40.85)
    for seed in seeds:
        zones = synthetic_zones(12, 12, bbox=bbox, seed=seed)
        rng = np.random.default_rng(seed + 100)
        pts = rng.uniform(bbox[:2], bbox[2:], (n, 2))
        r = measure_edge_drift(zones, H3, 9, pts, seed=seed)
        r["seed"] = seed
        edge_sweep.append(r)
        print(f"[calibrate] edge res=9 seed={seed}: "
              f"max_k={r['max_observed_k']} "
              f"({r['n_disagreements']} disagreements)", file=sys.stderr)

    cell_max = max(r["max_observed_k"] for r in cell_sweep)
    edge_max = max(r["max_observed_k"] for r in edge_sweep)
    return {
        "defaults": {
            "CELL_MARGIN_K": CELL_MARGIN_K,
            "EDGE_BAND_K": EDGE_BAND_K,
        },
        "cell_margin": {
            "max_observed_k": cell_max,
            "headroom_vs_default": round(CELL_MARGIN_K / max(cell_max, 1e-9), 3),
            "sweep": cell_sweep,
        },
        "edge_band": {
            "max_observed_k": edge_max,
            "headroom_vs_default": round(EDGE_BAND_K / max(edge_max, 1e-9), 3),
            "sweep": edge_sweep,
        },
        "meta": {
            "n_points_per_config": n,
            "seeds": list(seeds),
            "contract": "defaults must keep >= 2x headroom over "
                        "max_observed_k (tests/test_recheck.py)",
        },
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=200_000)
    ap.add_argument(
        "--out", default=os.path.join(REPO, "tests", "goldens",
                                      "recheck_margins.json")
    )
    args = ap.parse_args()
    doc = run_sweep(args.n)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({
        "cell_max_k": doc["cell_margin"]["max_observed_k"],
        "edge_max_k": doc["edge_band"]["max_observed_k"],
        "out": args.out,
    }))


if __name__ == "__main__":
    main()
