"""Expression-compiler smoke/bench: fused pipeline vs staged ops.

The CI twin of `mosaic_tpu/expr/`: write a 3-band MODIS-shaped GeoTIFF
(`tests/modis_fixture.py`), build the acceptance pipeline — NDVI, cloud
mask, zonal fold over vector zones — and run it two ways:

1. **fused** — ``ZonalEngine.map(expr)``: ONE device program per tile
   computes the whole tree and folds it (`expr/compile.py` pushes the
   expression into `zonal_fold_masked`). One launch per tile.
2. **staged** — the pre-existing op sequence: ``rst_mapbands`` evaluates
   the value tree into a NaN-nodata raster (one pixel program per
   tile), then ``ZonalEngine.zones`` folds that raster (a second fold
   program per tile). Two launches per tile, plus an intermediate
   (H, W) f64 raster that crosses the host boundary.

Asserted on the way (the CI expr-smoke lane re-asserts from the JSON):

- ``detail.agreement`` — fused vs staged AND fused vs the numpy-f64
  host interpreter (`expr/host_oracle.py`), fraction of stat rows that
  match bitwise; MUST be 1.0;
- ``detail.launches.fused < detail.launches.staged`` — launch counts
  from the per-path telemetry (tiles dispatched per stage), the fusion
  claim measured rather than asserted;
- after warmup the fused path adds ZERO backend compiles
  (``detail.warm_backend_compiles == 0``) — one program per bucket;
- every stage lands a timed ``expr_stage.<stage>`` telemetry event
  (map / pixels) — the keys `tools/perf_gate.py` gates.

The final stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr.

Usage (CI expr-smoke lane):
  python tools/expr_bench.py --width 960 --height 720 \
      --trail /tmp/expr.jsonl
  python tools/perf_gate.py --golden tests/goldens/perf_gate.json \
      --trail /tmp/expr.jsonl --stages-prefix expr_stage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: same bench world + zones as tools/raster_bench.py: the raster covers
#: x [-60, -12], y [4, 40]; the valid-data ellipse overlaps every zone;
#: zone edges cross tile boundaries, zone 0 carries a hole
WORLD = (-60.0, 48.0, 40.0, 36.0)
ZONES = [
    "POLYGON ((-56 12, -40 11, -34 22, -50 23, -56 21, -56 12), "
    "(-50 15, -46 15, -46 18, -50 18, -50 15))",
    "POLYGON ((-40 13, -33 13, -33 21, -36.5 17, -40 21, -40 13))",
    "POLYGON ((-58 13, -52 13, -52 17, -58 17, -58 13))",
]
NODATA = 32767


def bench_gt(width: int, height: int):
    x0, dx, y0, dy = WORLD
    return (x0, dx / width, 0.0, y0, 0.0, -dy / height)


def build_fixture(width: int, height: int, seed: int, tmpdir: str):
    """(path, grid, res, chip_index): a 3-band MODIS-shaped GeoTIFF
    (band 1 "red", band 2 "nir", band 3 "cloud score") + vector side."""
    from tests.modis_fixture import modis_like_field, write_tiled_geotiff

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index

    data = modis_like_field(width, height, bands=3, seed=seed)
    path = os.path.join(tmpdir, "expr_bench.tif")
    write_tiled_geotiff(
        path, data, gt=bench_gt(width, height), nodata=float(NODATA)
    )
    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    res = 3
    index = build_chip_index(
        tessellate(wkt.from_wkt(ZONES), grid, res, keep_core_geoms=False)
    )
    return path, grid, res, index


def result_rows(r) -> dict:
    """{key: (count, sum, min, max)} with float bit patterns preserved
    (repr-level equality == bit identity for finite f64)."""
    return {
        int(k): (int(c), float(s), float(mn), float(mx))
        for k, c, s, mn, mx in zip(r.keys, r.count, r.sum, r.min, r.max)
    }


def agreement(got, want) -> float:
    """Fraction of stat rows that match bitwise (keys, count, and the
    f64 bit patterns of sum/min/max)."""
    a, b = result_rows(got), result_rows(want)
    keys = set(a) | set(b)
    if not keys:
        return 1.0
    return sum(1 for k in keys if a.get(k) == b.get(k)) / len(keys)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=960)
    ap.add_argument("--height", type=int, default=720)
    ap.add_argument("--tile", default="256x256", help="TH x TW")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail as JSONL")
    args = ap.parse_args()

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    detail: dict = {}
    line = {"metric": "expr_fused_pixels_per_sec", "value": 0.0,
            "unit": "pixels/s", "detail": detail}
    stages: list = []
    root_span = None
    rc = 1
    try:
        import jax

        from mosaic_tpu import expr as E, obs
        from mosaic_tpu.dispatch import core as dispatch
        from mosaic_tpu.functions.raster import rst_mapbands
        from mosaic_tpu.raster import read_raster
        from mosaic_tpu.raster.zonal import ZonalEngine
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.sql import RasterStream

        tile = tuple(int(p) for p in args.tile.lower().split("x"))
        cap = telemetry.capture()
        stages = cap.__enter__()
        root_span = obs.start_span(
            "expr_bench", width=args.width, height=args.height
        )
        detail["platform"] = str(jax.devices()[0].platform)
        detail["shape"] = [args.height, args.width]
        detail["tile"] = list(tile)

        # the acceptance pipeline: NDVI, cloud mask, zonal fold. The
        # (red + nir) > 0 guard keeps 0/0 = NaN off VALID pixels —
        # NaN produced on a valid pixel is outside the bit-identity
        # contract (mask first, always)
        value = E.norm_diff(E.band(2), E.band(1)).mask_where(
            ((E.band(1) + E.band(2)) > 0.0) & (E.band(3) < 2600.0)
        )
        pipeline = value.zonal(by="zones")

        with tempfile.TemporaryDirectory() as tmpdir:
            path, grid, res, index = build_fixture(
                args.width, args.height, args.seed, tmpdir
            )
            raster = read_raster(path)
            pixels = raster.width * raster.height
            eng = ZonalEngine(grid, res, chip_index=index, lane="fold")

            # ---- fused: warmup compiles, then a warm timed map that
            # must add ZERO backend compiles (one program per bucket)
            eng.warmup_expr(pipeline, raster, tile=tile)
            c0 = dispatch.backend_compiles()
            t0 = time.perf_counter()
            fused = eng.map(pipeline, raster, tile=tile)
            fused_s = time.perf_counter() - t0
            warm_compiles = dispatch.backend_compiles() - c0
            detail["warm_backend_compiles"] = int(warm_compiles or 0)

            # ---- staged: the same pipeline as the pre-existing op
            # sequence (pixel program -> intermediate raster -> fold)
            t0 = time.perf_counter()
            ndvi_raster = rst_mapbands([raster], value, tile=tile)[0]
            staged = eng.zones(ndvi_raster, tile=tile)
            staged_s = time.perf_counter() - t0

            # ---- oracle: the numpy-f64 interpreter of the same tree
            oracle = E.host_expr_zonal_oracle(
                raster, pipeline, index_system=grid, resolution=res,
                chip_index=index, tile=tile,
            )

            # ---- fused durable scan rides the same program
            scan = RasterStream(index, grid, res).scan(
                raster, expr=pipeline, tile=tile,
                run_dir=os.path.join(tmpdir, "run"), snapshot_every=8,
            )

        agree = {
            "staged": agreement(fused, staged),
            "oracle": agreement(fused, oracle),
            "scan": agreement(scan.stats, fused),
        }
        detail["agreement"] = agree
        detail["zones_hit"] = int(len(fused.keys))

        # launch counts from the per-path telemetry: tiles dispatched
        # per stage. Fused = one program per tile; staged = a pixel
        # program per tile PLUS a fold program per tile.
        fused_tiles = staged_px_tiles = staged_fold_tiles = 0
        for e in stages:
            if e.get("event") == "expr_stage":
                if e.get("stage") == "map" and not fused_tiles:
                    fused_tiles = int(e.get("ntiles") or 0)
                elif e.get("stage") == "pixels":
                    staged_px_tiles += int(e.get("ntiles") or 0)
            elif (
                e.get("event") == "raster_stage"
                and e.get("stage") == "zonal"
            ):
                staged_fold_tiles += int(e.get("ntiles") or 0)
        launches = {
            "fused": fused_tiles,
            "staged": staged_px_tiles + staged_fold_tiles,
        }
        detail["launches"] = launches
        detail["seconds"] = {
            "fused": round(fused_s, 6),
            "staged": round(staged_s, 6),
        }
        detail["staged_over_fused"] = round(
            staged_s / max(fused_s, 1e-9), 3
        )
        line["value"] = round(pixels / max(fused_s, 1e-9), 1)

        bad = {k: v for k, v in agree.items() if v != 1.0}
        if bad:
            raise AssertionError(
                f"agreement below 1.0: {bad} — the fused program broke "
                "the bit-identity contract"
            )
        if not launches["fused"] or (
            launches["fused"] >= launches["staged"]
        ):
            raise AssertionError(
                f"fusion claim failed: {launches} — the fused path must "
                "launch strictly fewer programs than the staged one"
            )
        if warm_compiles:
            raise AssertionError(
                f"warm fused map compiled {warm_compiles} programs — "
                "warmup must cover every bucket signature"
            )
        rc = 0
    except Exception as e:  # lint: broad-except-ok (bench must always emit its JSON line; rc carries failure)
        detail["error"] = repr(e)[:400]

    if root_span is not None:
        try:
            root_span.end()
        except Exception:  # lint: broad-except-ok (span cleanup must not mask the bench result)
            pass
    if args.trail and stages:
        try:
            from mosaic_tpu import obs as _obs

            _obs.write_jsonl(stages, args.trail)
        except Exception as e:  # lint: broad-except-ok (a sick trail disk degrades the trail, not the bench)
            detail["trail_error"] = repr(e)[:200]

    emit_to.write(json.dumps(line) + "\n")
    emit_to.flush()
    return rc


if __name__ == "__main__":
    sys.exit(main())
