"""Stitch many processes' trails into one logical fleet timeline.

A restart storm is N processes, N monotonic clocks, N JSONL trails —
and no single trail tells the story. Every trail this repo writes
(bench ``--trail`` exports via `obs.write_jsonl`, flight-recorder
dumps) opens with one ``event="incarnation"`` line: the process's
:data:`~mosaic_tpu.runtime.telemetry.INCARNATION` id plus a paired
``ts_mono``/``ts_epoch`` sample. That pair is the bridge between
clocks: any event's wall time is

    ts_wall = anchor.ts_epoch + (e.ts_mono - anchor.ts_mono)

so this tool can merge trails from any number of incarnations onto ONE
wall-clock axis:

- every event gains ``incarnation`` and ``ts_wall`` fields and the
  merged trail is sorted by ``ts_wall`` (ties by incarnation, then
  ``seq`` — within one incarnation the original total order is
  preserved);
- per-incarnation summary: pid, start wall time, span covered, event
  count, and the trail files it came from;
- **incarnation links**: trace ids seen in more than one incarnation
  (a trace that survived a handoff), plus the restart chain — each
  incarnation's predecessor on the wall clock, with the gap seconds
  (how long the fleet slot was dark during the restart).

Trails WITHOUT an incarnation header (pre-ops-plane exports) still
stitch: they get a synthetic ``<file:stem>`` incarnation and their raw
monotonic stamps as ``ts_wall`` — ordering within the trail survives,
cross-trail placement is best-effort.

Usage:
  python tools/fleet_report.py /tmp/storm/*.jsonl [--out merged.jsonl]
  python tools/trace_report.py --fleet /tmp/storm/*.jsonl   # same core

Human-readable summary on stderr; the LAST stdout line is one JSON
object (the repo-wide bench contract). ``--out`` writes the merged
trail as JSONL, readable by `tools/trace_report.py` and
`tools/doctor.py`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def stitch(paths) -> tuple[list[dict], dict]:
    """Merge trails from ``paths`` onto one wall-clock axis.

    Returns ``(events, summary)``: the merged, ``ts_wall``-sorted event
    list (every event stamped with ``incarnation`` and ``ts_wall``) and
    the fleet summary (per-incarnation stats, restart chain, and
    cross-incarnation trace links).
    """
    from mosaic_tpu.obs import export
    from mosaic_tpu.runtime import telemetry

    merged: list[dict] = []
    incarnations: dict[str, dict] = {}
    with telemetry.timed("ops_stage", stage="stitch", trails=len(paths)):
        for path in paths:
            rows = export.read_trail(path)
            anchor = None
            if rows and isinstance(rows[0], dict) and (
                rows[0].get("event") == "incarnation"
            ):
                anchor = rows[0]
            if anchor is not None and isinstance(
                anchor.get("ts_mono"), (int, float)
            ) and isinstance(anchor.get("ts_epoch"), (int, float)):
                inc = str(anchor.get("incarnation"))
                offset = anchor["ts_epoch"] - anchor["ts_mono"]
                pid = anchor.get("pid")
            else:
                # pre-ops-plane trail: synthetic incarnation, raw
                # monotonic stamps as the wall axis (best-effort)
                inc = f"file:{os.path.splitext(os.path.basename(path))[0]}"
                offset = 0.0
                pid = None
            info = incarnations.setdefault(inc, {
                "incarnation": inc,
                "pid": pid,
                "synthetic": anchor is None,
                "trails": [],
                "events": 0,
                "first_wall": None,
                "last_wall": None,
            })
            info["trails"].append(path)
            for e in rows:
                if not isinstance(e, dict):
                    continue
                if e.get("event") == "incarnation":
                    continue
                t = e.get("ts_mono")
                wall = (
                    round(t + offset, 6)
                    if isinstance(t, (int, float)) else None
                )
                row = dict(e, incarnation=inc)
                if wall is not None:
                    row["ts_wall"] = wall
                    if info["first_wall"] is None or wall < info["first_wall"]:
                        info["first_wall"] = wall
                    if info["last_wall"] is None or wall > info["last_wall"]:
                        info["last_wall"] = wall
                info["events"] += 1
                merged.append(row)
        merged.sort(key=lambda e: (
            e.get("ts_wall", 0.0), e.get("incarnation", ""),
            e.get("seq", 0),
        ))
        summary = _summarize(merged, incarnations)
    return merged, summary


def _summarize(merged: list[dict], incarnations: dict) -> dict:
    # restart chain: incarnations in start order, gap to predecessor =
    # how long the slot was dark between one process's last event and
    # the next process's first
    chain = []
    ordered = sorted(
        (i for i in incarnations.values() if i["first_wall"] is not None),
        key=lambda i: i["first_wall"],
    )
    prev = None
    for info in ordered:
        link = {
            "incarnation": info["incarnation"],
            "start_wall": info["first_wall"],
            "span_s": round(info["last_wall"] - info["first_wall"], 6),
            "events": info["events"],
        }
        if prev is not None:
            link["prev"] = prev["incarnation"]
            link["gap_s"] = round(
                info["first_wall"] - prev["last_wall"], 6
            )
        chain.append(link)
        prev = info

    # cross-incarnation trace links: a trace id observed from more than
    # one process (e.g. a request traced across a handoff)
    trace_incs: dict = {}
    for e in merged:
        tid = e.get("trace_id")
        if tid:
            trace_incs.setdefault(tid, set()).add(e["incarnation"])
    links = {
        tid: sorted(incs)
        for tid, incs in trace_incs.items() if len(incs) > 1
    }

    return {
        "incarnations": {
            inc: {k: v for k, v in info.items() if k != "trails"}
            | {"trails": list(info["trails"])}
            for inc, info in incarnations.items()
        },
        "chain": chain,
        "cross_incarnation_traces": links,
        "events": len(merged),
    }


def fleet_report(paths, out: str | None = None) -> dict:
    """The full report dict for ``paths`` (the ``--fleet`` entry point
    `tools/trace_report.py` shares); writes the merged trail to ``out``
    when given."""
    from mosaic_tpu.obs import export

    merged, summary = stitch(paths)
    if out:
        # the merged trail is already multi-incarnation — no header
        export.write_jsonl(merged, out, stamp_incarnation=False)
    return {
        "metric": "fleet_report",
        "trails": list(paths),
        "events": summary["events"],
        "incarnations": len(summary["incarnations"]),
        "chain": summary["chain"],
        "cross_incarnation_traces": summary["cross_incarnation_traces"],
        "detail": {"incarnations": summary["incarnations"]},
        "out": out,
    }


def render(report: dict, w) -> None:
    """Human-readable fleet summary (stderr side of the contract)."""
    w(f"fleet: {report['incarnations']} incarnation(s), "
      f"{report['events']} events from {len(report['trails'])} trail(s)\n")
    for link in report["chain"]:
        gap = (
            f"  (+{link['gap_s']:.3f}s after {link['prev']})"
            if "prev" in link else ""
        )
        w(f"  {link['incarnation']}: {link['events']} events over "
          f"{link['span_s']:.3f}s{gap}\n")
    n_links = len(report["cross_incarnation_traces"])
    if n_links:
        w(f"  {n_links} trace(s) span incarnations\n")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trails", nargs="+",
                    help="JSONL trails / recorder dumps to stitch")
    ap.add_argument("--out", default=None,
                    help="write the merged trail (JSONL) here")
    ap.add_argument("--trail", default=None,
                    help="export this run's own telemetry trail "
                         "(ops_stage.stitch) as JSONL — the perf "
                         "gate's ops odds-pool input")
    args = ap.parse_args()

    from mosaic_tpu import obs
    from mosaic_tpu.runtime import telemetry

    with telemetry.capture() as events:
        report = fleet_report(args.trails, out=args.out)
    if args.trail:
        obs.write_jsonl(events, args.trail)
    render(report, sys.stderr.write)
    sys.stdout.write(json.dumps(report) + "\n")
    sys.stdout.flush()


if __name__ == "__main__":
    main()
