"""Self-tuning optimizer A/B bench: recommended knobs vs built-in defaults.

The CI twin of `mosaic_tpu/tune/`: two adversarial synthetic workloads on
the CUSTOM grid, each profiled (`tune.profiler`), each given a
recommendation (`tune.recommend` + committed bench history as priors), and
each run BOTH ways — the hand default configuration against the
recommended `TuningProfile` flowing through the normal ``profile=`` entry
points. The workloads are adversarial in opposite directions:

- **dense-urban (resident)** — many small polygons in a ~1 deg bbox, a
  large resident point stream. The hand default resolution under-
  tessellates (fat per-cell chip lists), so steady-state join time is
  dominated by probe work; the analyzer's finer resolution pays. Metric:
  warm join seconds against a resident index (build amortized, reported).
- **sparse-continental (one-shot)** — a handful of huge polygons across
  a 60x30 deg bbox, a sparse one-shot point batch. The same hand default
  resolution now OVER-tessellates (hundreds of thousands of cells for 4
  polygons); the analyzer's coarser pick collapses the build. Metric:
  end-to-end tessellate + index build + join seconds.

Asserted on the way (the CI tune-smoke lane re-asserts from the JSON):

- results are **bit-identical** across profiles on both workloads —
  ``pip_join(recheck=True)`` answers are f64-exact, hence
  resolution-independent (`detail.<workload>.bit_identical`);
- recommended is >= default on both workloads and strictly better on at
  least one (``value`` is the MIN speedup across workloads);
- the serve leg round-trips the recommendation through a versioned
  `ProfileStore` (fingerprinted against the recommended index), hot-swaps
  the live engine, and the swap introduces ZERO cold compiles
  (``detail.serve.cold_compiles_after_swap == 0``) while post-swap
  answers equal the device-path reference join;
- every recommendation carries its machine-checkable rationale
  ``{knob, value, rule, evidence}`` (re-asserted here);
- every stage lands a timed ``tune_stage.<stage>`` telemetry event
  (profile / recommend / ab_default / ab_recommended / hot_swap) — the
  keys `tools/perf_gate.py` gates.

The final stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr.

Usage (CI tune-smoke lane):
  python tools/tune_bench.py --points-a 200000 --trail /tmp/tune.jsonl
  python tools/perf_gate.py --golden tests/goldens/perf_gate.json \
      --trail /tmp/tune.jsonl --stages-prefix tune_stage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the hand default the recommendation is judged against — a plausible
#: global pick for the CUSTOM(10 deg root, 2 splits) grid: cells of
#: 10/2^6 ~ 0.16 deg, reasonable for country-scale data, adversarially
#: wrong in opposite directions for the two bench workloads
DEFAULT_RES = 6

#: dense-urban bbox: ~1x1 deg (small polygons, dense points)
CITY = (-74.5, 40.0, -73.5, 41.0)
#: sparse-continental bbox: 60x30 deg (4 huge polygons, sparse points)
CONT = (-60.0, -30.0, 0.0, 0.0)


def build_index(polys, grid, res):
    """(chip_index, seconds) — tessellate + chip-index build, timed."""
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index

    t0 = time.perf_counter()
    index = build_chip_index(
        tessellate(polys, grid, res, keep_core_geoms=False)
    )
    return index, time.perf_counter() - t0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points-a", type=int, default=500_000,
                    help="dense-urban resident point count")
    ap.add_argument("--points-b", type=int, default=50_000,
                    help="sparse-continental one-shot point count")
    ap.add_argument("--zones-a", type=int, default=10,
                    help="dense-urban zone grid side (n x n polygons)")
    ap.add_argument("--runs", type=int, default=2,
                    help="timed repetitions per resident arm (best-of)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail as JSONL")
    args = ap.parse_args()

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    detail: dict = {}
    line = {"metric": "tune_recommended_over_default", "value": 0.0,
            "unit": "x", "detail": detail}
    stages: list = []
    root_span = None
    rc = 1
    try:
        import jax
        import numpy as np

        from mosaic_tpu import datasets, obs
        from mosaic_tpu.core.index import CustomIndexSystem, GridConf
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.serve import ServeEngine
        from mosaic_tpu.sql.join import pip_join
        from mosaic_tpu.tune import (
            ProfileStore,
            TuningProfile,
            index_fingerprint,
            load_priors,
            profile_points,
            profile_polygons,
            recommend,
        )

        cap = telemetry.capture()
        stages = cap.__enter__()
        root_span = obs.start_span(
            "tune_bench", points_a=args.points_a, points_b=args.points_b
        )
        detail["platform"] = str(jax.devices()[0].platform)
        detail["default_resolution"] = DEFAULT_RES

        grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
        priors = load_priors()
        detail["priors"] = sorted(
            name for name in priors.get("artifacts", {})
        )

        na = args.zones_a
        workloads = {
            "dense_urban": {
                "mode": "resident",
                "polys": datasets.synthetic_zones(na, na, bbox=CITY,
                                                  seed=args.seed),
                "points": datasets.random_points(args.points_a, bbox=CITY,
                                                 seed=args.seed + 1),
            },
            "sparse_continental": {
                "mode": "one_shot",
                "polys": datasets.synthetic_zones(2, 2, bbox=CONT,
                                                  seed=args.seed, verts=48),
                "points": datasets.random_points(args.points_b, bbox=CONT,
                                                 seed=args.seed + 2),
            },
        }

        speedups = {}
        serve_ctx = None  # (rec_index, rec_profile, default_index, points)
        for name, w in workloads.items():
            polys, pts = w["polys"], w["points"]
            wd: dict = {"mode": w["mode"], "n_points": int(pts.shape[0]),
                        "n_polygons": len(polys)}
            detail[name] = wd

            # ---- profile both sides, recommend, merge. The default
            # index doubles as the point profiler's resident target.
            default_index, build_default_s = build_index(
                polys, grid, DEFAULT_RES
            )
            prof_poly = profile_polygons(polys, grid)
            prof_pts = profile_points(
                pts, default_index, grid, DEFAULT_RES, seed=args.seed
            )
            rec = TuningProfile.merged(
                recommend(prof_poly, priors), recommend(prof_pts, priors)
            )
            bad = [r for r in rec.rationale
                   if {"knob", "value", "rule", "evidence"} - set(r)]
            if bad or not rec.rationale:
                raise AssertionError(
                    f"{name}: recommendation rationale is not "
                    f"machine-checkable: {bad or 'empty'}"
                )
            rec_res = int(rec.resolution)
            rec_index, build_rec_s = build_index(polys, grid, rec_res)
            wd["recommended"] = {
                k: v for k, v in rec.as_dict().items()
                if k not in ("rationale", "source") and v is not None
            }
            wd["rationale_rules"] = sorted(
                {r["rule"] for r in rec.rationale}
            )
            wd["build_seconds"] = {
                "default": round(build_default_s, 4),
                "recommended": round(build_rec_s, 4),
            }

            # ---- the two arms. recheck=True answers are f64-exact and
            # therefore resolution-independent: bit-identity across the
            # two profiles is a correctness assertion, not luck.
            def arm(tag, index, res, profile, kw_pts=pts, kw_name=name,
                    mode=w["mode"], kw_polys=polys):
                best, out = float("inf"), None
                runs = args.runs if mode == "resident" else 1
                for _ in range(runs):
                    with telemetry.timed(
                        "tune_stage", stage=tag, workload=kw_name
                    ):
                        t0 = time.perf_counter()
                        if mode == "one_shot":
                            # one-shot pays tessellation + build too
                            index2, _ = build_index(kw_polys, grid, res)
                        else:
                            index2 = index
                        out = pip_join(
                            kw_pts, None, grid,
                            None if profile is not None else res,
                            chip_index=index2, recheck=True,
                            profile=profile,
                        )
                        best = min(best, time.perf_counter() - t0)
                return best, np.asarray(out)

            # resident arms warm the jit caches once, untimed
            if w["mode"] == "resident":
                pip_join(pts, None, grid, DEFAULT_RES,
                         chip_index=default_index, recheck=True)
                pip_join(pts, None, grid, None, chip_index=rec_index,
                         recheck=True, profile=rec)
            default_s, out_default = arm(
                "ab_default", default_index, DEFAULT_RES, None
            )
            rec_s, out_rec = arm(
                "ab_recommended", rec_index, rec_res, rec
            )

            identical = bool(np.array_equal(out_default, out_rec))
            wd["bit_identical"] = identical
            wd["seconds"] = {"default": round(default_s, 4),
                             "recommended": round(rec_s, 4)}
            speedups[name] = default_s / max(rec_s, 1e-9)
            wd["speedup"] = round(speedups[name], 3)
            if not identical:
                raise AssertionError(
                    f"{name}: recommended profile changed the answers — "
                    "recheck=True joins must be bit-identical across "
                    "resolutions"
                )
            if name == "dense_urban":
                serve_ctx = (rec_index, rec, default_index, pts)

        # ---- serve leg: store round-trip + hot swap on the live engine
        rec_index, rec, default_index, pts = serve_ctx
        serve: dict = {}
        detail["serve"] = serve
        queries = [pts[i * 512:(i + 1) * 512] for i in range(8)]
        with tempfile.TemporaryDirectory() as tmpdir:
            store = ProfileStore(os.path.join(tmpdir, "profiles"))
            fp = index_fingerprint(rec_index)
            store.save(rec, fingerprint=fp)
            loaded, payload = store.load_latest(expect_fingerprint=fp)
            serve["store_version"] = payload["profile_version"]

            with ServeEngine(
                default_index, grid, DEFAULT_RES, max_wait_s=0.0005
            ) as engine:
                engine.warmup()
                for q in queries:  # pre-swap traffic on the old core
                    engine.join(q, timeout=30.0)
                with telemetry.timed("tune_stage", stage="hot_swap"):
                    stats = engine.hot_swap(rec_index, profile=loaded)
                serve["swap_warmup"] = stats
                post = [
                    np.asarray(engine.join(q, timeout=30.0))
                    for q in queries
                ]
                cold = int(engine.metrics()["cold_compiles"])
                serve["cold_compiles_after_swap"] = cold
                serve["post_probe"] = engine.probe
                reference = pip_join(
                    np.concatenate(queries), None, grid,
                    int(rec.resolution), chip_index=rec_index,
                    recheck=False, probe=engine.probe,
                    writeback=engine.writeback, lookup=engine.lookup,
                )
                agree = bool(np.array_equal(
                    np.concatenate(post).astype(np.int64),
                    np.asarray(reference).astype(np.int64),
                ))
                serve["post_matches_reference"] = agree
        if cold:
            raise AssertionError(
                f"hot swap leaked {cold} cold compiles — warmup must "
                "precompile every recommended ladder rung before rebind"
            )
        if not agree:
            raise AssertionError(
                "post-swap serve answers diverge from the device-path "
                "reference join on the recommended index"
            )

        worst = min(speedups.values())
        best = max(speedups.values())
        detail["speedups"] = {k: round(v, 3) for k, v in speedups.items()}
        line["value"] = round(worst, 3)
        if worst < 1.0 or best < 1.1:
            raise AssertionError(
                f"recommendation did not pay: speedups {speedups} — must "
                "be >= 1.0 on both workloads and > 1.1 on at least one"
            )
        rc = 0
    except Exception as e:  # lint: broad-except-ok (bench must always emit its JSON line; rc carries failure)
        detail["error"] = repr(e)[:400]

    if root_span is not None:
        try:
            root_span.end()
        except Exception:  # lint: broad-except-ok (span cleanup must not mask the bench result)
            pass
    if args.trail and stages:
        try:
            from mosaic_tpu import obs as _obs

            _obs.write_jsonl(stages, args.trail)
        except Exception as e:  # lint: broad-except-ok (a sick trail disk degrades the trail, not the bench)
            detail["trail_error"] = repr(e)[:200]

    emit_to.write(json.dumps(line) + "\n")
    emit_to.flush()
    return rc


if __name__ == "__main__":
    sys.exit(main())
