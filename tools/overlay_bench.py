"""Overlay-join smoke/bench: device candidates + fused measures vs host twin.

The CI twin of the `sql/overlay.py` device lane: build two overlapping
square-grid polygon tables at >=100k-chip scale, tessellate once, run
`prepare_overlay` once (the amortized host pass), then measure the same
`st_overlap_fraction` tree two ways:

1. **device** — `overlay_measures(lane="device")`: candidate generation
   as a sorted segment equi-join on device, ONE fused clip+fold+tree
   program per `(tree-hash, buckets, index, mesh)` signature, epsilon
   -band host recheck spliced on top. Timed over ``--reps`` warm runs.
2. **host** — `overlay_measures(lane="host")`: the pure-f64 numpy twin
   (`expr/host_oracle.host_overlay_measures`) — the degradation target
   and the bit-identity oracle.

Asserted on the way (the CI overlay-smoke lane re-asserts from the
JSON):

- ``detail.agreement`` — device vs host-oracle, bitwise over the pair
  table, the evaluated value/mask lanes and the folded areas; every
  entry MUST be 1.0 (the acceptance contract of the overlay PR);
- after warmup the device lane adds ZERO backend compiles
  (``detail.warm_backend_compiles == 0``);
- ``detail.overflow == 0`` — the ladder swallowed the whole candidate
  stream, no OVERFLOW(-2) truncation at bench scale;
- ``detail.chips >= --min-chips`` (default 100k) — the scale claim is
  measured, not asserted;
- every device stage lands a timed ``overlay_stage.<stage>`` telemetry
  event (prepare / candidates / measures) — the keys
  `tools/perf_gate.py` gates, with the 10x ``--inject-slowdown``
  negative lane in CI.

``detail.speedup_vs_host`` is the committed-artifact headline the tune
router reads (`tune/recommend._overlay_lane_prior`); it is recorded,
not asserted — CI machines may be slower, the committed OVERLAY_r*.json
round is the measured claim.

The final stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr.

Usage (CI overlay-smoke lane):
  python tools/overlay_bench.py --n 24 --min-chips 10000 \
      --trail /tmp/overlay.jsonl
  python tools/perf_gate.py --golden tests/goldens/perf_gate.json \
      --trail /tmp/overlay.jsonl --stages-prefix overlay_stage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def square_grid(n: int, x0: float, y0: float, size: float,
                pitch: float) -> list:
    """n x n CCW squares of ``size`` on a ``pitch`` lattice (WKT)."""
    out = []
    for j in range(n):
        for i in range(n):
            x, y = x0 + i * pitch, y0 + j * pitch
            out.append(
                f"POLYGON (({x} {y}, {x + size} {y}, "
                f"{x + size} {y + size}, {x} {y + size}, {x} {y}))"
            )
    return out


def bitwise(a, b) -> float:
    """1.0 when the two arrays match bit for bit (shape, dtype, bytes)."""
    import numpy as np

    a, b = np.asarray(a), np.asarray(b)
    same = (
        a.shape == b.shape
        and a.dtype == b.dtype
        and a.tobytes() == b.tobytes()
    )
    return 1.0 if same else 0.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=110,
                    help="squares per side per table (geoms = 2*n^2)")
    ap.add_argument("--res", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--host-reps", type=int, default=1)
    ap.add_argument("--min-chips", type=int, default=100_000,
                    help="fail below this total chip count (the scale "
                    "claim); CI smoke lanes pass a smaller floor")
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail as JSONL")
    args = ap.parse_args()

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    detail: dict = {}
    line = {"metric": "overlay_device_pairs_per_sec", "value": 0.0,
            "unit": "zone-pairs/s", "detail": detail}
    stages: list = []
    root_span = None
    rc = 1
    try:
        import jax
        import numpy as np

        from mosaic_tpu import expr as E, obs
        from mosaic_tpu.core.geometry import wkt
        from mosaic_tpu.core.index import CustomIndexSystem, GridConf
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.dispatch import core as dispatch
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.sql import overlay as OV

        cap = telemetry.capture()
        stages = cap.__enter__()
        root_span = obs.start_span("overlay_bench", n=args.n,
                                   res=args.res)
        detail["platform"] = str(jax.devices()[0].platform)
        detail["n_per_side"] = args.n

        grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2,
                                          10.0, 10.0))
        cw, _ = grid.cell_size(args.res)
        # squares ~2.2 cells wide on a 2.4-cell pitch: every square
        # spans a 3x3-ish cell patch (mostly border chips — the clip
        # kernel does real work), same-side squares never overlap, and
        # the right grid's ~0.7-cell offset gives each left square up
        # to 4 right partners. The origin keeps the default n=110
        # lattice inside the grid bounds (squares past the edge would
        # silently shrink the chip count).
        size, pitch = 2.2 * cw, 2.4 * cw
        left = wkt.from_wkt(square_grid(args.n, -80.0, -82.0,
                                        size, pitch))
        right = wkt.from_wkt(square_grid(args.n, -80.0 + 0.73 * cw,
                                         -82.0 + 0.49 * cw,
                                         size, pitch))

        lt = tessellate(left, grid, args.res)
        rt = tessellate(right, grid, args.res)
        chips = (int(np.asarray(lt.cell_id).shape[0])
                 + int(np.asarray(rt.cell_id).shape[0]))
        detail["chips"] = chips
        detail["geoms"] = 2 * args.n * args.n

        t0 = time.perf_counter()
        with telemetry.timed("overlay_stage", stage="prepare"):
            prep = OV.prepare_overlay(lt, rt, left, right, grid,
                                      args.res)
        detail["prepare_s"] = round(time.perf_counter() - t0, 6)

        value = E.overlap_fraction()
        OV.warmup_overlay(left, right, grid, args.res, value, prep=prep)

        # ---- device: warm timed reps that must compile NOTHING
        c0 = dispatch.backend_compiles()
        t0 = time.perf_counter()
        for _ in range(args.reps):
            dev = OV.overlay_measures(left, right, grid, args.res,
                                      value, prep=prep)
        device_s = (time.perf_counter() - t0) / max(args.reps, 1)
        warm_compiles = int((dispatch.backend_compiles() - c0) or 0)
        detail["warm_backend_compiles"] = warm_compiles

        # ---- host: the pure-f64 numpy twin (oracle + fallback target)
        t0 = time.perf_counter()
        for _ in range(args.host_reps):
            host = OV.overlay_measures(left, right, grid, args.res,
                                       value, prep=prep, lane="host")
        host_s = (time.perf_counter() - t0) / max(args.host_reps, 1)

        pairs = int(dev.pairs.shape[0])
        agree = {
            "pairs": bitwise(dev.pairs, host.pairs),
            "value": bitwise(dev.value, host.value),
            "valid": bitwise(dev.valid, host.valid),
            "area": bitwise(dev.area, host.area),
        }
        detail["agreement"] = agree
        detail["pairs"] = pairs
        detail["overflow"] = int(dev.overflow)
        detail["host_overridden"] = int(dev.host_overridden)
        detail["lane"] = dev.lane
        detail["seconds"] = {
            "device": round(device_s, 6), "host": round(host_s, 6),
        }
        detail["host_pairs_per_sec"] = round(
            pairs / max(host_s, 1e-9), 1
        )
        detail["speedup_vs_host"] = round(
            host_s / max(device_s, 1e-9), 3
        )
        line["value"] = round(pairs / max(device_s, 1e-9), 1)

        bad = {k: v for k, v in agree.items() if v != 1.0}
        if bad:
            raise AssertionError(
                f"agreement below 1.0: {bad} — the device lane broke "
                "the bit-identity contract against the f64 host oracle"
            )
        if dev.lane != "device" or dev.degraded:
            raise AssertionError(
                f"device lane degraded: lane={dev.lane} "
                f"reason={dev.reason!r}"
            )
        if warm_compiles:
            raise AssertionError(
                f"warm device run compiled {warm_compiles} programs — "
                "warmup must cover the overlay signature"
            )
        if dev.overflow:
            raise AssertionError(
                f"candidate stream overflowed by {dev.overflow} at "
                "bench scale — the ladder must swallow it uncapped"
            )
        if chips < args.min_chips:
            raise AssertionError(
                f"only {chips} chips < --min-chips {args.min_chips} — "
                "the scale claim is unmet; raise --n"
            )
        rc = 0
    except Exception as e:  # lint: broad-except-ok (bench must always emit its JSON line; rc carries failure)
        detail["error"] = repr(e)[:400]

    if root_span is not None:
        try:
            root_span.end()
        except Exception:  # lint: broad-except-ok (span cleanup must not mask the bench result)
            pass
    if args.trail and stages:
        try:
            from mosaic_tpu import obs as _obs

            _obs.write_jsonl(stages, args.trail)
        except Exception as e:  # lint: broad-except-ok (a sick trail disk degrades the trail, not the bench)
            detail["trail_error"] = repr(e)[:200]

    emit_to.write(json.dumps(line) + "\n")
    emit_to.flush()
    return rc


if __name__ == "__main__":
    sys.exit(main())
