"""Adaptive-probe smoke: every routing lane, bit-identity, lane timings.

The CI twin of the adaptive router in `sql/join.py` (light scatter/MXU
path, heavy Pallas lane, convex reduced-edge lane): build a fixture
that genuinely populates ALL THREE density classes, run the probe on
CPU (the Pallas kernel under ``interpret=True``), force each lane via
``MOSAIC_PROBE_FORCE_LANE``, and assert:

1. every probe mode (``adaptive`` + each forced lane) is bit-identical
   to the ``scatter`` baseline, per batch — including the adversarial
   batches (near-edge band, all-heavy, all-light, convex-only);
2. the rechecked adaptive join equals the exact f64 host oracle row for
   row (``host_join_with_cells``);
3. each forced lane emits one timed ``probe_stage.<lane>`` telemetry
   event — the stage keys `tools/perf_gate.py` gates, so a lane-share
   regression fails CI, not just a headline slowdown.

The per-lane roofline rides along in ``detail.roofline``: bytes/pt per
lane computed from the index arrays the lane actually touches (never
hand-written) times the measured rate. The final stdout line is ALWAYS
one machine-parseable JSON object; everything else goes to stderr.

Usage (CI probe-smoke lane):
  python tools/probe_smoke.py --points 60000 --trail /tmp/probe.jsonl
  python tools/perf_gate.py --golden tests/goldens/perf_gate.json \
      --trail /tmp/probe.jsonl ...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: probed lanes, in gate-stage order
LANES = ("light", "heavy", "convex")


def build_fixture():
    """A chip index populating all three density classes + its zones.

    The custom grid keeps CPU compiles cheap (same reasoning as
    tests/test_stream.py); ``edge_cap=8`` forces genuine tier-2 (heavy)
    cells out of ordinary zones, and the axis-aligned rectangles are
    closed convex rings, so the convex tables populate too.
    """
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index

    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    res = 3
    # a 240-vertex star ring concentrates >32 edges into single cells —
    # the guaranteed-heavy zone; the rectangles are the convex ones
    th = np.linspace(0.0, 2 * np.pi, 240, endpoint=False)
    r = np.where(np.arange(240) % 2 == 0, 4.0, 2.0)
    sx, sy = 25.0 + r * np.cos(th), -14.0 + r * np.sin(th)
    star = ", ".join(f"{x:.6f} {y:.6f}" for x, y in zip(sx, sy))
    star += f", {sx[0]:.6f} {sy[0]:.6f}"
    zones = wkt.from_wkt(
        [
            "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), "
            "(5 5, 5 8, 8 8, 8 5, 5 5))",
            "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
            "MULTIPOLYGON (((-20 -20, -12 -20, -12 -12, -20 -12, "
            "-20 -20)), ((-8 -8, -2 -8, -2 -2, -8 -2, -8 -8)))",
            "POLYGON ((-24 5, -14 5, -14 15, -24 15, -24 5))",
            f"POLYGON (({star}))",
        ]
    )
    index = build_chip_index(
        tessellate(zones, grid, res, keep_core_geoms=False), edge_cap=8
    )
    return grid, res, zones, index


def classify_points(index, grid, res, pts):
    """(found, heavy, convex) bool masks per point, from the host-side
    density tables — drives the adversarial batch construction."""
    import jax.numpy as jnp

    cells = np.asarray(grid.point_to_cell(jnp.asarray(pts), res))
    ucells = np.asarray(index.cells)
    u = np.clip(np.searchsorted(ucells, cells), 0, len(ucells) - 1)
    found = ucells[u] == cells
    heavy = found & (np.asarray(index.cell_heavy)[u] >= 0)
    convex = found & (np.asarray(index.cell_convex)[u] >= 0)
    return found, heavy, convex


def near_edge_batch(index, rng, per_edge=2):
    """Points straddling real chip edges: midpoint ± a tiny normal
    offset (the band/parity stress batch), in RAW coordinates."""
    edges = np.asarray(index.cell_edges, dtype=np.float64)
    real = np.asarray(index.cell_ebits) != 0
    ab = edges[real]
    if not len(ab):
        return np.zeros((0, 2))
    ab = ab[rng.permutation(len(ab))[: 4000 // per_edge]]
    a, b = ab[:, 0:2], ab[:, 2:4]
    mid = 0.5 * (a + b)
    t = b - a
    nrm = np.stack([-t[:, 1], t[:, 0]], axis=1)
    nrm /= np.maximum(np.linalg.norm(nrm, axis=1, keepdims=True), 1e-30)
    shift = np.asarray(index.border.shift, dtype=np.float64)
    out = []
    for delta in (1e-6, 1e-4):
        out.append(mid + delta * nrm)
        out.append(mid - delta * nrm)
    return np.concatenate(out) + shift


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=60_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail as JSONL")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    detail: dict = {}
    line = {"metric": "probe_smoke", "value": 0, "unit": "lanes_verified",
            "detail": detail}
    stages: list = []
    root_span = None
    rc = 1
    try:
        import jax

        from mosaic_tpu import obs
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.sql.join import host_join, pip_join

        cap = telemetry.capture()
        stages = cap.__enter__()
        root_span = obs.start_span("probe_smoke", points=args.points)

        grid, res, zones, index = build_fixture()
        detail["platform"] = str(jax.devices()[0].platform)
        detail["heavy_cells"] = index.num_heavy_cells
        detail["convex_cells"] = index.num_convex_cells
        if not index.num_heavy_cells or not index.num_convex_cells:
            raise AssertionError(
                "fixture drift: need heavy AND convex cells, got "
                f"H={index.num_heavy_cells} CV={index.num_convex_cells}"
            )

        rng = np.random.default_rng(args.seed)
        pts = rng.uniform((-25, -25), (35, 20), (args.points, 2))
        found, heavy, convex = classify_points(index, grid, res, pts)
        light = found & ~heavy & ~convex
        batches = {
            "mixed": pts,
            "all_light": pts[light],
            "all_heavy": pts[heavy],
            "convex_only": pts[convex],
            "near_edge_band": near_edge_batch(index, rng),
        }
        detail["batches"] = {k: int(len(v)) for k, v in batches.items()}
        for k in ("all_heavy", "convex_only", "near_edge_band"):
            if not len(batches[k]):
                raise AssertionError(f"fixture drift: empty {k} batch")

        def run(p, probe, recheck=False):
            env = os.environ.pop("MOSAIC_PROBE_FORCE_LANE", None)
            try:
                if probe.startswith("force:"):
                    os.environ["MOSAIC_PROBE_FORCE_LANE"] = probe[6:]
                    probe = "adaptive"
                return np.asarray(pip_join(
                    p, None, grid, res, chip_index=index, recheck=recheck,
                    probe=probe,
                ))
            finally:
                os.environ.pop("MOSAIC_PROBE_FORCE_LANE", None)
                if env is not None:
                    os.environ["MOSAIC_PROBE_FORCE_LANE"] = env

        # 1) bit-identity of every mode vs the scatter baseline, per batch
        modes = ["adaptive"] + [f"force:{ln}" for ln in LANES]
        mismatches = 0
        for bname, bp in batches.items():
            base = run(bp, "scatter")
            for mode in modes:
                got = run(bp, mode)
                if not np.array_equal(got, base):
                    mismatches += 1
                    detail.setdefault("mismatch", []).append(
                        {"batch": bname, "mode": mode,
                         "rows": int((got != base).sum())}
                    )
        detail["identity_checks"] = len(batches) * len(modes)
        if mismatches:
            raise AssertionError(f"{mismatches} identity check(s) failed")

        # 2) rechecked adaptive == exact f64 host oracle, row for row
        for bname in ("mixed", "near_edge_band"):
            bp = batches[bname]
            oracle = host_join(bp, index.host, grid, res)
            got = run(bp, "adaptive", recheck=True)
            if not np.array_equal(got, oracle):
                raise AssertionError(
                    f"adaptive+recheck != host oracle on {bname}: "
                    f"{int((got != oracle).sum())} rows"
                )
        detail["oracle_identical"] = True

        # 3) timed forced-lane dispatches -> the gated probe_stage keys
        bucket_b = int(index.table_cell.shape[1]) * (
            index.table_cell.dtype.itemsize
            + index.table_slot.dtype.itemsize
        )
        edge_b = (
            int(index.cell_edges.shape[-1])
            * index.cell_edges.dtype.itemsize
            + index.cell_ebits.dtype.itemsize
        )
        e1 = int(index.cell_edges.shape[1])
        e2 = int(index.heavy_edges.shape[1])
        e3 = int(index.convex_edges.shape[2])
        lane_bpp = {
            "light": bucket_b + edge_b * e1,
            "heavy": bucket_b + edge_b * (e1 + e2),
            "convex": bucket_b + edge_b * e3,
        }
        roofline = {"per_lane": {}}
        n = len(pts)
        for lane in LANES:
            run(pts, f"force:{lane}")  # warm: compile outside the timing
            t0 = time.perf_counter()
            run(pts, f"force:{lane}")
            dt = time.perf_counter() - t0
            telemetry.record(
                "probe_stage", stage=lane, seconds=round(dt, 6), n=n
            )
            rate = n / max(dt, 1e-9)
            roofline["per_lane"][lane] = {
                "bytes_per_point": lane_bpp[lane],
                "points_per_sec": round(rate, 1),
                "achieved_gbps": round(lane_bpp[lane] * rate / 1e9, 3),
            }
        detail["roofline"] = roofline
        line["value"] = len(LANES)
        rc = 0
    except Exception as e:
        detail["error"] = repr(e)[:400]

    if root_span is not None:
        try:
            root_span.end()
        except Exception:
            pass
    if args.trail and stages:
        try:
            from mosaic_tpu import obs as _obs

            _obs.write_jsonl(stages, args.trail)
        except Exception as e:
            detail["trail_error"] = repr(e)[:200]

    out = json.dumps(line)
    emit_to.write(out + "\n")
    emit_to.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
