"""KNN-serving load generator: the KNN twin of `tools/serve_bench.py`.

Drives :meth:`mosaic_tpu.serve.ServeEngine.submit_knn` over a resident
:class:`mosaic_tpu.knn.KNNIndex` (dense convex candidates on the custom
grid index — the CPU-friendly fixture the knn test suite uses) and
reports the four things PR 19 promises:

- **agreement** — every served answer is bit-compared (neighbour ids
  AND f64 distance bits) against the engine-less frontend, the batch
  ``SpatialKNN`` model run exact, and the brute-force f64 host oracle;
  the headline artifact records the fraction that agree (must be 1.0);
- **closed-loop saturation** (``--requests`` / ``--concurrency``):
  workers resubmit the moment their previous answer lands — queries/s
  at saturation is the headline ``value``;
- **open-loop overload**: Poisson arrivals at ``--overload-mult`` x the
  measured closed-loop capacity; every rejected request must be a typed
  ``Overloaded`` (queue-full at submit or deadline at delivery) — the
  typed-shed fraction and a count of untyped failures (must be 0) land
  in ``detail``;
- **lane A/B** — the Voronoi convex fast path vs ring expansion on the
  same warmed batches: ``detail.voronoi_speedup_vs_ring`` is the number
  `tune/recommend.py` reads as its measured prior, and ``detail.
  voronoi_adopted`` records whether it clears the 1.3x adoption bar;
- **compile story** — signatures warmed per rung, cold compiles after
  warmup (must be 0), and a store-backed relaunch: a second frontend
  warms purely from the exported AOT program store and serves with zero
  backend compiles.

Last stdout line is ALWAYS one machine-parseable JSON object; everything
else goes to stderr.

CPU CI smoke:
  JAX_PLATFORMS=cpu MOSAIC_BENCH_PLATFORM=cpu python tools/knn_bench.py \
      --requests 40 --overload-requests 60 --out /tmp/KNN.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BBOX = (-25.0, -25.0, 35.0, 20.0)
RES = 3

PIP_ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
    "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
    "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
]


def _fixture(args):
    """Candidates + index + a query sampler that stays strictly inside
    the candidate bbox."""
    from mosaic_tpu import functions as F
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.knn import build_knn_index
    from mosaic_tpu.sql.join import build_chip_index

    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    rng = np.random.default_rng(args.seed)
    cx = rng.uniform(BBOX[0], BBOX[2], args.candidates)
    cy = rng.uniform(BBOX[1], BBOX[3], args.candidates)
    s = rng.uniform(0.5, 1.5, args.candidates)
    polys = [
        f"POLYGON(({x} {y}, {x + w} {y}, {x + w} {y + w},"
        f" {x} {y + w}, {x} {y}))"
        for x, y, w in zip(cx, cy, s)
    ]
    cand = F.st_geomfromwkt(np.array(polys))
    kx = build_knn_index(cand, index_system=grid, resolution=RES)
    pip = build_chip_index(
        tessellate(wkt.from_wkt(PIP_ZONES), grid, RES, keep_core_geoms=False)
    )
    lo = np.array([cx.min(), cy.min()])
    hi = np.array([cx.max(), cy.max()])

    def qpts(n, seed):
        r = np.random.default_rng(seed)
        return lo + r.uniform(0.1, 0.9, (n, 2)) * (hi - lo)

    return grid, cand, kx, pip, qpts


def _agreement(engine, frontend, cand, kx, qpts, args, detail) -> float:
    """Bit-compare served answers against the engine-less frontend, the
    exact batch model, and the f64 host oracle. Returns the fraction of
    queries where ALL four sources agree on ids and distance bits."""
    from mosaic_tpu.knn import brute_force_knn, decode_knn
    from mosaic_tpu.models import SpatialKNN

    k = args.k
    sizes = (args.rows - 1, args.rows, args.rows + 1)  # straddle a rung
    qs = [qpts(max(n, 1), 900 + i) for i, n in enumerate(sizes)]
    answers = [f.result() for f in
               [engine.submit_knn(q, k) for q in qs]]
    allq = np.concatenate(qs)
    sids = np.concatenate([a.ids for a in answers])
    sdist = np.concatenate([a.distance for a in answers])

    out, _ = frontend.dispatch(allq, k)
    fids, fdist = decode_knn(np.asarray(out), k)

    oids, odist = brute_force_knn(allq, kx, k)

    from mosaic_tpu import functions as F

    m = SpatialKNN(
        index=engine.index_system, resolution=RES, k_neighbours=k,
        max_iterations=64, early_stop_iterations=100, approximate=False,
    )
    res = m.transform(F.st_point(allq[:, 0], allq[:, 1]), cand)
    bids = np.full((allq.shape[0], k), -1, np.int64)
    bdist = np.full((allq.shape[0], k), np.inf)
    for li, ci, d, r in zip(
        res.landmark_id, res.candidate_id, res.distance, res.rank
    ):
        bids[li, r - 1] = ci
        bdist[li, r - 1] = d

    ok = (
        np.all(sids == fids, axis=1)
        & np.all(sids == oids, axis=1)
        & np.all(sids == bids, axis=1)
        & np.all(sdist == fdist, axis=1)
        & np.all(sdist == odist, axis=1)
        & np.all(sdist == bdist, axis=1)
    )
    detail["agreement"] = {
        "queries": int(allq.shape[0]),
        "k": k,
        "vs": ["frontend", "batch_spatial_knn", "oracle_f64"],
        "fraction": round(float(ok.mean()), 6),
    }
    return float(ok.mean())


def _closed_loop(engine, qpts, args, detail) -> float:
    """Saturation: each worker resubmits on completion. Returns measured
    queries/sec."""
    from mosaic_tpu.runtime.errors import Overloaded

    reqs = [qpts(args.rows, 100 + i) for i in range(args.requests)]
    cursor = {"i": 0}
    lock = threading.Lock()
    completed = {"q": 0, "shed": 0}

    def worker():
        while True:
            with lock:
                i = cursor["i"]
                if i >= len(reqs):
                    return
                cursor["i"] = i + 1
            try:
                engine.submit_knn(reqs[i], args.k).result()
                with lock:
                    completed["q"] += reqs[i].shape[0]
            except Overloaded:
                with lock:
                    completed["shed"] += 1

    threads = [
        threading.Thread(target=worker, daemon=True)  # lint: thread-context-adoption-ok (load generator: client-side throughput only, telemetry is emitted by the engine's own threads)
        for _ in range(max(args.concurrency, 1))
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    qps = completed["q"] / max(wall, 1e-9)
    detail["closed_loop"] = {
        "requests": args.requests,
        "rows_per_request": args.rows,
        "concurrency": args.concurrency,
        "wall_s": round(wall, 3),
        "queries_per_sec": round(qps, 2),
        "requests_per_sec": round(
            (args.requests - completed["shed"]) / max(wall, 1e-9), 2
        ),
        "shed": completed["shed"],
    }
    return qps


def _open_loop(engine, qpts, args, capacity_rps, detail) -> None:
    """Overload: Poisson arrivals at ``--overload-mult`` x the measured
    request capacity. Every rejection must be a typed ``Overloaded``."""
    from mosaic_tpu.runtime.errors import Overloaded

    rate = max(capacity_rps, 0.5) * args.overload_mult
    rng = np.random.default_rng(args.seed + 1)
    n = args.overload_requests
    shed_submit = shed_deadline = untyped = completed = 0
    futures = []
    next_t = time.perf_counter()
    t0 = next_t
    for i in range(n):
        next_t += float(rng.exponential(1.0 / rate))
        lag = next_t - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        try:
            futures.append(engine.submit_knn(
                qpts(args.rows, 500 + i), args.k,
                deadline_s=args.overload_deadline_s,
            ))
        except Overloaded:
            shed_submit += 1
        except Exception:  # lint: broad-except-ok (anything untyped at submit is exactly what this lane counts)
            untyped += 1
    for f in futures:
        try:
            f.result()
            completed += 1
        except Overloaded as e:
            if e.reason == "deadline":
                shed_deadline += 1
            else:
                shed_submit += 1
        except Exception:  # lint: broad-except-ok (anything untyped at delivery is exactly what this lane counts)
            untyped += 1
    detail["open_loop"] = {
        "requests": n,
        "rate_per_sec": round(rate, 2),
        "overload_mult": args.overload_mult,
        "deadline_s": args.overload_deadline_s,
        "completed": completed,
        "shed_submit": shed_submit,
        "shed_deadline": shed_deadline,
        "typed_shed_fraction": round(
            (shed_submit + shed_deadline) / max(n, 1), 4
        ),
        "untyped_failures": untyped,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _lane_ab(kx, qpts, args, detail) -> None:
    """Voronoi convex fast path vs ring expansion, both warmed, same
    batches; ``voronoi_speedup_vs_ring`` is the tune prior."""
    from mosaic_tpu.knn import KNNFrontend

    batches = [qpts(args.rows, 700 + i) for i in range(args.ab_batches)]

    def run(lane):
        fe = KNNFrontend(kx, lane=lane)
        fe.warmup()
        outs = []
        t0 = time.perf_counter()
        for q in batches:
            out, _ = fe.dispatch(q, args.k)
            outs.append(np.asarray(out))
        return time.perf_counter() - t0, outs, fe

    t_ring, out_r, _ = run("ring")
    t_vor, out_v, fv = run("voronoi")
    identical = all(
        np.array_equal(a, b) for a, b in zip(out_r, out_v)
    )
    speedup = t_ring / max(t_vor, 1e-9)
    detail["lane_ab"] = {
        "batches": args.ab_batches,
        "rows_per_batch": args.rows,
        "ring_wall_s": round(t_ring, 3),
        "voronoi_wall_s": round(t_vor, 3),
        "bit_identical": bool(identical),
        "voronoi_fallback_rows": fv.stats["voronoi_fallback"],
    }
    detail["voronoi_speedup_vs_ring"] = round(speedup, 3)
    detail["voronoi_adopted"] = bool(speedup >= 1.3 and identical)


def _relaunch(kx, qpts, args, detail) -> None:
    """Store-backed relaunch: warm a fresh frontend purely from the AOT
    program store exported by the first, then serve with zero backend
    compiles."""
    from mosaic_tpu.knn import KNNFrontend
    from mosaic_tpu.serve import backend_compiles

    store = tempfile.mkdtemp(prefix="knn_bench_store_")
    fe1 = KNNFrontend(kx, lane=args.lane, program_store=store)
    w1 = fe1.warmup()
    fe2 = KNNFrontend(kx, lane=args.lane, program_store=store)
    w2 = fe2.warmup()
    c0 = backend_compiles()
    for i in range(3):
        fe2.dispatch(qpts(args.rows, 800 + i), args.k)
    c1 = backend_compiles()
    detail["relaunch"] = {
        "store_exported": w1["aot"]["exported"],
        "store_loaded": w2["aot"]["loaded"],
        "relaunch_backend_compiles_serving": (
            c1 - c0 if c0 is not None and c1 is not None else None
        ),
        "relaunch_cold_compiles": fe2.cold_compiles,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--candidates", type=int, default=120)
    ap.add_argument("--requests", type=int, default=40,
                    help="closed-loop request count")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--rows", type=int, default=8,
                    help="queries per request")
    ap.add_argument("--k", type=int, default=3)
    ap.add_argument("--lane", choices=("ring", "voronoi"), default="ring",
                    help="lane the served engine dispatches")
    ap.add_argument("--overload-mult", type=float, default=10.0)
    ap.add_argument("--overload-requests", type=int, default=60)
    ap.add_argument("--overload-deadline-s", type=float, default=2.0)
    ap.add_argument("--ab-batches", type=int, default=4)
    ap.add_argument("--queue-cap", type=int, default=8)
    ap.add_argument("--window-ms", type=float, default=5.0)
    ap.add_argument("--seed", type=int, default=19)
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail "
                    "(knn_stage timings included) as JSONL")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    # the LAST stdout line must be the JSON artifact
    emit_to = sys.stdout
    sys.stdout = sys.stderr

    t_all = time.perf_counter()
    detail: dict = {}
    line = {
        "metric": "knn_throughput",
        "value": 0.0,
        "unit": "queries/sec",
        "detail": detail,
    }
    try:
        if os.environ.get("MOSAIC_BENCH_PLATFORM") == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        import jax

        from mosaic_tpu.knn import KNNFrontend
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.serve import BucketLadder, ServeEngine

        detail["device"] = str(jax.devices()[0])
        detail["lane"] = args.lane
        grid, cand, kx, pip, qpts = _fixture(args)
        detail["fixture"] = {
            "candidates": args.candidates,
            "index": "custom-grid",
            "resolution": RES,
            "voronoi_sites": int(kx.n),
        }

        fe = KNNFrontend(kx, lane=args.lane)
        engine = ServeEngine(
            pip, grid, RES, ladder=BucketLadder(64, 1024), bounds=BBOX,
            knn=fe, max_wait_s=args.window_ms / 1e3,
            queue_capacity=args.queue_cap, default_deadline_s=60.0,
        )
        t0 = time.perf_counter()
        warm = engine.warmup()
        detail["warmup"] = dict(
            warm, wall_s=round(time.perf_counter() - t0, 3)
        )

        with telemetry.capture() as events:
            main_sinks = telemetry.current_sinks()
            del main_sinks  # workers emit nothing; engine threads adopt downstream

            agreement = _agreement(
                engine, fe, cand, kx, qpts, args, detail
            )
            qps = _closed_loop(engine, qpts, args, detail)
            line["value"] = round(qps, 2)
            _open_loop(
                engine, qpts, args,
                detail["closed_loop"]["requests_per_sec"], detail,
            )

        m = engine.metrics()
        detail["engine"] = {
            "batches": m["batches"],
            "cold_compiles": m["cold_compiles"],
            "knn_queries": m["knn_queries"],
            "knn_degraded": m["knn_degraded"],
            "knn_pair_occupancy": m["knn_pair_occupancy"],
            "occupancy_mean": m["occupancy_mean"],
        }
        detail["stage_summary"] = telemetry.summarize(
            events, event="knn_stage"
        )
        engine.close()
        if args.trail:
            from mosaic_tpu import obs

            obs.write_jsonl(events, args.trail)

        _lane_ab(kx, qpts, args, detail)
        _relaunch(kx, qpts, args, detail)
        detail["agreement_ok"] = bool(agreement == 1.0)
    except Exception as e:  # lint: broad-except-ok (the artifact line must still parse — errors are reported inside it)
        detail["error"] = repr(e)[:400]
        try:
            import jax as _j

            detail.setdefault("device", str(_j.devices()[0]))
        except Exception:  # lint: broad-except-ok (best-effort device stamp on an already-failing run)
            detail.setdefault("device", "unknown")

    detail["total_wall_s"] = round(time.perf_counter() - t_all, 1)
    out = json.dumps(line)
    emit_to.write(out + "\n")
    emit_to.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if detail.get("error") and not line["value"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
