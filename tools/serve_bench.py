"""Online-serving load generator: the request-facing twin of
`tools/stream_bench.py`.

Drives a :class:`mosaic_tpu.serve.ServeEngine` (resident zone index,
warmed bucket ladder) with either load model:

- **closed loop** (``--mode closed``): ``--concurrency`` workers each
  submit their next request the moment the previous one resolves — the
  saturation throughput measurement;
- **open loop** (``--mode open``): requests arrive on a Poisson clock at
  ``--rate`` req/s regardless of completions — the overload measurement.
  When the arrival rate exceeds capacity the engine must SHED (typed
  ``Overloaded`` at admission or deadline expiry), never queue without
  bound: the shed rate and the p99 of *admitted* requests are the
  headline here.

Reported (last stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr): request + row throughput, latency
percentiles of admitted requests (`telemetry.summarize` over the
engine's ``serve_request`` events — the same helper stream_bench uses),
batch occupancy, shed/quarantine counters, and the compile story
(ladder size, warmup signatures, cold compiles after warmup, backend
compile count when jax's monitoring hook is available).

CPU CI smoke:
  JAX_PLATFORMS=cpu MOSAIC_BENCH_PLATFORM=cpu python tools/serve_bench.py \
      --mode closed --requests 200 --concurrency 8 --rows-max 512
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--requests", type=int, default=500)
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop worker count")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop arrival rate, requests/sec")
    ap.add_argument("--rows-min", type=int, default=1)
    ap.add_argument("--rows-max", type=int, default=1024)
    ap.add_argument("--deadline-ms", type=float, default=2000.0)
    ap.add_argument("--window-ms", type=float, default=2.0,
                    help="micro-batch max-wait window")
    ap.add_argument("--max-batch", type=int, default=16384)
    ap.add_argument("--queue-cap", type=int, default=64)
    ap.add_argument("--min-bucket", type=int, default=64)
    ap.add_argument("--max-bucket", type=int, default=16384)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--poison", type=int, default=0,
                    help="inject N NaN rows into one request "
                    "(quarantine demo lane)")
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail "
                    "(spans included) as JSONL")
    ap.add_argument("--chrome-trace", default=None,
                    help="export the trail as Chrome trace-event JSON "
                    "(Perfetto-loadable)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    # the LAST stdout line must be the JSON artifact
    emit_to = sys.stdout
    sys.stdout = sys.stderr

    t_all = time.perf_counter()
    detail: dict = {}
    line = {
        "metric": "serve_throughput",
        "value": 0.0,
        "unit": "requests/sec",
        "detail": detail,
    }
    try:
        if os.environ.get("MOSAIC_BENCH_PLATFORM") == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        import jax

        from bench import RES, _load_or_build_index, _load_zones
        from mosaic_tpu.core.index.h3 import H3IndexSystem
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.runtime.errors import Overloaded
        from mosaic_tpu.serve import BucketLadder, ServeEngine
        from mosaic_tpu.sql.join import join_cache_stats

        h3 = H3IndexSystem()
        zones, zones_src = _load_zones()
        b = zones.bounds()
        bbox = (
            float(np.nanmin(b[:, 0])), float(np.nanmin(b[:, 1])),
            float(np.nanmax(b[:, 2])), float(np.nanmax(b[:, 3])),
        )
        index, _, _ = _load_or_build_index(zones, zones_src, h3)
        detail.update(
            device=str(jax.devices()[0]), zones=zones_src, mode=args.mode,
        )

        engine = ServeEngine(
            index, h3, RES,
            ladder=BucketLadder(args.min_bucket, args.max_bucket),
            max_batch_rows=args.max_batch,
            max_wait_s=args.window_ms / 1e3,
            queue_capacity=args.queue_cap,
            default_deadline_s=args.deadline_ms / 1e3,
            bounds=bbox,
        )
        t0 = time.perf_counter()
        warm = engine.warmup()
        detail["warmup"] = dict(warm, wall_s=round(
            time.perf_counter() - t0, 3))

        rng = np.random.default_rng(args.seed)
        sizes = rng.integers(
            args.rows_min, args.rows_max + 1, args.requests
        )
        reqs = [
            rng.uniform(bbox[:2], bbox[2:], (int(n), 2)) for n in sizes
        ]
        if args.poison and reqs:
            reqs[0][: args.poison] = np.nan

        shed_submit = 0
        shed_lock = threading.Lock()
        futures: list = []

        with telemetry.capture() as events:
            # capture sinks are thread-local: closed-loop workers adopt
            # the main thread's so their serve_request events land here
            main_sinks = telemetry.current_sinks()
            t_load = time.perf_counter()
            if args.mode == "closed":
                cursor = {"i": 0}
                cursor_lock = threading.Lock()

                def worker():
                    nonlocal shed_submit
                    telemetry.adopt_sinks(main_sinks)
                    while True:
                        with cursor_lock:
                            i = cursor["i"]
                            if i >= len(reqs):
                                return
                            cursor["i"] = i + 1
                        try:
                            f = engine.submit(reqs[i])
                            with shed_lock:
                                futures.append(f)
                            try:
                                f.result()
                            except Overloaded:
                                pass
                        except Overloaded:
                            with shed_lock:
                                shed_submit += 1

                threads = [
                    threading.Thread(target=worker, daemon=True)  # lint: thread-context-adoption-ok (load generator: each submit captures its own request context; engine threads adopt downstream)
                    for _ in range(max(args.concurrency, 1))
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            else:
                # open loop: Poisson arrivals at --rate, submits never
                # wait on completions; at overload the engine sheds
                next_t = time.perf_counter()
                for pts in reqs:
                    next_t += float(rng.exponential(1.0 / args.rate))
                    lag = next_t - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    try:
                        futures.append(engine.submit(pts))
                    except Overloaded:
                        shed_submit += 1
                for f in futures:
                    try:
                        f.result()
                    except Overloaded:
                        pass
            load_wall = time.perf_counter() - t_load

        m = engine.metrics()
        lat = telemetry.summarize(events, event="serve_request")
        stages = telemetry.summarize(events, event="serve_stage")
        completed_rows = int(
            sum(
                e.get("rows", 0)
                for e in events
                if e.get("event") == "serve_request"
            )
        )
        admitted = len(futures)
        line["value"] = round(m["completed"] / max(load_wall, 1e-9), 1)
        detail.update(
            requests=args.requests,
            admitted=admitted,
            completed=m["completed"],
            shed_submit=shed_submit,
            shed_deadline=m["shed_deadline"],
            shed_total=shed_submit + m["shed_deadline"],
            shed_rate=round(
                (shed_submit + m["shed_deadline"]) / max(args.requests, 1),
                4,
            ),
            quarantined=m["quarantined"],
            degraded=m["degraded"],
            load_wall_s=round(load_wall, 3),
            requests_per_sec=line["value"],
            rows_per_sec=round(completed_rows / max(load_wall, 1e-9), 1),
            latency=lat,
            deadline_s=args.deadline_ms / 1e3,
            p99_under_deadline=bool(lat["p99"] <= args.deadline_ms / 1e3),
            batches=m["batches"],
            occupancy_mean=m["occupancy_mean"],
            requests_per_batch=round(
                m["batched_requests"] / max(m["batches"], 1), 2
            ),
            stage_summary=stages,
            compiles={
                "buckets": len(engine.ladder.buckets),
                "warmup_signatures": warm["signatures"],
                "cold_compiles": m["cold_compiles"],
                "backend_compiles_warmup": warm.get("backend_compiles"),
            },
            join_cache=join_cache_stats(emit=False),
        )
        engine.close()
        if args.trail or args.chrome_trace:
            from mosaic_tpu import obs

            if args.trail:
                obs.write_jsonl(events, args.trail)
            if args.chrome_trace:
                obs.write_chrome_trace(events, args.chrome_trace)
            traces = obs.trace_summary(events)
            detail["traces"] = {
                "count": len(traces),
                "connected": sum(
                    1 for t in traces.values()
                    if t["roots"] == 1 and not t["orphans"]
                ),
            }
    except Exception as e:  # the artifact line must still parse
        detail["error"] = repr(e)[:400]
        try:
            import jax as _j

            detail.setdefault("device", str(_j.devices()[0]))
        except Exception:
            detail.setdefault("device", "unknown")

    detail["total_wall_s"] = round(time.perf_counter() - t_all, 1)
    out = json.dumps(line)
    emit_to.write(out + "\n")
    emit_to.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if detail.get("error") and not line["value"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
