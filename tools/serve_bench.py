"""Online-serving load generator: the request-facing twin of
`tools/stream_bench.py`.

Drives a :class:`mosaic_tpu.serve.ServeEngine` (resident zone index,
warmed bucket ladder) with either load model:

- **closed loop** (``--mode closed``): ``--concurrency`` workers each
  submit their next request the moment the previous one resolves — the
  saturation throughput measurement;
- **open loop** (``--mode open``): requests arrive on a Poisson clock at
  ``--rate`` req/s regardless of completions — the overload measurement.
  When the arrival rate exceeds capacity the engine must SHED (typed
  ``Overloaded`` at admission or deadline expiry), never queue without
  bound: the shed rate and the p99 of *admitted* requests are the
  headline here.

Reported (last stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr): request + row throughput, latency
percentiles of admitted requests (`telemetry.summarize` over the
engine's ``serve_request`` events — the same helper stream_bench uses),
batch occupancy, shed/quarantine counters, and the compile story
(ladder size, warmup signatures, cold compiles after warmup, backend
compile count when jax's monitoring hook is available).

CPU CI smoke:
  JAX_PLATFORMS=cpu MOSAIC_BENCH_PLATFORM=cpu python tools/serve_bench.py \
      --mode closed --requests 200 --concurrency 8 --rows-max 512
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _tenant_ab(index, h3, bbox, args, detail) -> float:
    """The --tenants lane: tenant 0 floods at ``--aggressor-mult`` x the
    base rate while tenants 1..N-1 run at the base rate, once against a
    :class:`ServeRouter` (hard isolation: per-tenant queues/deadlines)
    and once against a single shared-queue engine. Per-tenant admission,
    shed-by-reason counts, and client-side latency percentiles land in
    ``detail``; returns the isolated lane's worst VICTIM shed rate (the
    headline — structurally ~0, because the aggressor cannot occupy a
    victim's quota)."""
    import concurrent.futures as cf
    import tempfile

    from bench import RES
    from mosaic_tpu.runtime import telemetry
    from mosaic_tpu.runtime.errors import Overloaded
    from mosaic_tpu.serve import BucketLadder, ServeEngine, ServeRouter

    n = args.tenants
    mult = args.aggressor_mult
    reqs = {}
    for t in range(n):
        r = np.random.default_rng(args.seed + t)
        count = int(args.requests * (mult if t == 0 else 1))
        sizes = r.integers(args.rows_min, args.rows_max + 1, count)
        reqs[t] = [
            r.uniform(bbox[:2], bbox[2:], (int(k), 2)) for k in sizes
        ]
    rates = {t: args.rate * (mult if t == 0 else 1.0) for t in range(n)}

    def load(submit):
        """Open-loop Poisson per tenant; latency stamped by the future's
        done-callback (completion time, not drain time)."""
        stats = {
            t: {"admitted": 0, "shed_submit": 0, "shed_deadline": 0,
                "shed_other": 0, "lat": []}
            for t in range(n)
        }
        lock = threading.Lock()
        futures: list = []
        sinks = telemetry.current_sinks()

        def worker(t):
            # router_stage.admit is recorded on the submitting thread;
            # adopting the caller's sinks puts it in the bench trail
            telemetry.adopt_sinks(sinks)
            r = np.random.default_rng(1000 + t)
            next_t = time.perf_counter()
            for pts in reqs[t]:
                next_t += float(r.exponential(1.0 / rates[t]))
                lag = next_t - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                t0 = time.perf_counter()
                try:
                    f = submit(t, pts)
                except Overloaded:
                    with lock:
                        stats[t]["shed_submit"] += 1
                    continue
                with lock:
                    stats[t]["admitted"] += 1
                    futures.append(f)

                def done(f, t=t, t0=t0):
                    dt = time.perf_counter() - t0
                    exc = f.exception()
                    with lock:
                        if exc is None:
                            stats[t]["lat"].append(dt)
                        elif (
                            isinstance(exc, Overloaded)
                            and exc.reason == "deadline"
                        ):
                            stats[t]["shed_deadline"] += 1
                        else:
                            stats[t]["shed_other"] += 1

                f.add_done_callback(done)

        threads = [
            threading.Thread(target=worker, args=(t,), daemon=True)  # lint: thread-context-adoption-ok (load generator: client-side latency only, no telemetry emitted on these threads)
            for t in range(n)
        ]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        cf.wait(futures)
        wall = time.perf_counter() - t0
        per = {}
        for t in range(n):
            s = stats[t]
            lat = np.asarray(s["lat"])
            total = len(reqs[t])
            per[f"tenant_{t}"] = {
                "requests": total,
                "admitted": s["admitted"],
                "completed": int(lat.size),
                "shed_submit": s["shed_submit"],
                "shed_deadline": s["shed_deadline"],
                "shed_other": s["shed_other"],
                "shed_rate": round(
                    (s["shed_submit"] + s["shed_deadline"]) / max(total, 1),
                    4,
                ),
                "p50": round(float(np.percentile(lat, 50)), 6)
                if lat.size else None,
                "p99": round(float(np.percentile(lat, 99)), 6)
                if lat.size else None,
            }
        return per, wall

    ekw = dict(
        ladder=BucketLadder(args.min_bucket, args.max_bucket),
        max_batch_rows=min(args.max_batch, args.max_bucket),
        max_wait_s=args.window_ms / 1e3,
        queue_capacity=args.queue_cap,
        default_deadline_s=args.deadline_ms / 1e3,
        bounds=bbox,
    )

    # isolated: per-tenant engines behind the router; the shared AOT
    # store means tenant 0 exports the ladder once and every other
    # tenant warms by loading it
    store = tempfile.mkdtemp(prefix="serve_tenants_")
    router = ServeRouter(
        h3, max_resident=n, program_store=store, engine_defaults=ekw,
    )
    t0 = time.perf_counter()
    warm = {}
    for t in range(n):
        warm[f"tenant_{t}"] = router.add_tenant(
            f"tenant_{t}", index, RES
        ).get("aot")
    warm_wall = time.perf_counter() - t0
    iso_per, iso_wall = load(
        lambda t, pts: router.submit(f"tenant_{t}", pts)
    )
    rm = router.metrics()
    router_shed = {
        name: {
            "submitted": m["submitted_router"],
            "shed_admit": m["shed_admit_router"],
        }
        for name, m in rm["tenants"].items()
    }
    router.close()

    # shared: one engine, one queue — every tenant behind the aggressor
    eng = ServeEngine(index, h3, RES, **ekw)
    eng.warmup()
    sh_per, sh_wall = load(lambda t, pts: eng.submit(pts))
    eng.close()

    victims = [f"tenant_{t}" for t in range(1, n)]
    iso_victim = max(iso_per[v]["shed_rate"] for v in victims)
    sh_victim = max(sh_per[v]["shed_rate"] for v in victims)
    detail.update(
        tenants=n,
        aggressor="tenant_0",
        aggressor_mult=mult,
        rate_per_tenant=args.rate,
        isolated={
            "per_tenant": iso_per,
            "router_shed": router_shed,
            "warmup": {"aot": warm, "wall_s": round(warm_wall, 3)},
            "resident": rm["resident"],
            "wall_s": round(iso_wall, 3),
        },
        shared={"per_tenant": sh_per, "wall_s": round(sh_wall, 3)},
        victim_shed_rate={"isolated": iso_victim, "shared": sh_victim},
    )
    return iso_victim


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--requests", type=int, default=500)
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop worker count")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop arrival rate, requests/sec")
    ap.add_argument("--rows-min", type=int, default=1)
    ap.add_argument("--rows-max", type=int, default=1024)
    ap.add_argument("--deadline-ms", type=float, default=2000.0)
    ap.add_argument("--window-ms", type=float, default=2.0,
                    help="micro-batch max-wait window")
    ap.add_argument("--max-batch", type=int, default=16384)
    ap.add_argument("--queue-cap", type=int, default=64)
    ap.add_argument("--min-bucket", type=int, default=64)
    ap.add_argument("--max-bucket", type=int, default=16384)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--tenants", type=int, default=0,
                    help=">= 2 runs the multi-tenant isolation A/B lane "
                    "instead of the single-engine bench: tenant 0 floods "
                    "at --aggressor-mult x the base rate, once against a "
                    "ServeRouter (per-tenant queues) and once against one "
                    "shared-queue engine; per-tenant shed counts and "
                    "latency land in the final JSON")
    ap.add_argument("--aggressor-mult", type=float, default=10.0,
                    help="tenant 0's rate/request multiplier in the "
                    "--tenants lane")
    ap.add_argument("--poison", type=int, default=0,
                    help="inject N NaN rows into one request "
                    "(quarantine demo lane)")
    ap.add_argument("--slo", action="store_true",
                    help="evaluate the run's trail against the default "
                    "SLO specs (MOSAIC_SLO_* thresholds) over the whole "
                    "run; verdicts land in detail.slo and breaches emit "
                    "real slo_violation events into the trail")
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail "
                    "(spans included) as JSONL")
    ap.add_argument("--chrome-trace", default=None,
                    help="export the trail as Chrome trace-event JSON "
                    "(Perfetto-loadable)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    # the LAST stdout line must be the JSON artifact
    emit_to = sys.stdout
    sys.stdout = sys.stderr

    t_all = time.perf_counter()
    detail: dict = {}
    line = {
        "metric": "serve_throughput",
        "value": 0.0,
        "unit": "requests/sec",
        "detail": detail,
    }
    try:
        if os.environ.get("MOSAIC_BENCH_PLATFORM") == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        import jax

        from bench import RES, _load_or_build_index, _load_zones
        from mosaic_tpu.core.index.h3 import H3IndexSystem
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.runtime.errors import Overloaded
        from mosaic_tpu.serve import BucketLadder, ServeEngine
        from mosaic_tpu.sql.join import join_cache_stats

        h3 = H3IndexSystem()
        zones, zones_src = _load_zones()
        b = zones.bounds()
        bbox = (
            float(np.nanmin(b[:, 0])), float(np.nanmin(b[:, 1])),
            float(np.nanmax(b[:, 2])), float(np.nanmax(b[:, 3])),
        )
        index, _, _ = _load_or_build_index(zones, zones_src, h3)
        detail.update(
            device=str(jax.devices()[0]), zones=zones_src, mode=args.mode,
        )

        if args.tenants >= 2:
            # multi-tenant isolation A/B: the headline is the WORST
            # victim shed rate under per-tenant queues (should be ~0
            # while the shared-queue lane's victims shed at the
            # aggressor's mercy)
            line["metric"], line["unit"] = "victim_shed_rate", "fraction"
            with telemetry.capture() as events:
                line["value"] = _tenant_ab(index, h3, bbox, args, detail)
                if args.slo:
                    # inside capture: breach transitions emit REAL
                    # slo_violation events that land in the trail
                    from mosaic_tpu.obs import slo as _slo

                    detail["slo"] = _slo.evaluate_trail(events)
            if args.trail or args.chrome_trace:
                from mosaic_tpu import obs

                if args.trail:
                    obs.write_jsonl(events, args.trail)
                if args.chrome_trace:
                    obs.write_chrome_trace(events, args.chrome_trace)
            detail["total_wall_s"] = round(time.perf_counter() - t_all, 1)
            out = json.dumps(line)
            emit_to.write(out + "\n")
            emit_to.flush()
            if args.out:
                with open(args.out, "w") as f:
                    f.write(out + "\n")
            return

        engine = ServeEngine(
            index, h3, RES,
            ladder=BucketLadder(args.min_bucket, args.max_bucket),
            max_batch_rows=args.max_batch,
            max_wait_s=args.window_ms / 1e3,
            queue_capacity=args.queue_cap,
            default_deadline_s=args.deadline_ms / 1e3,
            bounds=bbox,
        )
        t0 = time.perf_counter()
        warm = engine.warmup()
        detail["warmup"] = dict(warm, wall_s=round(
            time.perf_counter() - t0, 3))

        rng = np.random.default_rng(args.seed)
        sizes = rng.integers(
            args.rows_min, args.rows_max + 1, args.requests
        )
        reqs = [
            rng.uniform(bbox[:2], bbox[2:], (int(n), 2)) for n in sizes
        ]
        if args.poison and reqs:
            reqs[0][: args.poison] = np.nan

        shed_submit = 0
        shed_lock = threading.Lock()
        futures: list = []

        with telemetry.capture() as events:
            # capture sinks are thread-local: closed-loop workers adopt
            # the main thread's so their serve_request events land here
            main_sinks = telemetry.current_sinks()
            t_load = time.perf_counter()
            if args.mode == "closed":
                cursor = {"i": 0}
                cursor_lock = threading.Lock()

                def worker():
                    nonlocal shed_submit
                    telemetry.adopt_sinks(main_sinks)
                    while True:
                        with cursor_lock:
                            i = cursor["i"]
                            if i >= len(reqs):
                                return
                            cursor["i"] = i + 1
                        try:
                            f = engine.submit(reqs[i])
                            with shed_lock:
                                futures.append(f)
                            try:
                                f.result()
                            except Overloaded:
                                pass
                        except Overloaded:
                            with shed_lock:
                                shed_submit += 1

                threads = [
                    threading.Thread(target=worker, daemon=True)  # lint: thread-context-adoption-ok (load generator: each submit captures its own request context; engine threads adopt downstream)
                    for _ in range(max(args.concurrency, 1))
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            else:
                # open loop: Poisson arrivals at --rate, submits never
                # wait on completions; at overload the engine sheds
                next_t = time.perf_counter()
                for pts in reqs:
                    next_t += float(rng.exponential(1.0 / args.rate))
                    lag = next_t - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    try:
                        futures.append(engine.submit(pts))
                    except Overloaded:
                        shed_submit += 1
                for f in futures:
                    try:
                        f.result()
                    except Overloaded:
                        pass
            load_wall = time.perf_counter() - t_load
            if args.slo:
                # inside capture: breach transitions emit REAL
                # slo_violation events that land in the exported trail
                from mosaic_tpu.obs import slo as _slo

                detail["slo"] = _slo.evaluate_trail(events)

        m = engine.metrics()
        lat = telemetry.summarize(events, event="serve_request")
        stages = telemetry.summarize(events, event="serve_stage")
        completed_rows = int(
            sum(
                e.get("rows", 0)
                for e in events
                if e.get("event") == "serve_request"
            )
        )
        admitted = len(futures)
        line["value"] = round(m["completed"] / max(load_wall, 1e-9), 1)
        detail.update(
            requests=args.requests,
            admitted=admitted,
            completed=m["completed"],
            shed_submit=shed_submit,
            shed_deadline=m["shed_deadline"],
            shed_total=shed_submit + m["shed_deadline"],
            shed_rate=round(
                (shed_submit + m["shed_deadline"]) / max(args.requests, 1),
                4,
            ),
            quarantined=m["quarantined"],
            degraded=m["degraded"],
            load_wall_s=round(load_wall, 3),
            requests_per_sec=line["value"],
            rows_per_sec=round(completed_rows / max(load_wall, 1e-9), 1),
            latency=lat,
            deadline_s=args.deadline_ms / 1e3,
            p99_under_deadline=bool(lat["p99"] <= args.deadline_ms / 1e3),
            batches=m["batches"],
            occupancy_mean=m["occupancy_mean"],
            requests_per_batch=round(
                m["batched_requests"] / max(m["batches"], 1), 2
            ),
            stage_summary=stages,
            compiles={
                "buckets": len(engine.ladder.buckets),
                "warmup_signatures": warm["signatures"],
                "cold_compiles": m["cold_compiles"],
                "backend_compiles_warmup": warm.get("backend_compiles"),
            },
            join_cache=join_cache_stats(emit=False),
        )
        engine.close()
        if args.trail or args.chrome_trace:
            from mosaic_tpu import obs

            if args.trail:
                obs.write_jsonl(events, args.trail)
            if args.chrome_trace:
                obs.write_chrome_trace(events, args.chrome_trace)
            traces = obs.trace_summary(events)
            detail["traces"] = {
                "count": len(traces),
                "connected": sum(
                    1 for t in traces.values()
                    if t["roots"] == 1 and not t["orphans"]
                ),
            }
    except Exception as e:  # the artifact line must still parse
        detail["error"] = repr(e)[:400]
        try:
            import jax as _j

            detail.setdefault("device", str(_j.devices()[0]))
        except Exception:
            detail.setdefault("device", "unknown")

    detail["total_wall_s"] = round(time.perf_counter() - t_all, 1)
    out = json.dumps(line)
    emit_to.write(out + "\n")
    emit_to.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if detail.get("error") and not line["value"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
