"""Multi-chip sharded dispatch: measured scaling with asserted identity.

The dispatch core (`mosaic_tpu/dispatch`) runs every frontend's device
program data-parallel over a 1-D mesh with the ChipIndex replicated.
This bench is the lane's measurement twin: for each device count it
pads one batch to the bucket ladder, dispatches it through
`DispatchCore` on a ``dp``-sized mesh, and reports points/sec plus a
``scaling_efficiency`` number (rate at the largest mesh over
device-count x the single-device rate).

Identity is the non-negotiable part: at EVERY device count the sharded
result must equal the single-device result bit for bit, and the
single-device result must equal the exact f64 host oracle
(`host_join`). A rate without those asserts would be a number about a
different join.

On CPU the bench forces virtual host devices
(``--xla_force_host_platform_device_count``) so CI proves the identity
contract at mesh 1/2/4/8 — but virtual devices share the same host
cores, so CPU ``scaling_efficiency`` is correctness evidence, not a
perf claim. The >=0.8-of-linear-at-8-chips target is recorded as a
pending TPU-window criterion (``detail.scaling_gate``).

The final stdout line is ALWAYS one machine-parseable JSON object (all
other output goes to stderr). Stage timings ride the trail as
``multichip_stage.*`` events for `tools/perf_gate.py` (its own odds
pool — see the multichip-smoke CI job).

Usage:
  python tools/multichip_bench.py --points 262144 --out MULTICHIP_r07.json
  (CPU: env JAX_PLATFORMS=cpu MOSAIC_BENCH_PLATFORM=cpu; the bench
   forces 8 virtual devices itself when the platform exposes fewer)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the synthetic fixture: pure-arithmetic grid (the H3 digit pipeline
#: costs minutes to compile on CPU; the scaling contract is
#: index-system-agnostic) over zones with holes, multipolygons, and a
#: heavy-ish candidate mix
ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1), "
    "(5 5, 5 8, 8 8, 8 5, 5 5))",
    "POLYGON ((20 0, 30 0, 30 10, 25 4, 20 10, 20 0))",
    "MULTIPOLYGON (((-20 -20, -12 -20, -12 -12, -20 -12, -20 -20)), "
    "((-8 -8, -2 -8, -2 -2, -8 -2, -8 -8)))",
]
BBOX = (-25.0, -25.0, 35.0, 20.0)
RES = 3


def _force_host_devices(n: int) -> None:
    """Before jax imports: expose ``n`` virtual CPU devices unless the
    caller already pinned a count (CI sets the flag explicitly)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip()
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=262_144)
    ap.add_argument("--passes", type=int, default=3,
                    help="timed dispatches per device count")
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated mesh sizes to measure")
    ap.add_argument("--trail", default=None,
                    help="export the telemetry trail (spans included) "
                    "as JSONL")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    counts = sorted({int(c) for c in args.devices.split(",") if c.strip()})
    if counts[0] != 1:
        counts = [1] + counts  # the scaling baseline is not optional

    # the LAST stdout line must be the JSON artifact
    emit_to = sys.stdout
    sys.stdout = sys.stderr

    if os.environ.get("MOSAIC_BENCH_PLATFORM", "cpu") == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _force_host_devices(max(counts))

    t_all = time.perf_counter()
    detail: dict = {}
    line = {
        "metric": "multichip_join_points_per_sec",
        "value": 0.0,
        "unit": "points/sec",
        "detail": detail,
    }
    stages: list[dict] = []
    root_span = None
    try:
        import jax

        from mosaic_tpu import obs
        from mosaic_tpu.core.geometry import wkt
        from mosaic_tpu.core.index import CustomIndexSystem, GridConf
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.dispatch import core as dispatch
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.sql.join import build_chip_index, host_join

        cap_events = telemetry.capture()
        stages = cap_events.__enter__()
        root_span = obs.start_span(
            "multichip_bench", devices=len(jax.devices()),
        )

        avail = len(jax.devices())
        skipped = [c for c in counts if c > avail]
        counts = [c for c in counts if c <= avail]
        if skipped:
            detail["skipped_device_counts"] = skipped

        grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
        index = build_chip_index(
            tessellate(wkt.from_wkt(ZONES), grid, RES, keep_core_geoms=False)
        )
        rng = np.random.default_rng(42)
        pts = rng.uniform(BBOX[:2], BBOX[2:], (args.points, 2))
        detail.update(
            device=str(jax.devices()[0]),
            n_devices_available=avail,
            points=args.points,
            passes=args.passes,
        )

        # the ground truth every rate hangs off: exact f64 host join
        with telemetry.timed("multichip_stage", stage="oracle"):
            oracle = host_join(pts, index.host, grid, RES)
        detail["match_rate"] = round(float((oracle >= 0).mean()), 4)

        from mosaic_tpu.dispatch.bucket import BucketLadder

        # one bucket big enough for the whole batch: the bench measures
        # steady-state dispatch, not ladder selection (min_bucket must
        # divide over the largest mesh)
        top_bucket = 1 << max(10, (args.points - 1).bit_length())
        ladder = BucketLadder(min(1024, top_bucket), top_bucket)

        per_dev: dict = {}
        single = None
        for dp in counts:
            core = dispatch.DispatchCore(
                index, grid, RES, ladder=ladder,
                mesh=None if dp == 1 else dp,
            )
            padded, nn = core.ladder.pad(pts)
            detail.setdefault("bucket", int(padded.shape[0]))
            # first dispatch pays the (bucket, index, mesh) compile —
            # priced apart so the steady-state rate stays honest
            with telemetry.timed(
                "multichip_stage", stage=f"compile_dp{dp}"
            ):
                out = core.execute_padded(padded)[:nn]
            if dp == 1:
                single = out
                identical = bool(np.array_equal(out, oracle))
            else:
                identical = bool(np.array_equal(out, single)) and bool(
                    np.array_equal(out, oracle)
                )
            t0 = time.perf_counter()
            for _ in range(args.passes):
                with telemetry.timed("multichip_stage", stage=f"dp{dp}"):
                    core.execute_padded(padded)
            wall = time.perf_counter() - t0
            rate = args.passes * nn / max(wall, 1e-9)
            per_dev[str(dp)] = {
                "points_per_sec": round(rate, 1),
                "wall_s": round(wall, 4),
                "bit_identical": identical,
                "signatures": len(core.signatures),
            }
            sys.stderr.write(
                f"dp={dp}: {rate / 1e6:.2f}M pts/s, identical={identical}\n"
            )
            if not identical:
                raise AssertionError(
                    f"sharded dispatch at dp={dp} is not bit-identical"
                )

        detail["per_device_count"] = per_dev
        top = counts[-1]
        r1 = per_dev["1"]["points_per_sec"]
        rt = per_dev[str(top)]["points_per_sec"]
        line["value"] = rt
        detail["bit_identical_all"] = True
        detail["scaling_efficiency"] = round(rt / (top * r1), 4) if top > 1 else 1.0
        detail["scaling_gate"] = {
            "target": ">=0.8 of linear at 8 chips",
            "measured_at": top,
            "status": (
                "pending-tpu-window"
                if jax.devices()[0].platform == "cpu"
                else ("pass" if rt / (top * r1) >= 0.8 else "FAIL")
            ),
            "note": (
                "CPU virtual devices share the same host cores — the "
                "identity asserts are the CPU payload; efficiency gates "
                "on real chips"
            ),
        }
        root_span.end()
        cap_events.__exit__(None, None, None)
    except Exception as e:  # lint: broad-except-ok (bench must always emit its JSON line; rc carries failure)
        detail["error"] = repr(e)[:400]

    if args.trail:
        try:
            from mosaic_tpu import obs as _obs

            if root_span is not None:
                root_span.end()  # idempotent; closes on the error path
            _obs.write_jsonl(stages, args.trail)
        except Exception as e:  # lint: broad-except-ok (a sick trail disk degrades the trail, not the bench)
            detail["trail_error"] = repr(e)[:200]
    detail["stages"] = [
        s for s in stages if s.get("event") == "multichip_stage"
    ]
    detail["total_wall_s"] = round(time.perf_counter() - t_all, 1)
    out = json.dumps(line)
    emit_to.write(out + "\n")
    emit_to.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if detail.get("error"):
        sys.exit(1)


if __name__ == "__main__":
    main()
