"""Consolidate committed bench artifacts into one perf trajectory.

Every PR commits its bench results (`BENCH_r*.json`, `STREAM_*.json`,
`MULTICHIP_r*.json`, `RASTER_r*.json`, `BENCH_TPU_LIVE.json`) but
nothing reads them as a SERIES — the trajectory question ("did the
multichip lane actually get faster across PRs 6→7?") needs manual
spelunking. This tool scans the repo root, groups artifacts into lanes
(filename stem with the ``_rNN`` round suffix stripped), extracts each
round's headline ``{metric, value, unit}``, and writes ``TREND.json``
plus (``--write-readme``) a markdown table between the
``<!-- trend:begin -->`` / ``<!-- trend:end -->`` markers in README.md.

Artifact shapes handled:
- bare bench lines: ``{"metric", "value", "unit", "detail"}``
  (STREAM/MULTICHIP/RASTER/TPU_LIVE);
- driver wrappers: ``{"n", "cmd", "rc", "tail", "parsed"}`` where
  ``parsed`` is the bench line when the run's last stdout line parsed
  (``n`` is the round); unparseable/failed rounds are listed under
  ``skipped`` — a gap in the series is information, not noise.

The LAST stdout line is one JSON object (the repo-wide bench
contract): the TREND document itself.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PATTERNS = (
    "BENCH_r*.json",
    "BENCH_TPU_LIVE.json",
    "STREAM_*.json",
    "MULTICHIP_r*.json",
    "RASTER_r*.json",
    "STALL_r*.json",
    "TUNE_r*.json",
    "SERVE_RESTART_r*.json",
    "SERVE_TENANT_r*.json",
    "OVERLAY_r*.json",
    "EPOCH_r*.json",
    "KNN_r*.json",
    "OPS_r*.json",
)

_ROUND_RE = re.compile(r"_r(\d+)$")


def _lane_and_round(stem: str, doc: dict) -> tuple[str, object]:
    m = _ROUND_RE.search(stem)
    if m:
        return stem[: m.start()], int(m.group(1))
    if isinstance(doc.get("n"), int):
        return stem, doc["n"]
    return stem, "live" if "LIVE" in stem else None


def _sustained(doc: dict) -> float | None:
    """The sustained-rate fraction of single-batch carried by an
    artifact, from whichever shape holds it: a stream bench line
    (``detail.pipeline.sustained_frac_of_single`` when the pipelined
    A/B ran, else ``detail.sustained_frac_of_single``) or a stall
    report (``loss.sustained_frac``)."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc or "tail" in doc:  # driver wrapper
        doc = doc.get("parsed")
        if not isinstance(doc, dict):
            return None
    det = doc.get("detail") or {}
    for holder in (det.get("pipeline"), det, doc.get("loss")):
        if isinstance(holder, dict):
            v = holder.get("sustained_frac_of_single",
                           holder.get("sustained_frac"))
            if isinstance(v, (int, float)):
                return float(v)
    return None


def _slo_breaches(doc: dict) -> int | None:
    """Breached-SLO count carried by an artifact's ``detail.slo`` (the
    ``--slo`` lane verdict of serve_bench/stream_bench), or None when
    the lane didn't run."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc or "tail" in doc:  # driver wrapper
        doc = doc.get("parsed")
        if not isinstance(doc, dict):
            return None
    slo = (doc.get("detail") or {}).get("slo")
    if isinstance(slo, dict) and isinstance(slo.get("breached"), list):
        return len(slo["breached"])
    return None


def _headline(doc: dict) -> dict | None:
    """The ``{metric, value, unit}`` of one artifact, or None."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc or "tail" in doc:  # driver wrapper
        doc = doc.get("parsed")
        if not isinstance(doc, dict):
            return None
    if not isinstance(doc.get("value"), (int, float)):
        return None
    return {
        "metric": doc.get("metric"),
        "value": doc["value"],
        "unit": doc.get("unit"),
    }


def collect(root: str) -> dict:
    lanes: dict = {}
    skipped: list = []
    sustained: list = []
    slo_pts: list = []
    seen = set()
    for pat in PATTERNS:
        for path in sorted(glob.glob(os.path.join(root, pat))):
            if path in seen:
                continue
            seen.add(path)
            fname = os.path.basename(path)
            stem = fname[: -len(".json")]
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                skipped.append({"file": fname, "reason": repr(e)[:120]})
                continue
            lane, rnd = _lane_and_round(stem, doc)
            # main-lane series only: the STREAM_HOST/STREAM_1B
            # variants measure different configurations and would
            # put incomparable points at the same round
            sv = (
                _sustained(doc)
                if lane in ("STREAM", "STREAM_CPU", "STALL")
                else None
            )
            if sv is not None:
                sustained.append({
                    "round": rnd, "file": fname,
                    "metric": "sustained_frac_of_single",
                    "value": sv, "unit": "frac",
                })
            nb = _slo_breaches(doc)
            if nb is not None:
                slo_pts.append({
                    "round": rnd, "file": fname,
                    "metric": "slo_breaches",
                    "value": nb, "unit": "count",
                })
            head = _headline(doc)
            if head is None:
                if sv is None:
                    skipped.append({
                        "file": fname,
                        "reason": "no parseable {metric,value} headline"
                                  f" (rc={doc.get('rc')})"
                        if isinstance(doc, dict) else "not an object",
                    })
                continue
            lanes.setdefault(lane, []).append({
                "round": rnd, "file": fname, **head,
            })
    if sustained:
        # cross-lane series: every committed artifact that measures
        # sustained-vs-single (STREAM bench lines, STALL reports) in
        # one trajectory — the gap-closing story in a single row
        lanes["sustained_frac_of_single"] = sustained
    if slo_pts:
        # cross-lane series: breached-SLO counts from every --slo lane
        # artifact — the ops-plane headline (should stay 0)
        lanes["slo_breaches"] = slo_pts
    out = {}
    for lane, pts in sorted(lanes.items()):
        pts.sort(
            key=lambda p: (
                p["round"] if isinstance(p["round"], int) else 1 << 30
            )
        )
        first, latest = pts[0], pts[-1]
        out[lane] = {
            "metric": latest["metric"],
            "unit": latest["unit"],
            "points": pts,
            "first": first["value"],
            "latest": latest["value"],
            "ratio": (
                round(latest["value"] / first["value"], 3)
                if first["value"] else None
            ),
        }
    return {
        "metric": "bench_trend",
        "lanes": out,
        "skipped": skipped,
        "n_artifacts": sum(len(v["points"]) for v in out.values()),
    }


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, (int, float)) and abs(v) >= 1000:
        return f"{v:,.0f}"
    return f"{v}"


def readme_table(trend: dict) -> str:
    lines = [
        "| lane | metric | unit | first | latest | Δ× | rounds |",
        "|---|---|---|---:|---:|---:|---|",
    ]
    for lane, d in trend["lanes"].items():
        rounds = ", ".join(
            f"r{p['round']:02d}" if isinstance(p["round"], int)
            else str(p["round"])
            for p in d["points"]
        )
        ratio = f"{d['ratio']}×" if d["ratio"] is not None else "—"
        lines.append(
            f"| {lane} | {d['metric']} | {d['unit']} "
            f"| {_fmt(d['first'])} | {_fmt(d['latest'])} "
            f"| {ratio} | {rounds} |"
        )
    return "\n".join(lines)


def update_readme(path: str, table: str) -> bool:
    begin, end = "<!-- trend:begin -->", "<!-- trend:end -->"
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if begin not in text or end not in text:
        return False
    head, rest = text.split(begin, 1)
    _, tail = rest.split(end, 1)
    new = f"{head}{begin}\n{table}\n{end}{tail}"
    if new != text:
        with open(path, "w", encoding="utf-8") as f:
            f.write(new)
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=REPO)
    ap.add_argument(
        "--out", default=None,
        help="write TREND.json here (default <root>/TREND.json; "
             "'-' skips the file)",
    )
    ap.add_argument(
        "--write-readme", action="store_true",
        help="refresh the trend table between the README markers",
    )
    args = ap.parse_args()

    trend = collect(args.root)
    out = args.out or os.path.join(args.root, "TREND.json")
    if out != "-":
        with open(out, "w", encoding="utf-8") as f:
            json.dump(trend, f, indent=1, sort_keys=False)
            f.write("\n")
    print(readme_table(trend), file=sys.stderr)
    for s in trend["skipped"]:
        print(f"skipped {s['file']}: {s['reason']}", file=sys.stderr)
    if args.write_readme:
        ok = update_readme(
            os.path.join(args.root, "README.md"), readme_table(trend)
        )
        print(
            "README trend table "
            + ("updated" if ok else "markers missing — NOT updated"),
            file=sys.stderr,
        )
    print(json.dumps(trend))
    return 0


if __name__ == "__main__":
    sys.exit(main())
