"""Raster analytics smoke/bench: decode → tile → assign → zonal → scan.

The CI twin of the raster engine (`raster/tiles.py`, `raster/zonal.py`,
`sql/raster_stream.py`): write a synthetic MODIS-shaped GeoTIFF (tiled +
deflate + predictor-2 int16, `tests/modis_fixture.py`), decode it with
the native engine, and push one band through every device stage,
asserting the f64 host-oracle bit-identity contract on the way:

1. grid fold == `host_zonal_grid_oracle`, zones fold ==
   `host_zonal_zones_oracle`, and the durable scan == the zones fold —
   ``detail.agreement`` is the fraction of stat rows that match bitwise
   and MUST be 1.0 (the CI raster-smoke lane asserts it);
2. the f32 Pallas lane (``lane="tiled"``) agrees exactly on the
   integer-valued fixture;
3. every stage lands one timed ``raster_stage.<stage>`` telemetry event
   (decode / tile / assign / zonal / scan) — the keys
   `tools/perf_gate.py` gates, so a stage regression fails CI.

The roofline rides in ``detail.roofline``: per-stage pixels/sec and
achieved GB/s from the bytes the stage actually moves (file bytes for
decode, staged values+mask for tile, centers+cells for assign,
values+segments for the fold), plus ``pct_hbm_peak`` on known TPU
device kinds (None on CPU — GB/s is still reported).

The final stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr.

Usage (CI raster-smoke lane):
  python tools/raster_bench.py --width 960 --height 720 \
      --trail /tmp/raster.jsonl
  python tools/perf_gate.py --golden tests/goldens/perf_gate.json \
      --trail /tmp/raster.jsonl ...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: nominal HBM bandwidth per chip, GB/s, keyed by device_kind substring
#: (checked in order — "v5p" before "v5" matters); mirrors bench.py
_HBM_PEAK_GBPS = (
    ("v6e", 1640.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
)


def _hbm_peak_gbps():
    import jax

    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # lint: broad-except-ok (no backend => no roofline pct, GB/s still reported)
        return None
    for pat, peak in _HBM_PEAK_GBPS:
        if pat in kind:
            return peak
    return None


#: bench world: the raster always covers x [-60, -12], y [4, 40]
#: regardless of resolution (pixel size scales with width/height), so
#: the valid-data ellipse of `modis_like_field` (x ~[-57.6, -32.6],
#: y ~[13.2, 22.2]) overlaps every zone at every --width/--height; the
#: zones cross tile boundaries and include a hole + slanted edges
WORLD = (-60.0, 48.0, 40.0, 36.0)  # x0, dx_total, y0, dy_total
ZONES = [
    "POLYGON ((-56 12, -40 11, -34 22, -50 23, -56 21, -56 12), "
    "(-50 15, -46 15, -46 18, -50 18, -50 15))",
    "POLYGON ((-40 13, -33 13, -33 21, -36.5 17, -40 21, -40 13))",
    "POLYGON ((-58 13, -52 13, -52 17, -58 17, -58 13))",
]
NODATA = 32767


def bench_gt(width: int, height: int):
    x0, dx, y0, dy = WORLD
    return (x0, dx / width, 0.0, y0, 0.0, -dy / height)


def build_fixture(width: int, height: int, seed: int, tmpdir: str):
    """(path, grid, res, chip_index): a MODIS-shaped GeoTIFF whose
    pixels cover the bench zones, plus the vector side."""
    from tests.modis_fixture import modis_like_field, write_tiled_geotiff

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.sql.join import build_chip_index

    data = modis_like_field(width, height, bands=1, seed=seed)
    path = os.path.join(tmpdir, "raster_bench.tif")
    meta = (
        '<GDALMetadata>\n  <Item name="_FillValue">'
        f"{NODATA}</Item>\n</GDALMetadata>"
    )
    write_tiled_geotiff(
        path, data, gt=bench_gt(width, height), nodata=float(NODATA),
        meta_xml=meta,
    )
    grid = CustomIndexSystem(GridConf(-180, 180, -90, 90, 2, 10.0, 10.0))
    res = 3
    index = build_chip_index(
        tessellate(wkt.from_wkt(ZONES), grid, res, keep_core_geoms=False)
    )
    return path, grid, res, index


def small_valued_twin(raster):
    """The same raster with values folded into [0, 113): integer sums
    stay far below 2**24, so the f32 Pallas lane must agree with the
    f64 fold bit for bit (MODIS-scale sums would not be f32-exact)."""
    from mosaic_tpu.raster import Raster

    data = np.where(
        raster.data == NODATA, NODATA, raster.data % 113
    ).astype(raster.data.dtype)
    return Raster(
        data=data, gt=raster.gt, srid=raster.srid, nodata=raster.nodata
    )


def result_rows(r) -> dict:
    """{key: (count, sum, min, max)} with float bit patterns preserved
    (repr-level equality == bit identity for finite f64)."""
    return {
        int(k): (int(c), float(s), float(mn), float(mx))
        for k, c, s, mn, mx in zip(r.keys, r.count, r.sum, r.min, r.max)
    }


def agreement(got, want) -> float:
    """Fraction of oracle stat rows the device result matches bitwise
    (keys, count, and the f64 bit patterns of sum/min/max)."""
    a, b = result_rows(got), result_rows(want)
    keys = set(a) | set(b)
    if not keys:
        return 1.0
    same = sum(1 for k in keys if a.get(k) == b.get(k))
    return same / len(keys)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=960)
    ap.add_argument("--height", type=int, default=720)
    ap.add_argument("--tile", default="256x256", help="TH x TW, e.g. 256x256")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail as JSONL")
    args = ap.parse_args()

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    detail: dict = {}
    line = {"metric": "raster_zonal_pixels_per_sec", "value": 0.0,
            "unit": "pixels/s", "detail": detail}
    stages: list = []
    root_span = None
    rc = 1
    try:
        import jax

        from mosaic_tpu import obs
        from mosaic_tpu.raster import read_raster
        from mosaic_tpu.raster.zonal import (
            ZonalEngine,
            host_zonal_grid_oracle,
            host_zonal_zones_oracle,
        )
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.sql import RasterStream

        tile = tuple(int(p) for p in args.tile.lower().split("x"))
        cap = telemetry.capture()
        stages = cap.__enter__()
        root_span = obs.start_span(
            "raster_bench", width=args.width, height=args.height
        )
        detail["platform"] = str(jax.devices()[0].platform)
        detail["shape"] = [args.height, args.width]
        detail["tile"] = list(tile)
        peak = _hbm_peak_gbps()

        with tempfile.TemporaryDirectory() as tmpdir:
            path, grid, res, index = build_fixture(
                args.width, args.height, args.seed, tmpdir
            )

            # ---- decode (native tiled+deflate+predictor-2 engine)
            fbytes = os.path.getsize(path)
            t0 = time.perf_counter()
            raster = read_raster(path)
            dt = time.perf_counter() - t0
            telemetry.record(
                "raster_stage", stage="decode",
                seconds=round(dt, 6), bytes=fbytes,
                pixels=raster.width * raster.height,
            )
            pixels = raster.width * raster.height
            valid = int(raster.band(1).mask.sum())
            detail["file_bytes"] = fbytes
            detail["valid_fraction"] = round(valid / pixels, 4)
            stage_bytes = {"decode": fbytes}

            # ---- grid + zones folds (raster_stage.{tile,assign,zonal})
            eng = ZonalEngine(grid, res, chip_index=index, lane="fold")
            rgrid = eng.grid(raster, tile=tile)
            rzones = eng.zones(raster, tile=tile)
            agree = {
                "grid": agreement(
                    rgrid,
                    host_zonal_grid_oracle(raster, res, grid, tile=tile),
                ),
                "zones": agreement(
                    rzones,
                    host_zonal_zones_oracle(
                        raster, index, grid, res, tile=tile
                    ),
                ),
            }

            # ---- the f32 Pallas lane on a small-valued integer twin
            # (exact in f32, so fold vs tiled must be bit-identical)
            small = small_valued_twin(raster)
            tiled = ZonalEngine(
                grid, res, chip_index=index, lane="tiled"
            ).zones(small, tile=tile)
            fold_small = eng.zones(small, tile=tile)
            agree["tiled_lane"] = agreement(tiled, fold_small)

            # ---- durable scan (raster_stage.scan)
            scan = RasterStream(index, grid, res).scan(
                raster, tile=tile,
                run_dir=os.path.join(tmpdir, "run"), snapshot_every=8,
            )
            agree["scan"] = agreement(scan.stats, rzones)

        detail["agreement"] = agree
        detail["zones_hit"] = int(len(rzones.keys))
        detail["valid_pixels"] = valid

        # per-stage roofline from the bytes each stage actually moves
        padded = None
        for e in stages:
            if e.get("event") == "raster_stage" and e.get("stage") == "tile":
                padded = e.get("padded_pixels")
                break
        padded = int(padded or pixels)
        stage_bytes["tile"] = padded * (8 + 1)       # f64 vals + mask
        stage_bytes["assign"] = padded * (16 + 8)    # f64 centers + i64
        stage_bytes["zonal"] = padded * (8 + 4)      # f64 vals + i32 seg
        stage_bytes["scan"] = padded * (8 + 4)
        totals: dict[str, float] = {}
        for e in stages:
            if e.get("event") == "raster_stage" and "stage" in e:
                totals[e["stage"]] = (
                    totals.get(e["stage"], 0.0) + float(e["seconds"])
                )
        roofline = {}
        for st, secs in sorted(totals.items()):
            entry = {
                "seconds": round(secs, 6),
                "pixels_per_sec": round(padded / max(secs, 1e-9), 1),
            }
            if st in stage_bytes:
                gbps = stage_bytes[st] / max(secs, 1e-9) / 1e9
                entry["achieved_gbps"] = round(gbps, 3)
                entry["pct_hbm_peak"] = (
                    round(100.0 * gbps / peak, 2)
                    if peak is not None else None
                )
            roofline[st] = entry
        detail["roofline"] = roofline

        zonal_s = totals.get("zonal", 0.0)
        line["value"] = round(padded / max(zonal_s, 1e-9), 1)

        bad = {k: v for k, v in agree.items() if v != 1.0}
        if bad:
            raise AssertionError(
                f"oracle agreement below 1.0: {bad} — the zonal fold "
                "broke the bit-identity contract"
            )
        rc = 0
    except Exception as e:  # lint: broad-except-ok (bench must always emit its JSON line; rc carries failure)
        detail["error"] = repr(e)[:400]

    if root_span is not None:
        try:
            root_span.end()
        except Exception:  # lint: broad-except-ok (span cleanup must not mask the bench result)
            pass
    if args.trail and stages:
        try:
            from mosaic_tpu import obs as _obs

            _obs.write_jsonl(stages, args.trail)
        except Exception as e:  # lint: broad-except-ok (a sick trail disk degrades the trail, not the bench)
            detail["trail_error"] = repr(e)[:200]

    emit_to.write(json.dumps(line) + "\n")
    emit_to.flush()
    return rc


if __name__ == "__main__":
    sys.exit(main())
