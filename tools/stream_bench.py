"""Streamed ≥100M-point PIP join: the 1B-point north-star architecture.

Round-5 diagnosis (`STREAM_1B_r05.json`): the device-gen stream sustained
47.2M pts/s against a 132.2M single-batch rate (0.357x) because point
GENERATION ran inside every loop iteration and nothing overlapped cell
assignment with the probe — and `peak_hbm_bytes` came back 0 because the
axon tunnel exposes no memory stats. This bench now measures through the
`mosaic_tpu.sql.stream` pipeline layer, which separates the stages:

- **generator rate** — `gen_batch` alone in an identical fori_loop;
- **pure-join sustained rate** (the headline `value` in ring mode) — the
  loop cycles a pre-generated ring of K batches resident in HBM, with
  double-buffered prefetch of batch i+1's cell assignment overlapping
  batch i's PIP passes (`--no-ab` skips the prefetch-off comparison);
- **single-batch rate** — the same fused step on one pre-staged batch;
  `sustained_frac_of_single` is pure-join sustained over this;
- **peak_hbm_bytes** — runtime memory stats at the loop's high-water
  mark, falling back to a live-buffer census when the backend reports
  none (never 0 again); per-stage wall timings ride along in
  ``detail.stages`` (captured `stream_stage` telemetry events).

The final stdout line is ALWAYS one machine-parseable JSON object (all
other output goes to stderr). ``--verify`` (CPU CI) additionally asserts
the streamed loop is bit-identical to the per-batch path.

Usage:
  python tools/stream_bench.py --points 1000000000 --device-gen [--out F]
  python tools/stream_bench.py --points 100000000            # host-stream
  (CPU validation: MOSAIC_BENCH_PLATFORM=cpu --points 200000
   --batch 50000 --ring 2 --device-gen --verify)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _bucket(n: int) -> int:
    """bench.py's cap bucketing: pow2 below 128k, 128k multiples above —
    cap size directly scales tier gather/matmul cost."""
    if n <= 131072:
        return max(16, 1 << int(np.ceil(np.log2(n + 1))))
    return (n + 131071) // 131072 * 131072


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=100_000_000)
    ap.add_argument("--batch", type=int, default=4_000_000)
    ap.add_argument("--ring", type=int, default=8,
                    help="HBM-resident ring slots (device-gen mode)")
    ap.add_argument("--device-gen", action="store_true",
                    help="pure-join ring mode (device-generated batches)")
    ap.add_argument("--donate", action="store_true",
                    help="A/B the donate_ring lane: rerun the join loop "
                    "over a sacrificial ring copy with the ring buffer "
                    "donated to XLA, and record the rate delta plus the "
                    "bytes the copy-free loop keeps out of HBM")
    ap.add_argument("--no-ab", action="store_true",
                    help="skip the prefetch-off comparison compile")
    ap.add_argument("--fused", action="store_true",
                    help="also run the r05-style gen-in-loop stream")
    ap.add_argument("--verify", action="store_true",
                    help="assert stream == per-batch bit-identity (CPU)")
    ap.add_argument("--durable", action="store_true",
                    help="run the ring loop through run_durable "
                    "(checkpoint/resume, watchdog, retry+degradation)")
    ap.add_argument("--pipeline", action="store_true",
                    help="A/B the pipelined durable executor "
                    "(dispatch/pipeline.py) against the synchronous "
                    "segment loop: emits detail.pipeline with the "
                    "sustained-rate delta, window depth, and the "
                    "snapshot/device overlap fraction (implies "
                    "--durable)")
    ap.add_argument("--resume", action="store_true",
                    help="resume an interrupted --durable run from "
                    "--run-dir instead of starting fresh")
    ap.add_argument("--run-dir", default=None,
                    help="snapshot directory for --durable/--resume "
                    "(default: ./stream_run)")
    ap.add_argument("--snapshot-every", type=int, default=16,
                    help="ring cycles between durable snapshots")
    ap.add_argument("--poison", type=int, default=0,
                    help="inject N NaN rows into the staged batches "
                    "before admission (quarantine demo lane)")
    ap.add_argument("--slo", action="store_true",
                    help="evaluate the run's trail against the default "
                    "SLO specs (MOSAIC_SLO_* thresholds; set "
                    "MOSAIC_SLO_STREAM_RATE_MIN for the sustained-rate "
                    "floor) — verdicts land in detail.slo and breaches "
                    "emit real slo_violation events into the trail")
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail "
                    "(spans included) as JSONL")
    ap.add_argument("--chrome-trace", default=None,
                    help="export the trail as Chrome trace-event JSON "
                    "(Perfetto-loadable)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    # the LAST stdout line must be the JSON artifact: stray library prints
    # and progress chatter all divert to stderr
    emit_to = sys.stdout
    sys.stdout = sys.stderr

    t_all = time.perf_counter()
    detail: dict = {}
    line = {
        "metric": "stream_join_sustained",
        "value": 0.0,
        "unit": "points/sec/chip",
        "detail": detail,
    }
    stages: list[dict] = []
    root_span = None
    try:
        if os.environ.get("MOSAIC_BENCH_PLATFORM") == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
        import functools

        import jax
        import jax.numpy as jnp

        from bench import RES, _load_or_build_index, _load_zones
        from mosaic_tpu.core.index.h3 import H3IndexSystem
        from mosaic_tpu.runtime import telemetry
        from mosaic_tpu.sql.stream import (
            StreamJoin,
            fold_stats,
            generator_rate,
            hbm_peak,
            ring_from_generator,
        )

        from mosaic_tpu import obs

        cap_events = telemetry.capture()
        stages = cap_events.__enter__()
        # one root span: ring build, compiles, the measured loops, and
        # the durable lane are ONE trace in the exported trail
        root_span = obs.start_span(
            "stream_bench", mode="device-gen" if args.device_gen else "host",
        )

        h3 = H3IndexSystem()
        zones, zones_src = _load_zones()
        b = zones.bounds()
        bbox = (
            float(np.nanmin(b[:, 0])), float(np.nanmin(b[:, 1])),
            float(np.nanmax(b[:, 2])), float(np.nanmax(b[:, 3])),
        )
        index, _, _ = _load_or_build_index(zones, zones_src, h3)
        dev = jax.devices()[0]
        detail.update(device=str(dev), zones=zones_src)

        batch = min(args.batch, args.points)
        n_batches = (args.points + batch - 1) // batch

        # caps from a host presample, margined like bench.py; an overflow
        # in any batch is counted on device, reported in detail.overflow
        rng = np.random.default_rng(77)
        n_pre = min(200_000, max(20_000, batch))
        pre = rng.uniform(bbox[:2], bbox[2:], (n_pre, 2))
        pre_cells = np.asarray(
            h3.point_to_cell(jnp.asarray(pre, jnp.float32), RES)
        )
        cells_np = np.asarray(index.cells)
        pos = np.clip(
            np.searchsorted(cells_np, pre_cells), 0, cells_np.size - 1
        )
        ffrac = float((cells_np[pos] == pre_cells).mean())
        fcap = min(_bucket(int(1.5 * ffrac * batch)), batch)
        hmask = np.asarray(index.cell_heavy) >= 0
        hfrac = float(np.isin(pre_cells, cells_np[hmask]).mean())
        hcap = min(_bucket(int(1.5 * hfrac * batch)), fcap)

        lo = jnp.asarray(bbox[:2], dtype=jnp.float64)
        span = jnp.asarray(
            [bbox[2] - bbox[0], bbox[3] - bbox[1]], dtype=jnp.float64
        )

        @jax.jit
        def gen_batch(key):
            u = jax.random.uniform(key, (batch, 2), dtype=jnp.float32)
            return (lo + u * span).astype(jnp.float64)

        key = jax.random.PRNGKey(5)
        sj = StreamJoin(
            index, h3, RES, found_cap=fcap, heavy_cap=hcap, prefetch=True
        )
        detail.update(
            n_points=n_batches * batch, n_batches=n_batches, batch=batch,
            caps=[fcap, hcap], lookup=sj.lookup, compaction=sj.compaction,
        )

        # tunnel round-trip: every blocking scalar pull pays this (~60 ms
        # on the axon tunnel) — it must stay OUT of the streamed loop
        rtt_t = time.perf_counter()
        float(jnp.float32(1.0) + 1.0)
        rtt = time.perf_counter() - rtt_t
        detail["sync_rtt_s"] = round(rtt, 4)

        # compile + single-batch compute rate (pre-staged input)
        warm = gen_batch(jax.random.fold_in(key, 0))
        warm.block_until_ready()
        np.asarray(sj.step_stats(warm))
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(sj.step_stats(warm))
            reps.append(time.perf_counter() - t0)
        # rtt can exceed a fully-pipelined wall sample on the tunnel:
        # floor the device estimate at 20% of wall, never negative
        single_s = max(min(reps) - rtt, min(reps) * 0.2, 1e-9)
        single_rate = batch / single_s
        detail["single_batch_rate"] = round(single_rate, 1)
        # in the trail too, so stall_report can decompose sustained-vs-
        # single loss from the trail alone (artifacts embed stages)
        telemetry.record(
            "stream_stage", stage="single_batch",
            seconds=round(single_s, 6), batch=batch,
            points_per_sec=round(single_rate, 1),
        )

        if args.device_gen:
            detail["mode"] = "device-gen-ring"

            # (1) the generator alone, in an identical fori_loop — the
            # cost the r05 stream folded invisibly into its number
            gen_rate, gen_wall = generator_rate(
                gen_batch, key, n_batches, batch
            )
            detail["generator_points_per_sec"] = round(gen_rate, 1)
            detail["gen_wall_s"] = round(gen_wall, 3)

            # (2) the ring: K device-generated batches resident in HBM
            k = max(2, min(args.ring, n_batches))
            ring = ring_from_generator(gen_batch, key, k)
            detail["ring_k"] = k
            detail["ring_bytes"] = int(ring.nbytes)

            # (2b) durable lane: quarantine admission (+ optional poison
            # demo) and the checkpointed segment loop — slower than the
            # one-dispatch loop (one snapshot D2H per segment), priced
            # separately in detail.durable, never the headline
            if args.pipeline:
                args.durable = True
            if args.poison or args.durable or args.resume:
                host_batches = [np.array(b) for b in np.asarray(ring)]
                if args.poison:
                    host_batches[0][: args.poison] = np.nan
                ring, q_report = sj.admit(host_batches, bounds=bbox)
                detail["quarantine"] = q_report.metrics()
            if args.durable or args.resume:
                run_dir = args.run_dir or "stream_run"
                if args.resume:
                    res_d = sj.resume(run_dir, ring)
                else:
                    res_d = sj.run_durable(
                        ring, n_batches, run_dir=run_dir,
                        snapshot_every=args.snapshot_every,
                        extra_arrays={"gen_key": np.asarray(key)},
                    )
                detail["durable"] = dict(
                    res_d.metrics,
                    wall_s=round(res_d.wall_s, 3),
                    points_per_sec=round(res_d.points_per_sec, 1),
                    checksum=res_d.checksum,
                    matches=res_d.matches,
                    overflow=res_d.overflow,
                    sustained_frac_of_single=round(
                        res_d.points_per_sec / single_rate, 4
                    ),
                )
                # (2c) pipelined A/B: the same durable workload through
                # the asynchronous executor — the trail slice gives the
                # snapshot/device overlap fraction ("snapshots off the
                # critical path" as a measured number, not prose)
                if args.pipeline and not args.resume:
                    from mosaic_tpu.obs import timeline as _tl

                    i0 = len(stages)
                    res_p = sj.run_durable(
                        ring, n_batches, run_dir=run_dir + "_pipe",
                        snapshot_every=args.snapshot_every,
                        extra_arrays={"gen_key": np.asarray(key)},
                        pipeline=True,
                    )
                    tracks = _tl.build_tracks(stages[i0:])

                    def _iv(key_):
                        return tracks.get(key_, {}).get("intervals", [])

                    sync_rate = res_d.points_per_sec
                    pipe_rate = res_p.points_per_sec
                    detail["pipeline"] = dict(
                        res_p.metrics.get("pipeline", {}),
                        points_per_sec=round(pipe_rate, 1),
                        wall_s=round(res_p.wall_s, 3),
                        sustained_frac_of_single=round(
                            pipe_rate / single_rate, 4
                        ),
                        sustained_frac_delta_vs_sync=round(
                            (pipe_rate - sync_rate) / single_rate, 4
                        ),
                        speedup_vs_sync=round(
                            pipe_rate / max(sync_rate, 1e-9), 3
                        ),
                        snapshot_overlap_fraction=_tl.overlap_fraction(
                            _iv("span.stream.snapshot"),
                            _iv("span.stream.pipeline.drain")
                            + _iv("span.stream.segment"),
                        ),
                        consistent_with_sync=bool(
                            res_p.checksum == res_d.checksum
                            and res_p.matches == res_d.matches
                            and res_p.overflow == res_d.overflow
                        ),
                    )

            # (3) the join loop over the ring, prefetch on — ONE
            # dispatch, one (3,) result pull (per-batch python dispatch
            # over the tunnel measured 146 ms/batch for a ~63 ms device
            # step in r05: the host loop was dispatch-bound)
            sj.compile(ring, n_batches)
            res = sj.run(ring, n_batches)
            join_wall = max(res.wall_s - rtt, 1e-9)
            join_rate = res.n_points / join_wall
            line["value"] = round(join_rate, 1)
            detail.update(
                join_points_per_sec=round(join_rate, 1),
                join_wall_s=round(join_wall, 3),
                prefetch=True,
                sustained_frac_of_single=round(join_rate / single_rate, 4),
                match_rate=round(res.matches / res.n_points, 4),
                overflow=res.overflow,
                checksum=res.checksum,
            )
            if "durable" in detail:
                # the checkpointed segment loop must fold to the same
                # stats as the one-dispatch loop (free cross-check)
                detail["durable"]["consistent_with_loop"] = bool(
                    detail["durable"]["checksum"] == res.checksum
                    and detail["durable"]["matches"] == res.matches
                    and detail["durable"]["overflow"] == res.overflow
                )

            # (4) prefetch A/B: same ring without the double buffer
            # (costs one extra loop compile — --no-ab on flaky tunnels)
            if not args.no_ab:
                sj0 = StreamJoin(
                    index, h3, RES, found_cap=fcap, heavy_cap=hcap,
                    lookup=sj.lookup, compaction=sj.compaction,
                    prefetch=False,
                )
                sj0.compile(ring, n_batches)
                r0 = sj0.run(ring, n_batches)
                detail["no_prefetch_points_per_sec"] = round(
                    r0.n_points / max(r0.wall_s - rtt, 1e-9), 1
                )
                if (r0.checksum, r0.matches, r0.overflow) != (
                    res.checksum, res.matches, res.overflow
                ):
                    detail["prefetch_mismatch"] = True  # never expected

            # (4c) donation A/B: same loop with the ring buffer donated
            # to XLA — the loop reuses the ring's HBM in place of a
            # working copy, so the delta is the copy the non-donating
            # loop pays (ring_bytes of extra peak HBM + the copy time)
            if args.donate:
                sj_d = StreamJoin(
                    index, h3, RES, found_cap=fcap, heavy_cap=hcap,
                    lookup=sj.lookup, compaction=sj.compaction,
                    prefetch=True, donate_ring=True,
                )
                ring_d = jnp.array(ring, copy=True)  # sacrificial
                sj_d.compile(ring_d, n_batches)
                rd = sj_d.run(ring_d, n_batches)
                d_rate = rd.n_points / max(rd.wall_s - rtt, 1e-9)
                detail["donation"] = dict(
                    {k: rd.metrics[k] for k in (
                        "donate_ring", "ring_donated", "ring_bytes",
                    ) if k in rd.metrics},
                    points_per_sec=round(d_rate, 1),
                    delta_vs_copy=round(d_rate - join_rate, 1),
                    consistent_with_loop=bool(
                        rd.checksum == res.checksum
                        and rd.matches == res.matches
                        and rd.overflow == res.overflow
                    ),
                )

            # (5) optional r05-comparable fused lane: gen inside the loop
            if args.fused:
                @functools.partial(jax.jit, static_argnames=("nb",))
                def stream_fused(kk, nb):
                    def body(i, acc):
                        pts = gen_batch(jax.random.fold_in(kk, i))
                        cells = sj.assign(pts)
                        return acc + fold_stats(
                            sj.join(pts, cells, index)
                        )

                    return jax.lax.fori_loop(
                        0, nb, body, jnp.zeros(3, jnp.int32)
                    )

                np.asarray(stream_fused(key, n_batches))  # compile
                t0 = time.perf_counter()
                np.asarray(stream_fused(key, n_batches))
                fw = max(time.perf_counter() - t0 - rtt, 1e-9)
                detail["fused_points_per_sec"] = round(
                    n_batches * batch / fw, 1
                )

            # (6) high-water memory AFTER the loop (cumulative peak) —
            # every lane must report a REAL number: the census fallback
            # always sees at least the ring, so 0 is a measurement bug
            # (STREAM_r05's peak_hbm_bytes: 0), never a valid artifact
            peak, src = hbm_peak(dev, fallback_arrays=[ring])
            detail["peak_hbm_bytes"] = peak
            detail["hbm_source"] = src
            assert peak > 0, (
                f"peak_hbm_bytes must be > 0 (source={src!r}) — the "
                "live-buffer census fallback should at least see the ring"
            )

            # (7) bit-identity against the per-batch path (CPU CI)
            if args.verify:
                nb_v = min(n_batches, 2 * k + 1)
                rs = sj.run(ring, nb_v, collect=True)
                rb = sj.run_batched(ring, nb_v)
                same = bool(np.array_equal(rs.outs, rb.outs)) and (
                    rs.checksum, rs.matches, rs.overflow
                ) == (rb.checksum, rb.matches, rb.overflow)
                detail["verified"] = same
                if not same:
                    raise AssertionError("stream path != per-batch path")
        else:
            # host-stream: double-buffered H2D; stats accumulate ON
            # DEVICE and cross the tunnel once per SYNC_EVERY batches (a
            # per-batch float() costs one ~60 ms round trip each, which
            # alone capped a 25-batch 100M stream at ~20M pts/s). The
            # tunnel runs ~10 MB/s: this mode is transfer-bound by three
            # orders of magnitude (reported, not hidden).
            detail["mode"] = "host-stream"
            fold = jax.jit(fold_stats)

            def host_batch(i):
                r = np.random.default_rng(1000 + i)
                return r.uniform(bbox[:2], bbox[2:], (batch, 2))

            def stage_put(i):
                return jax.device_put(jnp.asarray(host_batch(i)))

            SYNC_EVERY = 16
            h2d_s = 0.0
            t0 = time.perf_counter()
            acc = None
            nxt = stage_put(0)
            for i in range(n_batches):
                cur = nxt
                if i + 1 < n_batches:
                    th = time.perf_counter()
                    nxt = stage_put(i + 1)  # async put overlaps batch i
                    h2d_s += time.perf_counter() - th
                s = fold(sj.step(cur))
                acc = s if acc is None else acc + s
                if (i + 1) % SYNC_EVERY == 0:
                    np.asarray(acc)
            acc_np = np.asarray(acc)
            wall = time.perf_counter() - t0
            n_total = n_batches * batch
            sustained = n_total / wall
            line["value"] = round(sustained, 1)
            detail.update(
                wall_s=round(wall, 2),
                host_stage_s=round(h2d_s, 2),
                join_points_per_sec=round(sustained, 1),
                sustained_frac_of_single=round(
                    sustained / single_rate, 4
                ),
                tunnel_limited=bool(sustained < 0.5 * single_rate),
                match_rate=round(int(acc_np[1]) / n_total, 4),
                overflow=int(acc_np[2]),
                checksum=int(acc_np[0]),
            )
            peak, src = hbm_peak(dev, fallback_arrays=[nxt])
            detail["peak_hbm_bytes"] = peak
            detail["hbm_source"] = src
            assert peak > 0, (
                f"peak_hbm_bytes must be > 0 (source={src!r}) — the "
                "census fallback should at least see the staged batch"
            )
        root_span.end()
        if args.slo:
            # still inside the capture scope: breach transitions emit
            # REAL slo_violation events that land in the exported trail
            from mosaic_tpu.obs import slo as _slo

            detail["slo"] = _slo.evaluate_trail(stages)
        cap_events.__exit__(None, None, None)
    except Exception as e:  # the artifact line must still parse
        detail["error"] = repr(e)[:400]
        try:
            import jax as _j

            detail.setdefault("device", str(_j.devices()[0]))
        except Exception:
            detail.setdefault("device", "unknown")

    if args.trail or args.chrome_trace:
        try:
            from mosaic_tpu import obs as _obs

            if root_span is not None:
                root_span.end()  # idempotent; closes on the error path
            if args.trail:
                _obs.write_jsonl(stages, args.trail)
            if args.chrome_trace:
                _obs.write_chrome_trace(stages, args.chrome_trace)
            traces = _obs.trace_summary(stages)
            detail["traces"] = {
                "count": len(traces),
                "connected": sum(
                    1 for t in traces.values()
                    if t["roots"] == 1 and not t["orphans"]
                ),
            }
        except Exception as e:
            detail["trail_error"] = repr(e)[:200]
    detail["stages"] = [
        s for s in stages if s.get("event") == "stream_stage"
    ]
    # percentile rollup via the shared helper (the serve bench uses the
    # same one for request latencies — one p99 definition everywhere)
    try:
        from mosaic_tpu.runtime import telemetry as _tele

        detail["stage_summary"] = _tele.summarize(
            detail["stages"], event="stream_stage"
        )
    except Exception:
        pass
    detail["total_wall_s"] = round(time.perf_counter() - t_all, 1)
    out = json.dumps(line)
    emit_to.write(out + "\n")
    emit_to.flush()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if detail.get("error") and (
        not line["value"] or detail.get("peak_hbm_bytes", 1) <= 0
    ):
        sys.exit(1)


if __name__ == "__main__":
    main()
