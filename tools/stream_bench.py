"""Streamed ≥100M-point PIP join: the 1B-point north-star architecture.

Reference analog: the Quickstart benchmark joins billions of points by
letting Spark stream partitions through executors; here one chip streams
host-generated batches through the fused cell-assign + probe step with
DOUBLE BUFFERING — batch i+1's H2D transfer and batch i's compute overlap
because JAX dispatch is asynchronous; the loop only forces batch i-1's
device-side checksum.

Emits ONE JSON line (artifact: STREAM_r05.json when --out is given):
sustained points/sec over the whole stream, the single-batch compute rate
for the same compiled step, and their ratio. On this rig the host↔device
tunnel runs at ~10 MB/s, so host-streamed mode is transfer-bound by three
orders of magnitude (reported, not hidden: ``tunnel_limited``);
``--device-gen`` streams device-generated batches through the identical
loop to validate the pipeline at full rate (the bench's scale lane does
the same for 16M).

Usage:
  python tools/stream_bench.py --points 100000000 [--device-gen] [--out F]
  (CPU validation: MOSAIC_BENCH_PLATFORM=cpu --points 2000000)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=100_000_000)
    ap.add_argument("--batch", type=int, default=4_000_000)
    ap.add_argument("--device-gen", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if os.environ.get("MOSAIC_BENCH_PLATFORM") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import functools

    import jax
    import jax.numpy as jnp

    from bench import RES, _load_or_build_index, _load_zones
    from mosaic_tpu.core.index.h3 import H3IndexSystem
    from mosaic_tpu.sql.join import pip_join_points

    t_all = time.perf_counter()
    h3 = H3IndexSystem()
    zones, zones_src = _load_zones()
    b = zones.bounds()
    bbox = (
        float(np.nanmin(b[:, 0])), float(np.nanmin(b[:, 1])),
        float(np.nanmax(b[:, 2])), float(np.nanmax(b[:, 3])),
    )
    index, _, _ = _load_or_build_index(zones, zones_src, h3)
    dtype = index.border.verts.dtype
    dev = jax.devices()[0]

    batch = min(args.batch, args.points)
    n_batches = (args.points + batch - 1) // batch

    @functools.partial(jax.jit, static_argnames=("fcap", "hcap"))
    def step(points_f64, chip_index, fcap, hcap):
        cells = h3.point_to_cell(points_f64.astype(jnp.float32), RES)
        shifted = (points_f64 - chip_index.border.shift).astype(dtype)
        out = pip_join_points(
            shifted, cells.astype(jnp.int64), chip_index,
            heavy_cap=hcap, found_cap=fcap,
            lookup="gather" if jax.devices()[0].platform == "cpu" else "mxu",
            compaction="scatter" if jax.devices()[0].platform == "cpu"
            else "mxu",
        )
        # device-side fold: checksum + match count + overflow count force
        # completion without streaming 4 B/point back over the link
        return (out ^ (out >> 16)).sum(), (out >= 0).sum(), (out == -2).sum()

    def bucket(n):
        """bench.py's cap bucketing: pow2 below 128k, 128k multiples
        above — cap size directly scales tier gather/matmul cost, so the
        old flat +65536 slack (which forced hcap to 131072 on NYC where
        65536 suffices) cost real throughput."""
        if n <= 131072:
            return max(16, 1 << int(np.ceil(np.log2(n + 1))))
        return (n + 131071) // 131072 * 131072

    # caps from a host presample, margined like bench.py; an overflow in
    # any batch is counted on device and reported in detail.overflow
    rng = np.random.default_rng(77)
    pre = rng.uniform(bbox[:2], bbox[2:], (200_000, 2))
    pre_cells = np.asarray(h3.point_to_cell(jnp.asarray(pre, jnp.float32), RES))
    cells_np = np.asarray(index.cells)
    pos = np.clip(np.searchsorted(cells_np, pre_cells), 0, cells_np.size - 1)
    ffrac = float((cells_np[pos] == pre_cells).mean())
    fcap = min(bucket(int(1.5 * ffrac * batch)), batch)
    hmask = np.asarray(index.cell_heavy) >= 0
    hfrac = float(np.isin(pre_cells, cells_np[hmask]).mean())
    hcap = min(bucket(int(1.5 * hfrac * batch)), fcap)

    lo = jnp.asarray(bbox[:2], dtype=jnp.float64)
    span = jnp.asarray(
        [bbox[2] - bbox[0], bbox[3] - bbox[1]], dtype=jnp.float64
    )

    @functools.partial(jax.jit, static_argnames=("n",))
    def gen_batch(key, n):
        u = jax.random.uniform(key, (n, 2), dtype=jnp.float32)
        return (lo + u * span).astype(jnp.float64)

    def host_batch(i):
        r = np.random.default_rng(1000 + i)
        return r.uniform(bbox[:2], bbox[2:], (batch, 2))

    key = jax.random.PRNGKey(5)

    def stage(i):
        if args.device_gen:
            return gen_batch(jax.random.fold_in(key, i), batch)
        return jax.device_put(jnp.asarray(host_batch(i)))

    # tunnel round-trip: every blocking scalar pull pays this (~60 ms on
    # the axon tunnel) — it must stay OUT of the streamed loop
    rtt_t = time.perf_counter()
    float(jnp.float32(1.0) + 1.0)
    rtt = time.perf_counter() - rtt_t

    # compile + single-batch compute rate (pre-staged input, like bench)
    warm = stage(0)
    warm.block_until_ready()
    s0, m0, v0 = step(warm, index, fcap, hcap)
    float(s0)
    reps = []
    for _ in range(3):
        t0 = time.perf_counter()
        s0, m0, v0 = step(warm, index, fcap, hcap)
        float(s0)
        reps.append(time.perf_counter() - t0)
    # rtt can exceed a fully-pipelined wall sample on the tunnel: floor
    # the device estimate at 20% of wall rather than going negative
    single_s = max(min(reps) - rtt, min(reps) * 0.2, 1e-9)
    single_rate = batch / single_s

    h2d_s = 0.0
    if args.device_gen:
        # device-gen streams the WHOLE run inside one jitted fori_loop:
        # one dispatch, one result pull. Per-batch python dispatch over
        # the axon tunnel does NOT overlap with device execution
        # (measured 2026-07-31: ~146 ms/batch wall for a ~63 ms device
        # step even with device-side accumulation and 16-batch syncs), so
        # the host loop was tunnel-dispatch-bound, not compute-bound.
        # This is also the honest 1B-point shape: a real ingest pipeline
        # keeps the device fed without a host round trip per batch.
        @functools.partial(jax.jit, static_argnames=("nb",))
        def stream_dev(k, nb):
            def body(i, c):
                s, m, v = c
                pts = gen_batch(jax.random.fold_in(k, i), batch)
                s2, m2, v2 = step(pts, index, fcap, hcap)
                # x64 mode promotes the bool-sum counts to i64: keep the
                # carry i32 (counts stay < 2^31 even at 1B points)
                return (
                    s + s2.astype(jnp.int32),
                    m + m2.astype(jnp.int32),
                    v + v2.astype(jnp.int32),
                )
            z = jnp.zeros((), jnp.int32)
            return jax.lax.fori_loop(0, nb, body, (z, z, z))

        s_tot, m_tot, v_tot = stream_dev(key, n_batches)  # compile
        float(s_tot)
        t0 = time.perf_counter()
        s_tot, m_tot, v_tot = stream_dev(key, n_batches)
        float(s_tot)
        wall = time.perf_counter() - t0 - rtt
    else:
        # host-stream: double-buffered H2D; checksum + match count
        # accumulate ON DEVICE and cross the tunnel once per SYNC_EVERY
        # batches (a per-batch float() costs one ~60 ms round trip each,
        # which alone capped a 25-batch 100M stream at ~20M pts/s)
        SYNC_EVERY = 16
        t0 = time.perf_counter()
        s_tot = m_tot = v_tot = None
        nxt = stage(0)
        for i in range(n_batches):
            cur = nxt
            if i + 1 < n_batches:
                th = time.perf_counter()
                nxt = stage(i + 1)  # async put/gen overlaps batch i
                h2d_s += time.perf_counter() - th
            s, m, v = step(cur, index, fcap, hcap)
            s_tot = s if s_tot is None else s_tot + s
            m_tot = m if m_tot is None else m_tot + m
            v_tot = v if v_tot is None else v_tot + v
            if (i + 1) % SYNC_EVERY == 0:
                float(s_tot)
        float(s_tot)
        wall = time.perf_counter() - t0
    matches = int(m_tot)
    overflow = int(v_tot)
    n_total = n_batches * batch
    sustained = n_total / wall

    mem = {}
    try:
        st = dev.memory_stats() or {}
        mem = {"peak_hbm_bytes": int(st.get("peak_bytes_in_use", 0))}
    except Exception:
        pass

    line = {
        "metric": "stream_join_sustained",
        "value": round(sustained, 1),
        "unit": "points/sec/chip",
        "detail": {
            "mode": "device-gen" if args.device_gen else "host-stream",
            "n_points": n_total,
            "n_batches": n_batches,
            "batch": batch,
            "wall_s": round(wall, 2),
            "host_stage_s": round(h2d_s, 2),
            "single_batch_rate": round(single_rate, 1),
            "sustained_frac_of_single": round(sustained / single_rate, 4),
            "tunnel_limited": bool(
                not args.device_gen and sustained < 0.5 * single_rate
            ),
            "match_rate": round(matches / n_total, 4),
            "overflow": overflow,
            "caps": [fcap, hcap],
            "device": str(dev),
            "zones": zones_src,
            "total_wall_s": round(time.perf_counter() - t_all, 1),
            **mem,
        },
    }
    out = json.dumps(line)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    main()
