"""Chaos sweep: one injected fault at EVERY registered site, one verdict.

`tests/goldens/registry.json` enumerates the fault sites the runtime
guards (`faults.maybe_fail` / `maybe_corrupt` hook names — regenerated
by `python tools/lint.py --update-registry`, so a new site cannot
hide). For each site this sweep runs the site's reference workload
clean, re-runs it with exactly ONE injected fault at that site, proves
the injection actually tripped (`plan.trips`), and asserts the outcome
is one of the published resilience contracts:

- **typed**     — a typed `MosaicRuntimeError` subclass reached the
                  caller: never a bare exception, never a hang, and the
                  driver re-proves the surface still serves afterwards;
- **identical** — the retry layer absorbed the fault and the result is
                  bit-identical to the clean run;
- **degraded**  — the result is explicitly flagged degraded AND still
                  matches the clean run (the f64 host-oracle fallback);
- **contained** — a data-corruption site: exactly the poisoned rows are
                  quarantined, callers' inputs untouched.

A registry site with NO driver here FAILS the sweep — adding a fault
site to the codebase obliges a chaos driver for it. Drivers for sites
not (yet) in the registry run too and are reported under
``detail.extra`` (the lint regen will fold them in).

The final stdout line is ALWAYS one machine-parseable JSON object;
everything else goes to stderr.

Usage (CI chaos-smoke lane):
  python tools/chaos_sweep.py --trail /tmp/chaos.jsonl
  python tools/chaos_sweep.py --sites 'epoch.*' --sites 'stream.*'
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# one-shot faults are retried by the guarded surfaces: keep the backoff
# out of the sweep's wall clock, and give dist_join its 8-way host mesh
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MOSAIC_RETRY_BASE_S", "0.01")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

RES = 3
RES_H3 = 7
BBOX = (-25.0, -25.0, 35.0, 20.0)
BBOX_NY = (-74.05, 40.60, -73.85, 40.78)
ZONES = [
    "POLYGON ((1 1, 13 2, 12 11, 6 14, 2 9, 1 1))",
    "POLYGON ((-20 -20, -5 -20, -5 -5, -20 -5, -20 -20))",
    "POLYGON ((20 -10, 30 -10, 30 5, 20 5, 20 -10))",
]
ZONE0_V2 = "POLYGON ((1 1, 14 1, 12 12, 5 13, 1 8, 1 1))"


class ChaosMiss(AssertionError):
    """A site's driver broke the chaos contract (never tripped, untyped
    escape, silent divergence) — the sweep fails on the first one."""


DRIVERS: dict = {}


def driver(site):
    def deco(fn):
        DRIVERS[site] = fn
        return fn
    return deco


_CACHE: dict = {}


def memo(key, fn):
    if key not in _CACHE:
        _CACHE[key] = fn()
    return _CACHE[key]


def tmpdir(tag: str) -> str:
    return tempfile.mkdtemp(prefix=f"chaos-{tag.replace('.', '-')}-")


# ------------------------------------------------------------- fixtures


def grid():
    from mosaic_tpu.core.index import CustomIndexSystem, GridConf

    return memo(
        "grid",
        lambda: CustomIndexSystem(GridConf(-180, 180, -90, 90, 2,
                                           10.0, 10.0)),
    )


def grid_index():
    def build():
        from mosaic_tpu.core.geometry import wkt
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.sql.join import build_chip_index

        col = wkt.from_wkt(ZONES)
        return build_chip_index(
            tessellate(col, grid(), RES, keep_core_geoms=False)
        )

    return memo("grid_index", build)


def grid_pts(n=256, seed=3):
    import numpy as np

    rng = np.random.default_rng(seed)
    return rng.uniform(BBOX[:2], BBOX[2:], (n, 2))


def h3_problem():
    """Zones + chip index with a tiny edge_cap (tier-2 cells genuinely
    exist) + points — the resilience-test fixture, verbatim."""

    def build():
        import numpy as np

        from mosaic_tpu.core.index.h3 import H3IndexSystem
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.datasets import random_points, synthetic_zones
        from mosaic_tpu.sql.join import build_chip_index

        h3 = H3IndexSystem()
        zones = synthetic_zones(3, 3, bbox=BBOX_NY)
        index = build_chip_index(
            tessellate(zones, h3, RES_H3, keep_core_geoms=False),
            edge_cap=8,
        )
        pts = random_points(1200, bbox=BBOX_NY, seed=5)
        return h3, zones, index, np.asarray(pts)

    return memo("h3_problem", build)


def overlay_squares():
    def build():
        from mosaic_tpu.core.geometry import wkt

        def squares(specs):
            return wkt.from_wkt([
                f"POLYGON (({x0} {y0}, {x0 + w} {y0}, {x0 + w} {y0 + h},"
                f" {x0} {y0 + h}, {x0} {y0}))"
                for x0, y0, w, h in specs
            ])

        left = squares([(i * 2.9, j * 2.9, 2.7, 2.7)
                        for i in range(4) for j in range(4)])
        right = squares([(i * 2.9 + 0.9, j * 2.9 + 0.6, 2.4, 2.4)
                         for i in range(4) for j in range(4)])
        return left, right

    return memo("overlay_squares", build)


def stream_ctx():
    def build():
        import numpy as np

        from mosaic_tpu.core.geometry import wkt
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.sql.join import build_chip_index
        from mosaic_tpu.sql.stream import StreamJoin, ring_from_host

        col = wkt.from_wkt(ZONES)
        index = build_chip_index(
            tessellate(col, grid(), RES, keep_core_geoms=False)
        )
        rng = np.random.default_rng(7)
        batches = [
            rng.uniform(BBOX[:2], BBOX[2:], (512, 2)) for _ in range(3)
        ]
        ring = ring_from_host(batches)
        sj = StreamJoin(index, grid(), RES, prefetch=True)
        return sj, batches, ring

    return memo("stream_ctx", build)


def fast_policy():
    from mosaic_tpu.runtime.retry import RetryPolicy

    return RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)


# ----------------------------------------------------------- comparators


def arr_same(a, b) -> bool:
    import numpy as np

    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and a.tobytes() == b.tobytes()


def fields_same(names):
    def same(a, b):
        return all(
            arr_same(getattr(a, f), getattr(b, f)) for f in names
        )
    return same


zonal_same = fields_same(("keys", "count", "sum", "min", "max"))
measures_same = fields_same(("pairs", "value", "valid", "area", "sure"))


def stats_same(a, b) -> bool:
    return (a.checksum, a.matches, a.overflow) == (
        b.checksum, b.matches, b.overflow
    )


# ------------------------------------------------------ one-shot harness


def catching(fn):
    try:
        return fn(), None
    except BaseException as e:  # lint: broad-except-ok (the sweep classifies EVERY escape: typed passes, untyped is the finding)
        return None, e


def require_typed(site, err):
    from mosaic_tpu.runtime.errors import MosaicRuntimeError

    if err is None:
        raise ChaosMiss(f"{site}: expected a typed error, got success")
    if not isinstance(err, MosaicRuntimeError):
        raise ChaosMiss(
            f"{site}: UNTYPED {type(err).__name__} escaped: {err!r}"
        )


def one_shot(site, run, same, clean=None):
    """Run ``run()`` clean, then with one injected fault at ``site``;
    classify the faulted outcome against the resilience contract."""
    from mosaic_tpu.runtime import faults, telemetry
    from mosaic_tpu.runtime.errors import DegradedResult

    if clean is None:
        clean = run()
    with telemetry.capture() as ev:
        with faults.transient_errors(1, sites=(site,)) as plan:
            out, err = catching(run)
    if not plan.trips:
        raise ChaosMiss(
            f"{site}: the one-shot fault never tripped — the driver "
            "does not reach this site"
        )
    retries = sum(1 for e in ev if e["event"] == "transient_retry")
    if err is not None:
        require_typed(site, err)
        return {"outcome": "typed", "error": type(err).__name__}
    degraded = isinstance(out, DegradedResult) or bool(
        getattr(out, "degraded", False)
    )
    if not degraded:
        m = getattr(out, "metrics", None)
        if isinstance(m, dict):
            degraded = bool(m.get("degraded"))
    if not same(out, clean):
        raise ChaosMiss(
            f"{site}: faulted result diverged from clean with no typed "
            "error and no degradation flag — a silent wrong answer"
        )
    return {
        "outcome": "degraded" if degraded else "identical",
        "retries": retries,
    }


# ------------------------------------------------------- join / overlay


@driver("pip_join.device")
def drive_pip_join():
    from mosaic_tpu.sql.join import pip_join

    pts = grid_pts()
    return one_shot(
        "pip_join.device",
        lambda: pip_join(pts, None, grid(), RES,
                         chip_index=grid_index(), recheck=False),
        arr_same,
    )


@driver("overlay.predicate")
def drive_overlay_predicate():
    from mosaic_tpu.datasets import synthetic_zones
    from mosaic_tpu.sql.overlay import overlay_join

    h3, zones, _, _ = h3_problem()
    left = zones
    right = memo("overlay_right",
                 lambda: synthetic_zones(2, 2, bbox=BBOX_NY))
    return one_shot(
        "overlay.predicate",
        lambda: overlay_join(left, right, h3, RES_H3),
        arr_same,
    )


def _overlay_measures_run():
    from mosaic_tpu import expr as E
    from mosaic_tpu.sql.overlay import overlay_measures

    left, right = overlay_squares()
    return overlay_measures(left, right, grid(), RES,
                            E.overlap_fraction())


@driver("overlay.device_candidates")
def drive_overlay_candidates():
    return one_shot(
        "overlay.device_candidates", _overlay_measures_run,
        measures_same,
    )


@driver("overlay.measures")
def drive_overlay_measures():
    return one_shot(
        "overlay.measures", _overlay_measures_run, measures_same,
    )


@driver("dist_join.step")
def drive_dist_join():
    import jax.numpy as jnp
    import numpy as np

    from mosaic_tpu.parallel import dist_pip_join, make_mesh

    h3, zones, index, pts = h3_problem()
    mesh = make_mesh(8, cell_axis=2)
    cells = np.asarray(h3.point_to_cell(jnp.asarray(pts), RES_H3))
    return one_shot(
        "dist_join.step",
        lambda: dist_pip_join(pts, cells, index, mesh, len(zones))[0],
        arr_same,
    )


@driver("knn.pair_distances")
def drive_knn():
    import numpy as np

    from mosaic_tpu.datasets import synthetic_zones
    from mosaic_tpu.models import SpatialKNN

    h3, zones, _, _ = h3_problem()
    lands = synthetic_zones(2, 2, bbox=(-74.0, 40.62, -73.9, 40.7))

    def run():
        knn = SpatialKNN(index=h3, resolution=RES_H3, k_neighbours=2)
        return knn.transform(lands, zones)

    def same(a, b):
        # the KNN degradation contract is the oracle distances at
        # rtol 1e-9 (the published bound), candidate ids exact
        return arr_same(a.candidate_id, b.candidate_id) and bool(
            np.allclose(a.distance, b.distance, rtol=1e-9)
        )

    return one_shot("knn.pair_distances", run, same)


# ---------------------------------------------------------- knn serving


def knn_ctx():
    """A warmed ring-lane KNN frontend over dense grid-indexed squares
    plus a fixed query batch — shared by the three knn.* site drivers
    (warmup is the expensive part; the clean answer is memoized too)."""

    def build():
        import numpy as np

        from mosaic_tpu import functions as F
        from mosaic_tpu.knn import KNNFrontend, build_knn_index

        rng = np.random.default_rng(23)
        n = 80
        cx = rng.uniform(BBOX[0], BBOX[2], n)
        cy = rng.uniform(BBOX[1], BBOX[3], n)
        s = rng.uniform(0.5, 1.5, n)
        cand = F.st_geomfromwkt(np.array([
            f"POLYGON(({x} {y}, {x + w} {y}, {x + w} {y + w},"
            f" {x} {y + w}, {x} {y}))"
            for x, y, w in zip(cx, cy, s)
        ]))
        kx = build_knn_index(cand, index_system=grid(), resolution=RES)
        fe = KNNFrontend(kx, lane="ring")
        fe.warmup()
        lo = np.array([cx.min(), cy.min()])
        hi = np.array([cx.max(), cy.max()])
        q = lo + rng.uniform(0.1, 0.9, (6, 2)) * (hi - lo)
        return fe, q

    return memo("knn_ctx", build)


def _knn_site(site):
    fe, q = knn_ctx()

    def run():
        out, _ = fe.dispatch(q, 2)
        return out

    clean = memo("knn_clean", run)
    r = one_shot(site, run, arr_same, clean=clean)
    # the frontend must keep serving exactly after the fault
    if not arr_same(run(), clean):
        raise ChaosMiss(f"{site}: frontend did not recover after the "
                        "injected fault")
    return r


@driver("knn.expand")
def drive_knn_expand():
    return _knn_site("knn.expand")


@driver("knn.distance")
def drive_knn_distance():
    return _knn_site("knn.distance")


@driver("knn.scatter")
def drive_knn_scatter():
    return _knn_site("knn.scatter")


# --------------------------------------------------------- expr / raster


@driver("expr.map")
def drive_expr_map():
    import numpy as np

    from mosaic_tpu import expr as E
    from mosaic_tpu.raster import Raster
    from mosaic_tpu.raster.zonal import ZonalEngine

    engine = ZonalEngine(grid(), RES, chip_index=grid_index())
    rng = np.random.default_rng(5)
    data = rng.uniform(0.0, 100.0, (3, 75, 90))
    for b in range(3):
        data[b][rng.random((75, 90)) < 0.08] = np.nan
    raster = Raster(data=data, gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0),
                    srid=0, nodata=float("nan"))
    pipe = E.ndvi(nir=2, red=1).mask_where(E.band(3) < 80.0).zonal(
        by="zones"
    )
    return one_shot(
        "expr.map",
        lambda: engine.map(pipe, raster, tile=(32, 32),
                           retry_policy=fast_policy()),
        zonal_same,
    )


@driver("raster.decode")
def drive_raster_decode():
    import numpy as np

    from mosaic_tpu.raster import Raster, read_raster, write_geotiff

    rng = np.random.default_rng(11)
    r = Raster(
        data=rng.uniform(0, 100, (1, 16, 16)),
        gt=(-74.05, 0.01, 0.0, 40.78, 0.0, -0.01),
        srid=4326, nodata=-9.0,
    )
    path = os.path.join(tmpdir("raster.decode"), "chaos.tif")
    write_geotiff(path, r)
    return one_shot(
        "raster.decode",
        lambda: read_raster(path),
        lambda a, b: arr_same(a.data, b.data),
    )


@driver("raster.zonal")
def drive_raster_zonal():
    import numpy as np

    from mosaic_tpu.raster import Raster
    from mosaic_tpu.raster.zonal import zonal_zones

    rng = np.random.default_rng(5)
    data = rng.uniform(0, 100, (1, 40, 40))
    data[0][rng.random((40, 40)) < 0.1] = -9.0
    r = Raster(data=data, gt=(-0.5, 1.0, 0.0, 15.5, 0.0, -1.0),
               srid=0, nodata=-9.0)
    return one_shot(
        "raster.zonal",
        lambda: zonal_zones(r, grid_index(), grid(), RES,
                            tile=(32, 32)),
        zonal_same,
    )


# ---------------------------------------------------------------- serve


def _serve_engine():
    from mosaic_tpu.serve import BucketLadder, ServeEngine

    return ServeEngine(grid_index(), grid(), RES,
                       ladder=BucketLadder(64, 4096), bounds=BBOX,
                       max_wait_s=0.01)


def _serve_site(site):
    import numpy as np

    from mosaic_tpu.sql.join import pip_join

    pts = grid_pts(90, seed=21)
    ref = np.asarray(
        pip_join(pts, None, grid(), RES, chip_index=grid_index(),
                 recheck=False)
    )
    with _serve_engine() as eng:
        eng.warmup()

        def run():
            return np.asarray(eng.join(pts, deadline_s=60.0))

        r = one_shot(site, run, arr_same, clean=ref)
        # the engine must keep serving cleanly after the fault
        if not arr_same(run(), ref):
            raise ChaosMiss(f"{site}: engine did not recover after "
                            "the injected fault")
        return r


@driver("serve.admit")
def drive_serve_admit():
    return _serve_site("serve.admit")


@driver("serve.batch")
def drive_serve_batch():
    return _serve_site("serve.batch")


@driver("serve.dispatch")
def drive_serve_dispatch():
    return _serve_site("serve.dispatch")


# --------------------------------------------------------------- router


def _mk_router():
    from mosaic_tpu.dispatch import BucketLadder
    from mosaic_tpu.serve import ServeRouter

    return ServeRouter(grid(), program_store=tmpdir("router-store"),
                       engine_defaults={
                           "ladder": BucketLadder(64, 256),
                           "bounds": BBOX,
                           "max_wait_s": 0.01,
                       })


@driver("router.admit")
def drive_router_admit():
    import numpy as np

    with _mk_router() as router:
        router.add_tenant("a", grid_index(), RES, warm=False)
        pts = grid_pts(16, seed=10)
        ref = np.asarray(router.join("a", pts))
        r = one_shot(
            "router.admit",
            lambda: np.asarray(router.join("a", pts)),
            arr_same, clean=ref,
        )
        if not arr_same(np.asarray(router.join("a", pts)), ref):
            raise ChaosMiss("router.admit: tenant did not keep serving "
                            "after the failed admission")
        return r


@driver("router.evict")
def drive_router_evict():
    from mosaic_tpu.runtime import faults

    with _mk_router() as router:
        router.add_tenant("a", grid_index(), RES, warm=False)
        router.join("a", grid_pts(8, seed=10))
        with faults.transient_errors(1, sites=("router.evict",)) as plan:
            _, err = catching(lambda: router.evict("a"))
        if not plan.trips:
            raise ChaosMiss("router.evict: fault never tripped")
        require_typed("router.evict", err)
        if not router.metrics()["tenants"]["a"]["resident"]:
            raise ChaosMiss("router.evict: failed evict must leave the "
                            "engine resident and serving")
        router.evict("a")
        if router.metrics()["tenants"]["a"]["resident"]:
            raise ChaosMiss("router.evict: clean evict did not release "
                            "the engine")
        return {"outcome": "typed", "error": type(err).__name__}


@driver("router.swap")
def drive_router_swap():
    import numpy as np

    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.runtime import faults
    from mosaic_tpu.sql.join import build_chip_index, pip_join

    index_b = build_chip_index(tessellate(
        wkt.from_wkt(["POLYGON ((-24 -24, 34 -24, 34 19, -24 19, "
                      "-24 -24))"]),
        grid(), RES, keep_core_geoms=False,
    ))
    pts = grid_pts(64, seed=10)
    ref_a = np.asarray(
        pip_join(pts, None, grid(), RES, chip_index=grid_index(),
                 recheck=False)
    )
    ref_b = np.asarray(
        pip_join(pts, None, grid(), RES, chip_index=index_b,
                 recheck=False)
    )
    with _mk_router() as router:
        router.add_tenant("a", grid_index(), RES, warm=False)
        with faults.transient_errors(1, sites=("router.swap",)) as plan:
            _, err = catching(lambda: router.swap("a", index_b))
        if not plan.trips:
            raise ChaosMiss("router.swap: fault never tripped")
        require_typed("router.swap", err)
        # all-or-nothing: the tenant still serves the OLD snapshot
        if not arr_same(np.asarray(router.join("a", pts)), ref_a):
            raise ChaosMiss("router.swap: failed swap left a torn "
                            "snapshot — answers match neither index")
        router.swap("a", index_b)
        if not arr_same(np.asarray(router.join("a", pts)), ref_b):
            raise ChaosMiss("router.swap: clean swap after the fault "
                            "did not take")
        return {"outcome": "typed", "error": type(err).__name__}


# --------------------------------------------------------------- stream


@driver("stream.admit")
def drive_stream_admit():
    import numpy as np

    from mosaic_tpu.runtime import faults

    sj, batches, _ = stream_ctx()
    originals = [b.copy() for b in batches]
    with faults.corrupt_batches(rows=4, n=1,
                                sites=("stream.admit",)) as plan:
        _, rep = sj.admit(batches, bounds=BBOX)
    if not getattr(plan, "corrupted", 0):
        raise ChaosMiss("stream.admit: the corruption plan never "
                        "touched a batch")
    if rep.n_quarantined != 4:
        raise ChaosMiss(f"stream.admit: expected exactly the 4 poisoned "
                        f"rows quarantined, got {rep.n_quarantined}")
    for b, o in zip(batches, originals):
        if not np.array_equal(b, o):
            raise ChaosMiss("stream.admit: admission mutated the "
                            "caller's arrays")
    return {"outcome": "contained", "quarantined": rep.n_quarantined}


@driver("stream.prefetch")
def drive_stream_prefetch():
    import numpy as np

    from mosaic_tpu.sql.stream import ring_from_host

    _, batches, _ = stream_ctx()
    return one_shot(
        "stream.prefetch",
        lambda: np.asarray(ring_from_host(batches)),
        arr_same,
    )


def _stream_durable(site):
    sj, _, ring = stream_ctx()

    def run():
        return sj.run_durable(
            ring, 7, run_dir=tmpdir(site), snapshot_every=2,
            retry_policy=fast_policy(),
        )

    return one_shot(site, run, stats_same)


@driver("stream.scan_step")
def drive_stream_scan_step():
    return _stream_durable("stream.scan_step")


@driver("stream.snapshot")
def drive_stream_snapshot():
    return _stream_durable("stream.snapshot")


# ---------------------------------------------------------------- epoch


def _mk_epochal(tag):
    from mosaic_tpu.core.geometry import wkt
    from mosaic_tpu.index.epoch import EpochalIndex

    d = tmpdir(tag)
    ep = EpochalIndex(wkt.from_wkt(ZONES), grid(), RES, log_dir=d,
                      keep_core_geoms=False)
    ep.publish()
    return ep, d


def _epoch_apply(ep):
    from mosaic_tpu.core.geometry import wkt

    ep.apply(upsert=wkt.from_wkt([ZONE0_V2]), ids=[0])


def _replay_equals_live(site, ep, d):
    from mosaic_tpu.index.epoch import EpochalIndex, chip_index_equal

    r = EpochalIndex.replay(d, grid())
    if not chip_index_equal(r.index, ep.index):
        raise ChaosMiss(f"{site}: replay of the delta log diverged "
                        "from the live index after the fault")


@driver("epoch.apply")
def drive_epoch_apply():
    from mosaic_tpu.runtime import faults

    ep, d = _mk_epochal("epoch.apply")
    with faults.transient_errors(1, sites=("epoch.apply",)) as plan:
        _, err = catching(lambda: _epoch_apply(ep))
    if not plan.trips:
        raise ChaosMiss("epoch.apply: fault never tripped")
    require_typed("epoch.apply", err)
    if ep.applied_epoch != 0:
        raise ChaosMiss("epoch.apply: a killed apply must not advance "
                        "the applied epoch")
    _epoch_apply(ep)
    ep.publish()
    _replay_equals_live("epoch.apply", ep, d)
    return {"outcome": "typed", "error": type(err).__name__}


@driver("epoch.publish")
def drive_epoch_publish():
    from mosaic_tpu.runtime import faults

    ep, d = _mk_epochal("epoch.publish")
    _epoch_apply(ep)
    with faults.transient_errors(1, sites=("epoch.publish",)) as plan:
        _, err = catching(ep.publish)
    if not plan.trips:
        raise ChaosMiss("epoch.publish: fault never tripped")
    require_typed("epoch.publish", err)
    if ep.epoch != 0:
        raise ChaosMiss("epoch.publish: a killed publish must leave the "
                        "old epoch serving")
    ep.publish()
    if ep.epoch != 1:
        raise ChaosMiss("epoch.publish: retried publish did not land")
    _replay_equals_live("epoch.publish", ep, d)
    return {"outcome": "typed", "error": type(err).__name__}


@driver("epoch.compact")
def drive_epoch_compact():
    from mosaic_tpu.runtime import faults

    ep, d = _mk_epochal("epoch.compact")
    _epoch_apply(ep)
    ep.publish()
    with faults.transient_errors(1, sites=("epoch.compact",)) as plan:
        _, err = catching(ep.compact)
    if not plan.trips:
        raise ChaosMiss("epoch.compact: fault never tripped")
    require_typed("epoch.compact", err)
    _replay_equals_live("epoch.compact", ep, d)  # log still whole
    ep.compact()
    _replay_equals_live("epoch.compact", ep, d)  # compacted log too
    return {"outcome": "typed", "error": type(err).__name__}


# ----------------------------------------------------------------- main


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--registry",
                    default=os.path.join(REPO, "tests", "goldens",
                                         "registry.json"))
    ap.add_argument("--sites", action="append", default=None,
                    help="fnmatch pattern(s) restricting the sweep; "
                    "repeatable (default: every site)")
    ap.add_argument("--trail", default=None,
                    help="export the captured telemetry trail as JSONL")
    args = ap.parse_args()

    emit_to = sys.stdout
    sys.stdout = sys.stderr

    detail: dict = {}
    line = {"metric": "chaos_sites_clean", "value": 0, "unit": "sites",
            "detail": detail}
    stages: list = []
    root_span = None
    rc = 1
    try:
        with open(args.registry) as f:
            registered = list(json.load(f)["fault_sites"])

        missing = sorted(s for s in registered if s not in DRIVERS)
        extra = sorted(set(DRIVERS) - set(registered))
        targets = sorted(set(registered) | set(DRIVERS))
        if args.sites:
            targets = [t for t in targets
                       if any(fnmatch.fnmatch(t, p) for p in args.sites)]
            missing = [m for m in missing
                       if any(fnmatch.fnmatch(m, p) for p in args.sites)]

        from mosaic_tpu import obs
        from mosaic_tpu.runtime import telemetry

        cap = telemetry.capture()
        stages = cap.__enter__()
        root_span = obs.start_span("chaos_sweep", sites=len(targets))

        outcomes: dict = {}
        failures: dict = {}
        for site in targets:
            fn = DRIVERS.get(site)
            if fn is None:
                continue  # already recorded in `missing`
            t0 = time.perf_counter()
            try:
                r = fn()
                r["seconds"] = round(time.perf_counter() - t0, 3)
                outcomes[site] = r
                print(f"[chaos] {site}: {r['outcome']} "
                      f"({r['seconds']}s)", file=sys.stderr)
            except Exception as e:  # lint: broad-except-ok (one site's failure must not hide the rest of the sweep)
                failures[site] = repr(e)[:300]
                print(f"[chaos] {site}: FAIL {e!r}", file=sys.stderr)
            telemetry.record(
                "chaos_site", site=site,
                outcome=outcomes.get(site, {}).get("outcome", "fail"),
            )

        detail["outcomes"] = outcomes
        detail["failures"] = failures
        detail["missing_drivers"] = missing
        detail["extra"] = extra
        detail["registered"] = len(registered)
        line["value"] = len(outcomes)

        if missing:
            raise AssertionError(
                f"{len(missing)} registered fault site(s) have no chaos "
                f"driver: {missing} — every site in the registry must "
                "ship one"
            )
        if failures:
            raise AssertionError(
                f"{len(failures)} site(s) broke the chaos contract: "
                f"{sorted(failures)}"
            )
        rc = 0
    except Exception as e:  # lint: broad-except-ok (the sweep must always emit its JSON line; rc carries failure)
        detail["error"] = repr(e)[:400]

    if root_span is not None:
        try:
            root_span.end()
        except Exception:  # lint: broad-except-ok (span cleanup must not mask the sweep result)
            pass
    if args.trail and stages:
        try:
            from mosaic_tpu import obs as _obs

            _obs.write_jsonl(stages, args.trail)
        except Exception as e:  # lint: broad-except-ok (a sick trail disk degrades the trail, not the sweep)
            detail["trail_error"] = repr(e)[:200]

    emit_to.write(json.dumps(line) + "\n")
    emit_to.flush()
    return rc


if __name__ == "__main__":
    sys.exit(main())
