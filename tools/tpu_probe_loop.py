"""Persistent TPU-tunnel probe loop for round 5.

Runs forever: every cycle it launches the same subprocess probe bench.py
uses (compile+run a tiny jitted op — devices() alone can succeed while
compilation hangs).  Every attempt is appended to
``TPU_PROBE_TRAIL_r05.jsonl``.  The moment a probe succeeds, it runs the
full ``bench.py`` pinned to the TPU; a nonzero result is saved to
``BENCH_TPU_LIVE.json`` and a timestamped copy is kept per attempt so a
later, better number never overwrites the evidence that an earlier one
existed.  After a success it keeps probing at a slower cadence and
re-benches hourly so improvements made later in the round still land.

Round-4 lesson (TPU_PROBE_TRAIL_r04.jsonl): single-shot probing loses
whole rounds; the tunnel can hang for hours then recover.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIL = os.path.join(REPO, "TPU_PROBE_TRAIL_r05.jsonl")
LIVE = os.path.join(REPO, "BENCH_TPU_LIVE.json")

#: after a good bench, re-run this often while the tunnel stays up (the
#: code under test improves during the round)
REBENCH_S = 3600.0

# the probe snippet lives in bench.py (single source of the round-2
# lesson: devices() can succeed while compilation hangs) — but the
# watchdog must keep probing even while bench.py is mid-edit and broken,
# so a minimal self-contained fallback covers import failure
sys.path.insert(0, REPO)
try:
    from bench import _PROBE_CODE
except Exception:  # noqa: BLE001 — any bench.py breakage, keep watching
    _PROBE_CODE = """
import json, sys
import jax, jax.numpy as jnp
devs = jax.devices()
if devs[0].platform in ("cpu",):
    sys.exit(3)
x = jnp.arange(1024, dtype=jnp.int32)
r = int(jax.jit(lambda v: ((v * v + 1) ^ (v >> 7)).sum())(x))
print(json.dumps({"platform": str(devs[0].platform), "device": str(devs[0])}))
sys.exit(0 if r == int(((x * x + 1) ^ (x >> 7)).sum()) else 4)
"""


def log(rec: dict) -> None:
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(TRAIL, "a") as f:
        f.write(json.dumps(rec) + "\n")


def probe(timeout: float = 300.0) -> dict:
    rec: dict = {}
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            timeout=timeout, capture_output=True, text=True,
        )
        lines = r.stdout.strip().splitlines()
        if lines:
            try:
                rec.update(json.loads(lines[-1]))
            except ValueError:
                rec["stdout"] = lines[-1][:160]
        if r.returncode == 0:
            rec["outcome"] = "tpu"
        elif r.returncode == 3:
            rec["outcome"] = "cpu_verdict"
        else:
            rec["outcome"] = f"error_rc{r.returncode}"
            rec["stderr"] = r.stderr[-200:]
    except subprocess.TimeoutExpired:
        rec["outcome"] = f"hang_timeout_{timeout:.0f}s"
    except OSError as e:
        # the loop must survive spawn failures (fd exhaustion etc.)
        rec["outcome"] = f"spawn_error:{e!r}"[:160]
    return rec


def _live_ok() -> bool:
    try:
        with open(LIVE) as f:
            return bool(json.load(f).get("value", 0))
    except (OSError, ValueError):
        return False


def _promotes(line: dict, quick: bool) -> bool:
    """Complete full-bench artifacts outrank quick or salvaged ones;
    within the same grade, a higher headline wins. bench.py's salvage
    path (late-lane failure) exits 0 with value>0 but detail.error set —
    such a line must never replace a complete LIVE artifact."""
    try:
        with open(LIVE) as f:
            cur = json.load(f)
    except (OSError, ValueError):
        return True

    def grade(obj: dict, is_quick: bool) -> int:
        det = obj.get("detail", {})
        return 2 if not (is_quick or det.get("quick") or det.get("error")) \
            else 1

    g_new = grade(line, quick)
    g_cur = grade(cur, False)
    if g_new != g_cur:
        return g_new > g_cur
    return float(line.get("value", 0)) >= float(cur.get("value", 0))


def run_bench(quick: bool = False) -> bool:
    """Bench pinned to TPU; True if a line with value>0 was captured.

    ``quick`` runs the reduced lane set (headline + autotune + pallas +
    baselines, 2 passes, no scale/recheck/secondary) to bank a number
    inside a short tunnel window; the caller follows up with the full run.

    The tunnel can die MID-bench (observed 2026-07-31: probe ok at 01:01,
    jax.devices() hung at 01:33), so the bench checkpoints its detail dict
    to BENCH_TPU_PARTIAL.json at every lane boundary — on a timeout that
    partial (plus the stderr progress trail) is the salvage."""
    partial = os.path.join(REPO, "BENCH_TPU_PARTIAL.json")
    env = dict(os.environ)
    env.update(MOSAIC_BENCH_PLATFORM="tpu", MOSAIC_BENCH_NO_REEXEC="1",
               MOSAIC_BENCH_PARTIAL=partial)
    if quick:
        env.update(MOSAIC_BENCH_QUICK="1", MOSAIC_BENCH_SCALE_POINTS="0",
                   MOSAIC_BENCH_PASSES="2")
    try:  # a stale partial from a previous run must never pose as salvage
        os.unlink(partial)
    except OSError:
        pass
    t0 = time.time()
    r = None
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, timeout=1500 if quick else 3600,
            capture_output=True, text=True, cwd=REPO,
        )
        line = json.loads(r.stdout.strip().splitlines()[-1])
        try:  # run completed: its checkpoint is not salvage evidence
            os.unlink(partial)
        except OSError:
            pass
    except Exception as e:  # noqa: BLE001 — any failure is just a trail entry
        rec = {"outcome": f"bench_fail:{e!r}"[:200],
               "bench_s": round(time.time() - t0, 1)}
        # TimeoutExpired carries stderr on the exception; for post-exit
        # failures (empty stdout after an OOM kill, bad JSON) it lives on
        # the CompletedProcess instead
        err = getattr(e, "stderr", None) or (r.stderr if r else None)
        if err:
            if isinstance(err, bytes):
                err = err.decode(errors="replace")
            marks = [ln for ln in err.splitlines() if ln.startswith("[bench")]
            rec["progress_tail"] = marks[-3:]
        if os.path.exists(partial):  # preserve the salvage per attempt
            stamp = time.strftime("%m%d_%H%M%S")
            try:
                os.replace(partial,
                           os.path.join(REPO, f"BENCH_TPU_PARTIAL_{stamp}.json"))
                rec["partial_saved"] = f"BENCH_TPU_PARTIAL_{stamp}.json"
            except OSError:
                pass
        log(rec)
        return False
    line.setdefault("detail", {})["bench_wall_s"] = round(time.time() - t0, 1)
    stamp = time.strftime("%m%d_%H%M%S")
    kind = "QUICK_" if quick else ""
    with open(
        os.path.join(REPO, f"BENCH_TPU_LIVE_{kind}{stamp}.json"), "w"
    ) as f:
        json.dump(line, f, indent=1)
    ok = bool(line.get("value", 0))
    if ok and _promotes(line, quick):
        # LIVE holds the best evidence so far: a quick or salvaged
        # (detail.error set) number never replaces a complete full run
        with open(LIVE, "w") as f:
            json.dump(line, f, indent=1)
    log({"outcome": ("bench_quick_ok" if quick else "bench_ok") if ok
         else "bench_zero",
         "value": line.get("value"), "bench_s": round(time.time() - t0, 1)})
    return ok


def run_aux() -> None:
    """After a good bench: capture the trace + stream artifacts on the
    live chip (VERDICT r4 items 2 and 5). Each failure is just a trail
    entry — a partial haul beats none."""
    jobs = [
        ("trace", [sys.executable, os.path.join(REPO, "tools", "trace_join.py"),
                   "--out", os.path.join(REPO, "TRACE_r05.json")], 1200),
        ("stream_devgen", [sys.executable,
                           os.path.join(REPO, "tools", "stream_bench.py"),
                           "--points", "100000000", "--device-gen",
                           "--out", os.path.join(REPO, "STREAM_r05.json")], 1800),
        ("stream_host", [sys.executable,
                         os.path.join(REPO, "tools", "stream_bench.py"),
                         "--points", "16000000",
                         "--out", os.path.join(REPO, "STREAM_HOST_r05.json")],
         1800),
    ]
    for name, cmd, tmo in jobs:
        t0 = time.time()
        try:
            r = subprocess.run(
                cmd, timeout=tmo, capture_output=True, text=True, cwd=REPO
            )
            tail = (r.stdout if r.returncode == 0 else r.stderr).strip()
            log({"outcome": f"aux_{name}_rc{r.returncode}",
                 "aux_s": round(time.time() - t0, 1),
                 "tail": tail[-200:]})
        except Exception as e:  # noqa: BLE001
            log({"outcome": f"aux_{name}_fail:{e!r}"[:200],
                 "aux_s": round(time.time() - t0, 1)})


def main() -> None:
    last_bench = time.time() - REBENCH_S if _live_ok() else None
    quick_done = _live_ok()
    aux_done = os.path.exists(os.path.join(REPO, "TRACE_r05.json"))
    while True:
        rec = probe()
        rec["phase"] = "post-bench" if last_bench else "hunting"
        log(rec)
        if rec["outcome"] == "tpu" and (
            last_bench is None or time.time() - last_bench >= REBENCH_S
        ):
            # bank a number fast first (tunnel windows can be minutes),
            # then go for the full lane set
            if not quick_done:
                quick_done = run_bench(quick=True)
            if run_bench():
                quick_done = True  # a full number makes quick redundant
                last_bench = time.time()
                if not aux_done:
                    run_aux()
                    aux_done = True
        # hunt aggressively until we have a number, then back off
        time.sleep(120.0 if last_bench else 30.0)


if __name__ == "__main__":
    main()
