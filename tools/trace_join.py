"""Capture an XLA profiler trace of the 4M-point NYC join on the real
chip (VERDICT r4 item 2: 'capture a utils.device_trace of the 4M-point
join ... with a trace artifact in the repo').

Saves the xprof trace under traces/r05/ and prints one JSON line with
the timed phase breakdown measured around the same dispatches (cells
pipeline alone, full fused step, tier split), so the artifact carries
numbers even where the trace viewer isn't available.

Usage: python tools/trace_join.py [--points 4000000] [--out TRACE_r05.json]
(CPU validation: MOSAIC_BENCH_PLATFORM=cpu --points 200000)
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=4_000_000)
    ap.add_argument("--out", default=None)
    ap.add_argument("--trace-dir", default=os.path.join(REPO, "traces", "r05"))
    args = ap.parse_args()

    if os.environ.get("MOSAIC_BENCH_PLATFORM") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from bench import RES, _load_or_build_index, _load_zones
    from mosaic_tpu.core.index.h3 import H3IndexSystem
    from mosaic_tpu.sql.join import pip_join_points
    from mosaic_tpu.utils import annotate, device_trace

    h3 = H3IndexSystem()
    zones, zones_src = _load_zones()
    b = zones.bounds()
    bbox = (
        float(np.nanmin(b[:, 0])), float(np.nanmin(b[:, 1])),
        float(np.nanmax(b[:, 2])), float(np.nanmax(b[:, 3])),
    )
    index, _, _ = _load_or_build_index(zones, zones_src, h3)
    dtype = index.border.verts.dtype
    n = args.points
    rng = np.random.default_rng(42)
    pts = jnp.asarray(rng.uniform(bbox[:2], bbox[2:], (n, 2)))
    pts.block_until_ready()

    cells_np = np.asarray(index.cells)

    @jax.jit
    def cells_only(p):
        c = h3.point_to_cell(p.astype(jnp.float32), RES)
        return (c ^ (c >> 32)).astype(jnp.int32).sum()

    @functools.partial(jax.jit, static_argnames=("fcap", "hcap"))
    def step(p, chip_index, fcap, hcap):
        with annotate("cells"):
            cells = h3.point_to_cell(p.astype(jnp.float32), RES)
        with annotate("probe"):
            shifted = (p - chip_index.border.shift).astype(dtype)
            out = pip_join_points(
                shifted, cells.astype(jnp.int64), chip_index,
                heavy_cap=hcap, found_cap=fcap,
                lookup="gather" if jax.devices()[0].platform == "cpu"
                else "mxu",
            compaction="scatter" if jax.devices()[0].platform == "cpu"
            else "mxu",
            )
        return (out ^ (out >> 16)).sum()

    pre = np.asarray(
        h3.point_to_cell(pts[:200_000].astype(jnp.float32), RES)
    )
    pos = np.clip(np.searchsorted(cells_np, pre), 0, cells_np.size - 1)
    ffrac = float((cells_np[pos] == pre).mean())
    fcap = min(((int(2 * ffrac * n) + 131071) // 131072 + 1) * 131072, n)
    hmask = np.asarray(index.cell_heavy) >= 0
    hfrac = float(np.isin(pre, cells_np[hmask]).mean())
    hcap = min(((int(2 * hfrac * n) + 131071) // 131072 + 1) * 131072, fcap)

    def timed(fn, *a):
        fn(*a).block_until_ready()  # compile
        best = np.inf
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(*a))
            best = min(best, time.perf_counter() - t0)
        return best

    cells_s = timed(cells_only, pts)
    step_s = timed(step, pts, index, fcap, hcap)

    os.makedirs(args.trace_dir, exist_ok=True)
    with device_trace(args.trace_dir):
        float(step(pts, index, fcap, hcap))
        float(cells_only(pts))

    line = {
        "metric": "join_trace",
        "value": round(n / step_s, 1),
        "unit": "points/sec/chip",
        "detail": {
            "n_points": n,
            "cells_only_s": round(cells_s, 4),
            "full_step_s": round(step_s, 4),
            "probe_s_approx": round(step_s - cells_s, 4),
            "caps": [fcap, hcap],
            "device": str(jax.devices()[0]),
            "zones": zones_src,
            "trace_dir": os.path.relpath(args.trace_dir, REPO),
        },
    }
    out = json.dumps(line)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    main()
